"""Warm-cache smoke: the cross-process warm-start gate for the compile
cache (ISSUE 17 tentpole).

Three REAL child processes run against ONE cache dir — process
boundaries, not clear_caches(), so the pin covers exactly the restart
path the cache exists for (same host; XLA:CPU artifacts are not
portable across machines, see tests/conftest.py):

  1. COLD    — a journaled flagship cycle over an empty cache dir:
               must record at least one persistent-cache miss (it is
               doing the compiling) and publish its placements;
  2. WARM    — the same cycle, fresh process, same dir: must compile
               ZERO programs (persistent cache_misses == 0 with hits)
               and place every pod bit-identically to the cold run;
  3. RECOVER — a fresh process over the same journal + cache dir runs
               restart recovery: `recover()` must report
               compiled_programs == 0 and replay the cold run's
               placements bit-identically.

Correctness + absence-of-compilation only, never wall-clock.
Usage: JAX_PLATFORMS=cpu python tools/warm_cache_smoke.py
Child mode (internal): ... --child <cold|warm|recover> <workdir> <seed>
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from koordinator_tpu.compilecache import counters
from koordinator_tpu.compilecache.cache import CompileCache
from koordinator_tpu.metrics import Registry
from koordinator_tpu.scheduler.frameworkext import SchedulerService
from koordinator_tpu.scheduler.journal import CommitJournal
from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
from koordinator_tpu.utils import synthetic

N_NODES, N_PODS = 32, 64
MARK = "WARM_CACHE_SMOKE_REPORT "


def make_inputs(seed: int):
    snap = synthetic.synthetic_cluster(N_NODES, seed=seed, num_quotas=4,
                                       num_gangs=4)
    pods = synthetic.synthetic_pods(N_PODS, seed=seed + 7, num_quotas=4,
                                    num_gangs=4)
    return snap, pods


def make_service(workdir: str, journal_name: str) -> SchedulerService:
    cache = CompileCache(os.path.join(workdir, "cache"))
    journal = CommitJournal(os.path.join(workdir, journal_name))
    svc = SchedulerService(metrics=SchedulerMetrics(Registry()),
                           num_rounds=2, k_choices=4, guards=False,
                           journal=journal, compile_cache=cache)
    svc._sleep = lambda _s: None
    return svc


def child(mode: str, workdir: str, seed: int) -> int:
    """One process life: cold/warm schedule or restart recovery. The
    verdict rides one JSON line on stdout for the parent."""
    snap, pods = make_inputs(seed)
    # the warm probe gets its OWN journal: it re-runs the batch as a
    # fresh epoch, and a second completed epoch in the shared journal
    # would complicate the recover child's replay set. The cache dir —
    # the thing under test — is shared by all three.
    svc = make_service(workdir, "journal_warm.bin" if mode == "warm"
                       else "journal.bin")
    with counters.watch() as w:
        if mode == "recover":
            svc.publish(snap)
            report = svc.recover({1: pods})
            assignment = np.asarray(report["results"][1].assignment)
            compiled = report["compiled_programs"]
        else:
            svc.publish(snap)
            assignment = np.asarray(svc.schedule(pods).assignment)
            compiled = w.cache_misses
    print(MARK + json.dumps({
        "mode": mode,
        "assignment": assignment.tolist(),
        "compiled_programs": int(compiled),
        "persistent_hits": int(w.cache_hits),
        "persistent_misses": int(w.cache_misses),
        "manifest_hits": svc.compile_cache.hits,
        "manifest_misses": svc.compile_cache.misses,
        "manifest_entries": svc.compile_cache.stats()["entries"],
    }), flush=True)
    return 0


def run_child(mode: str, workdir: str, seed: int) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", mode,
         workdir, str(seed)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        raise AssertionError(
            f"{mode} child exited {proc.returncode};\nstderr tail: "
            f"{proc.stderr[-2000:]}")
    for line in proc.stdout.splitlines():
        if line.startswith(MARK):
            return json.loads(line[len(MARK):])
    raise AssertionError(f"{mode} child printed no report;\nstdout "
                         f"tail: {proc.stdout[-2000:]}")


def check(cond, what):
    if not cond:
        raise AssertionError(what)


def main(argv) -> int:
    if argv[:1] == ["--child"]:
        return child(argv[1], argv[2],
                     int(argv[3]) if len(argv) > 3 else 0)
    seed = int(argv[0]) if argv else 0
    workdir = tempfile.mkdtemp(prefix="warm_cache_smoke_")
    try:
        cold = run_child("cold", workdir, seed)
        check(cold["persistent_misses"] >= 1,
              f"cold run compiled nothing ({cold}) — the cache dir "
              f"cannot have been active")
        check(cold["manifest_entries"] >= 1,
              f"cold run recorded no manifest entries ({cold})")
        print(f"WARM OK    cold: {cold['persistent_misses']} compile(s), "
              f"{cold['manifest_entries']} manifest entr(ies)", flush=True)

        warm = run_child("warm", workdir, seed)
        check(warm["persistent_misses"] == 0,
              f"warm run still compiled {warm['persistent_misses']} "
              f"program(s) — the warm-start contract is broken")
        check(warm["persistent_hits"] >= 1,
              f"warm run hit nothing ({warm}) — it cannot have read "
              f"the cache")
        check(warm["manifest_misses"] == 0,
              f"warm run took {warm['manifest_misses']} manifest "
              f"miss(es): the cycle program's cache key drifted "
              f"between identical processes")
        check(warm["assignment"] == cold["assignment"],
              "warm placements diverged from the cold run")
        print(f"WARM OK    warm: 0 compiles, "
              f"{warm['persistent_hits']} persistent hit(s)", flush=True)

        rec = run_child("recover", workdir, seed)
        check(rec["compiled_programs"] == 0,
              f"restart recovery compiled {rec['compiled_programs']} "
              f"program(s) against a warmed cache")
        check(rec["assignment"] == cold["assignment"],
              "recovered placements diverged from the cold run")
        print("WARM OK    recover: 0 compiles, replay bit-identical",
              flush=True)
        print("WARM CACHE SMOKE: cold->warm->recover converge with "
              "zero warm-path compilations", flush=True)
        return 0
    except AssertionError as exc:
        print(f"WARM FAIL  {exc}", flush=True)
        return 1
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
