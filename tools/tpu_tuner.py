"""One-shot TPU tuning battery, armed while the tunnel is wedged.

Probes the tunnel every few minutes; on the first healthy window it
runs the round-5 hardware experiments back-to-back and exits:

  1. canonical bench (batched readbacks, exact top-k, 512 tails) and
     the approx_max_k contrast (BENCH_APPROX=1)
  2. approx_max_k quality bound where it binds (KOORD_TEST_PLATFORM)
  3. packed full-gate bisection (tools/profile_fullgate.py)
  4. full-gate chunk sweep (BENCH_FULL_CHUNK 1000 / 500)
  5. full-gate rounds sweep (BENCH_ROUNDS=1 BENCH_K=16)
  6. wide-tail contrasts for both paths (BENCH_TAIL_CHUNK=2000)
  7. slim chunk sweep (BENCH_CHUNK=1000)

Coordination with tools/tpu_capture.py: the capture artifact is the
round's EVIDENCE and takes priority — while it is stale the tuner
yields (sleeps) so the watcher can freeze a fresh artifact first.
Everything is logged to tools/tpu_tuner.log; each experiment's stdout
tail is inlined so one file tells the whole story.
"""

import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
LOG = os.path.join(REPO, "tools", "tpu_tuner.log")
PROBE_INTERVAL = float(os.environ.get("TUNER_PROBE_INTERVAL", "300"))


def log(msg):
    stamp = datetime.datetime.now(datetime.timezone.utc).isoformat()
    with open(LOG, "a") as f:
        f.write(f"[{stamp}] {msg}\n")


def run_exp(tag, cmd, env_extra, timeout):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "axon")
    env["BENCH_PROBE_ATTEMPTS"] = "1"
    env["BENCH_PROBE_TIMEOUT"] = "120"
    env.update(env_extra)
    log(f"exp {tag}: {' '.join(cmd)} env={env_extra}")
    out_path = os.path.join(REPO, "tools", f"tuner_{tag}.out")
    with open(out_path, "wb") as out:
        try:
            rc = subprocess.run(cmd, cwd=REPO, env=env, stdout=out,
                                stderr=subprocess.STDOUT,
                                timeout=timeout).returncode
        except subprocess.TimeoutExpired:
            log(f"exp {tag}: TIMEOUT after {timeout}s")
            return False
    with open(out_path, errors="replace") as f:
        lines = [l.rstrip() for l in f if l.strip()]
    for l in lines[-8:]:
        log(f"  {tag}| {l}")
    log(f"exp {tag}: rc={rc}")
    return rc == 0


def capture_fresh():
    try:
        with open(os.path.join(REPO, "bench_tpu_capture.json")) as f:
            art = json.load(f)
        age = (datetime.datetime.now(datetime.timezone.utc)
               - datetime.datetime.fromisoformat(art["captured_at"])
               ).total_seconds()
        return age < 7200
    except (OSError, ValueError, KeyError):
        return False


def main():
    import bench
    while True:
        if not bench._probe_once(100):
            time.sleep(PROBE_INTERVAL)
            continue
        log("tunnel healthy")
        if not capture_fresh():
            # the watcher's capture is the round's evidence; yield
            log("capture artifact stale - yielding to tpu_capture")
            time.sleep(240)
            continue
        break
    py = sys.executable
    bench_one = [py, "-c",
                 "import bench; bench.main(bench.ensure_platform())"]
    run_exp("canonical", bench_one, {"BENCH_EXTRAS": "0"}, 1500)
    run_exp("canonical_approx", bench_one,
            {"BENCH_EXTRAS": "0", "BENCH_APPROX": "1"}, 1500)
    run_exp("approx_bound",
            [py, "-m", "pytest", "tests/test_approx_topk.py", "-q"],
            {"KOORD_TEST_PLATFORM": "axon"}, 1500)
    run_exp("bisect", [py, "tools/profile_fullgate.py", "10000", "10000"],
            {}, 2400)
    fg = [py, "-c", ("import bench; bench.ensure_platform(); "
                     "bench.run_northstar(full_gate=True)")]
    run_exp("fg_chunk1000", fg, {"BENCH_FULL_CHUNK": "1000"}, 2400)
    run_exp("fg_chunk500", fg, {"BENCH_FULL_CHUNK": "500"}, 2400)
    run_exp("fg_rounds1", fg, {"BENCH_ROUNDS": "1", "BENCH_K": "16"},
            2400)
    run_exp("fg_tailwide2000", fg, {"BENCH_TAIL_CHUNK": "2000"}, 2400)
    slim = [py, "-c", ("import bench; bench.ensure_platform(); "
                       "bench.run_northstar(full_gate=False)")]
    run_exp("slim_chunk1000", slim, {"BENCH_CHUNK": "1000"}, 1500)
    run_exp("slim_tailwide2000", slim, {"BENCH_TAIL_CHUNK": "2000"}, 1500)
    log("tuner battery complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
