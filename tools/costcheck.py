"""koordcost drift gate: static cost/memory accounting vs a checked-in
baseline.

`obs/costmodel.py` prices every program the scheduler can dispatch —
all contracted kernels, the flagship cycle per cascade form, the
donated tail, and the packed-snapshot byte contract — entirely from
AOT lowering, no device run. This tool freezes that model into
`perf/COST_BASELINE.json` (``--stamp``) and fails CI when any number
moves beyond tolerance without a restamp:

  * flops / bytes-accessed growth (a pad explosion, a rank growth, an
    accidental broadcast);
  * peak-memory growth (argument+output+temp-alias);
  * alias collapse (a lost `donate_argnums` shows up as alias_bytes
    dropping to zero — flagged by name, not just by percentage);
  * packed-representation growth (a bf16->f32 upcast in
    snapshot/packing.py doubles `packed_bytes` here long before it
    doubles checkpoint volume on hardware).

The baseline is a loud-provenance manifest in the compilecache style:
it records the contract fingerprint, jax version, backend, and working
set it was stamped at, and the gate REFUSES to compare across a
provenance mismatch — a contract edit or jax upgrade demands an
explicit restamp in the same change, so the diff shows the new numbers.

Every drift finding carries the ``COST DRIFT`` marker
(`tools/seedmut.py` smokes key on it).

Usage:
  JAX_PLATFORMS=cpu python tools/costcheck.py              # gate
  JAX_PLATFORMS=cpu python tools/costcheck.py --stamp      # rewrite baseline
  JAX_PLATFORMS=cpu python tools/costcheck.py --only packing/   # label-prefix subset
  JAX_PLATFORMS=cpu python tools/costcheck.py --self-test-mutation
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

BASELINE_VERSION = 1
BASELINE_PATH = os.path.join("perf", "COST_BASELINE.json")
MARKER = "COST DRIFT"

# relative tolerance per compared field; 0.0 means exact. The model is
# deterministic for fixed (fingerprint, jax, backend) — the slack on
# the float fields absorbs only cost-analysis rounding, not real drift.
TOLERANCES: Dict[str, float] = {
    "flops": 0.01,
    "bytes_accessed": 0.01,
    "argument_bytes": 0.0,
    "output_bytes": 0.0,
    "temp_bytes": 0.01,
    "alias_bytes": 0.0,
    "peak_bytes": 0.01,
    "hlo_instructions": 0.02,
    "hlo_output_bytes": 0.02,
    "packed_bytes": 0.0,
    "unpacked_bytes": 0.0,
    "saved_bytes": 0.0,
}


def baseline_path(root: str = REPO_ROOT) -> str:
    return os.path.join(root, BASELINE_PATH)


def _provenance() -> Dict[str, Any]:
    import jax

    from koordinator_tpu.compilecache import keys

    return {
        "fingerprint": keys.contract_fingerprint(),
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
    }


def load_baseline(path: str) -> Optional[Dict[str, Any]]:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        manifest = json.load(f)
    if manifest.get("version") != BASELINE_VERSION:
        return None
    return manifest


def save_baseline(path: str, manifest: Dict[str, Any]) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def compare_entry(label: str, old: Dict[str, Any], new: Dict[str, Any]
                  ) -> List[str]:
    """Drift findings for one program: every compared field beyond its
    tolerance, with the lost-donation case called out by name."""
    findings = []
    for field, tol in TOLERANCES.items():
        if field not in old and field not in new:
            continue
        ov = float(old.get(field, 0.0))
        nv = float(new.get(field, 0.0))
        rel = abs(nv - ov) / max(abs(ov), 1.0)
        if rel <= tol:
            continue
        extra = ""
        if field == "alias_bytes" and ov > 0 and nv == 0:
            extra = " (donation aliasing LOST)"
        findings.append(
            f"{MARKER}: {label} {field} {ov:.0f} -> {nv:.0f} "
            f"({rel:+.1%} vs tol {tol:.1%}){extra}")
    return findings


def compare(baseline: Dict[str, Any], entries: Dict[str, Dict[str, Any]],
            only: Optional[str] = None) -> List[str]:
    old_entries = baseline["entries"]
    if only:
        old_entries = {k: v for k, v in old_entries.items()
                       if k.startswith(only)}
    findings: List[str] = []
    for label in sorted(set(old_entries) | set(entries)):
        if label not in entries:
            findings.append(f"{MARKER}: {label} vanished from the cost "
                            f"model (baseline still stamps it)")
        elif label not in old_entries:
            findings.append(f"{MARKER}: {label} is new and unstamped "
                            f"(run --stamp to baseline it)")
        else:
            findings.extend(
                compare_entry(label, old_entries[label], entries[label]))
    return findings


def run_gate(stamp: bool, only: Optional[str],
             root: str = REPO_ROOT) -> int:
    from koordinator_tpu.obs import costmodel

    prov = _provenance()
    path = baseline_path(root)
    baseline = load_baseline(path)

    if stamp:
        entries = costmodel.collect(log_fn=print)
        manifest = {
            "version": BASELINE_VERSION,
            "sizes": dict(costmodel.COST_SIZES),
            **prov,
            "entries": entries,
        }
        save_baseline(path, manifest)
        print(f"costcheck: stamped {len(entries)} programs -> "
              f"{os.path.relpath(path, root)} "
              f"(fingerprint {prov['fingerprint'][:12]}, "
              f"jax {prov['jax_version']}, {prov['backend']})")
        return 0

    if baseline is None:
        print(f"{MARKER}: no readable baseline at "
              f"{os.path.relpath(path, root)} — run --stamp first")
        return 1
    # loud provenance: never compare numbers whose meaning changed
    stale = [k for k in ("fingerprint", "jax_version", "backend")
             if baseline.get(k) != prov[k]]
    if stale:
        for k in stale:
            print(f"{MARKER}: baseline {k} {baseline.get(k)!r} != "
                  f"current {prov[k]!r}")
        print(f"{MARKER}: provenance mismatch — restamp the baseline "
              f"in the same change that moved it")
        return 1

    sizes = dict(baseline.get("sizes", costmodel.COST_SIZES))
    if only and only.startswith("packing/"):
        entries: Dict[str, Dict[str, Any]] = {
            k: dict(v, kind="packing")
            for k, v in costmodel.packing_report(sizes).items()}
    else:
        entries = costmodel.collect(sizes=sizes)
        if only:
            entries = {k: v for k, v in entries.items()
                       if k.startswith(only)}

    findings = compare(baseline, entries, only=only)
    _count_drift_check(bool(findings))
    for line in findings:
        print(line)
    scope = f" (only {only})" if only else ""
    if findings:
        print(f"costcheck: {len(findings)} drift finding(s) across "
              f"{len(entries)} program(s){scope} — restamp if "
              f"intentional")
        return 1
    print(f"costcheck: {len(entries)} program(s){scope} within "
          f"tolerance of {os.path.relpath(path, root)}")
    return 0


def _count_drift_check(drifted: bool) -> None:
    """Feed scheduler_cost_drift_checks{result=...} so any embedding
    process (soak harness, resident service running periodic checks)
    exposes gate outcomes alongside its other scheduler metrics."""
    try:
        from koordinator_tpu.metrics import Registry
        from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
        m = SchedulerMetrics(Registry())
        m.cost_drift_checks.labels(
            "drift" if drifted else "clean").inc()
    except Exception:
        pass  # the gate's verdict never depends on the metrics plane


# The planted defect for the self-test: upcast the packable columns to
# f32 inside snapshot/packing.py. No shape contract covers packing's
# internal dtype (packable columns are unpacked back to their declared
# dtypes), so koordshape and koordlint are blind to it BY DESIGN — only
# the byte contract (packing/* packed_bytes) moves, and it moves ~44%.
PACKING_MUTATION_ANCHOR = "return jnp.bfloat16"
PACKING_MUTATION_REPLACEMENT = "return jnp.float32"


def self_test_mutation() -> int:
    from tools import seedmut

    mutation = seedmut.Mutation(
        relpath=os.path.join("koordinator_tpu", "snapshot", "packing.py"),
        anchor=PACKING_MUTATION_ANCHOR,
        replacement=PACKING_MUTATION_REPLACEMENT,
        note="bf16->f32 upcast in the packable path",
    )
    py = sys.executable
    rc = seedmut.check_gate_catches(
        mutation, [py, os.path.join("tools", "costcheck.py"),
                   "--only", "packing/"],
        marker=MARKER, label="costcheck")
    if rc:
        return rc
    # complementarity: the same defect must be INVISIBLE to the static
    # tiers — koordlint reads source only, koordshape checks declared
    # shapes/dtypes at contract boundaries, and packing's upcast
    # changes neither
    rc = seedmut.check_gate_passes(
        mutation, [py, "-m", "tools.lint", "--root", "{tree}"],
        label="koordlint")
    if rc:
        return rc
    return seedmut.check_gate_passes(
        mutation, [py, os.path.join("tools", "shapecheck.py")],
        label="shapecheck")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--stamp", action="store_true",
                        help="rewrite the baseline from the live model")
    parser.add_argument("--only", default=None, metavar="PREFIX",
                        help="restrict to baseline labels with PREFIX "
                             "(e.g. packing/)")
    parser.add_argument("--self-test-mutation", action="store_true",
                        help="prove the gate catches a planted f32 "
                             "upcast the static tiers miss")
    args = parser.parse_args(argv)
    if args.self_test_mutation:
        return self_test_mutation()
    return run_gate(stamp=args.stamp, only=args.only)


if __name__ == "__main__":
    sys.exit(main())
