"""Chaos smoke: the fault-injection matrix for the resilience layer.

For EVERY fault class in koordinator_tpu.testing.faults.ALL_FAULTS this
stage asserts, on a small full-gate workload:

  1. DETECTED   — the guard word carries the expected defect bit, the
                  failure classifies to the expected FailureClass, or
                  the delta guard surfaces the typed reject reason;
  2. QUARANTINED — corrupted node rows end the cycle schedulable=False,
                  corrupted pod rows end unplaced and drain through the
                  error chain as infrastructure errors
                  (unschedulable=False);
  3. SERVICE UP — schedule() returns (degrading if it must) and the
                  NEXT clean cycle also completes;
  4. CONFORMANT — placements on clean rows are BIT-IDENTICAL to a
                  no-fault oracle run (for column faults the oracle is
                  the same batch with the corrupted rows masked
                  manually; for runtime faults it is the same clean
                  inputs at the ladder state the service ended in).

Runs on CPU in CI (tools/ci.sh); correctness-only, never wall-clock.
Usage: JAX_PLATFORMS=cpu python tools/chaos_smoke.py [fault ...]
       --overhead additionally measures guarded-vs-unguarded warm time.
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# a small virtual device fleet (CPU), set before the backend
# initializes: the device_lost cases must exercise the mesh-shrink
# rung (>= 2 survivors), not only the single-device abandon path.
# Programs still run on device 0 unless a rung shards them, so every
# other fault class is unaffected.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4").strip()

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.api.types import Node, NodeMetric, ObjectMeta, Pod
from koordinator_tpu.metrics import Registry
from koordinator_tpu.scheduler import guards
from koordinator_tpu.scheduler.errorhandler import FailureClass
from koordinator_tpu.scheduler.frameworkext import (
    DegradationLadder,
    LadderState,
    SchedulerService,
)
from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
from koordinator_tpu.snapshot import SnapshotBuilder
from koordinator_tpu.testing import faults
from koordinator_tpu.utils import synthetic

N_NODES, N_PODS = 64, 192
SEED = int(os.environ.get("CHAOS_SEED", "0"))


def make_inputs(seed):
    snap = synthetic.full_gate_cluster(N_NODES, seed=seed, num_quotas=8,
                                       num_gangs=8)
    pods = synthetic.full_gate_pods(N_PODS, N_NODES, seed=seed + 100,
                                    num_quotas=8, num_gangs=8)
    return snap, pods


def make_service(**kw):
    svc = SchedulerService(metrics=SchedulerMetrics(Registry()),
                           num_rounds=2, k_choices=4, **kw)
    svc._sleep = lambda _s: None  # chaos runs don't wait out real backoff
    return svc


def typed_pods_for(p):
    return [Pod(meta=ObjectMeta(name=f"pod-{i}", namespace="chaos"))
            for i in range(p)]


def infra_error_collector(svc):
    """Default error handler recording (row order lost, names kept)."""
    drained = {"infra": [], "unschedulable": []}

    def handler(pod_info, err):
        key = "unschedulable" if err.unschedulable else "infra"
        drained[key].append(pod_info.pod.meta.name)

    svc.error_dispatcher.set_default_handler(handler)
    return drained


def oracle_assignment(snap, pods, bad_nodes=None, bad_pods=None,
                      ladder_state=None):
    """The no-fault oracle: clean columns with the corrupted rows
    masked the way quarantine semantically masks them (node
    schedulable=False / pod valid=False), run at `ladder_state`."""
    import jax.numpy as jnp

    if bad_nodes is not None and len(bad_nodes):
        sched = np.asarray(snap.nodes.schedulable).copy()
        sched[np.asarray(bad_nodes)] = False
        snap = snap.replace(nodes=snap.nodes.replace(
            schedulable=jnp.asarray(sched)))
    if bad_pods is not None and len(bad_pods):
        valid = np.asarray(pods.valid).copy()
        valid[np.asarray(bad_pods)] = False
        pods = pods.replace(valid=jnp.asarray(valid))
    svc = make_service()
    if ladder_state is not None:
        svc.ladder.level = ladder_state.level
        svc.ladder.chunk_splits = ladder_state.chunk_splits
    svc.publish(snap)
    return np.asarray(svc.schedule(pods).assignment)


def check(cond, what):
    if not cond:
        raise AssertionError(what)


def run_snapshot_fault(kind):
    inj = faults.FaultInjector(SEED)
    snap, pods = make_inputs(3)
    bad_snap, rows = inj.corrupt_snapshot(snap, kind, n_rows=2)
    svc = make_service()
    svc.publish(bad_snap)
    res = svc.schedule(pods)
    word = svc.last_health_word
    # 1. detected
    check(word & faults.EXPECTED_BIT[kind],
          f"{kind}: expected bit not in word 0x{word:x}")
    # 2. quarantined: the committed snapshot pins the nodes out
    sched = np.asarray(svc.store.current().nodes.schedulable)
    check(not sched[rows].any(), f"{kind}: rows {rows} still schedulable")
    assign = np.asarray(res.assignment)
    check(not np.isin(assign, rows).any(),
          f"{kind}: a pod landed on a quarantined node")
    # 4. clean-row conformance, bit-identical
    oracle = oracle_assignment(snap, pods, bad_nodes=rows)
    check(np.array_equal(assign, oracle),
          f"{kind}: placements drifted from the masked-row oracle")
    # 3. service stays up on the next cycle
    svc.schedule(pods)
    return {"fault": kind, "quarantined_nodes": len(rows),
            "word": hex(word)}


def run_batch_fault(kind):
    inj = faults.FaultInjector(SEED)
    snap, pods = make_inputs(5)
    bad_pods_batch, rows = inj.corrupt_batch(pods, kind, n_rows=3)
    svc = make_service()
    drained = infra_error_collector(svc)
    svc.publish(snap)
    res = svc.schedule(bad_pods_batch,
                       typed_pods=typed_pods_for(N_PODS))
    word = svc.last_health_word
    check(word & faults.EXPECTED_BIT[kind],
          f"{kind}: expected bit not in word 0x{word:x}")
    assign = np.asarray(res.assignment)
    check((assign[rows] == -1).all(), f"{kind}: a corrupt row was placed")
    # quarantined rows drained as INFRASTRUCTURE errors, not no-fit
    names = {f"pod-{i}" for i in rows}
    check(names <= set(drained["infra"]),
          f"{kind}: quarantined rows missing from the infra drain "
          f"({sorted(names - set(drained['infra']))[:5]})")
    check(not (names & set(drained["unschedulable"])),
          f"{kind}: a quarantined row drained as unschedulable")
    oracle = oracle_assignment(snap, pods, bad_pods=rows)
    check(np.array_equal(assign, oracle),
          f"{kind}: placements drifted from the masked-row oracle")
    svc.schedule(pods)
    return {"fault": kind, "quarantined_pods": len(rows),
            "word": hex(word)}


def run_runtime_fault(kind):
    inj = faults.FaultInjector(SEED)
    snap, pods = make_inputs(7)
    svc = make_service()
    svc.publish(snap)
    expected = {
        "xla_oom": FailureClass.RESOURCE_EXHAUSTED,
        "xla_transient": FailureClass.XLA_INTERNAL,
        "device_lost": FailureClass.DEVICE_LOST,
        "watchdog_stall": FailureClass.WATCHDOG_STALL,
    }[kind]
    if kind == "xla_oom":
        svc.fault_injection = inj.oom_above(N_PODS // 2)
    elif kind == "xla_transient":
        svc.fault_injection = inj.xla_transient(fail_attempts={1, 2})
    elif kind == "device_lost":
        # one lost-device hiccup is absorbed by the transient retry at
        # the SAME rung; only an exhausted retry budget (RetryPolicy
        # max_attempts=3) moves the ladder — to the mesh-shrink rung
        # when >= 2 devices survive, to single-device otherwise
        svc.fault_injection = inj.device_lost(fail_attempts={1, 2, 3, 4})
    else:
        inj.stall_watchdog(svc)
    res = svc.schedule(pods)
    assign = np.asarray(res.assignment)
    # 1. detected: the typed class was counted
    counted = svc.metrics.failures_classified.labels(expected.value).get()
    check(counted >= 1, f"{kind}: class {expected.value} never counted")
    # 3. service completed THIS cycle and the next clean one
    svc.fault_injection = None
    svc.monitor.timeout = 30.0
    svc.schedule(pods)
    # 4. conformance at the ladder state the service ended the faulted
    # cycle in (chunked placements differ from one-shot BY DESIGN; the
    # oracle runs the same clean inputs at the same rung)
    oracle = oracle_assignment(snap, pods,
                               ladder_state=svc.last_ladder_state
                               if kind != "watchdog_stall" else None)
    check(np.array_equal(assign, oracle),
          f"{kind}: placements drifted from the same-rung oracle")
    if kind == "xla_oom":
        check(svc.ladder.level == DegradationLadder.L_CHUNKED,
              f"{kind}: expected the chunked rung, "
              f"got {svc.ladder.state().label()}")
    if kind == "device_lost":
        # with >= 2 survivors the mesh SHRINKS instead of being
        # abandoned (ISSUE 14); single-device only on a 1-device host
        expected_level = (DegradationLadder.L_MESH_SHRINK
                          if jax.device_count() >= 2
                          else DegradationLadder.L_SINGLE_DEVICE)
        check(svc.ladder.level == expected_level,
              f"{kind}: expected "
              f"{DegradationLadder.LEVELS[expected_level]}, "
              f"got {svc.ladder.state().label()}")
    if kind == "watchdog_stall":
        check(svc.monitor.timeouts >= 1, "stall never tripped the monitor")
        check(svc.ladder.level > 0, "stall did not degrade the next cycle")
    return {"fault": kind, "class": expected.value,
            "ladder": svc.ladder.state().label(),
            "transitions": svc.ladder.transitions}


def run_device_lost_mid_chunk(kind):
    """ISSUE 14 satellite: a device dies MID-chunked-batch (chunks 0-1
    already committed to the journal) and stays dead. The service must
    resume on the SHRUNK mesh from the last committed chunk — zero
    duplicated and zero lost placements, bit-identical to the no-fault
    chunked oracle — instead of restarting (or abandoning) the batch;
    probe-up then restores the full mesh."""
    import shutil
    import tempfile

    from koordinator_tpu.scheduler.journal import CommitJournal

    if jax.device_count() < 3:
        # needs >= 2 survivors after losing one device; the module
        # header forces 4 virtual devices, so this only trips when a
        # caller overrode XLA_FLAGS
        return {"fault": kind, "skipped": f"{jax.device_count()} devices"}
    inj = faults.FaultInjector(SEED)
    snap, pods = make_inputs(11)
    workdir = tempfile.mkdtemp(prefix="chaos_mid_chunk_")
    try:
        svc = make_service(
            journal=CommitJournal(os.path.join(workdir, "journal.bin")))
        svc.ladder.level = DegradationLadder.L_CHUNKED
        svc.ladder.chunk_splits = 2  # 4 journaled chunks
        svc.ladder.probe_after = 2
        # the device dies after 2 chunk programs and STAYS dead until
        # the mesh stops including it (faults.lost_device_until_shrunk)
        svc.fault_injection = inj.lost_device_until_shrunk(after_calls=2)
        survivors = jax.devices()[:-1]
        svc.device_health = lambda: survivors
        svc.publish(snap)
        res = svc.schedule(pods)
        # 1. detected + degraded to the NEW rung, not single_device
        check(svc.ladder.level == DegradationLadder.L_MESH_SHRINK,
              f"{kind}: expected mesh_shrink, "
              f"got {svc.ladder.state().label()}")
        check(svc.metrics.mesh_shrink_events.value() == 1,
              f"{kind}: mesh-shrink event not counted")
        check(svc.metrics.mesh_size.value() == len(survivors),
              f"{kind}: mesh-size gauge {svc.metrics.mesh_size.value()} "
              f"!= {len(survivors)} survivors")
        # 2. resumed, not restarted: the pre-crash chunks were REPLAYED
        # from the journal (asserted bit-identical inside it, never
        # re-appended) and every chunk appears exactly once
        check(svc.metrics.recovery_replayed.value() == 2,
              f"{kind}: expected 2 replayed chunks, got "
              f"{svc.metrics.recovery_replayed.value()}")
        records = svc.journal.records_for(1)
        check(sorted(records) == [0, 1, 2, 3],
              f"{kind}: journal chunk set {sorted(records)} is not "
              f"exactly one record per chunk")
        # 4. no duplicate, no lost placements: bit-identical to the
        # no-fault chunked oracle on the full mesh
        oracle = oracle_assignment(
            snap, pods, ladder_state=LadderState(
                DegradationLadder.L_CHUNKED, 2))
        check(np.array_equal(np.asarray(res.assignment), oracle),
              f"{kind}: resumed placements drifted from the chunked "
              f"no-fault oracle")
        # 3. service up, and probe-up restores the FULL mesh
        svc.fault_injection = None
        svc.device_health = None
        for _ in range(8):
            svc.schedule(pods)
            if svc.ladder.level < DegradationLadder.L_MESH_SHRINK:
                break
        check(svc.ladder.level < DegradationLadder.L_MESH_SHRINK,
              f"{kind}: probe-up never left mesh_shrink "
              f"({svc.ladder.transitions})")
        check(svc.metrics.mesh_size.value() == jax.device_count(),
              f"{kind}: full mesh not restored after probe-up")
        return {"fault": kind, "ladder": svc.ladder.state().label(),
                "replayed": 2, "survivors": len(survivors),
                "transitions": svc.ladder.transitions}
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def run_delta_fault(kind):
    from koordinator_tpu.snapshot.delta import DeltaRejectReason

    inj = faults.FaultInjector(SEED)
    b = SnapshotBuilder(max_nodes=8)
    for i in range(8):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={RK.CPU: 8_000.0,
                                     RK.MEMORY: 16_384.0}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=100.0,
                                     node_usage={RK.CPU: 500.0}))
    snap, _ = b.build(now=105.0)
    svc = make_service()
    svc.publish(snap)
    fresh = b.metric_delta(["n1"], now=106.0, pad_to=2)
    svc.ingest(fresh)
    before = np.asarray(svc.store.current().nodes.usage).copy()
    v_before = svc.store.version
    stale = inj.stale_delta(
        b.metric_delta(["n2"], now=107.0, pad_to=2),
        applied_version=svc.store.applied_delta_version)
    svc.ingest(stale)
    # 1. detected with the typed reason on the metric
    rejected = sum(
        svc.metrics.delta_rejected.labels(r.value).get()
        for r in DeltaRejectReason)
    check(rejected == 1, "stale delta not surfaced to metrics")
    # 2. quarantined == not applied: columns and version untouched
    check(svc.store.version == v_before, "stale delta bumped the version")
    check(np.array_equal(
        np.asarray(svc.store.current().nodes.usage), before),
        "stale delta scattered rows")
    # 3./4. the service still schedules, identically to the oracle
    pods = synthetic.full_gate_pods(32, 8, seed=9, num_quotas=2,
                                    num_gangs=2)
    snap_now = svc.store.current()  # BEFORE the commit mutates the store
    assign = np.asarray(svc.schedule(pods).assignment)
    o = make_service()
    o.publish(snap_now)
    check(np.array_equal(assign, np.asarray(o.schedule(pods).assignment)),
          "post-rejection placements drifted")
    return {"fault": kind, "rejections": int(rejected)}


def measure_overhead():
    """Warm guarded-vs-unguarded wall clock at the 20k x 2k full-gate
    CPU proxy, run the way the service (and the bench sweep) actually
    runs it: chunks of 2000 pods scheduled sequentially against the
    evolving snapshot. The acceptance bound is <= 2% added warm
    wall-clock; checked on the proxy host, not in CI wall-clock."""
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.plugins import loadaware

    n = int(os.environ.get("CHAOS_OVERHEAD_NODES", "2000"))
    p = int(os.environ.get("CHAOS_OVERHEAD_PODS", "20000"))
    chunk = int(os.environ.get("CHAOS_OVERHEAD_CHUNK", "2000"))
    snap0 = synthetic.full_gate_cluster(n, seed=1, num_quotas=32)
    pods = synthetic.full_gate_pods(p, n, seed=2, num_quotas=32)
    import jax

    pods = jax.device_put(pods)
    cfg = loadaware.LoadAwareConfig.make()
    kw = dict(num_rounds=2, k_choices=8, score_dims=(0, 1),
              tie_break=True, quota_depth=2, fit_dims=(0, 1, 2, 3),
              cascade=True)

    def sweep(fn, snap):
        counts = tuple(getattr(pods, f) for f in core.COUNT_FIELDS)
        assigns = []
        for start in range(0, p, chunk):
            batch = synthetic.slice_batch(pods, start, chunk)
            batch = batch.replace(**dict(zip(core.COUNT_FIELDS, counts)))
            out = fn(snap, batch, cfg, **kw)
            res = out[0] if isinstance(out, tuple) else out
            counts = core.charge_all_counts(counts, batch,
                                            res.assignment)
            snap = res.snapshot
            assigns.append(res.assignment)
        return np.asarray(jnp_concat(assigns))

    def jnp_concat(parts):
        import jax.numpy as jnp
        return jnp.concatenate(parts)

    def timed(fn):
        sweep(fn, jax.device_put(snap0))  # compile + warm
        t0 = time.perf_counter()
        sweep(fn, jax.device_put(snap0))
        return time.perf_counter() - t0

    base = timed(core.schedule_batch)
    guarded = timed(guards.guarded_schedule_batch)
    print(f"overhead ({p}x{n} full-gate, chunk {chunk}): "
          f"base={base:.3f}s guarded={guarded:.3f}s "
          f"({(guarded / base - 1) * 100:+.2f}%)", flush=True)


def main(argv):
    overhead = "--overhead" in argv
    selected = [a for a in argv if not a.startswith("-")]
    matrix = selected or list(faults.ALL_FAULTS)
    failures = []
    for fault in matrix:
        if fault == "device_lost_mid_chunk":
            runner = run_device_lost_mid_chunk
        elif fault in faults.SNAPSHOT_FAULTS:
            runner = run_snapshot_fault
        elif fault in faults.BATCH_FAULTS:
            runner = run_batch_fault
        elif fault in faults.RUNTIME_FAULTS:
            runner = run_runtime_fault
        elif fault in faults.DELTA_FAULTS:
            runner = run_delta_fault
        else:
            raise SystemExit(f"unknown fault class {fault!r} "
                             f"(known: {faults.ALL_FAULTS})")
        try:
            verdict = runner(fault)
            print(f"CHAOS OK   {fault}: {verdict}", flush=True)
        except AssertionError as exc:
            failures.append((fault, str(exc)))
            print(f"CHAOS FAIL {fault}: {exc}", flush=True)
    if overhead:
        measure_overhead()
    print(f"CHAOS SMOKE: {len(matrix) - len(failures)}/{len(matrix)} "
          f"fault classes green (seed {SEED})", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
