"""Sampled kernel-phase attribution of the flagship full-gate batch.

Captures a `jax.profiler.trace` of one warmed `core.schedule_batch`
dispatch on the full-gate workload, parses the trace-event stream the
profiler writes (Perfetto's `*.trace.json.gz`), and attributes device
time to the shared koordtrace phase table
(koordinator_tpu/obs/phases.py) — every kernel region is wrapped in a
`jax.named_scope` phase label (cascade stage 1, the stage-2 gate
families, top-k + ICI merge, the adaptive tail), so each XLA
instruction's `op_name` metadata carries the `koord/...` scope.

The join is two-step because backends differ in what the trace stream
preserves: TPU-style captures embed the scope path in the event args
(substring match suffices), but the CPU profiler emits only the bare
HLO instruction names (`add.635`, `fusion.19`) — so the tool also
compiles the SAME program, parses `op_name="...koord/..."` metadata
out of the HLO text, and joins trace events to phases through the
instruction-name map. Same program, same names, exact join.

This is the SAMPLED attribution; tools/profile_fullgate.py is the
SUBTRACTIVE one (gate-off deltas). Both emit koordtrace JSONL keyed by
the same phase names, so the two can be compared line-for-line.

Usage: JAX_PLATFORMS=cpu python tools/trace_fullgate.py [pods] [nodes]
  TRACE_FULLGATE_OUT=<path>  also write the per-phase koordtrace JSONL
  TRACE_FULLGATE_DIR=<dir>   keep the raw profiler capture (default: a
                             temp dir, deleted after parsing)

If a backend yields no attributable events the tool says so and exits
0 — an empty capture is a backend property, not a phase-table failure.
"""

import functools
import glob
import gzip
import json
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from koordinator_tpu.obs import hloattrib
from koordinator_tpu.obs.trace import jsonl_record
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.utils import synthetic

P = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000
N = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000

# attribution-coverage floor over the compiled program's instructions.
# Deliberately modest: the full parse counts EVERY instruction line —
# parameter/constant plumbing and XLA-introduced copies carry no
# op_name at all — and the measured flagship sits near 8% instructions
# / 26% output bytes. The floor exists to catch the scope labels
# silently vanishing (a named_scope refactor dropping the koord/
# prefix), not to demand XLA annotate its own plumbing.
MIN_INSTRUCTION_COVERAGE = 0.02


def build_step():
    step = jax.jit(functools.partial(
        core.schedule_batch, num_rounds=2, k_choices=8,
        score_dims=(0, 1), tie_break=True, quota_depth=2,
        fit_dims=(0, 1, 2, 3), cascade=True,
        enable_numa=True, enable_devices=True))
    snap = jax.device_put(synthetic.full_gate_cluster(
        N, seed=0, num_quotas=8, num_gangs=8))
    pods = jax.device_put(synthetic.full_gate_pods(
        P, N, seed=1, num_quotas=8, num_gangs=8))
    cfg = jax.device_put(LoadAwareConfig.make())
    return step, snap, pods, cfg


def instruction_phases(step, snap, pods, cfg):
    """{hlo instruction name: phase} plus attribution coverage, both
    from the SHARED parser (obs.hloattrib) — the named_scope labels end
    up as op_name path components, and the profiler's X events reuse
    the instruction names verbatim. Using hloattrib here means this
    sampled view and the static-cost view (obs.costmodel) join on
    literally the same regexes and the same innermost-scope rule."""
    txt = step.lower(snap, pods, cfg).compile().as_text()
    mapping = hloattrib.instruction_phases(txt)
    cov = hloattrib.coverage(hloattrib.attribute_bytes(txt))
    return mapping, cov


def capture(step, snap, pods, cfg, trace_dir):
    """One compiled dispatch under jax.profiler.trace (warmed first —
    the capture must hold the steady-state dispatch, not the
    compile)."""
    jax.block_until_ready(step(snap, pods, cfg).assignment)
    with jax.profiler.trace(trace_dir):
        out = step(snap, pods, cfg)
        jax.block_until_ready(out.assignment)
    return int((jax.numpy.asarray(out.assignment) >= 0).sum())


def load_trace_events(trace_dir):
    """All traceEvents from every Perfetto JSON the profiler wrote
    (plugins/profile/*/.../*.trace.json.gz)."""
    events = []
    pats = (os.path.join(trace_dir, "**", "*.trace.json.gz"),
            os.path.join(trace_dir, "**", "*.trace.json"))
    for pat in pats:
        for path in sorted(glob.glob(pat, recursive=True)):
            opener = gzip.open if path.endswith(".gz") else open
            try:
                with opener(path, "rt") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            events.extend(doc.get("traceEvents", []))
    return events


def phase_of(event, instr2phase):
    """Map one profiler X event to a koordtrace phase, or None — the
    shared two-step join (exact instruction-name first, scope-substring
    over name + string args second) lives in obs.hloattrib now."""
    name = str(event.get("name", ""))
    args = event.get("args")
    extra = ([str(v) for v in args.values()]
             if isinstance(args, dict) else [])
    return hloattrib.phase_of_event(name, extra, instr2phase)


def attribute(events, instr2phase):
    """({phase: (total_duration_s, event_count)}, device-time coverage)
    over complete ('X') events; container/metadata events carry no
    duration and are skipped. Coverage counts how many duration-
    carrying events (and how much of their device time) mapped to a
    phase — the unmapped remainder is reported, never dropped
    silently."""
    totals = {}
    mapped_ev = unmapped_ev = 0
    mapped_s = unmapped_s = 0.0
    for ev in events:
        if ev.get("ph") != "X":
            continue
        dur_s = float(ev.get("dur", 0)) / 1e6   # trace-event us
        phase = phase_of(ev, instr2phase)
        if phase is None:
            unmapped_ev += 1
            unmapped_s += dur_s
            continue
        mapped_ev += 1
        mapped_s += dur_s
        tot, cnt = totals.get(phase, (0.0, 0))
        totals[phase] = (tot + dur_s, cnt + 1)
    total_ev = mapped_ev + unmapped_ev
    total_s = mapped_s + unmapped_s
    cov = {
        "events_total": total_ev, "events_mapped": mapped_ev,
        "event_coverage": mapped_ev / total_ev if total_ev else 0.0,
        "device_time_total_s": total_s, "device_time_mapped_s": mapped_s,
        "device_time_coverage": mapped_s / total_s if total_s else 0.0,
    }
    return totals, cov


def main():
    keep_dir = (os.environ.get("TRACE_FULLGATE_DIR") or "").strip()
    trace_dir = keep_dir or tempfile.mkdtemp(prefix="trace_fullgate_")
    print(f"platform={jax.devices()[0].platform} P={P} N={N} "
          f"capture={trace_dir}", flush=True)
    try:
        step, snap, pods, cfg = build_step()
        instr2phase, static_cov = instruction_phases(step, snap, pods,
                                                     cfg)
        print(f"hlo_instructions_mapped={len(instr2phase)} "
              f"instruction_coverage="
              f"{static_cov['instruction_coverage']:.3f} "
              f"output_byte_coverage="
              f"{static_cov['output_byte_coverage']:.3f}", flush=True)
        if static_cov["instruction_coverage"] < MIN_INSTRUCTION_COVERAGE:
            print(f"trace_fullgate: ATTRIBUTION COVERAGE below floor "
                  f"({static_cov['instruction_coverage']:.3f} < "
                  f"{MIN_INSTRUCTION_COVERAGE}) — the koord/ scope "
                  f"labels are not reaching op_name metadata",
                  flush=True)
            return 1
        placed = capture(step, snap, pods, cfg, trace_dir)
        events = load_trace_events(trace_dir)
        totals, ev_cov = attribute(events, instr2phase)
        print(f"placed={placed} profiler_events={len(events)} "
              f"attributed_phases={len(totals)} "
              f"events_mapped={ev_cov['events_mapped']}"
              f"/{ev_cov['events_total']} "
              f"device_time_mapped="
              f"{ev_cov['device_time_mapped_s'] * 1e3:.3f}ms"
              f"/{ev_cov['device_time_total_s'] * 1e3:.3f}ms",
              flush=True)
        if not totals:
            print("trace_fullgate: no phase-attributed events in this "
                  "backend's capture (empty capture is a backend "
                  "property, not a phase-table failure)", flush=True)
            return 0
        width = max(len(p) for p in totals)
        lines = []
        for phase, (dur_s, cnt) in sorted(totals.items(),
                                          key=lambda kv: -kv[1][0]):
            print(f"{phase:{width}s} total={dur_s * 1e3:9.3f}ms "
                  f"events={cnt}", flush=True)
            lines.append(jsonl_record(
                phase, dur_s,
                attrs={"source": "trace_fullgate", "events": cnt,
                       "pods": P, "nodes": N}))
        out = (os.environ.get("TRACE_FULLGATE_OUT") or "").strip()
        if out:
            with open(out, "w") as f:
                f.write("\n".join(lines) + "\n")
            print(f"koordtrace JSONL -> {out}", flush=True)
        return 0
    finally:
        if not keep_dir:
            shutil.rmtree(trace_dir, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
