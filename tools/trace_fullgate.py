"""Sampled kernel-phase attribution of the flagship full-gate batch.

Captures a `jax.profiler.trace` of one warmed `core.schedule_batch`
dispatch on the full-gate workload, parses the trace-event stream the
profiler writes (Perfetto's `*.trace.json.gz`), and attributes device
time to the shared koordtrace phase table
(koordinator_tpu/obs/phases.py) — every kernel region is wrapped in a
`jax.named_scope` phase label (cascade stage 1, the stage-2 gate
families, top-k + ICI merge, the adaptive tail), so each XLA
instruction's `op_name` metadata carries the `koord/...` scope.

The join is two-step because backends differ in what the trace stream
preserves: TPU-style captures embed the scope path in the event args
(substring match suffices), but the CPU profiler emits only the bare
HLO instruction names (`add.635`, `fusion.19`) — so the tool also
compiles the SAME program, parses `op_name="...koord/..."` metadata
out of the HLO text, and joins trace events to phases through the
instruction-name map. Same program, same names, exact join.

This is the SAMPLED attribution; tools/profile_fullgate.py is the
SUBTRACTIVE one (gate-off deltas). Both emit koordtrace JSONL keyed by
the same phase names, so the two can be compared line-for-line.

Usage: JAX_PLATFORMS=cpu python tools/trace_fullgate.py [pods] [nodes]
  TRACE_FULLGATE_OUT=<path>  also write the per-phase koordtrace JSONL
  TRACE_FULLGATE_DIR=<dir>   keep the raw profiler capture (default: a
                             temp dir, deleted after parsing)

If a backend yields no attributable events the tool says so and exits
0 — an empty capture is a backend property, not a phase-table failure.
"""

import functools
import glob
import gzip
import json
import os
import re
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from koordinator_tpu.obs import phases as obs_phases
from koordinator_tpu.obs.trace import jsonl_record
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.utils import synthetic

P = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000
N = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000

_OP_NAME = re.compile(r'%?([\w.-]+) = [^\n]*op_name="([^"]*)"')
_PHASE_IN_OP = re.compile(r"(koord/\w+)")


def build_step():
    step = jax.jit(functools.partial(
        core.schedule_batch, num_rounds=2, k_choices=8,
        score_dims=(0, 1), tie_break=True, quota_depth=2,
        fit_dims=(0, 1, 2, 3), cascade=True,
        enable_numa=True, enable_devices=True))
    snap = jax.device_put(synthetic.full_gate_cluster(
        N, seed=0, num_quotas=8, num_gangs=8))
    pods = jax.device_put(synthetic.full_gate_pods(
        P, N, seed=1, num_quotas=8, num_gangs=8))
    cfg = jax.device_put(LoadAwareConfig.make())
    return step, snap, pods, cfg


def instruction_phases(step, snap, pods, cfg):
    """{hlo instruction name: phase} parsed out of the compiled
    program's `op_name` metadata — the named_scope labels end up as
    path components there, and the profiler's X events reuse the
    instruction names verbatim."""
    txt = step.lower(snap, pods, cfg).compile().as_text()
    mapping = {}
    for instr, op_name in _OP_NAME.findall(txt):
        m = _PHASE_IN_OP.search(op_name)
        if m and m.group(1) in obs_phases.KERNEL_PHASES:
            mapping[instr] = m.group(1)
    return mapping


def capture(step, snap, pods, cfg, trace_dir):
    """One compiled dispatch under jax.profiler.trace (warmed first —
    the capture must hold the steady-state dispatch, not the
    compile)."""
    jax.block_until_ready(step(snap, pods, cfg).assignment)
    with jax.profiler.trace(trace_dir):
        out = step(snap, pods, cfg)
        jax.block_until_ready(out.assignment)
    return int((jax.numpy.asarray(out.assignment) >= 0).sum())


def load_trace_events(trace_dir):
    """All traceEvents from every Perfetto JSON the profiler wrote
    (plugins/profile/*/.../*.trace.json.gz)."""
    events = []
    pats = (os.path.join(trace_dir, "**", "*.trace.json.gz"),
            os.path.join(trace_dir, "**", "*.trace.json"))
    for pat in pats:
        for path in sorted(glob.glob(pat, recursive=True)):
            opener = gzip.open if path.endswith(".gz") else open
            try:
                with opener(path, "rt") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            events.extend(doc.get("traceEvents", []))
    return events


def phase_of(event, instr2phase):
    """Map one profiler X event to a koordtrace phase, or None. Exact
    instruction-name join first (the CPU stream carries nothing else);
    scope-substring match over name + string args second (TPU-style
    captures embed the full path) — innermost (longest) phase wins
    when scopes nest."""
    name = str(event.get("name", ""))
    hit = instr2phase.get(name)
    if hit is not None:
        return hit
    hay = [name]
    args = event.get("args")
    if isinstance(args, dict):
        hay.extend(str(v) for v in args.values())
    best = None
    for phase in obs_phases.KERNEL_PHASES:
        if any(phase in h for h in hay):
            if best is None or len(phase) > len(best):
                best = phase
    return best


def attribute(events, instr2phase):
    """{phase: (total_duration_s, event_count)} over complete ('X')
    events; container/metadata events carry no duration and are
    skipped."""
    totals = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        phase = phase_of(ev, instr2phase)
        if phase is None:
            continue
        dur_s = float(ev.get("dur", 0)) / 1e6   # trace-event us
        tot, cnt = totals.get(phase, (0.0, 0))
        totals[phase] = (tot + dur_s, cnt + 1)
    return totals


def main():
    keep_dir = (os.environ.get("TRACE_FULLGATE_DIR") or "").strip()
    trace_dir = keep_dir or tempfile.mkdtemp(prefix="trace_fullgate_")
    print(f"platform={jax.devices()[0].platform} P={P} N={N} "
          f"capture={trace_dir}", flush=True)
    try:
        step, snap, pods, cfg = build_step()
        instr2phase = instruction_phases(step, snap, pods, cfg)
        print(f"hlo_instructions_mapped={len(instr2phase)}", flush=True)
        placed = capture(step, snap, pods, cfg, trace_dir)
        events = load_trace_events(trace_dir)
        totals = attribute(events, instr2phase)
        print(f"placed={placed} profiler_events={len(events)} "
              f"attributed_phases={len(totals)}", flush=True)
        if not totals:
            print("trace_fullgate: no phase-attributed events in this "
                  "backend's capture (empty capture is a backend "
                  "property, not a phase-table failure)", flush=True)
            return 0
        width = max(len(p) for p in totals)
        lines = []
        for phase, (dur_s, cnt) in sorted(totals.items(),
                                          key=lambda kv: -kv[1][0]):
            print(f"{phase:{width}s} total={dur_s * 1e3:9.3f}ms "
                  f"events={cnt}", flush=True)
            lines.append(jsonl_record(
                phase, dur_s,
                attrs={"source": "trace_fullgate", "events": cnt,
                       "pods": P, "nodes": N}))
        out = (os.environ.get("TRACE_FULLGATE_OUT") or "").strip()
        if out:
            with open(out, "w") as f:
                f.write("\n".join(lines) + "\n")
            print(f"koordtrace JSONL -> {out}", flush=True)
        return 0
    finally:
        if not keep_dir:
            shutil.rmtree(trace_dir, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
