"""CI/deploy cache warmer: enumerate the configured working set and
AOT-compile every program into a compile-cache dir, so the first REAL
scheduling cycle of the next process over that dir (same host — see
tests/conftest.py on artifact portability) traces but never compiles.

The enumeration is the koordshape-registry walk in
koordinator_tpu/compilecache/precompile.py: the flagship cycle per
cascade form, every shrunk-mesh rung (devices, devices-1, ..., 1)
padded exactly as the service's mesh-shrink failover pads it, and the
canonical donated tail-compaction form.

Usage:
  python tools/precompile.py --cache-dir /path/to/cache \\
      [--devices N] [--size P=256 --size N=128 ...] [--guards] \\
      [--no-tail] [--cascade on|off|both] [--json]

Exit code 0 on success; the report (per-program hit/warm/miss lines +
totals) goes to stdout. `bench.py BENCH_PRECOMPILE=1` wraps the same
warm() for the bench's own working set.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def parse_sizes(pairs):
    sizes = {}
    for pair in pairs or ():
        key, _, val = pair.partition("=")
        if not val or not val.lstrip("-").isdigit():
            raise SystemExit(f"--size wants KEY=INT, got {pair!r}")
        sizes[key] = int(val)
    return sizes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cache-dir", required=True,
                    help="compile-cache dir to warm (created if absent; "
                         "SAME-HOST use only)")
    ap.add_argument("--devices", type=int, default=None,
                    help="top of the shrunk-mesh ladder "
                         "(default: all visible devices)")
    ap.add_argument("--size", action="append", metavar="KEY=INT",
                    help="working-set dim override (P, N, I, Z, G, ...); "
                         "repeatable")
    ap.add_argument("--guards", action="store_true",
                    help="warm the guarded fusion instead of the bare "
                         "kernel")
    ap.add_argument("--no-tail", action="store_true",
                    help="skip the canonical tail-compaction form")
    ap.add_argument("--cascade", choices=("on", "off", "both"),
                    default="both", help="cascade forms to warm")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON line")
    args = ap.parse_args(argv)

    from koordinator_tpu.compilecache import precompile
    from koordinator_tpu.compilecache.cache import CompileCache

    cascade_forms = {"on": (True,), "off": (False,),
                     "both": (False, True)}[args.cascade]
    ws = precompile.WorkSet(
        sizes=parse_sizes(args.size),
        devices=(args.devices if args.devices is not None
                 else len(jax.devices())),
        cascade_forms=cascade_forms,
        tail=None if args.no_tail else dict(precompile.DEFAULT_TAIL),
        guards=args.guards)
    cache = CompileCache(args.cache_dir)
    report = precompile.warm(
        cache, ws,
        log_fn=None if args.json else lambda s: print(s, flush=True))
    report["cache_dir"] = args.cache_dir
    report["fingerprint"] = cache.fingerprint[:16]
    if args.json:
        print(json.dumps(report))
    else:
        print(f"precompile: {report['programs']} program(s) "
              f"({report['hit']} hit / {report['warm']} warm / "
              f"{report['miss']} miss) in {report['seconds']}s "
              f"-> {args.cache_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
