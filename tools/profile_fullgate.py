"""Bisect the full-gate per-chunk cost on live hardware.

Runs the SWEEP only (no tail) at a reduced pod count so each compile is
cheap, toggling one gate family off at a time; the delta against the
all-on baseline localizes where the 100k x 10k full-gate time goes.
Usage: JAX_PLATFORMS=axon python tools/profile_fullgate.py [pods] [nodes]

Besides the human table, the bisection emits its per-gate deltas as
koordtrace JSONL (obs.trace.jsonl_record) keyed by the SHARED phase
table (koordinator_tpu/obs/phases.py) — the same names
tools/trace_fullgate.py attributes from the XLA profiler stream and the
`scheduler_cycle_phase_seconds{phase=...}` series carries, so the
subtractive and the sampled attributions land in one namespace and can
be compared line-for-line. PROFILE_TRACE_OUT=<path> writes the records
there; unset, they print after the table.
"""

import functools
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

# honor JAX_PLATFORMS explicitly: the CI hosts' site config pins the
# axon tunnel platform and silently overrides the env var (the
# tests/conftest.py lesson) — a "cpu" run would otherwise hang on a
# wedged tunnel at first device touch
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from koordinator_tpu.obs import phases as obs_phases
from koordinator_tpu.obs.trace import jsonl_record
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.utils import synthetic

P = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
N = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
CHUNK = 2_000

# which shared phase each subtractive gate-off row attributes to; gate
# families without a kernel phase of their own (the topo score terms,
# taints) charge the whole-batch phase with the family in the attrs
GATE_PHASES = {
    "numa": obs_phases.PHASE_STAGE2_NUMA,
    "devices": obs_phases.PHASE_STAGE2_DEVICESHARE,
}


def time_sweep(tag, pods, step_kw, slim=False, pack=False):
    cfg = LoadAwareConfig.make()
    if pack:
        # mirror the bench full-gate configuration: all three nested
        # prefixes + domain classes
        pods, prefixes, _ = synthetic.pack_gate_prefixes(pods, CHUNK)
        step_kw = dict(step_kw, topo_prefix=prefixes["topo"],
                       dom_classes=synthetic.dom_classes(pods))
        if step_kw.get("enable_numa", True):
            step_kw["numa_prefix"] = prefixes["numa"]
        if step_kw.get("enable_devices", True):
            step_kw["gpu_prefix"] = prefixes["gpu"]
    stacked = synthetic.stack_pod_chunks(pods, CHUNK)
    snap = jax.device_put(synthetic.full_gate_cluster(N, num_quotas=32,
                                                      seed=0))
    stacked = jax.device_put(stacked)
    pods_d = jax.device_put(pods)
    counts = jax.device_put(tuple(getattr(pods, f)
                                  for f in core.COUNT_FIELDS))
    step = functools.partial(core.schedule_batch, num_rounds=2,
                             k_choices=8, score_dims=(0, 1),
                             approx_topk=True, tie_break=True,
                             quota_depth=2, fit_dims=(0, 1, 2, 3),
                             **step_kw)

    def charge(counts, batch, assignment):
        # mirror bench.py: the full-gate bench pays charge_all_counts
        # regardless of which gate families are compiled in, so gate-off
        # rows must keep paying it too or the bisection mislocalizes
        if slim:
            return counts
        return core.charge_all_counts(counts, batch, assignment)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def sweep(snap, counts, stacked, pods_d, cfg):
        def body(carry, cols):
            snap, counts = carry
            batch = pods_d.replace(**cols).replace(
                **dict(zip(core.COUNT_FIELDS, counts)))
            res = step(snap, batch, cfg)
            counts = charge(counts, batch, res.assignment)
            return (res.snapshot, counts), res.assignment
        (snap, counts), assign = jax.lax.scan(body, (snap, counts),
                                              stacked)
        return snap, counts, assign.reshape(-1)

    jax.block_until_ready((stacked, pods_d, cfg, snap, counts))
    t0 = time.perf_counter()
    out = sweep(snap, counts, stacked, pods_d, cfg)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0

    runs = []
    placed = -1
    for rep in range(3):
        snap = jax.device_put(synthetic.full_gate_cluster(
            N, num_quotas=32, seed=7 + rep))
        counts = jax.device_put(tuple(getattr(pods, f)
                                      for f in core.COUNT_FIELDS))
        jax.block_until_ready((snap, counts))
        t0 = time.perf_counter()
        out = sweep(snap, counts, stacked, pods_d, cfg)
        jax.block_until_ready(out)
        runs.append(time.perf_counter() - t0)
        placed = int((out[2] >= 0).sum())
    run_s = min(runs)
    per_chunk = run_s / (P / CHUNK)
    print(f"{tag:28s} min={run_s:7.3f}s per_chunk={per_chunk * 1e3:8.1f}ms"
          f" all={['%.3f' % r for r in runs]}"
          f" placed={placed} compile={compile_s:6.1f}s", flush=True)
    return run_s


def emit_gate_trace(baseline_s, gate_rows):
    """Render the subtractive attribution as koordtrace JSONL: one
    record per gate family, `duration_s` = the delta the family costs
    over the all-on packed baseline (clamped at zero — timing noise on
    a cheap gate must not emit a negative span). Synthetic spans anchor
    at t=0 (obs.trace.jsonl_record), so any JSONL consumer — including
    obs.export's chrome conversion — renders them side by side."""
    lines = [jsonl_record(
        obs_phases.PHASE_SCHEDULE_BATCH, baseline_s,
        attrs={"source": "profile_fullgate", "row": "ALL-ON packed",
               "pods": P, "nodes": N, "chunk": CHUNK})]
    for gate, off_s in gate_rows:
        phase = GATE_PHASES.get(gate, obs_phases.PHASE_SCHEDULE_BATCH)
        lines.append(jsonl_record(
            phase, max(baseline_s - off_s, 0.0),
            attrs={"source": "profile_fullgate", "gate": gate,
                   "baseline_s": round(baseline_s, 4),
                   "gate_off_s": round(off_s, 4)}))
    out = (os.environ.get("PROFILE_TRACE_OUT") or "").strip()
    if out:
        with open(out, "w") as f:
            f.write("\n".join(lines) + "\n")
        print(f"koordtrace JSONL -> {out}", flush=True)
    else:
        for line in lines:
            print(line, flush=True)


def main():
    print(f"platform={jax.devices()[0].platform} P={P} N={N} chunk={CHUNK}",
          flush=True)
    pods = synthetic.full_gate_pods(P, N, seed=1, num_quotas=32)
    full_kw = dict(enable_numa=True, enable_devices=True)
    time_sweep("ALL-ON unpacked (ref)", pods, full_kw)
    baseline_s = time_sweep("ALL-ON packed", pods, full_kw, pack=True)
    gate_rows = [
        ("numa", time_sweep("packed, numa off", pods,
                            dict(enable_numa=False, enable_devices=True),
                            pack=True)),
        ("devices", time_sweep("packed, devices off", pods,
                               dict(enable_numa=True,
                                    enable_devices=False), pack=True)),
        ("spread", time_sweep("packed, spread off",
                              pods.replace(has_spread=False), full_kw,
                              pack=True)),
        ("anti", time_sweep("packed, anti off",
                            pods.replace(has_anti=False), full_kw,
                            pack=True)),
        ("aff", time_sweep("packed, aff off",
                           pods.replace(has_aff=False), full_kw,
                           pack=True)),
        ("taints", time_sweep("packed, taints off",
                              pods.replace(has_taints=False), full_kw,
                              pack=True)),
        ("topo_all", time_sweep("packed, topo all off", pods.replace(
            has_spread=False, has_anti=False, has_aff=False), full_kw,
            pack=True)),
    ]
    slim_pods = synthetic.synthetic_pods(P, seed=1, num_quotas=32)
    time_sweep("slim workload (ref)", slim_pods, dict(enable_numa=False),
               slim=True)
    emit_gate_trace(baseline_s, gate_rows)


if __name__ == "__main__":
    main()
