"""Bisect the full-gate per-chunk cost on live hardware.

Runs the SWEEP only (no tail) at a reduced pod count so each compile is
cheap, toggling one gate family off at a time; the delta against the
all-on baseline localizes where the 100k x 10k full-gate time goes.
Usage: JAX_PLATFORMS=axon python tools/profile_fullgate.py [pods] [nodes]
"""

import functools
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

# honor JAX_PLATFORMS explicitly: the CI hosts' site config pins the
# axon tunnel platform and silently overrides the env var (the
# tests/conftest.py lesson) — a "cpu" run would otherwise hang on a
# wedged tunnel at first device touch
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.utils import synthetic

P = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
N = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
CHUNK = 2_000


def time_sweep(tag, pods, step_kw, slim=False, pack=False):
    cfg = LoadAwareConfig.make()
    if pack:
        # mirror the bench full-gate configuration: all three nested
        # prefixes + domain classes
        pods, prefixes, _ = synthetic.pack_gate_prefixes(pods, CHUNK)
        step_kw = dict(step_kw, topo_prefix=prefixes["topo"],
                       dom_classes=synthetic.dom_classes(pods))
        if step_kw.get("enable_numa", True):
            step_kw["numa_prefix"] = prefixes["numa"]
        if step_kw.get("enable_devices", True):
            step_kw["gpu_prefix"] = prefixes["gpu"]
    stacked = synthetic.stack_pod_chunks(pods, CHUNK)
    snap = jax.device_put(synthetic.full_gate_cluster(N, num_quotas=32,
                                                      seed=0))
    stacked = jax.device_put(stacked)
    pods_d = jax.device_put(pods)
    counts = jax.device_put(tuple(getattr(pods, f)
                                  for f in core.COUNT_FIELDS))
    step = functools.partial(core.schedule_batch, num_rounds=2,
                             k_choices=8, score_dims=(0, 1),
                             approx_topk=True, tie_break=True,
                             quota_depth=2, fit_dims=(0, 1, 2, 3),
                             **step_kw)

    def charge(counts, batch, assignment):
        # mirror bench.py: the full-gate bench pays charge_all_counts
        # regardless of which gate families are compiled in, so gate-off
        # rows must keep paying it too or the bisection mislocalizes
        if slim:
            return counts
        return core.charge_all_counts(counts, batch, assignment)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def sweep(snap, counts, stacked, pods_d, cfg):
        def body(carry, cols):
            snap, counts = carry
            batch = pods_d.replace(**cols).replace(
                **dict(zip(core.COUNT_FIELDS, counts)))
            res = step(snap, batch, cfg)
            counts = charge(counts, batch, res.assignment)
            return (res.snapshot, counts), res.assignment
        (snap, counts), assign = jax.lax.scan(body, (snap, counts),
                                              stacked)
        return snap, counts, assign.reshape(-1)

    jax.block_until_ready((stacked, pods_d, cfg, snap, counts))
    t0 = time.perf_counter()
    out = sweep(snap, counts, stacked, pods_d, cfg)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0

    runs = []
    placed = -1
    for rep in range(3):
        snap = jax.device_put(synthetic.full_gate_cluster(
            N, num_quotas=32, seed=7 + rep))
        counts = jax.device_put(tuple(getattr(pods, f)
                                      for f in core.COUNT_FIELDS))
        jax.block_until_ready((snap, counts))
        t0 = time.perf_counter()
        out = sweep(snap, counts, stacked, pods_d, cfg)
        jax.block_until_ready(out)
        runs.append(time.perf_counter() - t0)
        placed = int((out[2] >= 0).sum())
    run_s = min(runs)
    per_chunk = run_s / (P / CHUNK)
    print(f"{tag:28s} min={run_s:7.3f}s per_chunk={per_chunk * 1e3:8.1f}ms"
          f" all={['%.3f' % r for r in runs]}"
          f" placed={placed} compile={compile_s:6.1f}s", flush=True)
    return run_s


def main():
    print(f"platform={jax.devices()[0].platform} P={P} N={N} chunk={CHUNK}",
          flush=True)
    pods = synthetic.full_gate_pods(P, N, seed=1, num_quotas=32)
    full_kw = dict(enable_numa=True, enable_devices=True)
    time_sweep("ALL-ON unpacked (ref)", pods, full_kw)
    time_sweep("ALL-ON packed", pods, full_kw, pack=True)
    time_sweep("packed, numa off", pods, dict(enable_numa=False,
                                              enable_devices=True),
               pack=True)
    time_sweep("packed, devices off", pods, dict(enable_numa=True,
                                                 enable_devices=False),
               pack=True)
    time_sweep("packed, spread off", pods.replace(has_spread=False),
               full_kw, pack=True)
    time_sweep("packed, anti off", pods.replace(has_anti=False),
               full_kw, pack=True)
    time_sweep("packed, aff off", pods.replace(has_aff=False),
               full_kw, pack=True)
    time_sweep("packed, taints off", pods.replace(has_taints=False),
               full_kw, pack=True)
    time_sweep("packed, topo all off", pods.replace(
        has_spread=False, has_anti=False, has_aff=False), full_kw,
        pack=True)
    slim_pods = synthetic.synthetic_pods(P, seed=1, num_quotas=32)
    time_sweep("slim workload (ref)", slim_pods, dict(enable_numa=False),
               slim=True)


if __name__ == "__main__":
    main()
