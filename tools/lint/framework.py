"""Core model for koordlint: findings, the parsed-module Project, the
analyzer plugin registry, and the baseline-suppression file.

Everything here is stdlib-only by design: the linter must run (and fail
CI) on hosts where jax is broken or absent, and must never pay a device
runtime import to analyze source text.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# directories never scanned: fixture trees hold INTENTIONAL violations,
# and environment/cache dirs hold third-party code the gate must not
# judge (site-packages ships plenty of orphan *_pb2.py)
DEFAULT_EXCLUDES = (
    ".git",
    "__pycache__",
    os.path.join("tests", "fixtures"),
    ".venv",
    "venv",
    ".tox",
    ".eggs",
    "node_modules",
    "site-packages",
    "__pypackages__",
    ".mypy_cache",
    ".pytest_cache",
)


@dataclass(frozen=True)
class Finding:
    """One lint violation.

    `key` is the analyzer-chosen stable identity (symbol names, lock
    pairs, metric names — never raw line numbers), so baseline entries
    survive unrelated edits to the file.
    """

    analyzer: str
    code: str
    path: str          # relative to the project root, "/" separators
    line: int
    message: str
    key: str = ""

    @property
    def fingerprint(self) -> str:
        key = self.key or f"L{self.line}"
        return f"{self.analyzer}:{self.code}:{self.path}:{key}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} " \
               f"[{self.analyzer}] {self.message}"


@dataclass
class Module:
    """A parsed python source file."""

    path: str        # absolute
    relpath: str     # root-relative, "/" separators
    source: str
    tree: ast.Module

    @property
    def dotted(self) -> str:
        """Dotted module name relative to the project root
        (koordinator_tpu/snapshot/store.py -> koordinator_tpu.snapshot.store)."""
        rel = self.relpath[:-3] if self.relpath.endswith(".py") else self.relpath
        parts = rel.split("/")
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)


class Project:
    """The cross-file analysis unit: every parsable .py under `root`
    (minus excludes), indexed by relpath and dotted name, plus the
    non-python files analyzers care about (*.proto)."""

    def __init__(self, root: str,
                 excludes: Sequence[str] = DEFAULT_EXCLUDES):
        self.root = os.path.abspath(root)
        self.modules: List[Module] = []
        self.by_relpath: Dict[str, Module] = {}
        self.by_dotted: Dict[str, Module] = {}
        self.proto_files: List[str] = []   # root-relative
        self.parse_errors: List[Finding] = []
        self._load(excludes)

    def _load(self, excludes: Sequence[str]) -> None:
        norm_excludes = tuple(e.replace("/", os.sep) for e in excludes)
        for dirpath, dirnames, filenames in os.walk(self.root):
            rel_dir = os.path.relpath(dirpath, self.root)
            rel_dir = "" if rel_dir == "." else rel_dir
            dirnames[:] = sorted(
                d for d in dirnames
                if not _excluded(os.path.join(rel_dir, d), norm_excludes))
            for fn in sorted(filenames):
                rel = os.path.join(rel_dir, fn) if rel_dir else fn
                if _excluded(rel, norm_excludes):
                    continue
                if fn.endswith(".proto"):
                    self.proto_files.append(rel.replace(os.sep, "/"))
                    continue
                if not fn.endswith(".py"):
                    continue
                abspath = os.path.join(dirpath, fn)
                relpath = rel.replace(os.sep, "/")
                try:
                    with open(abspath, encoding="utf-8") as f:
                        source = f.read()
                    tree = ast.parse(source, filename=abspath)
                except (SyntaxError, UnicodeDecodeError) as exc:
                    self.parse_errors.append(Finding(
                        analyzer="framework", code="KL000", path=relpath,
                        line=getattr(exc, "lineno", 0) or 0,
                        message=f"unparsable source: {exc}",
                        key="parse-error"))
                    continue
                mod = Module(abspath, relpath, source, tree)
                self.modules.append(mod)
                self.by_relpath[relpath] = mod
                self.by_dotted[mod.dotted] = mod

    def read_text(self, relpath: str) -> str:
        with open(os.path.join(self.root, relpath.replace("/", os.sep)),
                  encoding="utf-8") as f:
            return f.read()

    def read_bytes(self, relpath: str) -> bytes:
        with open(os.path.join(self.root, relpath.replace("/", os.sep)),
                  "rb") as f:
            return f.read()


def _excluded(rel: str, norm_excludes: Sequence[str]) -> bool:
    rel = rel.lstrip(os.sep)
    for e in norm_excludes:
        if rel == e or rel.startswith(e + os.sep) \
                or os.path.basename(rel) == e:
            return True
    return False


def _tree_signature(root: str,
                    norm_excludes: Sequence[str]) -> Tuple[tuple, ...]:
    """(relpath, mtime_ns, size) for every analyzable file under root —
    a stat-only walk, no reads, no parses."""
    sig: List[tuple] = []
    for dirpath, dirnames, filenames in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root)
        rel_dir = "" if rel_dir == "." else rel_dir
        dirnames[:] = sorted(
            d for d in dirnames
            if not _excluded(os.path.join(rel_dir, d), norm_excludes))
        for fn in sorted(filenames):
            if not fn.endswith((".py", ".proto")):
                continue
            rel = os.path.join(rel_dir, fn) if rel_dir else fn
            if _excluded(rel, norm_excludes):
                continue
            try:
                st = os.stat(os.path.join(dirpath, fn))
            except OSError:
                sig.append((rel, -1, -1))
                continue
            sig.append((rel, st.st_mtime_ns, st.st_size))
    return tuple(sig)


# (abs root, excludes) -> (tree signature, parsed Project). One entry
# per root a process analyzes; a Project is a few MB of ASTs, so this
# is bounded by the handful of roots tests exercise.
_PROJECT_CACHE: Dict[Tuple[str, Tuple[str, ...]],
                     Tuple[Tuple[tuple, ...], "Project"]] = {}


def cached_project(root: str,
                   excludes: Sequence[str] = DEFAULT_EXCLUDES
                   ) -> "Project":
    """A Project for `root`, reusing this process's parsed tree when no
    analyzable file was added, removed, resized, or touched since the
    last call (per-file mtime_ns + size). Editing a file between runs —
    as the fingerprint-drift tests do — always yields a fresh parse;
    repeat runs over an unchanged tree skip the os.walk + ast.parse
    cost entirely."""
    key = (os.path.abspath(root), tuple(excludes))
    norm_excludes = tuple(e.replace("/", os.sep) for e in excludes)
    sig = _tree_signature(key[0], norm_excludes)
    hit = _PROJECT_CACHE.get(key)
    if hit is not None and hit[0] == sig:
        return hit[1]
    project = Project(root, excludes)
    _PROJECT_CACHE[key] = (sig, project)
    return project


class Analyzer:
    """Base class for lint passes. Subclasses set `name`/`description`
    and implement `run(project)` yielding Findings; `register` adds them
    to the plugin registry the runner iterates."""

    name: str = ""
    description: str = ""

    def run(self, project: Project) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Analyzer] = {}


def register(cls: type) -> type:
    """Class decorator: instantiate and add to the analyzer registry."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"{cls.__name__} has no analyzer name")
    if inst.name in _REGISTRY:
        raise ValueError(f"duplicate analyzer {inst.name!r}")
    _REGISTRY[inst.name] = inst
    return cls


def all_analyzers() -> Dict[str, Analyzer]:
    # import for the registration side effect, late to avoid cycles
    import tools.lint.analyzers  # noqa: F401
    return dict(_REGISTRY)


@dataclass
class Baseline:
    """The suppression file: a sorted list of finding fingerprints. An
    empty baseline means the tree is lint-clean; entries are only meant
    to freeze pre-existing debt, never to excuse new findings."""

    path: str
    fingerprints: Tuple[str, ...] = ()

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, dict) or "suppressions" not in data:
            raise ValueError(f"{path}: expected {{'suppressions': [...]}}")
        return cls(path=path, fingerprints=tuple(data["suppressions"]))

    def save(self, findings: Sequence[Finding]) -> None:
        data = {
            "comment": "koordlint baseline: fingerprints of findings "
                       "frozen as pre-existing debt. Keep this empty; "
                       "see docs/DESIGN.md 'Hot-path hygiene rules'.",
            "suppressions": sorted({f.fingerprint for f in findings}),
        }
        with open(self.path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2)
            f.write("\n")

    def split(self, findings: Iterable[Finding]
              ) -> Tuple[List[Finding], List[Finding]]:
        """-> (new, suppressed)"""
        known = set(self.fingerprints)
        new: List[Finding] = []
        suppressed: List[Finding] = []
        for f in findings:
            (suppressed if f.fingerprint in known else new).append(f)
        return new, suppressed
