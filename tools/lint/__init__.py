"""koordlint — AST-based hot-path purity & concurrency lint suite.

A self-contained, stdlib-only (`ast`, no jax/numpy imports) analyzer
framework guarding the invariants the jitted score+bind core and the
informer-side concurrency depend on (docs/DESIGN.md "Hot-path hygiene
rules"):

- per-file and cross-file passes over a parsed-module Project model
- a plugin registry (`tools.lint.framework.register`) the six built-in
  analyzers self-register into on import
- a baseline-suppression file (tools/lint/baseline.json) holding stable
  finding fingerprints, so pre-existing debt can be frozen while new
  findings fail CI
- `python -m tools.lint` exits non-zero on any unsuppressed finding

Run `python -m tools.lint --list` for the analyzer catalog.
"""

from tools.lint.framework import (  # noqa: F401
    Analyzer,
    Finding,
    Project,
    all_analyzers,
    register,
)
from tools.lint.runner import run_lint  # noqa: F401
