"""Abstract shape interpretation of contracted kernel bodies.

A tiny forward dataflow over one function: parameters bind to the
symbolic shapes their contract declares, a recognized subset of
jnp/lax/array operations propagates shapes, and EVERYTHING else joins
to "unknown", which silences all downstream checks — the interpreter
never guesses, so a finding is always backed by declared dims flowing
through recognized ops only.

Defects surfaced (the analyzer assigns the SH codes):
  - conflict: two distinct named dims forced equal by a broadcast,
    concatenate, matmul contraction, or take_along_axis (SH001)
  - rank_growth: implicit (no [None] / broadcast_to) rank promotion
    between non-scalar operands (SH002)
  - cross: an argument passed to another CONTRACTED function
    disagreeing with the callee's declared spec, or a return value
    disagreeing with the function's own declared returns (SH003 /
    SH001 respectively)

With `track_pads=True` (the pad-soundness analyzer; the shape analyzer
leaves it off and is bit-identical to before), every ArrVal also
carries per-axis CANONICAL PAD FILLS and the interpreter applies the
algebra in tools/lint/shapes/pads.py, surfacing three more kinds:
  - pad_reduce: a reduction over a padded axis whose declared/derived
    fill is not neutral for that reduction (PS001)
  - pad_gather: indexing by an array whose padded axis carries the -1
    sentinel without clamping — negative indices wrap in jax, so pad
    rows silently read (or scatter into!) the last real row (PS002)
  - pad_cross: a kernel-boundary pad disagreement — an argument or
    return whose derived fill contradicts the declared predicate
    (PS003); only known-vs-known disagreements count
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from tools.lint.astutil import dotted_name
from tools.lint.shapes import pads as padalg
from tools.lint.shapes.contracts import AstContract
from tools.lint.shapes.spec import (
    DimProp,
    LeafSpec,
    Spec,
    StructRef,
    SymShape,
    broadcast_join,
    dims_compatible,
)

# --- the value lattice -----------------------------------------------------


class Val:
    """Top: statically unknown."""


UNKNOWN = Val()


@dataclass(frozen=True)
class ArrVal(Val):
    dims: SymShape            # entries: symbol | int | None
    # canonical pad fill per axis (pads.FILL_VALUES key or None),
    # parallel to dims; () when nothing is known, so pad-free values
    # stay equal to plain ArrVal literals. Only populated under
    # track_pads.
    pads: Tuple[Optional[str], ...] = ()


def _pad_at(v: ArrVal, i: int) -> Optional[str]:
    return v.pads[i] if i < len(v.pads) else None


def _norm_pads(pads) -> Tuple[Optional[str], ...]:
    t = tuple(pads)
    return t if any(p is not None for p in t) else ()


@dataclass(frozen=True)
class StructVal(Val):
    name: str


@dataclass(frozen=True)
class IntVal(Val):
    """A python int statically tied to a dim (or a literal)."""

    dim: object               # symbol str or int literal


@dataclass(frozen=True)
class ScalarVal(Val):
    """A scalar of unknown value (loop indices, int() casts, inf)."""


@dataclass(frozen=True)
class FloatVal(ScalarVal):
    """A float literal of statically known value — pad-fill algebra
    needs 0.0 / -1.0 / inf branches of jnp.where etc. Scalar in every
    shape rule (isinstance ScalarVal)."""

    value: float = 0.0


@dataclass(frozen=True)
class NoneVal(Val):
    pass


@dataclass(frozen=True)
class TupleVal(Val):
    items: tuple


@dataclass(frozen=True)
class ShapeTupleVal(Val):
    """`x.shape` of a known array."""

    dims: SymShape


@dataclass(frozen=True)
class AtVal(Val):
    """`x.at` / `x.at[idx]`: the pending in-place update view."""

    dims: SymShape


_SCALARISH = (IntVal, ScalarVal)

_ELEMENTWISE = {
    "where", "maximum", "minimum", "logical_and", "logical_or",
    "logical_not", "logical_xor", "floor", "ceil", "abs", "exp", "sqrt",
    "log", "isfinite", "isnan", "mod", "power", "add", "subtract",
    "multiply", "divide", "equal", "not_equal", "greater", "less",
    "greater_equal", "less_equal", "sign", "square", "round", "clip",
}
_REDUCTIONS = {"sum", "any", "all", "max", "min", "mean", "prod",
               "argmax", "argmin"}
_SHAPE_PRESERVING_METHODS = {"astype", "copy", "clip", "round"}
_SHAPE_PRESERVING_FUNCS = {"argsort", "sort", "cumsum", "cumprod",
                           "flip", "negative", "asarray"}
_AT_METHODS = {"add", "set", "mul", "max", "min", "subtract", "divide",
               "multiply", "apply", "get"}
_SCALAR_CONSTS = {
    "jax.numpy.inf", "jax.numpy.nan", "jax.numpy.pi",
    "numpy.inf", "numpy.nan", "numpy.pi", "math.inf", "math.nan",
}
_NEWAXIS = {"jax.numpy.newaxis", "numpy.newaxis"}
_SCALAR_CASTS = {"int", "float", "bool", "len", "min", "max",
                 "jax.numpy.int32", "jax.numpy.float32",
                 "jax.numpy.int8", "jax.numpy.uint32",
                 "jax.numpy.bool_"}


@dataclass
class Defect:
    kind: str                  # "conflict" | "rank_growth" | "cross"
    line: int
    detail: str
    key: str


class ShapeInterp:
    """One contracted function body, interpreted.

    `resolve_const(dotted) -> Val|None` resolves module-level numeric
    constants (EPS, POLICY_NONE) through imports to IntVal/ScalarVal.
    `resolve_contract(call) -> (AstContract, param_names)|None` resolves
    a Call to another contracted function for the cross checks.
    `struct_field(struct, field) -> Spec|None` reads the struct tables.
    """

    def __init__(self, contract: AstContract,
                 resolve_dotted: Callable[[str], str],
                 resolve_const: Callable[[str], Optional[float]],
                 resolve_contract: Callable[[ast.Call],
                                            Optional[AstContract]],
                 struct_field: Callable[[str, str], Optional[Spec]],
                 track_pads: bool = False):
        self.contract = contract
        self.resolve_dotted = resolve_dotted
        self.resolve_const = resolve_const
        self.resolve_contract = resolve_contract
        self.struct_field = struct_field
        self.track_pads = track_pads
        self.defects: List[Defect] = []
        self._keys_seen: Dict[str, int] = {}

    # --- pad bookkeeping -------------------------------------------------

    def _arr(self, dims, pads=()) -> ArrVal:
        if not self.track_pads or not pads:
            return ArrVal(tuple(dims))
        return ArrVal(tuple(dims), _norm_pads(pads))

    def _contrib(self, v: Val, out_rank: int,
                 axis: int) -> padalg.Contrib:
        """Operand v's pad contribution at output axis `axis` in the
        trailing-aligned out_rank frame (pads.py Contrib)."""
        if isinstance(v, IntVal) and isinstance(v.dim, int):
            return ("lit", float(v.dim))
        if isinstance(v, FloatVal):
            return ("lit", v.value)
        if isinstance(v, ArrVal):
            j = axis - (out_rank - len(v.dims))
            if j < 0 or v.dims[j] == 1:
                return None           # broadcast: real values repeat
            f = _pad_at(v, j)
            return ("fill", padalg.FILL_VALUES[f]) if f else None
        return None

    def _ew_pads(self, op: str, operands: List[Val],
                 out_rank: int) -> tuple:
        """Per-axis result fills of an elementwise op over `operands`
        (in call order — sub/div/where are order-sensitive)."""
        if not self.track_pads or out_rank == 0:
            return ()
        out: List[Optional[str]] = []
        for ax in range(out_rank):
            cs = [self._contrib(v, out_rank, ax) for v in operands]
            if op == "where" and len(cs) == 3:
                out.append(padalg.where_fill(cs[0], cs[1], cs[2]))
            elif len(cs) == 1:
                out.append(padalg.unary(op, cs[0]))
            else:
                cur = cs[0]
                for nxt in cs[1:]:
                    f = padalg.combine(op, cur, nxt)
                    cur = ("fill", padalg.FILL_VALUES[f]) if f else None
                out.append(padalg.fill_of_value(cur[1])
                           if cur else None)
        return tuple(out)

    def _clip_pads(self, x: ArrVal, bounds: List[Val]) -> tuple:
        """clip(x, lo, hi) == minimum(maximum(x, lo), hi); a None
        bound is absent."""
        if not self.track_pads:
            return ()
        rank = len(x.dims)
        out: List[Optional[str]] = []
        for ax in range(rank):
            cur = self._contrib(x, rank, ax)
            for b, op in zip(bounds[:2], ("maximum", "minimum")):
                if b is None or isinstance(b, NoneVal):
                    continue
                f = padalg.combine(op, cur,
                                   self._contrib(b, rank, ax))
                cur = ("fill", padalg.FILL_VALUES[f]) if f else None
            out.append(padalg.fill_of_value(cur[1]) if cur else None)
        return tuple(out)

    def _check_reduce(self, arr: ArrVal, ax: int, fname: str,
                      line: int) -> None:
        """PS001: a reduction over a padded axis with a known
        non-neutral fill."""
        if not self.track_pads or not (0 <= ax < len(arr.dims)):
            return
        fill = _pad_at(arr, ax)
        dim = arr.dims[ax]
        if fill is None or not isinstance(dim, str):
            return
        neutral = padalg.reduction_neutral(fname, fill)
        if neutral is None or neutral:
            return
        self._report(
            "pad_reduce", line,
            f"`{fname}` reduces over padded axis `{dim}` whose pad "
            f"rows carry fill `{fill}` — not neutral for {fname}; "
            f"mask the pads first (jnp.where / multiply by the "
            f"validity mask) or pad with a neutral fill",
            key=f"reduce:{fname}:{dim}:{fill}")

    def _check_gather(self, idx: Val, line: int, where: str) -> None:
        """PS002: indexing by an array whose padded axis carries the
        -1 sentinel — jax wraps negative indices, so pad rows read
        (or scatter into) the last real row; clamp with
        jnp.maximum(idx, 0) under the validity mask."""
        if not self.track_pads:
            return
        if isinstance(idx, TupleVal):
            for item in idx.items:
                self._check_gather(item, line, where)
            return
        if not isinstance(idx, ArrVal):
            return
        for ax, f in enumerate(idx.pads):
            dim = idx.dims[ax]
            if f == "-1" and isinstance(dim, str):
                self._report(
                    "pad_gather", line,
                    f"{where} indexed by an array whose padded axis "
                    f"`{dim}` carries the -1 'none' sentinel — "
                    f"negative indices wrap in jax, so pad rows "
                    f"silently hit the last real row; clamp first "
                    f"(jnp.maximum(idx, 0)) and mask the result",
                    key=f"gather:{where}:{dim}")

    # --- entry -----------------------------------------------------------

    def run(self) -> List[Defect]:
        env: Dict[str, Val] = {}
        for name, spec in self.contract.args.items():
            env[name] = self._spec_val(spec)
        for name, dim in self.contract.static.items():
            env[name] = IntVal(dim) if dim is not None else ScalarVal()
        self._walk_body(self.contract.fn_node.body, env)
        return self.defects

    def _spec_val(self, spec: Spec) -> Val:
        if isinstance(spec, LeafSpec):
            return self._arr(
                spec.dims,
                tuple(padalg.canonical(p) for p in spec.pads))
        if isinstance(spec, StructRef):
            return StructVal(spec.name)
        if isinstance(spec, DimProp):
            return IntVal(spec.dim)
        if isinstance(spec, tuple):
            return TupleVal(tuple(self._spec_val(s) for s in spec))
        return UNKNOWN

    # --- reporting -------------------------------------------------------

    def _report(self, kind: str, line: int, detail: str, key: str) -> None:
        base = f"{self.contract.name}:{key}"
        n = self._keys_seen.get(base, 0)
        self._keys_seen[base] = n + 1
        if n:
            base = f"{base}#{n}"
        self.defects.append(Defect(kind=kind, line=line, detail=detail,
                                   key=base))

    def _check_join(self, join, line: int, where: str) -> None:
        for a, b in join.conflicts:
            self._report(
                "conflict", line,
                f"dims `{a}` and `{b}` forced equal in {where} — "
                f"distinct contract dims never broadcast together",
                key=f"{a}<>{b}:{where}")
        if join.rank_growth:
            self._report(
                "rank_growth", line,
                f"implicit rank growth in {where}: add an explicit "
                f"[None] / jnp.broadcast_to so the promoted axes are "
                f"declared", key=f"rank:{where}")

    # --- statements ------------------------------------------------------

    def _walk_body(self, body: List[ast.stmt],
                   env: Dict[str, Val]) -> None:
        for stmt in body:
            self._walk_stmt(stmt, env)

    def _walk_stmt(self, stmt: ast.stmt, env: Dict[str, Val]) -> None:
        if isinstance(stmt, (ast.Import, ast.ImportFrom, ast.ClassDef,
                             ast.Global, ast.Nonlocal, ast.Pass)):
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs see a snapshot of the closure; their params
            # are unknown, their bindings stay local
            inner = dict(env)
            for p in [a.arg for a in stmt.args.posonlyargs
                      + stmt.args.args + stmt.args.kwonlyargs]:
                inner[p] = UNKNOWN
            self._walk_body(stmt.body, inner)
            return
        if isinstance(stmt, ast.Assign):
            val = self._eval(stmt.value, env)
            for t in stmt.targets:
                self._bind(t, val, env)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._bind(stmt.target, self._eval(stmt.value, env), env)
            return
        if isinstance(stmt, ast.AugAssign):
            left = self._eval(stmt.target, env)
            right = self._eval(stmt.value, env)
            out = self._binop_val(left, right, stmt.lineno,
                                  _op_name(stmt.op))
            self._bind(stmt.target, out, env)
            return
        if isinstance(stmt, ast.Return):
            val = self._eval(stmt.value, env) if stmt.value is not None \
                else NoneVal()
            self._check_return(val, stmt.lineno)
            return
        if isinstance(stmt, ast.Expr):
            self._eval(stmt.value, env)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._eval(stmt.test, env)
            self._walk_branches(env, stmt.body, stmt.orelse)
            return
        if isinstance(stmt, ast.For):
            self._bind(stmt.target, self._iter_val(stmt.iter, env), env)
            self._walk_branches(env, stmt.body, stmt.orelse)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._eval(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, UNKNOWN, env)
            self._walk_body(stmt.body, env)
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, env)
            for h in stmt.handlers:
                self._walk_body(h.body, env)
            self._walk_body(stmt.orelse, env)
            self._walk_body(stmt.finalbody, env)
            return
        if isinstance(stmt, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._eval(child, env)
            return
        # anything else: evaluate child expressions for their checks
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._eval(child, env)

    def _walk_branches(self, env: Dict[str, Val],
                       *bodies: List[ast.stmt]) -> None:
        """Walk alternative bodies on copies, then join: names whose
        post-branch values disagree become unknown (loop bodies run
        once — enough for the checks, sound for the join)."""
        results = []
        for body in bodies:
            branch = dict(env)
            self._walk_body(body, branch)
            results.append(branch)
        keys = set()
        for r in results:
            keys |= set(r)
        for k in keys:
            vals = [r.get(k, env.get(k)) for r in results]
            base = vals[0]
            if all(v == base for v in vals):
                if base is not None:
                    env[k] = base
            else:
                env[k] = UNKNOWN

    def _iter_val(self, it: ast.expr, env: Dict[str, Val]) -> Val:
        v = self._eval(it, env)
        if isinstance(it, ast.Call):
            dotted = dotted_name(it.func) or ""
            if self.resolve_dotted(dotted) == "range":
                return ScalarVal()
        if isinstance(v, ArrVal) and len(v.dims) >= 1:
            # iterating strips the lead axis
            return self._arr(v.dims[1:], v.pads[1:] if v.pads else ())
        return UNKNOWN

    def _bind(self, target: ast.AST, val: Val,
              env: Dict[str, Val]) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            items: Tuple = ()
            if isinstance(val, TupleVal) and len(val.items) == len(elts):
                items = val.items
            elif isinstance(val, ShapeTupleVal) \
                    and len(val.dims) == len(elts):
                items = tuple(IntVal(d) if d is not None else ScalarVal()
                              for d in val.dims)
            if items:
                for e, v in zip(elts, items):
                    self._bind(e, v, env)
            else:
                for e in elts:
                    self._bind(e, UNKNOWN, env)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, UNKNOWN, env)
        # attribute/subscript stores introduce no names

    # --- returns / cross-contract checks ---------------------------------

    def _check_return(self, val: Val, line: int) -> None:
        spec = self.contract.returns
        if spec is None:
            return
        self._check_against_spec(val, spec, line, "return",
                                 kind="conflict")

    def _check_against_spec(self, val: Val, spec: Spec, line: int,
                            where: str, kind: str) -> None:
        if isinstance(spec, tuple):
            if isinstance(val, TupleVal) \
                    and len(val.items) == len(spec):
                for i, (v, s) in enumerate(zip(val.items, spec)):
                    self._check_against_spec(v, s, line,
                                             f"{where}[{i}]", kind)
            return
        if isinstance(spec, LeafSpec):
            if isinstance(val, NoneVal):
                if not spec.optional:
                    self._report(kind, line,
                                 f"{where}: None where the contract "
                                 f"declares a required "
                                 f"{spec.dtype}[{','.join(map(str, spec.dims))}]",
                                 key=f"{where}:none")
                return
            if isinstance(val, ArrVal):
                for a, b in dims_compatible(tuple(spec.dims), val.dims):
                    self._report(
                        kind, line,
                        f"{where}: contract declares dim `{a}` but the "
                        f"value carries `{b}`", key=f"{where}:{a}<>{b}")
                if self.track_pads \
                        and len(val.dims) == len(spec.dims):
                    for ax, pred in enumerate(spec.pads):
                        want = padalg.canonical(pred)
                        got = _pad_at(val, ax)
                        if want is not None and got is not None \
                                and want != got:
                            self._report(
                                "pad_cross", line,
                                f"{where}: axis `{spec.dims[ax]}` "
                                f"declares pad predicate `{pred}` "
                                f"(fill `{want}`) but the value's pad "
                                f"rows carry `{got}`",
                                key=f"{where}:pad:{spec.dims[ax]}")
            return
        if isinstance(spec, StructRef) and isinstance(val, StructVal):
            if val.name != spec.name:
                self._report(kind, line,
                             f"{where}: contract declares struct "
                             f"{spec.name!r} but the value is "
                             f"{val.name!r}",
                             key=f"{where}:{spec.name}<>{val.name}")

    # --- expressions -----------------------------------------------------

    def _eval(self, node: ast.expr, env: Dict[str, Val]) -> Val:
        if node is None:
            return UNKNOWN
        if isinstance(node, ast.Constant):
            if node.value is None:
                return NoneVal()
            if isinstance(node.value, bool):
                return FloatVal(1.0 if node.value else 0.0)
            if isinstance(node.value, int):
                return IntVal(node.value)
            if isinstance(node.value, float):
                return FloatVal(node.value)
            if isinstance(node.value, complex):
                return ScalarVal()
            return UNKNOWN
        if isinstance(node, ast.Name):
            return env.get(node.id, self._const_val(node.id))
        if isinstance(node, ast.Attribute):
            return self._eval_attribute(node, env)
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node, env)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, ast.BinOp):
            left = self._eval(node.left, env)
            right = self._eval(node.right, env)
            if isinstance(node.op, ast.MatMult):
                return self._matmul_val(left, right, node.lineno)
            return self._binop_val(left, right, node.lineno,
                                   _op_name(node.op))
        if isinstance(node, ast.UnaryOp):
            v = self._eval(node.operand, env)
            opname = type(node.op).__name__.lower()   # usub/uadd/...
            if opname == "usub":
                if isinstance(v, IntVal) and isinstance(v.dim, int):
                    return IntVal(-v.dim)
                if isinstance(v, FloatVal):
                    return FloatVal(-v.value)
            if isinstance(v, ArrVal) and v.pads \
                    and opname in ("usub", "invert", "not"):
                rank = len(v.dims)
                return self._arr(v.dims, tuple(
                    padalg.unary(opname, self._contrib(v, rank, ax))
                    for ax in range(rank)))
            return v
        if isinstance(node, ast.Compare):
            out = self._eval(node.left, env)
            for cmp_op, comp in zip(node.ops, node.comparators):
                out = self._binop_val(
                    out, self._eval(comp, env), node.lineno, "compare",
                    opdetail=type(cmp_op).__name__.lower())
            return out
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                self._eval(v, env)
            return UNKNOWN
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env)
            a = self._eval(node.body, env)
            b = self._eval(node.orelse, env)
            return a if a == b else UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List)):
            return TupleVal(tuple(self._eval(e, env) for e in node.elts))
        if isinstance(node, ast.Starred):
            self._eval(node.value, env)
            return UNKNOWN
        if isinstance(node, (ast.Lambda, ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp, ast.Dict)):
            return UNKNOWN
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._eval(child, env)
        return UNKNOWN

    def _const_val(self, name: str) -> Val:
        resolved = self.resolve_dotted(name)
        if resolved in _NEWAXIS:
            return NoneVal()
        if resolved in _SCALAR_CONSTS:
            return ScalarVal()
        c = self.resolve_const(resolved)
        return c if c is not None else UNKNOWN

    def _eval_attribute(self, node: ast.Attribute,
                        env: Dict[str, Val]) -> Val:
        dotted = dotted_name(node)
        if dotted is not None:
            head = dotted.partition(".")[0]
            if head not in env:
                resolved = self.resolve_dotted(dotted)
                if resolved in _SCALAR_CONSTS:
                    return ScalarVal()
                if resolved in _NEWAXIS:
                    return NoneVal()
                c = self.resolve_const(resolved)
                if c is not None:
                    return c
        base = self._eval(node.value, env)
        if isinstance(base, StructVal):
            field = self.struct_field(base.name, node.attr)
            if field is not None:
                return self._spec_val(field)
            return UNKNOWN
        if isinstance(base, ArrVal):
            if node.attr == "shape":
                return ShapeTupleVal(base.dims)
            if node.attr == "T":
                return self._arr(tuple(reversed(base.dims)),
                                 tuple(reversed(base.pads))
                                 if base.pads else ())
            if node.attr == "at":
                return AtVal(base.dims)
            if node.attr in ("dtype", "ndim", "size"):
                return ScalarVal()
        return UNKNOWN

    # --- subscripts ------------------------------------------------------

    def _eval_subscript(self, node: ast.Subscript,
                        env: Dict[str, Val]) -> Val:
        base = self._eval(node.value, env)
        sl = node.slice
        if isinstance(base, ShapeTupleVal):
            idx = self._eval(sl, env)
            if isinstance(idx, IntVal) and isinstance(idx.dim, int) \
                    and -len(base.dims) <= idx.dim < len(base.dims):
                d = base.dims[idx.dim]
                return IntVal(d) if d is not None else ScalarVal()
            return ScalarVal()
        if isinstance(base, AtVal):
            self._check_gather(self._eval(sl, env), node.lineno,
                               "`.at[...]` update")
            return AtVal(base.dims)
        if isinstance(base, TupleVal):
            idx = self._eval(sl, env)
            if isinstance(idx, IntVal) and isinstance(idx.dim, int) \
                    and -len(base.items) <= idx.dim < len(base.items):
                return base.items[idx.dim]
            return UNKNOWN
        if not isinstance(base, ArrVal):
            self._eval(sl, env)
            return UNKNOWN
        elements = list(sl.elts) if isinstance(sl, ast.Tuple) else [sl]
        out: List = []
        out_pads: List = []
        axis = 0
        advanced = 0
        for el in elements:
            if isinstance(el, ast.Constant) and el.value is Ellipsis:
                return UNKNOWN
            if isinstance(el, ast.Slice):
                if axis >= len(base.dims):
                    return UNKNOWN
                if el.lower is None and el.upper is None \
                        and el.step is None:
                    out.append(base.dims[axis])
                    out_pads.append(_pad_at(base, axis))
                else:
                    for b in (el.lower, el.upper, el.step):
                        if b is not None:
                            self._eval(b, env)
                    out.append(None)      # sliced extent: unknown
                    out_pads.append(None)
                axis += 1
                continue
            v = self._eval(el, env)
            if isinstance(v, NoneVal):
                out.append(1)             # explicit broadcast axis
                out_pads.append(None)
                continue
            if isinstance(v, (IntVal, ScalarVal)):
                if axis >= len(base.dims):
                    return UNKNOWN
                axis += 1                 # scalar index drops the axis
                continue
            if isinstance(v, ArrVal):
                if axis >= len(base.dims):
                    return UNKNOWN
                advanced += 1
                if advanced > 1:
                    return UNKNOWN        # multi-array indexing: punt
                self._check_gather(v, node.lineno, "advanced indexing")
                out.extend(v.dims)
                # gathered content: real rows land in pad positions
                out_pads.extend([None] * len(v.dims))
                axis += 1
                continue
            return UNKNOWN
        out.extend(base.dims[axis:])
        out_pads.extend(_pad_at(base, i)
                        for i in range(axis, len(base.dims)))
        return self._arr(out, out_pads)

    # --- operators -------------------------------------------------------

    def _binop_val(self, left: Val, right: Val, line: int,
                   where: str, opdetail: Optional[str] = None) -> Val:
        op = opdetail or where
        if isinstance(left, ArrVal) and isinstance(right, ArrVal):
            join = broadcast_join(left.dims, right.dims)
            self._check_join(join, line, where)
            if join.dims is None:
                return UNKNOWN
            return self._arr(join.dims,
                             self._ew_pads(op, [left, right],
                                           len(join.dims)))
        for a, b in ((left, right), (right, left)):
            if isinstance(a, ArrVal) and isinstance(b, _SCALARISH):
                return self._arr(a.dims,
                                 self._ew_pads(op, [left, right],
                                               len(a.dims)))
        if isinstance(left, _SCALARISH) and isinstance(right, _SCALARISH):
            if isinstance(left, IntVal) and isinstance(right, IntVal) \
                    and left.dim == right.dim:
                return left
            return ScalarVal()
        return UNKNOWN

    def _matmul_val(self, left: Val, right: Val, line: int) -> Val:
        if not (isinstance(left, ArrVal) and isinstance(right, ArrVal)):
            return UNKNOWN
        a, b = left.dims, right.dims
        if len(a) < 1 or len(b) < 2:
            return UNKNOWN
        k1, k2 = a[-1], b[-2]
        if k1 is not None and k2 is not None and k1 != k2 \
                and not (k1 == 1 or k2 == 1) \
                and type(k1) is type(k2):
            self._report("conflict", line,
                         f"matmul contracts dim `{k1}` against `{k2}`",
                         key=f"{k1}<>{k2}:matmul")
        if len(a) == 1:
            return ArrVal(tuple(b[:-2]) + (b[-1],))
        return ArrVal(tuple(a[:-1]) + (b[-1],))

    # --- calls -----------------------------------------------------------

    def _eval_call(self, node: ast.Call, env: Dict[str, Val]) -> Val:
        argvals = [self._eval(a, env) for a in node.args]
        kwvals = {kw.arg: self._eval(kw.value, env)
                  for kw in node.keywords if kw.arg is not None}
        for kw in node.keywords:
            if kw.arg is None:
                self._eval(kw.value, env)
        # method-style calls on evaluated receivers
        if isinstance(node.func, ast.Attribute):
            recv = self._eval(node.func.value, env)
            out = self._eval_method(node, recv, argvals)
            if out is not None:
                return out
        dotted = dotted_name(node.func)
        resolved = self.resolve_dotted(dotted) if dotted else ""

        if resolved in _SCALAR_CASTS:
            return ScalarVal()
        if resolved == "range":
            return UNKNOWN

        if resolved.startswith("jax.numpy.") \
                or resolved.startswith("jax.lax."):
            return self._eval_jax_call(node, resolved.rpartition(".")[2],
                                       argvals, kwvals, env)

        # cross-contract call: check args, then trust the declared
        # return ONLY when every declared arg was known (a sliced /
        # rebuilt operand must not smuggle the callee's dims back in)
        target = self.resolve_contract(node)
        if target is not None:
            return self._eval_contract_call(node, target, argvals,
                                            kwvals)
        return UNKNOWN

    def _eval_method(self, node: ast.Call, recv: Val,
                     argvals: List[Val]) -> Optional[Val]:
        """Shape rules for attribute calls; None = not handled here
        (fall through to function-call resolution)."""
        attr = node.func.attr
        if isinstance(recv, AtVal) and attr in _AT_METHODS:
            return ArrVal(recv.dims)
        if isinstance(recv, ArrVal):
            if attr == "clip":
                return self._arr(recv.dims,
                                 self._clip_pads(recv, argvals))
            if attr in _SHAPE_PRESERVING_METHODS:
                # astype/copy/round keep fills (canonical fills are
                # integral or inf — round is identity on them)
                return self._arr(recv.dims, recv.pads)
            if attr in _REDUCTIONS:
                return self._reduce_dims(recv, node, axis_offset=0,
                                         fname=attr)
            if attr == "reshape":
                return self._reshape_dims(node, argvals)
            if attr == "flatten" or attr == "ravel":
                return ArrVal((None,))
        if isinstance(recv, StructVal) and attr == "replace":
            return UNKNOWN
        if isinstance(recv, (TupleVal, ShapeTupleVal)) \
                and attr in ("index", "count"):
            return ScalarVal()
        return None

    def _reduce_dims(self, arr: ArrVal, node: ast.Call,
                     axis_offset: int, fname: str) -> Val:
        dims = arr.dims
        axis_node = None
        for kw in node.keywords:
            if kw.arg == "keepdims":
                return UNKNOWN
            if kw.arg == "axis":
                axis_node = kw.value
        if axis_node is None and len(node.args) > axis_offset:
            axis_node = node.args[axis_offset]
        full_reduce = axis_node is None or (
            isinstance(axis_node, ast.Constant)
            and axis_node.value is None)
        if full_reduce:
            for i in range(len(dims)):
                self._check_reduce(arr, i, fname, node.lineno)
            return ArrVal(())
        ax = _const_int(axis_node)
        if ax is not None and -len(dims) <= ax < len(dims):
            ax %= len(dims)
            self._check_reduce(arr, ax, fname, node.lineno)
            pads = ()
            if arr.pads:
                kept = arr.pads[:ax] + arr.pads[ax + 1:]
                pads = tuple(padalg.reduce_surviving(fname, f)
                             for f in kept)
            return self._arr(dims[:ax] + dims[ax + 1:], pads)
        return UNKNOWN

    def _reshape_dims(self, node: ast.Call, argvals: List[Val]) -> Val:
        """reshape(-1) / reshape(a, b) / reshape(x.shape): known IntVal
        args become dims, -1 becomes unknown, anything else punts."""
        vals = argvals
        if len(vals) == 1 and isinstance(vals[0], TupleVal):
            vals = list(vals[0].items)
        if len(vals) == 1 and isinstance(vals[0], ShapeTupleVal):
            return ArrVal(vals[0].dims)
        out: List = []
        for v in vals:
            if isinstance(v, IntVal):
                out.append(None if v.dim == -1 else v.dim)
            elif isinstance(v, ScalarVal):
                out.append(None)
            else:
                return UNKNOWN
        return ArrVal(tuple(out)) if out else UNKNOWN

    def _eval_jax_call(self, node: ast.Call, fname: str,
                       argvals: List[Val], kwvals: Dict[str, Val],
                       env: Dict[str, Val]) -> Val:
        if fname in _ELEMENTWISE:
            arrs = [v for v in argvals + list(kwvals.values())
                    if isinstance(v, ArrVal)]
            if any(v is UNKNOWN for v in argvals) \
                    or any(v is UNKNOWN for v in kwvals.values()):
                return UNKNOWN
            if not arrs:
                return ScalarVal() if argvals else UNKNOWN
            if fname == "clip" and isinstance(argvals[0], ArrVal) \
                    and not any(isinstance(v, ArrVal)
                                for v in argvals[1:]):
                return self._arr(argvals[0].dims,
                                 self._clip_pads(argvals[0],
                                                 argvals[1:]))
            out = arrs[0]
            for other in arrs[1:]:
                join = broadcast_join(out.dims, other.dims)
                self._check_join(join, node.lineno, fname)
                if join.dims is None:
                    return UNKNOWN
                out = ArrVal(join.dims)
            return self._arr(out.dims,
                             self._ew_pads(fname, argvals,
                                           len(out.dims)))
        if fname in _REDUCTIONS:
            if argvals and isinstance(argvals[0], ArrVal):
                return self._reduce_dims(argvals[0], node,
                                         axis_offset=1, fname=fname)
            return UNKNOWN
        if fname in _SHAPE_PRESERVING_FUNCS:
            if argvals and isinstance(argvals[0], ArrVal):
                src = argvals[0]
                if fname == "asarray":
                    return src
                if fname == "negative":
                    return self._arr(src.dims, tuple(
                        padalg.unary("usub",
                                     self._contrib(src, len(src.dims),
                                                   ax))
                        for ax in range(len(src.dims)))
                        if src.pads else ())
                # sort/cumsum/flip move pad rows out of the trailing
                # region — fills no longer hold
                return ArrVal(src.dims)
            return UNKNOWN
        if fname == "associative_scan":
            if len(argvals) >= 2 and isinstance(argvals[1], ArrVal):
                return ArrVal(argvals[1].dims)
            return UNKNOWN
        if fname in ("zeros", "ones", "empty", "full"):
            out = self._from_shape_arg(node, argvals[:1])
            fill = self._uniform_fill(fname, argvals)
            if isinstance(out, ArrVal) and fill is not None:
                return self._arr(out.dims, (fill,) * len(out.dims))
            return out
        if fname in ("zeros_like", "ones_like", "full_like",
                     "empty_like"):
            if argvals and isinstance(argvals[0], ArrVal):
                fill = self._uniform_fill(fname[:-5], argvals)
                dims = argvals[0].dims
                if fill is not None:
                    return self._arr(dims, (fill,) * len(dims))
                return ArrVal(dims)
            return UNKNOWN
        if fname == "arange":
            if argvals and isinstance(argvals[0], IntVal) \
                    and len(node.args) == 1:
                return ArrVal((argvals[0].dim,))
            return ArrVal((None,))
        if fname == "broadcast_to":
            out = self._from_shape_arg(node, argvals[1:2])
            if isinstance(out, ArrVal) and self.track_pads \
                    and argvals and isinstance(argvals[0], ArrVal) \
                    and argvals[0].pads:
                src, rank = argvals[0], len(out.dims)
                pads = []
                for ax in range(rank):
                    j = ax - (rank - len(src.dims))
                    pads.append(_pad_at(src, j)
                                if j >= 0 and src.dims[j] != 1
                                else None)
                return self._arr(out.dims, pads)
            return out
        if fname == "expand_dims":
            return UNKNOWN
        if fname == "reshape":
            return self._reshape_dims(node, argvals[1:]) \
                if argvals else UNKNOWN
        if fname == "concatenate":
            return self._concat_dims(node, argvals, kwvals)
        if fname == "stack":
            return self._stack_dims(node, argvals, kwvals)
        if fname == "take":
            return self._take_dims(node, argvals, kwvals)
        if fname == "take_along_axis":
            return self._take_along_dims(node, argvals, kwvals)
        if fname in ("top_k", "approx_max_k", "approx_min_k"):
            if argvals and isinstance(argvals[0], ArrVal) \
                    and len(argvals[0].dims) >= 1:
                arr = argvals[0]
                # the selection scans the last axis like a reduction
                self._check_reduce(
                    arr, len(arr.dims) - 1,
                    "min" if fname == "approx_min_k" else "top_k",
                    node.lineno)
                lead = arr.pads[:-1] if arr.pads else ()
                vals = self._arr(arr.dims[:-1] + (None,),
                                 lead + (None,) if lead else ())
                idxs = ArrVal(arr.dims[:-1] + (None,))
                return TupleVal((vals, idxs))
            return UNKNOWN
        if fname in ("int32", "float32", "int8", "uint32", "bool_",
                     "asarray", "array"):
            if argvals and isinstance(argvals[0], ArrVal):
                src = argvals[0]
                return self._arr(src.dims, tuple(
                    padalg.cast_fill(fname, f) for f in src.pads))
            if argvals and isinstance(argvals[0], _SCALARISH):
                return ScalarVal()
            return UNKNOWN
        return UNKNOWN

    def _uniform_fill(self, ctor: str,
                      argvals: List[Val]) -> Optional[str]:
        """The fill every position (so every pad slice) of a
        constructor's result carries; None for empty/unknown."""
        if not self.track_pads:
            return None
        if ctor == "zeros":
            return "zero"
        if ctor == "ones":
            return "one"
        if ctor == "full" and len(argvals) >= 2:
            v = argvals[1]
            if isinstance(v, IntVal) and isinstance(v.dim, int):
                return padalg.fill_of_value(v.dim)
            if isinstance(v, FloatVal):
                return padalg.fill_of_value(v.value)
        return None

    def _from_shape_arg(self, node: ast.Call,
                        shape_vals: List[Val]) -> Val:
        if not shape_vals:
            return UNKNOWN
        v = shape_vals[0]
        if isinstance(v, TupleVal):
            out: List = []
            for item in v.items:
                if isinstance(item, IntVal):
                    out.append(item.dim if item.dim != -1 else None)
                elif isinstance(item, ScalarVal):
                    out.append(None)
                else:
                    return UNKNOWN
            return ArrVal(tuple(out))
        if isinstance(v, ShapeTupleVal):
            return ArrVal(v.dims)
        if isinstance(v, IntVal):
            return ArrVal((v.dim,))
        return UNKNOWN

    def _concat_dims(self, node: ast.Call, argvals: List[Val],
                     kwvals: Dict[str, Val]) -> Val:
        if not argvals or not isinstance(argvals[0], TupleVal):
            return UNKNOWN
        parts = argvals[0].items
        if not parts or not all(isinstance(p, ArrVal) for p in parts):
            return UNKNOWN
        ranks = {len(p.dims) for p in parts}
        if len(ranks) != 1:
            return UNKNOWN
        rank = ranks.pop()
        axis = self._axis_arg(node, default=0)
        if axis is None or not (-rank <= axis < rank):
            return UNKNOWN
        axis %= rank
        out: List = []
        out_pads: List = []
        for i in range(rank):
            if i == axis:
                # real+pad|real+pad: the pad region is no longer a
                # trailing block of the concatenated axis
                out.append(None)          # concatenated extent
                out_pads.append(None)
                continue
            dims_i = [p.dims[i] for p in parts]
            known = [d for d in dims_i if d is not None]
            strs = {d for d in known if isinstance(d, str)}
            if len(strs) > 1:
                a, b = sorted(strs)[:2]
                self._report(
                    "conflict", node.lineno,
                    f"concatenate requires equal non-axis dims but "
                    f"axis {i} mixes `{a}` and `{b}`",
                    key=f"{a}<>{b}:concat")
            out.append(known[0] if len(set(known)) == 1 and known
                       else None)
            fills = {_pad_at(p, i) for p in parts}
            out_pads.append(fills.pop()
                            if len(fills) == 1 else None)
        return self._arr(out, out_pads)

    def _stack_dims(self, node: ast.Call, argvals: List[Val],
                    kwvals: Dict[str, Val]) -> Val:
        if not argvals or not isinstance(argvals[0], TupleVal):
            return UNKNOWN
        parts = argvals[0].items
        if not parts or not all(isinstance(p, ArrVal) for p in parts):
            return UNKNOWN
        base = parts[0]
        for other in parts[1:]:
            join = broadcast_join(base.dims, other.dims)
            self._check_join(join, node.lineno, "stack")
            if join.dims is None:
                return UNKNOWN
            base = ArrVal(join.dims)
        axis = self._axis_arg(node, default=0)
        rank = len(base.dims) + 1
        if axis is None or not (-rank <= axis < rank):
            return UNKNOWN
        axis %= rank
        dims = list(base.dims)
        dims.insert(axis, len(parts))
        pads: List = []
        for i in range(len(base.dims)):
            fills = {_pad_at(p, i + len(p.dims) - len(base.dims))
                     if len(p.dims) == len(base.dims) else None
                     for p in parts}
            pads.append(fills.pop() if len(fills) == 1 else None)
        pads.insert(axis, None)
        return self._arr(dims, pads)

    def _take_dims(self, node: ast.Call, argvals: List[Val],
                   kwvals: Dict[str, Val]) -> Val:
        if len(argvals) < 2 or not isinstance(argvals[0], ArrVal):
            return UNKNOWN
        idx = argvals[1]
        axis = self._axis_arg(node, default=None)
        arr = argvals[0]
        base = arr.dims
        if axis is None or not isinstance(idx, ArrVal) \
                or not (-len(base) <= axis < len(base)):
            return UNKNOWN
        axis %= len(base)
        self._check_gather(idx, node.lineno, "jnp.take")
        pads = ()
        if arr.pads:
            pads = (arr.pads[:axis] + (None,) * len(idx.dims)
                    + arr.pads[axis + 1:])
        return self._arr(base[:axis] + idx.dims + base[axis + 1:],
                         pads)

    def _take_along_dims(self, node: ast.Call, argvals: List[Val],
                         kwvals: Dict[str, Val]) -> Val:
        if len(argvals) < 2 or not isinstance(argvals[0], ArrVal) \
                or not isinstance(argvals[1], ArrVal):
            return UNKNOWN
        x, idx = argvals[0].dims, argvals[1].dims
        axis = self._axis_arg(node, default=None)
        if axis is None or len(x) != len(idx) \
                or not (-len(x) <= axis < len(x)):
            return UNKNOWN
        axis %= len(x)
        self._check_gather(argvals[1], node.lineno,
                           "jnp.take_along_axis")
        out: List = []
        out_pads: List = []
        for i, (a, b) in enumerate(zip(x, idx)):
            if i == axis:
                out.append(b)
                out_pads.append(None)   # gathered content
                continue
            if a is not None and b is not None and a != b \
                    and 1 not in (a, b) \
                    and isinstance(a, str) and isinstance(b, str):
                self._report(
                    "conflict", node.lineno,
                    f"take_along_axis requires equal non-axis dims "
                    f"but axis {i} mixes `{a}` and `{b}`",
                    key=f"{a}<>{b}:take_along_axis")
            out.append(a if a is not None else b)
            # a non-axis pad slice of x is uniform fill, so the
            # gathered rows in it are too
            out_pads.append(_pad_at(argvals[0], i))
        return self._arr(out, out_pads)

    def _axis_arg(self, node: ast.Call, default) -> Optional[int]:
        for kw in node.keywords:
            if kw.arg == "axis":
                got = _const_int(kw.value)
                return got if got is not None else None
        if len(node.args) >= 2:
            # positional axis for the (x, axis) / (parts, axis) forms
            got = _const_int(node.args[-1])
            if got is not None:
                return got
        return default

    def _eval_contract_call(self, node: ast.Call, target: AstContract,
                            argvals: List[Val],
                            kwvals: Dict[str, Val]) -> Val:
        params = target.params
        bound: Dict[str, Val] = {}
        for i, v in enumerate(argvals):
            if i < len(params):
                bound[params[i]] = v
        bound.update(kwvals)
        all_known = True
        for name, spec in target.args.items():
            v = bound.get(name)
            if v is None or v is UNKNOWN or isinstance(v, _SCALARISH):
                all_known = False
                continue
            before = len(self.defects)
            self._check_against_spec(
                v, spec, node.lineno,
                f"arg `{name}` of `{target.name}`", kind="cross")
            if len(self.defects) > before:
                all_known = False
        if not all_known:
            return UNKNOWN
        if target.returns is None:
            return UNKNOWN
        return self._spec_val(target.returns)


def _op_name(op: ast.operator) -> str:
    return type(op).__name__.lower()


def _const_int(node: ast.AST) -> Optional[int]:
    """A literal int, including the UnaryOp form of negatives."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_int(node.operand)
        return -inner if inner is not None else None
    return None
