"""AST extraction of @shape_contract declarations and register_struct
calls — the static tier's view of the runtime registry in
koordinator_tpu/snapshot/schema.py, read without executing anything.

Every spec in a contract is required to be a LITERAL (string / tuple of
strings / dict of string literals); anything computed is a malformed
declaration (SH005) because neither tier could trust it.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tools.lint.astutil import dotted_name, param_names
from tools.lint.framework import Project
from tools.lint.callgraph import ModuleIndex, ProjectIndex, project_index
from tools.lint.shapes.spec import (
    DimProp,
    Spec,
    SpecError,
    parse_spec,
)

_CONTRACT_TAIL = ".shape_contract"
_STRUCT_TAIL = ".register_struct"


@dataclass
class AstContract:
    """One @shape_contract declaration as the AST sees it."""

    name: str
    relpath: str
    line: int
    fn_node: ast.AST                       # the decorated FunctionDef
    args: Dict[str, Spec] = field(default_factory=dict)
    returns: Optional[Spec] = None
    # static params bound to a dim symbol ("tail_chunk" -> "TC") or just
    # known to exist (value None)
    static: Dict[str, Optional[str]] = field(default_factory=dict)
    callables: Tuple[str, ...] = ()

    @property
    def params(self) -> List[str]:
        return param_names(self.fn_node)


@dataclass
class SpecProblem:
    relpath: str
    line: int
    message: str
    key: str


@dataclass
class ContractIndex:
    """Project-wide contract/struct tables plus every malformed
    declaration found on the way (the SH005 feed)."""

    contracts: Dict[Tuple[str, str], AstContract] = field(
        default_factory=dict)            # (relpath, fn name) -> contract
    structs: Dict[str, Dict[str, Spec]] = field(default_factory=dict)
    struct_sites: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    problems: List[SpecProblem] = field(default_factory=list)
    # struct name re-registered with a different field table (SH003)
    struct_drift: List[SpecProblem] = field(default_factory=list)

    def contract_for(self, relpath: str,
                     fn_name: str) -> Optional[AstContract]:
        return self.contracts.get((relpath, fn_name))


def _is_call_to(mi: ModuleIndex, call: ast.Call, tail: str) -> bool:
    dotted = dotted_name(call.func)
    if dotted is None:
        return False
    resolved = mi.resolve_dotted(dotted)
    return resolved.endswith(tail) or resolved == tail.lstrip(".")


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _literal_spec_value(node: ast.AST):
    """String or (nested) tuple/list of strings -> the raw value
    parse_spec accepts; None when the node is not a literal."""
    s = _literal_str(node)
    if s is not None:
        return s
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            v = _literal_spec_value(elt)
            if v is None:
                return None
            out.append(v)
        return tuple(out)
    return None


def extract_contracts(project: Project) -> ContractIndex:
    """Walk every module for @shape_contract decorators and
    register_struct calls."""
    index = ContractIndex()
    pidx: ProjectIndex = project_index(project)
    for mi in pidx.modules.values():
        rel = mi.module.relpath
        for info in mi.functions:
            for dec in info.node.decorator_list:
                if isinstance(dec, ast.Call) \
                        and _is_call_to(mi, dec, _CONTRACT_TAIL):
                    c = _parse_contract(index, rel, info.node, dec)
                    index.contracts[(rel, info.node.name)] = c
        for node in ast.walk(mi.module.tree):
            if isinstance(node, ast.Call) \
                    and _is_call_to(mi, node, _STRUCT_TAIL):
                _parse_struct(index, rel, node)
    return index


def _parse_contract(index: ContractIndex, rel: str, fn: ast.AST,
                    dec: ast.Call) -> AstContract:
    c = AstContract(name=fn.name, relpath=rel, line=dec.lineno,
                    fn_node=fn)
    params = set(c.params)
    for kw in dec.keywords:
        if kw.arg is None:
            index.problems.append(SpecProblem(
                rel, dec.lineno,
                f"contract on `{fn.name}` uses **kwargs expansion; "
                f"specs must be literal keywords",
                key=f"{fn.name}:kwargs"))
            continue
        if kw.arg == "_returns":
            raw = _literal_spec_value(kw.value)
            if raw is None and not (isinstance(kw.value, ast.Constant)
                                    and kw.value.value is None):
                index.problems.append(SpecProblem(
                    rel, kw.value.lineno,
                    f"contract on `{fn.name}`: _returns must be a "
                    f"literal spec string or tuple",
                    key=f"{fn.name}:_returns"))
                continue
            c.returns = _try_parse(index, rel, kw.value.lineno, fn.name,
                                   "_returns", raw)
        elif kw.arg == "_static":
            if not isinstance(kw.value, ast.Dict):
                index.problems.append(SpecProblem(
                    rel, kw.value.lineno,
                    f"contract on `{fn.name}`: _static must be a "
                    f"literal dict", key=f"{fn.name}:_static"))
                continue
            for k, v in zip(kw.value.keys, kw.value.values):
                name = _literal_str(k) if k is not None else None
                if name is None:
                    continue
                sval = _literal_str(v)
                dim = None
                if sval is not None:
                    try:
                        parsed = parse_spec(sval)
                        if isinstance(parsed, DimProp):
                            dim = parsed.dim
                    except SpecError:
                        index.problems.append(SpecProblem(
                            rel, v.lineno,
                            f"contract on `{fn.name}`: _static "
                            f"[{name!r}] names no known dim symbol: "
                            f"{sval!r}", key=f"{fn.name}:_static:{name}"))
                c.static[name] = dim
        elif kw.arg == "_callable":
            if isinstance(kw.value, ast.Dict):
                c.callables = tuple(
                    _literal_str(k) for k in kw.value.keys
                    if k is not None and _literal_str(k))
        elif kw.arg == "_pad":
            continue
        else:
            raw = _literal_spec_value(kw.value)
            if raw is None:
                index.problems.append(SpecProblem(
                    rel, kw.value.lineno,
                    f"contract on `{fn.name}`: spec for `{kw.arg}` is "
                    f"not a literal string/tuple",
                    key=f"{fn.name}:{kw.arg}:literal"))
                continue
            if kw.arg not in params:
                index.problems.append(SpecProblem(
                    rel, kw.value.lineno,
                    f"contract on `{fn.name}` declares `{kw.arg}` "
                    f"which is not a parameter of the function",
                    key=f"{fn.name}:{kw.arg}:unknown-param"))
                continue
            parsed = _try_parse(index, rel, kw.value.lineno, fn.name,
                                kw.arg, raw)
            if parsed is not None:
                c.args[kw.arg] = parsed
    return c


def _try_parse(index: ContractIndex, rel: str, line: int, fn_name: str,
               arg: str, raw) -> Optional[Spec]:
    if raw is None:
        return None
    try:
        return parse_spec(raw)
    except SpecError as exc:
        index.problems.append(SpecProblem(
            rel, line,
            f"contract on `{fn_name}`: bad spec for `{arg}`: {exc}",
            key=f"{fn_name}:{arg}:spec"))
        return None


def _parse_struct(index: ContractIndex, rel: str, call: ast.Call) -> None:
    if len(call.args) < 2:
        return
    name_node, fields_node = call.args[0], call.args[1]
    dotted = dotted_name(name_node)
    name = dotted.rsplit(".", 1)[-1] if dotted else None
    if name is None or not isinstance(fields_node, ast.Dict):
        index.problems.append(SpecProblem(
            rel, call.lineno,
            "register_struct needs a class and a literal field dict",
            key=f"register_struct:L{call.lineno}"))
        return
    fields: Dict[str, Spec] = {}
    for k, v in zip(fields_node.keys, fields_node.values):
        fname = _literal_str(k) if k is not None else None
        raw = _literal_spec_value(v)
        if fname is None or raw is None:
            index.problems.append(SpecProblem(
                rel, (v or call).lineno,
                f"struct {name!r}: non-literal field spec",
                key=f"{name}:field-literal"))
            continue
        parsed = _try_parse(index, rel, v.lineno, name, fname, raw)
        if parsed is not None:
            fields[fname] = parsed
    prior = index.structs.get(name)
    if prior is not None and prior != fields:
        here, there = (rel, call.lineno), index.struct_sites[name]
        index.struct_drift.append(SpecProblem(
            rel, call.lineno,
            f"struct {name!r} re-registered with a different field "
            f"table (first at {there[0]}:{there[1]}) — one struct, one "
            f"contract", key=f"{name}:re-register"))
        return
    index.structs[name] = fields
    index.struct_sites[name] = (rel, call.lineno)
