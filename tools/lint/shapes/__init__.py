"""koordshape's symbolic-dimension model: the spec grammar, the AST
contract extractor, and the abstract shape interpreter behind the
`shape-contract` analyzer (tools/lint/analyzers/shape_contract.py).

Stdlib-only by the same rule as the rest of koordlint: the static tier
must fail CI on hosts where jax is broken or absent. The dynamic tier
(tools/shapecheck.py) imports jax and the runtime registry instead —
this package is the half both tiers share the GRAMMAR of, and
tests/test_shape_contract.py pins the dim vocabulary here equal to
koordinator_tpu.snapshot.schema.DIM_VOCAB.
"""
