"""Pad-fill algebra for the koordpad static tier (pad-soundness).

The abstract interpreter tracks, per array axis, what the PAD REGION
along that axis contains, as a CANONICAL FILL:

    "zero" | "one" | "-1" | "inf" | None (statically unknown)

Predicates from the spec grammar map into this space via
spec.PAD_FILLS ("false"/"unschedulable" -> "zero"; "invalid"/"any" ->
None). The rules in this module answer: given an operation and what is
known about each operand's pad slices, what do the RESULT's pad slices
contain?

Soundness direction: a rule may only claim a fill when the claim holds
for every runtime content of the unknown operands; when in doubt the
answer is None, which silences every downstream check — never-guess.
Two deliberate assumptions lean on tree-wide invariants and can, at
worst, SILENCE a finding that Tier B (tools/padcheck.py) still
exercises concretely:
  - `~` / `&` / `|` on arrays are treated with bool-mask semantics
    (the tree uses them exclusively on masks; int bitwise `|`/`~` over
    an array with declared 0/1 pads would evaluate differently).
  - multiply-by-zero annihilates (x * 0 -> 0); a runtime +-inf/nan in
    the other operand would make it nan instead. Score surfaces are
    finite by construction; quota runtime's +inf columns never meet a
    zero mask multiplicatively.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

from tools.lint.shapes.spec import NEUTRAL_PADS, PAD_FILLS

Fill = Optional[str]

# canonical fill -> the numeric value of every pad entry
FILL_VALUES = {"zero": 0.0, "one": 1.0, "-1": -1.0, "inf": math.inf}

# An operand's CONTRIBUTION on one output axis:
#   ("fill", v)  a non-broadcast array whose pad slice is uniformly v
#   ("lit", v)   a scalar literal v (uniform over every position)
#   None         statically unknown content (broadcast operands too:
#                their single row holds REAL values, not fill)
Contrib = Optional[Tuple[str, float]]


def canonical(pred: Optional[str]) -> Fill:
    """Spec pad predicate -> canonical fill (None for invalid/any)."""
    if pred is None:
        return None
    return PAD_FILLS.get(pred)


def fill_of_value(v) -> Fill:
    """Map a computed pad value back into the canonical space; any
    value outside it is unrepresentable -> None (unknown)."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    if math.isnan(f):
        return None
    if f == 0.0:
        return "zero"
    if f == 1.0:
        return "one"
    if f == -1.0:
        return "-1"
    if f == math.inf:
        return "inf"
    return None


def _truthy(v) -> float:
    return 1.0 if v else 0.0


def _safe_div(a: float, b: float) -> Optional[float]:
    if b == 0.0:
        return None
    return a / b


_BINOPS = {
    # ast.BinOp names (_op_name) and jnp function names, one table
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "subtract": lambda a, b: a - b,
    "mult": lambda a, b: a * b,
    "multiply": lambda a, b: a * b,
    "div": _safe_div,
    "divide": _safe_div,
    "truediv": _safe_div,
    "floordiv": lambda a, b: float(math.floor(a / b)) if b else None,
    "pow": lambda a, b: float(a ** b),
    "power": lambda a, b: float(a ** b),
    "maximum": max,
    "minimum": min,
    # bool-mask semantics (see module docstring)
    "bitand": lambda a, b: _truthy(a and b),
    "logical_and": lambda a, b: _truthy(a and b),
    "bitor": lambda a, b: _truthy(a or b),
    "logical_or": lambda a, b: _truthy(a or b),
    "bitxor": lambda a, b: _truthy(bool(a) != bool(b)),
    "logical_xor": lambda a, b: _truthy(bool(a) != bool(b)),
    # ast.Compare op class names, lowercased, plus jnp spellings
    "lt": lambda a, b: _truthy(a < b),
    "lte": lambda a, b: _truthy(a <= b),
    "gt": lambda a, b: _truthy(a > b),
    "gte": lambda a, b: _truthy(a >= b),
    "eq": lambda a, b: _truthy(a == b),
    "noteq": lambda a, b: _truthy(a != b),
    "less": lambda a, b: _truthy(a < b),
    "less_equal": lambda a, b: _truthy(a <= b),
    "greater": lambda a, b: _truthy(a > b),
    "greater_equal": lambda a, b: _truthy(a >= b),
    "equal": lambda a, b: _truthy(a == b),
    "not_equal": lambda a, b: _truthy(a != b),
}

# ops where ONE known operand value forces the result regardless of the
# other operand's (unknown) content
_ANNIHILATORS = {
    "mult": 0.0,
    "multiply": 0.0,
    "bitand": 0.0,
    "logical_and": 0.0,
    "bitor": 1.0,
    "logical_or": 1.0,
    "maximum": math.inf,
}

_UNARY = {
    "usub": lambda v: -v,
    "negative": lambda v: -v,
    "abs": abs,
    "square": lambda v: v * v,
    "sign": lambda v: float((v > 0) - (v < 0)),
    "floor": lambda v: float(math.floor(v)),
    "ceil": lambda v: float(math.ceil(v)),
    "round": lambda v: float(round(v)),
    "sqrt": math.sqrt,
    "exp": math.exp,
    "log": math.log,
    "isnan": lambda v: 0.0,          # canonical fills are never nan
    "isfinite": lambda v: _truthy(not math.isinf(v)),
    # bool-mask semantics for `~` (see module docstring)
    "invert": lambda v: _truthy(not v),
    "not": lambda v: _truthy(not v),
    "logical_not": lambda v: _truthy(not v),
}


def combine(op: str, a: Contrib, b: Contrib) -> Fill:
    """The result fill on one axis of a binary (or pairwise-folded
    n-ary) op over two operand contributions."""
    ann = _ANNIHILATORS.get(op)
    if ann is not None:
        for c in (a, b):
            if c is not None and c[1] == ann:
                return fill_of_value(ann)
    fn = _BINOPS.get(op)
    if fn is None or a is None or b is None:
        return None
    try:
        r = fn(a[1], b[1])
    except (ArithmeticError, OverflowError, ValueError):
        return None
    return fill_of_value(r) if r is not None else None


def unary(op: str, c: Contrib) -> Fill:
    fn = _UNARY.get(op)
    if fn is None or c is None:
        return None
    try:
        r = fn(c[1])
    except (ArithmeticError, OverflowError, ValueError):
        return None
    return fill_of_value(r)


def where_fill(c: Contrib, a: Contrib, b: Contrib) -> Fill:
    """jnp.where(c, a, b) on one axis: a known condition fill selects
    the matching branch's contribution; an unknown condition still
    yields a fill when BOTH branches agree on a known one."""
    if c is not None:
        pick = a if c[1] else b
        return fill_of_value(pick[1]) if pick is not None else None
    if a is not None and b is not None and a[1] == b[1]:
        return fill_of_value(a[1])
    return None


def reduction_neutral(op: str, fill: Fill) -> Optional[bool]:
    """Whether `fill` pads cannot perturb the real rows of a reduction
    over the padded axis; None when op is not a known reduction family
    or the fill is unknown (silent either way)."""
    fam = NEUTRAL_PADS.get(op)
    if fam is None or fill is None:
        return None
    return fill in fam


def reduce_surviving(op: str, fill: Fill) -> Fill:
    """After reducing away some OTHER axis, what a surviving padded
    axis's pad slices contain: the slice was uniformly `fill`, so the
    reduction of identical values is often exactly computable (the
    reduced extent itself is symbolic, so sums of nonzero fills are
    not)."""
    if fill is None:
        return None
    if op in ("max", "min", "mean", "nanmax", "nanmin", "nanmean",
              "median"):
        return fill
    if op in ("sum", "nansum"):
        return fill if fill in ("zero", "inf") else None
    if op in ("prod", "nanprod"):
        return fill if fill in ("zero", "one", "inf") else None
    if op in ("any", "all"):
        return "one" if FILL_VALUES[fill] else "zero"
    if op in ("argmax", "argmin"):
        return "zero"                 # ties resolve to index 0
    if op == "count_nonzero":
        return "zero" if fill == "zero" else None
    if op in ("std", "var"):
        return None if fill == "inf" else "zero"
    return None


def cast_fill(cast: str, fill: Fill) -> Fill:
    """Dtype-cast constructors (jnp.int32(x), x.astype, bool_)."""
    if fill is None:
        return None
    if cast == "bool_":
        return "one" if FILL_VALUES[fill] else "zero"
    if cast in ("int32", "int8"):
        return None if fill == "inf" else fill
    if cast == "uint32":
        return fill if fill in ("zero", "one") else None
    return fill
