"""The koordshape spec grammar and symbolic-shape algebra.

A spec string is one of:
  "f32[P,N]"    leaf array: dtype + named/fixed/int dims
  "f32[]"       scalar array
  "?f32[P,N]"   optional leaf (the value may be None)
  "PodBatch"    reference to a registered struct (CapWord, has lowercase)
  "N"           bare dim symbol: a symbolic-int PROPERTY of a struct

A dim symbol in a leaf may carry a PAD PREDICATE (the koordpad tier):
  "f32[N~pad:zero,R]"      pad rows along N are zero-filled
  "i32[P~pad:-1]"          pad rows carry the -1 sentinel
  "bool[P~pad:invalid]"    pad content unspecified, masked by the
                           struct's validity column
Every dim in PADDED_DIMS is a padded capacity and MUST declare its
predicate in registered structs and contracts (pad_soundness PS004);
dims outside PADDED_DIMS must not carry one (PS005).

Symbolic shapes are tuples whose entries are dim symbols (str), int
literals, or None (statically unknown). The broadcast join implements
numpy trailing alignment and reports two defect classes:
  - distinct named symbols forced equal (the SH001 bug class)
  - implicit rank growth between non-scalar operands (SH002)
Unknown entries join silently — the interpreter never guesses.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional, Tuple, Union

# The named-dimension vocabulary. This is the linter's own copy (the
# stdlib tier cannot import jax-importing schema.py);
# tests/test_shape_contract.py pins it equal to schema.DIM_VOCAB.
DIM_VOCAB = {
    "P": "pending pods in the batch",
    "N": "node columns (padded capacity)",
    "I": "GPU instances per node",
    "Z": "NUMA zones per node",
    "G": "gangs (PodGroups)",
    "Q": "elastic-quota tree nodes",
    "V": "reservation slots",
    "R": "resource dims (NUM_RESOURCES; padded like any capacity)",
    "S": "distinct pod node-selectors",
    "L": "node label-equivalence groups",
    "T": "distinct pod toleration sets",
    "TG": "node taint-equivalence groups",
    "SG": "pod-topology-spread groups",
    "AG": "inter-pod anti-affinity groups",
    "FG": "inter-pod affinity groups",
    "DM": "topology domains per constraint group",
    "J": "aux (RDMA/FPGA) VF instances per pool",
    "K": "delta rows per ingest tick",
    "KC": "gathered per-shard top-k candidates (k x node shards)",
    "TC": "tail retry-chunk width",
    "RD": "descheduler threshold resource dims",
    "NS": "descheduler namespace rows (padded)",
}

# dims pinned to module constants (schema.FIXED_DIMS carries the values;
# the static tier only needs the symbols)
FIXED_DIM_SYMBOLS = ("AGG", "DEV", "AX", "QD")

# The pad-predicate vocabulary (the koordpad tier). This is the
# linter's own copy; tests/test_pad_soundness.py pins it equal to
# schema.PAD_VOCAB. Each predicate names what the PAD REGION along the
# annotated dim contains — the machine-readable form of the prose
# `_pad=` notes.
PAD_VOCAB = {
    "zero": "pad entries are 0 (False for bool)",
    "one": "pad entries are 1 (True for bool)",
    "false": "pad entries are False (bool columns only)",
    "-1": "pad entries carry the -1 'none' sentinel",
    "inf": "pad entries are +inf (never gate; f32 only)",
    "unschedulable": "zero-filled node rows additionally killed by the "
                     "schedulable=False guard (pad_nodes_to_mesh rows)",
    "invalid": "content unspecified; masked by the carrying struct's "
               "validity column (valid/gpu_valid/numa_valid/...)",
    "any": "content unspecified; every consumer must guard it "
           "explicitly (no inertness is asserted)",
}

# Dims that are PADDED CAPACITIES — their extent may exceed the real
# element count, with a declared-fill pad region at the end. Every
# occurrence of one of these in a registered struct / contract leaf
# must carry a ~pad: predicate (PS004). Deliberately exempt:
#   R   fixed NUM_RESOURCES in practice (kernels index it by
#       ResourceKind constants; zero columns are uniformly inert)
#   S/L/T/TG/SG/AG/FG  equivalence-class tables sized exactly
#   TC  a static retry-window width (runtime-masked by `attempt`,
#       never a trailing pad region)
#   KC/RD  derived widths (k x shards / threshold dims), sized exactly
PADDED_DIMS = frozenset(
    {"P", "N", "Q", "G", "V", "Z", "I", "J", "DM", "K", "NS"})

# predicate -> canonical FILL the static tier can reason about; None =
# content statically unknown (invalid/any — Tier B's differential run
# still exercises them, but never-guess keeps Tier A silent)
PAD_FILLS = {
    "zero": "zero",
    "one": "one",
    "false": "zero",
    "-1": "-1",
    "inf": "inf",
    "unschedulable": "zero",
    "invalid": None,
    "any": None,
}

# reduction family -> canonical fills NEUTRAL for it (a pad region
# carrying a neutral fill cannot perturb the reduction's real rows).
# zero/-1 are neutral for max/argmax/top_k because every score surface
# in the tree is >= 0 and lax tie-breaking is stable toward the lowest
# index with pads appended AFTER real rows.
NEUTRAL_PADS = {
    "sum": {"zero"},
    "any": {"zero"},
    "count_nonzero": {"zero"},
    "nansum": {"zero"},
    "max": {"zero", "-1"},
    "argmax": {"zero", "-1"},
    "nanmax": {"zero", "-1"},
    "top_k": {"zero", "-1"},
    "min": {"inf"},
    "argmin": {"inf"},
    "nanmin": {"inf"},
    "all": {"one"},
    "prod": {"one"},
    "nanprod": {"one"},
    "mean": set(),
    "nanmean": set(),
    "std": set(),
    "var": set(),
    "median": set(),
}

DTYPES = {
    "f32": "float32",
    "i32": "int32",
    "i8": "int8",
    "u32": "uint32",
    "bool": "bool",
}

Dim = Union[str, int]           # a known dim: symbol or literal
SymDim = Optional[Dim]          # None = statically unknown
SymShape = Tuple[SymDim, ...]


class SpecError(ValueError):
    """A malformed contract spec (the SH005 bug class)."""


@dataclass(frozen=True)
class LeafSpec:
    dtype: str                  # key of DTYPES
    dims: Tuple[Dim, ...]
    optional: bool = False
    # pad predicate per dim (PAD_VOCAB key or None), parallel to
    # `dims`; () when NO dim carries one, so pad-free specs stay equal
    # to pre-koordpad LeafSpec literals
    pads: Tuple[Optional[str], ...] = ()

    def pad_for(self, axis: int) -> Optional[str]:
        return self.pads[axis] if axis < len(self.pads) else None


@dataclass(frozen=True)
class StructRef:
    name: str


@dataclass(frozen=True)
class DimProp:
    """A bare dim symbol: a symbolic-int struct property (num_nodes)."""

    dim: str


Spec = Union[LeafSpec, StructRef, DimProp, tuple]

_LEAF_RE = re.compile(r"^(\?)?([a-z][a-z0-9]*)\[([^\[\]]*)\]$")
_WORD_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def known_dim(symbol: str) -> bool:
    return symbol in DIM_VOCAB or symbol in FIXED_DIM_SYMBOLS


def parse_spec(raw) -> Spec:
    """Parse one spec value (a string, or a tuple/list of specs for
    multi-output contracts). Raises SpecError on anything malformed."""
    if isinstance(raw, (tuple, list)):
        return tuple(parse_spec(r) for r in raw)
    if not isinstance(raw, str):
        raise SpecError(f"spec must be a string or tuple, got {raw!r}")
    m = _LEAF_RE.match(raw)
    if m:
        optional, dtype, body = bool(m.group(1)), m.group(2), m.group(3)
        if dtype not in DTYPES:
            raise SpecError(f"unknown dtype {dtype!r} in {raw!r} "
                            f"(expected one of {sorted(DTYPES)})")
        dims: List[Dim] = []
        pads: List[Optional[str]] = []
        body = body.strip()
        for tok in (body.split(",") if body else []):
            tok = tok.strip()
            if not tok:
                raise SpecError(f"empty dim in {raw!r}")
            pad = None
            if "~" in tok:
                tok, _, anno = tok.partition("~")
                tok = tok.strip()
                anno = anno.strip()
                if not anno.startswith("pad:"):
                    raise SpecError(f"malformed dim annotation {anno!r} "
                                    f"in {raw!r} (expected pad:<pred>)")
                pad = anno[len("pad:"):].strip()
                if pad not in PAD_VOCAB:
                    raise SpecError(f"unknown pad predicate {pad!r} in "
                                    f"{raw!r} (vocabulary: "
                                    f"{sorted(PAD_VOCAB)})")
            if tok.isdigit():
                dims.append(int(tok))
            elif known_dim(tok):
                dims.append(tok)
            else:
                raise SpecError(f"undeclared dim symbol {tok!r} in "
                                f"{raw!r} (vocabulary: "
                                f"{sorted(DIM_VOCAB)} + "
                                f"{sorted(FIXED_DIM_SYMBOLS)})")
            pads.append(pad)
        if all(p is None for p in pads):
            pads = []
        return LeafSpec(dtype=dtype, dims=tuple(dims), optional=optional,
                        pads=tuple(pads))
    if not _WORD_RE.match(raw):
        raise SpecError(f"malformed spec {raw!r}")
    if known_dim(raw):
        return DimProp(dim=raw)
    if raw[0].isupper() and any(c.islower() for c in raw):
        return StructRef(name=raw)
    raise SpecError(f"undeclared dim symbol {raw!r} (a struct reference "
                    f"needs CapWord form, a dim symbol must be in the "
                    f"vocabulary)")


def spec_shape(spec: Spec) -> Optional[SymShape]:
    """The symbolic shape a leaf spec declares; None for non-leaves."""
    if isinstance(spec, LeafSpec):
        return tuple(spec.dims)
    return None


@dataclass
class Join:
    """Result of a broadcast join: the joined shape plus the defects the
    join itself proves."""

    dims: Optional[SymShape]
    conflicts: List[Tuple[Dim, Dim]]        # distinct knowns forced equal
    rank_growth: bool = False               # implicit non-scalar growth


def broadcast_join(a: Optional[SymShape],
                   b: Optional[SymShape]) -> Join:
    """Numpy trailing-aligned broadcast of two symbolic shapes. Unknown
    operands (None) poison the result silently; unknown ENTRIES join to
    unknown entries without a conflict."""
    if a is None or b is None:
        return Join(dims=None, conflicts=[])
    conflicts: List[Tuple[Dim, Dim]] = []
    rank_growth = len(a) != len(b) and min(len(a), len(b)) >= 1
    n = max(len(a), len(b))
    out: List[SymDim] = []
    for i in range(1, n + 1):
        x = a[-i] if i <= len(a) else 1
        y = b[-i] if i <= len(b) else 1
        out.append(_join_dim(x, y, conflicts))
    return Join(dims=tuple(reversed(out)), conflicts=conflicts,
                rank_growth=rank_growth)


def _join_dim(x: SymDim, y: SymDim,
              conflicts: List[Tuple[Dim, Dim]]) -> SymDim:
    if x is None or y is None:
        return None
    if x == y:
        return x
    if x == 1:
        return y
    if y == 1:
        return x
    if isinstance(x, str) and isinstance(y, str):
        conflicts.append((x, y))
        return None
    if isinstance(x, int) and isinstance(y, int):
        conflicts.append((x, y))
        return None
    # symbol vs int literal: statically undecidable (the symbol may be
    # bound to exactly that size) — join to unknown, no conflict
    return None


def dims_compatible(declared: SymShape, got: SymShape
                    ) -> List[Tuple[Dim, Dim]]:
    """Positional (non-broadcast) comparison for contract boundaries:
    argument passing and returns. Only KNOWN-vs-KNOWN disagreements
    count; a rank mismatch between fully-known shapes is reported as a
    pseudo-conflict on the rank."""
    if len(declared) != len(got):
        if all(d is not None for d in declared) \
                and all(g is not None for g in got):
            return [(f"rank {len(declared)}", f"rank {len(got)}")]
        return []
    out: List[Tuple[Dim, Dim]] = []
    for d, g in zip(declared, got):
        if d is None or g is None or d == g:
            continue
        if isinstance(d, str) and isinstance(g, str):
            out.append((d, g))
        elif isinstance(d, int) and isinstance(g, int):
            out.append((d, g))
        # symbol vs int: undecidable, skip
    return out
