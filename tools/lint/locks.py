"""Shared lock-identity resolution for the LK (lock-discipline) and GB
(race-guard) analyzer families.

Both analyzers must agree on what "the lock" is before they can agree
on anything else: LK edges and GB guard obligations are keyed by
canonical lock identities, and a disagreement (LK calling Histogram's
lock `metrics.Histogram._lock` while GB calls it
`metrics._Metric._lock`) would let a finding in one family contradict
an exemption in the other. So identity lives here, once:

  * `self.X = threading.Lock()/RLock/Condition()` anywhere in a class
    body makes X a lock attribute OWNED by that class;
  * a subclass (same module, `class Histogram(_Metric)`) inherits its
    bases' lock attributes, and the canonical identity stays with the
    OWNER: `with self._lock:` inside Histogram resolves to
    `koordinator_tpu.metrics._Metric._lock`;
  * `NAME = threading.Lock()` at module level makes NAME a module lock
    (`module.NAME`).

This module also parses `@guarded_by(...)` / `guard_module(...)`
contract tables (koordinator_tpu/utils/sync.py) out of the AST —
literal keyword strings only, never an import of the analyzed tree —
so the GB analyzer can check declarations against acquisitions and the
LK analyzer can resolve a guard-named lock through the same owner walk.
Everything is stdlib `ast`.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.lint.astutil import Imports, call_target, collect_imports
from tools.lint.framework import Module

LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}

# the non-lock guard vocabulary of utils/sync.py, mirrored: these
# declare a synchronization DISCIPLINE rather than a lock, so GB001
# never enforces them — their value is the declaration itself plus the
# GB004/GB005 checks that keep the table honest
GUARD_VOCAB = ("publish-once", "confined", "racy-monitor")
_IDENT = re.compile(r"^[A-Za-z_]\w*$")
_EXTERNAL = re.compile(r"^external:[A-Za-z_]\w*(\.[A-Za-z_]\w*)+$")


def guard_kind(guard: str) -> str:
    """"lock" (an instance lock-attribute name), "vocab", "external",
    or "bad" for anything the sync.py grammar rejects."""
    if guard in GUARD_VOCAB:
        return "vocab"
    if guard.startswith("external:"):
        return "external" if _EXTERNAL.match(guard) else "bad"
    return "lock" if _IDENT.match(guard) else "bad"


@dataclass
class GuardTable:
    """One parsed `@guarded_by(...)` decoration or `guard_module(...)`
    call. `table` holds only the well-formed literal entries; every
    AST-visible grammar violation lands in `malformed` as a
    (line, slug, human reason) triple for GB005."""

    line: int
    table: Dict[str, str] = field(default_factory=dict)
    malformed: List[Tuple[int, str, str]] = field(default_factory=list)


@dataclass
class ClassLocks:
    """Lock facts for one module-body class."""

    name: str
    node: ast.ClassDef
    bases: Tuple[str, ...]          # same-module base-class names
    locks: Set[str] = field(default_factory=set)   # own ctor assignments
    conds: Set[str] = field(default_factory=set)
    wraps: Dict[str, str] = field(default_factory=dict)  # cond -> wrapped
    guard: Optional[GuardTable] = None
    extra_guards: List[GuardTable] = field(default_factory=list)


@dataclass
class ModuleLocks:
    """The per-module lock index both analyzer families resolve
    against."""

    module: Module
    imports: Imports
    classes: Dict[str, ClassLocks] = field(default_factory=dict)
    module_locks: Set[str] = field(default_factory=set)
    module_conds: Set[str] = field(default_factory=set)
    module_wraps: Dict[str, str] = field(default_factory=dict)
    module_guard: Optional[GuardTable] = None
    extra_module_guards: List[GuardTable] = field(default_factory=list)

    def lock_owner(self, cls: str, attr: str) -> Optional[str]:
        """The class (cls itself or a same-module base, breadth-first)
        whose body constructs `self.<attr>` as a lock; None when none
        does."""
        seen: Set[str] = set()
        queue = [cls]
        while queue:
            c = queue.pop(0)
            if c in seen:
                continue
            seen.add(c)
            info = self.classes.get(c)
            if info is None:
                continue
            if attr in info.locks:
                return c
            queue.extend(info.bases)
        return None

    def lock_attrs(self, cls: str) -> Set[str]:
        """Every lock attribute visible on `cls`: its own plus those
        inherited from same-module bases."""
        out: Set[str] = set()
        seen: Set[str] = set()
        queue = [cls]
        while queue:
            c = queue.pop(0)
            if c in seen:
                continue
            seen.add(c)
            info = self.classes.get(c)
            if info is None:
                continue
            out |= info.locks
            queue.extend(info.bases)
        return out

    def cond_owner(self, cls: str, attr: str) -> Optional[str]:
        seen: Set[str] = set()
        queue = [cls]
        while queue:
            c = queue.pop(0)
            if c in seen:
                continue
            seen.add(c)
            info = self.classes.get(c)
            if info is None:
                continue
            if attr in info.conds:
                return c
            queue.extend(info.bases)
        return None

    def cond_wrapped(self, cls: str, attr: str) -> Optional[str]:
        owner = self.cond_owner(cls, attr)
        if owner is None:
            return None
        return self.classes[owner].wraps.get(attr)

    def canonical(self, cls: str, attr: str) -> Optional[str]:
        """`module.Owner.attr` for a lock attribute reached from `cls`
        (owner = the defining class, so subclasses and their bases
        agree on identity); None when attr is not a known lock."""
        owner = self.lock_owner(cls, attr)
        if owner is None:
            return None
        return f"{self.module.dotted}.{owner}.{attr}"

    def module_lock_id(self, name: str) -> Optional[str]:
        if name in self.module_locks:
            return f"{self.module.dotted}.{name}"
        return None


def _lock_ctor(value: ast.AST, imports: Imports) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    tgt = call_target(value)
    resolved = imports.resolve(tgt) if tgt is not None else None
    return resolved if resolved in LOCK_CTORS else None


def _cond_wrapped_attr(value: ast.Call) -> Optional[str]:
    """`threading.Condition(self.X)` / `Condition(NAME)` wraps an
    EXISTING lock: wait() releases that lock, so the LK004 analysis
    must not count it as pinned. Returns the wrapped attr/name."""
    if not value.args:
        return None
    arg = value.args[0]
    if isinstance(arg, ast.Attribute) and isinstance(arg.value, ast.Name) \
            and arg.value.id == "self":
        return arg.attr
    if isinstance(arg, ast.Name):
        return arg.id
    return None


def _resolves_to(call_func: ast.AST, imports: Imports, tail: str) -> bool:
    dotted = None
    if isinstance(call_func, (ast.Name, ast.Attribute)):
        from tools.lint.astutil import dotted_name
        dotted = dotted_name(call_func)
    if dotted is None:
        return False
    resolved = imports.resolve(dotted)
    return resolved == tail or resolved.endswith("." + tail) \
        or resolved.endswith(f".sync.{tail}")


def _parse_guard_call(call: ast.Call, skip_args: int,
                      what: str) -> GuardTable:
    """Parse the keyword table of a guarded_by/guard_module call into a
    GuardTable, recording every grammar violation the AST can see.
    `skip_args` positional args are expected (guard_module's module
    name); any beyond that is malformed."""
    gt = GuardTable(line=call.lineno)
    if len(call.args) > skip_args:
        gt.malformed.append((call.lineno, "positional-args",
                             f"{what} takes guard entries as keyword "
                             f"arguments only"))
    for kw in call.keywords:
        if kw.arg is None:
            gt.malformed.append((kw.value.lineno, "splat",
                                 f"{what} table must be literal keyword "
                                 f"arguments, not a ** splat — the "
                                 f"static tier cannot read a computed "
                                 f"table"))
            continue
        if not (isinstance(kw.value, ast.Constant)
                and isinstance(kw.value.value, str)):
            gt.malformed.append((kw.value.lineno, f"{kw.arg}:non-literal",
                                 f"guard for `{kw.arg}` must be a "
                                 f"string literal"))
            continue
        guard = kw.value.value
        if guard_kind(guard) == "bad":
            gt.malformed.append((kw.value.lineno, f"{kw.arg}:bad-guard",
                                 f"guard {guard!r} for `{kw.arg}` is "
                                 f"neither a lock-attribute name, one "
                                 f"of {GUARD_VOCAB}, nor "
                                 f"'external:Owner.lock_attr'"))
            continue
        if kw.arg in gt.table:
            gt.malformed.append((kw.value.lineno, f"{kw.arg}:duplicate",
                                 f"`{kw.arg}` declared twice"))
            continue
        gt.table[kw.arg] = guard
    if not gt.table and not gt.malformed:
        gt.malformed.append((call.lineno, "empty",
                             f"{what} with an empty table declares "
                             f"nothing"))
    return gt


def stmt_bodies(stmt: ast.stmt):
    """The nested statement lists of a compound statement (if/try/for/
    while bodies, else/finally, except handlers)."""
    for attr in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, attr, None)
        if isinstance(sub, list) and sub and isinstance(sub[0], ast.stmt):
            yield sub
    for h in getattr(stmt, "handlers", []) or []:
        yield h.body


def header_exprs(stmt: ast.stmt):
    """Expressions evaluated by a compound statement itself (its test /
    iterable), as opposed to its nested bodies."""
    for attr in ("test", "iter"):
        node = getattr(stmt, attr, None)
        if node is not None:
            yield node


def short(lock: str) -> str:
    """`Class.attr` tail of a canonical lock id, for messages."""
    return ".".join(lock.split(".")[-2:])


def index_module(module: Module) -> ModuleLocks:
    """Build the lock + contract index for one parsed module."""
    package = module.dotted.rsplit(".", 1)[0] if "." in module.dotted \
        else ""
    imports = collect_imports(module.tree, package)
    idx = ModuleLocks(module=module, imports=imports)

    for node in module.tree.body:
        if isinstance(node, ast.Assign):
            ctor = _lock_ctor(node.value, imports)
            if ctor is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        idx.module_locks.add(t.id)
                        if ctor == "threading.Condition":
                            idx.module_conds.add(t.id)
                            wrapped = _cond_wrapped_attr(node.value)
                            if wrapped is not None:
                                idx.module_wraps[t.id] = wrapped
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            if _resolves_to(node.value.func, imports, "guard_module"):
                gt = _parse_guard_call(node.value, skip_args=1,
                                       what="guard_module")
                if idx.module_guard is None:
                    idx.module_guard = gt
                else:
                    idx.extra_module_guards.append(gt)
        elif isinstance(node, ast.ClassDef):
            bases = tuple(b.id for b in node.bases
                          if isinstance(b, ast.Name))
            info = ClassLocks(name=node.name, node=node, bases=bases)
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                ctor = _lock_ctor(sub.value, imports)
                if ctor is None:
                    continue
                for t in sub.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        info.locks.add(t.attr)
                        if ctor == "threading.Condition":
                            info.conds.add(t.attr)
                            wrapped = _cond_wrapped_attr(sub.value)
                            if wrapped is not None:
                                info.wraps[t.attr] = wrapped
            for deco in node.decorator_list:
                if isinstance(deco, ast.Call) \
                        and _resolves_to(deco.func, imports, "guarded_by"):
                    gt = _parse_guard_call(deco, skip_args=0,
                                           what="guarded_by")
                    if info.guard is None:
                        info.guard = gt
                    else:
                        info.extra_guards.append(gt)
            idx.classes[node.name] = info
    return idx
