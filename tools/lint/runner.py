"""koordlint runner: `python -m tools.lint` — exits non-zero on any
finding not frozen in the baseline file."""

from __future__ import annotations

import argparse
import io
import os
import re
import sys
import tokenize
import weakref
from typing import List, Optional, Sequence, Tuple

import json

from tools.lint.framework import (
    Baseline,
    Finding,
    Project,
    all_analyzers,
    cached_project,
)

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json")


# `# koordlint: disable=HS006` (or `disable=CODE1,CODE2`, or an analyzer
# name) on the FINDING's own line suppresses it in place. Unlike a
# baseline entry — which freezes pre-existing debt file-wide and is kept
# EMPTY in this repo — an inline marker is a visible, reviewed statement
# at the exact site that the flagged pattern is deliberate (e.g. the
# host-tail conformance oracle in bench.py that the tail-readback
# analyzer exists to police everywhere else).
_INLINE_DISABLE_RE = re.compile(r"koordlint:\s*disable=([A-Za-z0-9_,\s-]+)")
# `# koordlint: disable-file=CODE` on a COMMENT line anywhere in the
# file suppresses that code (or analyzer) for the whole file — for
# generated files and conformance oracles where per-line markers would
# have to be repeated at every site. Still named, still reviewed: a
# bare `disable-file=` with no code disables nothing.
_FILE_DISABLE_RE = re.compile(r"koordlint:\s*disable-file=([A-Za-z0-9_,\s-]+)")


def _inline_disabled(project: Project, finding: Finding) -> bool:
    mod = project.by_relpath.get(finding.path)
    if mod is None:
        return False
    if finding.code in _file_disable_tokens(project, finding.path) \
            or finding.analyzer in _file_disable_tokens(project,
                                                        finding.path):
        return True
    if finding.line < 1:
        return False
    lines = mod.source.splitlines()
    if finding.line > len(lines):
        return False
    m = _INLINE_DISABLE_RE.search(lines[finding.line - 1])
    if not m:
        return False
    # split on commas AND whitespace: `disable=HS006 measured oracle`
    # (trailing prose after the code) must still disable HS006 rather
    # than producing an unmatchable space-containing token
    tokens = {t for t in re.split(r"[,\s]+", m.group(1)) if t}
    return finding.code in tokens or finding.analyzer in tokens


# per-Project cache of file-level disable tokens; weak keys so a
# GC'd Project can never alias a recycled id
_FILE_TOKEN_CACHE: "weakref.WeakKeyDictionary[Project, dict]" = \
    weakref.WeakKeyDictionary()


def _file_disable_tokens(project: Project, relpath: str) -> frozenset:
    """Codes/analyzer names disabled file-wide by `disable-file=`
    markers in COMMENTS. Real tokenization, not a line scan: a marker
    quoted inside a (multi-line) string literal — docs describing the
    pragma are the obvious case — must not silence anything."""
    per_file = _FILE_TOKEN_CACHE.setdefault(project, {})
    cached = per_file.get(relpath)
    if cached is not None:
        return cached
    mod = project.by_relpath.get(relpath)
    tokens: set = set()
    if mod is not None:
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(mod.source).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                m = _FILE_DISABLE_RE.search(tok.string)
                if m:
                    tokens |= {t for t in re.split(r"[,\s]+",
                                                   m.group(1)) if t}
        except (tokenize.TokenError, IndentationError):
            tokens = set()  # untokenizable: disable nothing
    out = frozenset(tokens)
    per_file[relpath] = out
    return out


def run_lint(root: str = REPO_ROOT,
             analyzers: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = None,
             ) -> Tuple[List[Finding], List[Finding]]:
    """-> (new findings, baseline-suppressed findings). Parse errors
    count as findings of the framework itself; inline
    `# koordlint: disable=<code>` markers drop findings on their line
    before the baseline split."""
    registry = all_analyzers()
    if analyzers is not None:
        unknown = [a for a in analyzers if a not in registry]
        if unknown:
            raise KeyError(f"unknown analyzers: {unknown}; "
                           f"known: {sorted(registry)}")
        selected = {name: registry[name] for name in analyzers}
    else:
        selected = registry
    # per-process cache: repeat runs (tests invoke run_lint dozens of
    # times) skip the walk+parse when no file's stat signature moved
    project = cached_project(root)
    findings: List[Finding] = list(project.parse_errors)
    for name in sorted(selected):
        findings.extend(selected[name].run(project))
    findings = [f for f in findings if not _inline_disabled(project, f)]
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    baseline = Baseline.load(baseline_path or DEFAULT_BASELINE)
    return baseline.split(findings)


def _github_escape(s: str, properties: bool = False) -> str:
    """Workflow-command escaping: %/\\r/\\n always; , and : inside
    property values (file=..., title=...)."""
    s = s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    if properties:
        s = s.replace(",", "%2C").replace(":", "%3A")
    return s


def _github_line(f: Finding) -> str:
    """One `::error` workflow command per finding — the Actions runner
    turns these into inline PR annotations at the flagged line."""
    return (f"::error file={_github_escape(f.path, properties=True)},"
            f"line={f.line},"
            f"title={_github_escape(f.code + ' [' + f.analyzer + ']', properties=True)}"
            f"::{_github_escape(f.message)}")


def _sarif_doc(new: Sequence[Finding],
               suppressed: Sequence[Finding]) -> dict:
    """SARIF 2.1.0 for code-scanning upload. Baseline-suppressed
    findings ride along with a suppression record so dashboards show
    frozen debt without failing the gate."""
    registry = all_analyzers()
    rules: dict = {}
    results = []
    for f, is_suppressed in [(f, False) for f in new] + \
                            [(f, True) for f in suppressed]:
        an = registry.get(f.analyzer)
        rules.setdefault(f.code, {
            "id": f.code,
            "name": f.analyzer,
            "shortDescription": {
                "text": an.description if an else f.analyzer},
        })
        result = {
            "ruleId": f.code,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
            "partialFingerprints": {"koordlint/v1": f.fingerprint},
        }
        if is_suppressed:
            result["suppressions"] = [{"kind": "external",
                                       "justification": "baseline"}]
        results.append(result)
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "koordlint",
                "informationUri":
                    "https://github.com/koordinator-sh/koordinator",
                "rules": sorted(rules.values(),
                                key=lambda r: r["id"]),
            }},
            "results": results,
        }],
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="koordlint: AST-based hot-path purity & concurrency "
                    "lint for the koordinator_tpu tree")
    parser.add_argument("--root", default=REPO_ROOT,
                        help="tree to analyze (default: repo root)")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline suppression file")
    parser.add_argument("--write-baseline", action="store_true",
                        help="freeze current findings into the baseline "
                             "and exit 0")
    parser.add_argument("--analyzers",
                        help="comma-separated subset to run")
    parser.add_argument("--list", action="store_true",
                        help="list analyzers and exit")
    parser.add_argument("--stamp-protos", action="store_true",
                        help="write/refresh proto content stamps into "
                             "the *_pb2.py files, then exit")
    parser.add_argument("--format", default="text",
                        choices=("text", "sarif", "github"),
                        help="finding output: human text (default), a "
                             "SARIF 2.1.0 document on stdout, or "
                             "GitHub Actions ::error annotations")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="suppress the per-finding listing")
    args = parser.parse_args(argv)

    if args.list:
        for name, an in sorted(all_analyzers().items()):
            print(f"{name:24s} {an.description}")
        return 0

    if args.stamp_protos:
        from tools.lint.analyzers.proto_drift import stamp_project
        rewritten = stamp_project(Project(args.root))
        for rel in rewritten:
            print(f"stamped {rel}")
        print(f"{len(rewritten)} pb2 file(s) updated")
        return 0

    selected = args.analyzers.split(",") if args.analyzers else None
    new, suppressed = run_lint(args.root, selected, args.baseline)

    if args.write_baseline:
        Baseline(path=args.baseline).save(new + suppressed)
        print(f"baseline: froze {len(new) + len(suppressed)} finding(s) "
              f"into {args.baseline}")
        return 0

    if args.format == "sarif":
        print(json.dumps(_sarif_doc(new, suppressed), indent=2))
        return 1 if new else 0
    if not args.quiet:
        for f in new:
            print(_github_line(f) if args.format == "github"
                  else f.render())
    tally = f"koordlint: {len(new)} finding(s)"
    if suppressed:
        tally += f", {len(suppressed)} suppressed by baseline"
    print(tally)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
