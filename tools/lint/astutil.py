"""Shared AST helpers: dotted-name resolution, per-module import tables,
and function scope indexing. Pure stdlib `ast`."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple, Union

FuncDef = Union[ast.FunctionDef, ast.AsyncFunctionDef]


def dotted_name(node: ast.AST) -> Optional[str]:
    """ "a.b.c" for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_target(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """Literal str or tuple/list of str -> tuple of str (the accepted
    forms of static_argnames)."""
    s = str_const(node)
    if s is not None:
        return (s,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            s = str_const(elt)
            if s is None:
                return None
            out.append(s)
        return tuple(out)
    return None


def int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Literal int or tuple/list of int -> tuple (donate_argnums forms)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)
                    and not isinstance(elt.value, bool)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


@dataclass
class Imports:
    """Local-alias -> fully dotted target for one module.

    `modules`:  alias -> dotted module  (import x.y as z; from p import mod)
    `symbols`:  alias -> (dotted module, symbol)  (from p.mod import f as g)

    `from p import name` is ambiguous (module or symbol); it lands in
    both tables and resolution tries modules first against the project.
    """

    modules: Dict[str, str] = field(default_factory=dict)
    symbols: Dict[str, Tuple[str, str]] = field(default_factory=dict)

    def resolve(self, dotted: str) -> str:
        """Expand the leading alias of "a.b.c" to its full target."""
        head, _, rest = dotted.partition(".")
        if head in self.modules:
            base = self.modules[head]
            return f"{base}.{rest}" if rest else base
        if head in self.symbols:
            mod, sym = self.symbols[head]
            base = f"{mod}.{sym}"
            return f"{base}.{rest}" if rest else base
        return dotted


def collect_imports(tree: ast.Module, package: str = "") -> Imports:
    """`package` is the importing module's package (for relative
    imports), e.g. "koordinator_tpu.snapshot"."""
    imp = Imports()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                alias = a.asname or a.name.split(".")[0]
                target = a.name if a.asname else a.name.split(".")[0]
                imp.modules[alias] = target
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                pkg_parts = package.split(".") if package else []
                keep = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                base = ".".join(keep + ([node.module] if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                alias = a.asname or a.name
                imp.modules.setdefault(alias, f"{base}.{a.name}"
                                       if base else a.name)
                imp.symbols[alias] = (base, a.name)
    return imp


def iter_functions(tree: ast.Module) -> Iterator[Tuple[FuncDef, List[ast.AST]]]:
    """Every function/method def with its enclosing-scope chain
    (module, classes, outer functions), depth-first."""

    def walk(node: ast.AST, chain: List[ast.AST]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, chain
                yield from walk(child, chain + [child])
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, chain + [child])
            elif isinstance(child, (ast.If, ast.Try, ast.With, ast.For,
                                    ast.While, ast.Module)):
                yield from walk(child, chain)

    yield from walk(tree, [tree])


def param_names(fn: FuncDef) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if a.vararg:
        names.append(a.vararg.arg)
    names += [p.arg for p in a.kwonlyargs]
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def positional_params(fn: FuncDef) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]
