"""jit entry-point discovery, intra-project call resolution, and the
light taint engine shared by the host-sync and recompilation analyzers.

Taint model ("traced"): values that are jax tracers inside a jitted
region. Sources are the entry's non-static parameters and any call into
the jax/jax.numpy namespace; `.shape`/`.dtype`/`.ndim`/`.size` reads and
`len()` are static regardless of receiver (jax shapes are Python values
under trace), which is what keeps the scheduler's intentional
shape-specialization idioms (`n_inst = devices0.gpu_free.shape[1]`)
clean without suppressions. Function calls resolvable inside the project
propagate taint through per-function return summaries (element-wise for
tuple returns), so `n_g, n_d = count0.shape`-style statics survive an
unpack through a helper.
"""

from __future__ import annotations

import ast
import weakref
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple, Union

from tools.lint.astutil import (
    FuncDef,
    Imports,
    call_target,
    collect_imports,
    dotted_name,
    int_tuple,
    iter_functions,
    param_names,
    positional_params,
    str_tuple,
)
from tools.lint.framework import Module, Project

# attribute reads that are static under trace even on a traced receiver
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding"})
# jax namespaces whose call results are traced values
TRACED_NAMESPACES = ("jax.numpy.", "jax.lax.", "jax.nn.", "jax.random.",
                     "jax.scipy.")
# jax control-flow combinators whose callable arguments run under trace
JAX_HOF = frozenset({
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.cond",
    "jax.lax.fori_loop", "jax.lax.switch", "jax.lax.map",
    "jax.checkpoint", "jax.remat", "jax.vmap",
})


@dataclass
class FunctionInfo:
    module: Module
    node: FuncDef
    qualname: str                 # enclosing-scope-qualified
    scope_chain: Tuple[ast.AST, ...]   # module/class/function enclosures


@dataclass
class JitEntry:
    """One jax.jit (or functools.partial(jax.jit, ...)) entry point.

    `alias_name` is set for the assignment form `g = jax.jit(f, ...)`:
    the jitted callable is bound to `g`, NOT to `f` — donation applies
    to calls through the alias, while direct `f(...)` calls stay plain.
    """

    fn: FunctionInfo
    static_argnames: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    donate_argnames: Tuple[str, ...] = ()
    decorator_line: int = 0
    alias_name: Optional[str] = None
    alias_module_relpath: Optional[str] = None

    @property
    def traced_params(self) -> FrozenSet[str]:
        donated = set(self.donate_argnames)
        pos = positional_params(self.fn.node)
        donated.update(pos[i] for i in self.donate_argnums
                       if 0 <= i < len(pos))
        # donated params are still traced; donation affects buffer reuse,
        # not tracedness
        return frozenset(p for p in param_names(self.fn.node)
                         if p not in self.static_argnames)


class ModuleIndex:
    """Per-module lookup tables: imports, every function def with scope,
    top-level functions by name, nested functions by parent."""

    def __init__(self, module: Module):
        self.module = module
        package = module.dotted.rsplit(".", 1)[0] \
            if "." in module.dotted else ""
        self.imports: Imports = collect_imports(module.tree, package)
        self.functions: List[FunctionInfo] = []
        self.top_level: Dict[str, FunctionInfo] = {}
        self.nested: Dict[ast.AST, Dict[str, FunctionInfo]] = {}
        for fn, chain in iter_functions(module.tree):
            qual = ".".join(
                [c.name for c in chain
                 if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))] + [fn.name])
            info = FunctionInfo(module, fn, qual, tuple(chain))
            self.functions.append(info)
            parent = chain[-1]
            if isinstance(parent, ast.Module):
                self.top_level[fn.name] = info
            self.nested.setdefault(parent, {})[fn.name] = info

    def resolve_dotted(self, dotted: str) -> str:
        return self.imports.resolve(dotted)


class ProjectIndex:
    """Project-wide: module indexes plus jit entry discovery. Build via
    `project_index()` so the analyzers share one index per Project."""

    def __init__(self, project: Project):
        self.project = project
        self.modules: Dict[str, ModuleIndex] = {
            m.relpath: ModuleIndex(m) for m in project.modules}
        self._partial_cache: Dict[int, Dict[str, str]] = {}
        self._entries: Optional[List[JitEntry]] = None

    def index_of(self, module: Module) -> ModuleIndex:
        return self.modules[module.relpath]

    # --- jit entries -----------------------------------------------------

    def jit_entries(self) -> List[JitEntry]:
        if self._entries is None:
            self._entries = self._discover_entries()
        return self._entries

    def _discover_entries(self) -> List[JitEntry]:
        entries: List[JitEntry] = []
        for mi in self.modules.values():
            for info in mi.functions:
                for dec in info.node.decorator_list:
                    e = self._entry_from_decorator(mi, info, dec)
                    if e is not None:
                        entries.append(e)
            # assignment form: g = jax.jit(f, static_argnames=...)
            for node in ast.walk(mi.module.tree):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                call = node.value
                if mi.resolve_dotted(call_target(call) or "") != "jax.jit":
                    continue
                if not (call.args and isinstance(call.args[0], ast.Name)):
                    continue
                target = mi.top_level.get(call.args[0].id)
                if target is None:
                    continue
                entry = self._entry_from_call(
                    mi, target, call, call.lineno)
                if len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    entry.alias_name = node.targets[0].id
                    entry.alias_module_relpath = mi.module.relpath
                entries.append(entry)
        return entries

    def _entry_from_decorator(self, mi: ModuleIndex, info: FunctionInfo,
                              dec: ast.AST) -> Optional[JitEntry]:
        if dotted_name(dec) is not None \
                and mi.resolve_dotted(dotted_name(dec)) == "jax.jit":
            return JitEntry(fn=info, decorator_line=dec.lineno)
        if not isinstance(dec, ast.Call):
            return None
        target = mi.resolve_dotted(call_target(dec) or "")
        if target == "jax.jit":
            return self._entry_from_call(mi, info, dec, dec.lineno)
        if target == "functools.partial" and dec.args:
            inner = mi.resolve_dotted(dotted_name(dec.args[0]) or "")
            if inner == "jax.jit":
                return self._entry_from_call(mi, info, dec, dec.lineno)
        return None

    @staticmethod
    def _entry_from_call(mi: ModuleIndex, info: FunctionInfo,
                         call: ast.Call, line: int) -> JitEntry:
        statics: Tuple[str, ...] = ()
        dnums: Tuple[int, ...] = ()
        dnames: Tuple[str, ...] = ()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                statics = str_tuple(kw.value) or ()
            elif kw.arg == "donate_argnums":
                dnums = int_tuple(kw.value) or ()
            elif kw.arg == "donate_argnames":
                dnames = str_tuple(kw.value) or ()
            elif kw.arg == "static_argnums":
                nums = int_tuple(kw.value) or ()
                pos = positional_params(info.node)
                statics = statics + tuple(
                    pos[i] for i in nums if 0 <= i < len(pos))
        return JitEntry(fn=info, static_argnames=statics,
                        donate_argnums=dnums, donate_argnames=dnames,
                        decorator_line=line)

    # --- call resolution -------------------------------------------------

    def resolve_call(self, mi: ModuleIndex, scope_chain: Tuple[ast.AST, ...],
                     call: ast.Call) -> Optional[FunctionInfo]:
        """Resolve a call to a FunctionInfo inside the project: local
        nested defs (inner scopes first), module top-level defs, `from m
        import f` symbols, and `mod.f` attribute calls on imported
        project modules. functools.partial aliases bound in an enclosing
        scope resolve to the partial's target."""
        dotted = call_target(call)
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        # scope-local defs and partial aliases, innermost first
        for scope in reversed(scope_chain):
            local = self.nested_defs(mi, scope).get(head)
            if local is not None and not rest:
                return local
            alias = self.partial_aliases(mi, scope).get(head)
            if alias is not None and not rest:
                return self._resolve_dotted_fn(mi, scope_chain, alias)
        return self._resolve_dotted_fn(mi, scope_chain, dotted)

    def _resolve_dotted_fn(self, mi: ModuleIndex,
                           scope_chain: Tuple[ast.AST, ...],
                           dotted: str) -> Optional[FunctionInfo]:
        head, _, rest = dotted.partition(".")
        if not rest:
            if head in mi.top_level:
                return mi.top_level[head]
            sym = mi.imports.symbols.get(head)
            if sym is not None:
                src = self.project.by_dotted.get(sym[0])
                if src is not None:
                    return self.index_of(src).top_level.get(sym[1])
            return None
        full = mi.resolve_dotted(dotted)
        mod_name, _, fn_name = full.rpartition(".")
        src = self.project.by_dotted.get(mod_name)
        if src is not None and "." not in fn_name:
            return self.index_of(src).top_level.get(fn_name)
        return None

    def nested_defs(self, mi: ModuleIndex,
                    scope: ast.AST) -> Dict[str, FunctionInfo]:
        if isinstance(scope, ast.Module):
            return mi.top_level
        return mi.nested.get(scope, {})

    def partial_aliases(self, mi: ModuleIndex,
                        scope: ast.AST) -> Dict[str, str]:
        """name -> dotted target for `name = functools.partial(tgt, ...)`
        assignments directly inside `scope`."""
        cached = self._partial_cache.get(id(scope))
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        body = getattr(scope, "body", [])
        for stmt in body:
            if not (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Call)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            if mi.resolve_dotted(call_target(stmt.value) or "") \
                    != "functools.partial" or not stmt.value.args:
                continue
            tgt = dotted_name(stmt.value.args[0])
            if tgt:
                out[stmt.targets[0].id] = tgt
        self._partial_cache[id(scope)] = out
        return out


_INDEX_CACHE: "weakref.WeakKeyDictionary[Project, ProjectIndex]" = \
    weakref.WeakKeyDictionary()


def project_index(project: Project) -> ProjectIndex:
    """One shared ProjectIndex per Project: the module indexing pass is
    the analyzers' common fixed cost, so building it per-analyzer would
    triple the CI fast path for nothing."""
    idx = _INDEX_CACHE.get(project)
    if idx is None:
        idx = ProjectIndex(project)
        _INDEX_CACHE[project] = idx
    return idx


# ---------------------------------------------------------------------------
# taint engine


Taint = Union[bool, Tuple[bool, ...]]


def _any(t: Taint) -> bool:
    return any(t) if isinstance(t, tuple) else bool(t)


@dataclass
class FunctionScan:
    """One function analyzed under a given traced-parameter set."""

    sinks: List[Tuple[ast.AST, str, str]] = field(default_factory=list)
    # (callee FunctionInfo, frozenset of traced callee params)
    calls: List[Tuple[FunctionInfo, FrozenSet[str]]] = field(
        default_factory=list)
    return_taint: Taint = True


class TaintEngine:
    """Forward single-pass taint over a function body. `sink_check`
    (optional) is called at every Call node with (call, env, engine) and
    may record findings; used by the host-sync analyzer."""

    def __init__(self, index: ProjectIndex, mi: ModuleIndex,
                 max_depth: int = 8):
        self.index = index
        self.mi = mi
        self.max_depth = max_depth
        self._summary_cache: Dict[Tuple[int, FrozenSet[str]], Taint] = {}

    # --- expression taint ------------------------------------------------

    def expr_taint(self, node: ast.AST, env: Dict[str, bool],
                   depth: int = 0) -> bool:
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return env.get(node.id, False)
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_taint(node.value, env, depth)
        if isinstance(node, ast.Call):
            return _any(self.call_taint(node, env, depth))
        if isinstance(node, ast.Subscript):
            return self.expr_taint(node.value, env, depth)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr_taint(e, env, depth) for e in node.elts)
        if isinstance(node, ast.Lambda):
            return False
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            # comprehension taint: join everything mentioned
            return any(env.get(n.id, False) for n in ast.walk(node)
                       if isinstance(n, ast.Name))
        out = False
        for child in ast.iter_child_nodes(node):
            out = out or self.expr_taint(child, env, depth)
        return out

    def call_taint(self, call: ast.Call, env: Dict[str, bool],
                   depth: int = 0) -> Taint:
        dotted = call_target(call)
        resolved = self.mi.resolve_dotted(dotted) if dotted else ""
        if resolved.startswith(TRACED_NAMESPACES) or resolved in JAX_HOF:
            return True
        if resolved in ("len", "range", "enumerate", "zip", "sorted",
                        "isinstance", "functools.partial", "repr", "str"):
            return False
        callee = self.index.resolve_call(
            self.mi, getattr(self, "_scope_chain", ()), call)
        arg_taints = [self.expr_taint(a, env, depth) for a in call.args]
        kw_taints = {kw.arg: self.expr_taint(kw.value, env, depth)
                     for kw in call.keywords if kw.arg}
        if callee is not None and depth < self.max_depth \
                and callee.module.relpath in self.index.modules:
            traced = self._bind_taint(callee, arg_taints, kw_taints)
            return self.return_summary(callee, traced, depth + 1)
        return any(arg_taints) or any(kw_taints.values())

    @staticmethod
    def _bind_taint(callee: FunctionInfo, arg_taints: List[bool],
                    kw_taints: Dict[str, bool]) -> FrozenSet[str]:
        pos = positional_params(callee.node)
        traced: Set[str] = set()
        for i, t in enumerate(arg_taints):
            if t and i < len(pos):
                traced.add(pos[i])
        for name, t in kw_taints.items():
            if t:
                traced.add(name)
        return frozenset(traced)

    # --- function summaries ----------------------------------------------

    def return_summary(self, info: FunctionInfo,
                       traced_params: FrozenSet[str],
                       depth: int) -> Taint:
        key = (id(info.node), traced_params)
        if key in self._summary_cache:
            return self._summary_cache[key]
        # optimistic placeholder breaks recursion cycles
        self._summary_cache[key] = True
        engine = TaintEngine(self.index, self.index.index_of(info.module),
                             self.max_depth)
        scan = engine.scan(info, traced_params, depth=depth)
        self._summary_cache[key] = scan.return_taint
        return scan.return_taint

    # --- statement walk --------------------------------------------------

    def scan(self, info: FunctionInfo, traced_params: FrozenSet[str],
             sink_check=None, depth: int = 0) -> FunctionScan:
        self._scope_chain = info.scope_chain + (info.node,)
        env: Dict[str, bool] = {p: (p in traced_params)
                                for p in param_names(info.node)}
        scan = FunctionScan()
        returns: List[Taint] = []
        self._walk_body(info.node.body, env, scan, returns, sink_check,
                        depth)
        scan.return_taint = _join_returns(returns)
        return scan

    def _walk_body(self, body: List[ast.stmt], env: Dict[str, bool],
                   scan: FunctionScan, returns: List[Taint],
                   sink_check, depth: int) -> None:
        for stmt in body:
            self._walk_stmt(stmt, env, scan, returns, sink_check, depth)

    def _walk_stmt(self, stmt: ast.stmt, env: Dict[str, bool],
                   scan: FunctionScan, returns: List[Taint],
                   sink_check, depth: int) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs analyzed when resolved as callees
        # record resolvable calls + run sink checks on every Call node in
        # the statement (including inside expressions)
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._visit_call(node, env, scan, sink_check, depth)
        if isinstance(stmt, ast.Assign):
            taint = self._rhs_taint(stmt.value, env, depth)
            for target in stmt.targets:
                self._bind(target, taint, env)
        elif isinstance(stmt, ast.AugAssign):
            t = self.expr_taint(stmt.value, env, depth) \
                or self.expr_taint(stmt.target, env, depth)
            self._bind(stmt.target, t, env)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._bind(stmt.target,
                       self._rhs_taint(stmt.value, env, depth), env)
        elif isinstance(stmt, ast.Return):
            if stmt.value is None:
                returns.append(False)
            else:
                returns.append(self._rhs_taint(stmt.value, env, depth))
        elif isinstance(stmt, ast.For):
            self._bind(stmt.target,
                       self.expr_taint(stmt.iter, env, depth), env)
            self._walk_body(stmt.body, env, scan, returns, sink_check,
                            depth)
            self._walk_body(stmt.orelse, env, scan, returns, sink_check,
                            depth)
        elif isinstance(stmt, (ast.If, ast.While)):
            self._walk_body(stmt.body, env, scan, returns, sink_check,
                            depth)
            self._walk_body(stmt.orelse, env, scan, returns, sink_check,
                            depth)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                if item.optional_vars is not None:
                    self._bind(item.optional_vars,
                               self.expr_taint(item.context_expr, env,
                                               depth), env)
            self._walk_body(stmt.body, env, scan, returns, sink_check,
                            depth)
        elif isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, env, scan, returns, sink_check,
                            depth)
            for h in stmt.handlers:
                self._walk_body(h.body, env, scan, returns, sink_check,
                                depth)
            self._walk_body(stmt.orelse, env, scan, returns, sink_check,
                            depth)
            self._walk_body(stmt.finalbody, env, scan, returns,
                            sink_check, depth)

    def _rhs_taint(self, value: ast.AST, env: Dict[str, bool],
                   depth: int) -> Taint:
        """Tuple RHS keeps element-wise taint for unpacking; a call RHS
        uses the callee's (possibly tuple) return summary."""
        if isinstance(value, (ast.Tuple, ast.List)):
            return tuple(self.expr_taint(e, env, depth)
                         for e in value.elts)
        if isinstance(value, ast.Call):
            return self.call_taint(value, env, depth)
        return self.expr_taint(value, env, depth)

    def _bind(self, target: ast.AST, taint: Taint,
              env: Dict[str, bool]) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = _any(taint)
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            if isinstance(taint, tuple) and len(taint) == len(elts):
                for e, t in zip(elts, taint):
                    self._bind(e, t, env)
            else:
                for e in elts:
                    self._bind(e, _any(taint), env)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, _any(taint), env)
        # attribute/subscript stores don't introduce names

    def _visit_call(self, call: ast.Call, env: Dict[str, bool],
                    scan: FunctionScan, sink_check, depth: int) -> None:
        if sink_check is not None:
            sink_check(call, env, self)
        dotted = call_target(call)
        resolved = self.mi.resolve_dotted(dotted) if dotted else ""
        if resolved in JAX_HOF:
            # callables handed to jax control flow run fully traced
            for arg in call.args:
                name = dotted_name(arg)
                if name is None or "." in name:
                    continue
                fn = None
                for scope in reversed(getattr(self, "_scope_chain", ())):
                    fn = self.index.nested_defs(self.mi, scope).get(name)
                    if fn is not None:
                        break
                if fn is not None:
                    scan.calls.append(
                        (fn, frozenset(param_names(fn.node))))
            return
        callee = self.index.resolve_call(
            self.mi, getattr(self, "_scope_chain", ()), call)
        if callee is None:
            return
        arg_taints = [self.expr_taint(a, env, depth) for a in call.args]
        kw_taints = {kw.arg: self.expr_taint(kw.value, env, depth)
                     for kw in call.keywords if kw.arg}
        scan.calls.append(
            (callee, self._bind_taint(callee, arg_taints, kw_taints)))


def _join_returns(returns: List[Taint]) -> Taint:
    if not returns:
        return False
    widths = {len(t) for t in returns if isinstance(t, tuple)}
    if len(widths) == 1 and all(isinstance(t, tuple) for t in returns):
        w = widths.pop()
        return tuple(any(t[i] for t in returns) for i in range(w))
    return any(_any(t) for t in returns)
