"""koordtrace phase-name discipline: profiler annotation labels must
come from the shared phase table (`koordinator_tpu/obs/phases.py`).

A `jax.named_scope(...)` / `jax.profiler.TraceAnnotation(...)` /
`kernel_timer(hist, ...)` label spelled as a bare string literal can
silently drift from the table the trace parsers
(tools/trace_fullgate.py, tools/trace_smoke.py) and the
`scheduler_cycle_phase_seconds{phase=...}` series join on — a renamed
constant keeps every consumer honest, a renamed literal orphans the
phase in one consumer and nobody notices until a trace stops
attributing.

The pass activates only when the scanned project contains a phase
table (any module whose relpath ends `obs/phases.py` — the fixture
roots and the tools self-lint root stay inert), mirroring the
metric-registry pass's registry gating. The table module itself is
exempt (the literals LIVE there), and Name/Attribute label arguments
are accepted unverified — the table's `check_phase` raises at runtime
on a constant that drifted.

Codes:
  OB001  bare string-literal annotation label while a shared phase
         table exists — use the obs/phases.py constant
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from tools.lint.astutil import call_target, str_const
from tools.lint.framework import Analyzer, Finding, Module, Project, register

# callables whose label argument is a trace/profiler annotation, and
# which positional slot carries it (keyword fallback in _label_node)
ANNOTATION_CALLS = {
    "named_scope": (0, "name"),
    "TraceAnnotation": (0, "name"),
    "kernel_timer": (1, "annotation"),
}


def _is_phase_table(module: Module) -> bool:
    return module.relpath.endswith("obs/phases.py")


def _label_node(call: ast.Call, pos: int, kw: str) -> Optional[ast.AST]:
    if len(call.args) > pos:
        return call.args[pos]
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    return None


@register
class TracePhasesAnalyzer(Analyzer):
    name = "trace-phases"
    description = ("bare string-literal jax.named_scope/TraceAnnotation/"
                   "kernel_timer labels while a shared obs/phases.py "
                   "table exists")

    def run(self, project: Project) -> Iterable[Finding]:
        if not any(_is_phase_table(m) for m in project.modules):
            return []
        findings: List[Finding] = []
        for module in project.modules:
            if _is_phase_table(module):
                continue
            for call in ast.walk(module.tree):
                if not isinstance(call, ast.Call):
                    continue
                target = call_target(call)
                if target is None:
                    continue
                tail = target.rsplit(".", 1)[-1]
                spec = ANNOTATION_CALLS.get(tail)
                if spec is None:
                    continue
                node = _label_node(call, *spec)
                if node is None:
                    continue
                literal = str_const(node)
                if literal is None:
                    continue
                findings.append(Finding(
                    analyzer="trace-phases", code="OB001",
                    path=module.relpath, line=node.lineno,
                    message=f"annotation label {literal!r} is a bare "
                            f"string literal; use the constant from "
                            f"the shared phase table (obs/phases.py) "
                            f"so trace parsers and the phase metric "
                            f"cannot drift",
                    key=f"bare:{tail}:{literal}"))
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))
