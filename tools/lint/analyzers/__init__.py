"""Built-in koordlint analyzers; importing this package registers every
analyzer into the framework registry."""

from tools.lint.analyzers import (  # noqa: F401
    determinism,
    donation,
    host_sync,
    lock_discipline,
    metric_names,
    pad_soundness,
    proto_drift,
    race,
    recompile,
    robustness,
    shape_contract,
    tail_readback,
    trace_phases,
)
