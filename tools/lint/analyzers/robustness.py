"""robustness: broad exception handlers around device-program calls
must route through the typed FailureClass classifier.

The bug class: `except Exception:` (or a bare `except:`) wrapped around
a jitted-kernel call swallows OOM, device-lost, and XLA-internal
failures indistinguishably — the service can neither retry transients,
degrade on OOM, nor alert on corruption, and the failure model
(errorhandler.FailureClass, docs/DESIGN.md "Failure model & degradation
ladder") silently loses coverage. A broad handler IS legitimate at
evidence-guard boundaries — but only after the exception has been
classified: referencing `classify_failure` (or `FailureClass`) in the
handler body is the visible, reviewed statement that the failure enters
the typed model.

Scope: every module except tests/ (test code legitimately catches
broadly). "Device-program call" = a call resolving — directly or
through project functions — to a `jax.jit` entry point (the same entry
discovery the host-sync analyzer uses), or through a jitted alias
(`g = jax.jit(f)`).

Code:
  RB001  bare/`except Exception` around a device-program call without
         FailureClass classification
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set, Tuple

from tools.lint.astutil import call_target, dotted_name
from tools.lint.callgraph import ProjectIndex, project_index
from tools.lint.framework import Analyzer, Finding, Module, Project, register

# names whose presence in a handler body marks the failure as routed
# through the typed model
CLASSIFIER_NAMES = frozenset({"classify_failure", "FailureClass"})
BROAD_TYPES = frozenset({"Exception", "BaseException"})


def _device_reaching(index: ProjectIndex
                     ) -> Tuple[Set[int], Set[Tuple[str, str]]]:
    """-> (ids of FunctionInfo.nodes that reach a jit entry, per-module
    jit alias names). Fixed point over project-resolvable call edges."""
    reaching: Set[int] = set()
    aliases: Set[Tuple[str, str]] = set()
    for entry in index.jit_entries():
        reaching.add(id(entry.fn.node))
        if entry.alias_name:
            aliases.add((entry.alias_module_relpath, entry.alias_name))
    changed = True
    while changed:
        changed = False
        for mi in index.modules.values():
            for info in mi.functions:
                if id(info.node) in reaching:
                    continue
                chain = info.scope_chain + (info.node,)
                for call in _calls_under(info.node):
                    if _is_device_call(index, mi, chain, call, reaching,
                                       aliases):
                        reaching.add(id(info.node))
                        changed = True
                        break
    return reaching, aliases


def _calls_under(node: ast.AST) -> Iterable[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def _is_device_call(index: ProjectIndex, mi, chain, call: ast.Call,
                    reaching: Set[int],
                    aliases: Set[Tuple[str, str]]) -> bool:
    dotted = call_target(call)
    if dotted is not None and "." not in dotted \
            and (mi.module.relpath, dotted) in aliases:
        return True
    callee = index.resolve_call(mi, chain, call)
    return callee is not None and id(callee.node) in reaching


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    for t in types:
        name = dotted_name(t)
        if name is not None and name.split(".")[-1] in BROAD_TYPES:
            return True
    return False


def _handler_classifies(handler: ast.ExceptHandler) -> bool:
    for sub in ast.walk(handler):
        if isinstance(sub, ast.Name) and sub.id in CLASSIFIER_NAMES:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in CLASSIFIER_NAMES:
            return True
    return False


@register
class RobustnessAnalyzer(Analyzer):
    name = "robustness"
    description = ("bare `except Exception`/`except:` around "
                   "device-program calls must route through the "
                   "FailureClass classifier "
                   "(errorhandler.classify_failure)")

    def run(self, project: Project) -> Iterable[Finding]:
        index = project_index(project)
        reaching, aliases = _device_reaching(index)
        findings = []
        for mod in project.modules:
            if mod.relpath.startswith("tests/"):
                continue
            mi = index.index_of(mod)
            self._walk(mod.tree, mod, mi, index, reaching, aliases,
                       (mod.tree,), findings)
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))

    def _walk(self, node: ast.AST, mod: Module, mi, index, reaching,
              aliases, chain, findings) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                self._walk(child, mod, mi, index, reaching, aliases,
                           chain + (child,), findings)
                continue
            if isinstance(child, ast.Try):
                self._check_try(child, mod, mi, index, reaching, aliases,
                                chain, findings)
            self._walk(child, mod, mi, index, reaching, aliases, chain,
                       findings)

    def _check_try(self, node: ast.Try, mod: Module, mi, index, reaching,
                   aliases, chain, findings) -> None:
        device_call = None
        for stmt in node.body:
            for call in _calls_under(stmt):
                if _is_device_call(index, mi, chain, call, reaching,
                                   aliases):
                    device_call = call_target(call) or "<call>"
                    break
            if device_call:
                break
        if device_call is None:
            return
        qual = ".".join(c.name for c in chain
                        if isinstance(c, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef))) or "<module>"
        for handler in node.handlers:
            if not _is_broad_handler(handler) \
                    or _handler_classifies(handler):
                continue
            caught = "except:" if handler.type is None else \
                f"except {ast.unparse(handler.type)}"
            findings.append(Finding(
                analyzer=self.name, code="RB001", path=mod.relpath,
                line=handler.lineno,
                message=(f"`{caught}` in `{qual}` swallows device-"
                         f"program failures from `{device_call}` "
                         f"untyped; classify them "
                         f"(errorhandler.classify_failure / "
                         f"FailureClass) so OOM, device-lost, and "
                         f"internal errors stay distinguishable to "
                         f"the retry/degradation ladder"),
                key=f"{qual}:{device_call}"))
