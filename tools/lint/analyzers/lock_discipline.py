"""lock-discipline: a static lock-order graph over threading.Lock/RLock
acquisitions, complementing the dynamic `tests/test_ingest_race.py`.

Lock identities are `module.Class.attr` for `self.X = threading.Lock()`
assignments and `module.NAME` for module-level locks. Acquisitions are
`with self.X:` / `with NAME:` blocks; ordering edges come from
syntactically nested `with` blocks and from same-module calls made while
holding a lock (closed transitively over method/function summaries).

Codes:
  LK001  lock-order cycle (potential deadlock between threads taking
         the locks in opposite orders)
  LK002  lock held across a blocking call (time.sleep, RPC/HTTP,
         subprocess, block_until_ready): every other thread needing the
         lock stalls for the full blocking latency — the informer-side
         counterpart of a host-sync stall
  LK003  manual .acquire() on a known lock — invisible to the
         with-based order analysis and leak-prone on exceptions; use a
         `with` block
  LK004  Condition.wait()/.wait_for() while holding ANOTHER lock:
         wait releases only the condition's own lock — itself, or the
         existing lock a `Condition(self._lk)` constructor wrapped —
         so every other held lock is pinned until a notify arrives: a
         stall at best, a deadlock when the notifier needs that lock
         (same-function analysis; Condition with-blocks themselves
         ride the LK001/LK002 machinery like any lock)
  LK005  file I/O (open / os.replace / fsync / pathlib writes) while
         holding a COMMIT lock (any lock whose name contains "commit",
         e.g. SchedulerService._commit_lock) outside the commit
         journal's bounded append seam: disk latency under the commit
         lock stalls every publish/ingest/schedule for the full fsync.
         The journal module (scheduler/journal.py) is the ONE
         sanctioned seam — append-before-publish must be inside the
         commit critical section, and its writes are bounded to one
         header + one int32 row block — so units defined in a
         `journal.py` are exempt; everything else must move its I/O
         outside the lock or into the journal.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.lint.astutil import call_target, collect_imports
from tools.lint.framework import Analyzer, Finding, Module, Project, register
from tools.lint.locks import (
    LOCK_CTORS,  # noqa: F401  (re-exported; fixtures/tests import it here)
    ModuleLocks,
    header_exprs as _header_exprs,
    index_module,
    short as _short,
    stmt_bodies as _bodies,
)

BLOCKING_DOTTED = {
    "time.sleep",
    "jax.block_until_ready",
    "jax.device_get",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "urllib.request.urlopen",
    "socket.create_connection",
    "requests.get", "requests.post", "requests.put", "requests.delete",
    "requests.request",
}
BLOCKING_ATTRS = {"block_until_ready", "urlopen"}

# LK005: file-I/O entry points that must not run under a commit lock
# outside the journal seam
FILE_IO_DOTTED = {
    "os.replace", "os.rename", "os.fsync", "os.remove", "os.unlink",
    "os.truncate", "os.makedirs", "os.mkdir",
    "shutil.move", "shutil.copy", "shutil.copyfile", "shutil.copytree",
    "shutil.rmtree",
}
FILE_IO_ATTRS = {"write_bytes", "write_text", "read_bytes", "read_text"}
# the sanctioned bounded append seam: units defined in a journal
# module may do file I/O under the commit lock (scheduler/journal.py)
FILE_IO_SEAM_BASENAMES = {"journal.py"}


def _is_commit_lock(lock: str) -> bool:
    return "commit" in lock.rsplit(".", 1)[-1].lower()


@dataclass
class _Unit:
    """One function/method body, with its class context (if any)."""

    module: Module
    cls: Optional[str]
    name: str
    node: ast.AST
    # direct facts
    acquires: Set[str] = field(default_factory=set)
    blocking: Set[Tuple[str, int]] = field(default_factory=set)
    # same-scope calls: method names (self.x()) or module-level names
    calls: Set[str] = field(default_factory=set)
    # (held lock, acquired lock, line) from nested withs
    edges: Set[Tuple[str, str, int]] = field(default_factory=set)
    # (held lock, callee, line) — resolved against summaries later
    held_calls: Set[Tuple[str, str, int]] = field(default_factory=set)
    # (held lock, blocking target, line)
    held_blocking: Set[Tuple[str, str, int]] = field(default_factory=set)
    manual_acquires: Set[Tuple[str, int]] = field(default_factory=set)
    # (held lock, condition waited on, line) — held != condition
    held_waits: Set[Tuple[str, str, int]] = field(default_factory=set)
    # LK005 facts: direct file-I/O targets, and (held lock, target,
    # line) while a lock was held — empty for seam-exempt modules
    file_io: Set[Tuple[str, int]] = field(default_factory=set)
    held_file_io: Set[Tuple[str, str, int]] = field(default_factory=set)

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@register
class LockDisciplineAnalyzer(Analyzer):
    name = "lock-discipline"
    description = ("lock-order cycles and locks held across blocking "
                   "calls over threading.Lock/RLock with-blocks")

    def run(self, project: Project) -> Iterable[Finding]:
        units: List[_Unit] = []
        for module in project.modules:
            units.extend(self._scan_module(module))
        # transitive closure: what a callee may acquire / block on
        summaries = _close_summaries(units)

        findings: List[Finding] = []
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for u in units:
            for held, acquired, line in u.edges:
                if held != acquired:
                    edges.setdefault((held, acquired),
                                     (u.module.relpath, line))
            for held, callee, line in u.held_calls:
                cs = summaries.get((u.module.relpath, u.cls, callee)) \
                    or summaries.get((u.module.relpath, None, callee))
                if cs is None:
                    continue
                for acq in cs[0]:
                    if acq != held:
                        edges.setdefault((held, acq),
                                         (u.module.relpath, line))
                for target in cs[1]:
                    findings.append(Finding(
                        analyzer="lock-discipline", code="LK002",
                        path=u.module.relpath, line=line,
                        message=f"`{u.qual}` holds `{_short(held)}` "
                                f"across a call to `{callee}` which may "
                                f"block on `{target}`; release the lock "
                                f"first or move the blocking work out",
                        key=f"{u.qual}:{_short(held)}:{callee}"))
                if _is_commit_lock(held):
                    for target in cs[2]:
                        findings.append(_lk005(u, held, target, line,
                                               via=callee))
            for held, target, line in u.held_file_io:
                if _is_commit_lock(held):
                    findings.append(_lk005(u, held, target, line))
            for held, target, line in u.held_blocking:
                findings.append(Finding(
                    analyzer="lock-discipline", code="LK002",
                    path=u.module.relpath, line=line,
                    message=f"`{u.qual}` holds `{_short(held)}` across "
                            f"blocking `{target}`: every thread needing "
                            f"the lock stalls for the full latency; "
                            f"snapshot state under the lock, then block "
                            f"outside it",
                    key=f"{u.qual}:{_short(held)}:{target}"))
            for held, cond, line in u.held_waits:
                findings.append(Finding(
                    analyzer="lock-discipline", code="LK004",
                    path=u.module.relpath, line=line,
                    message=f"`{u.qual}` calls `{_short(cond)}.wait()` "
                            f"while holding `{_short(held)}`: wait "
                            f"releases only the condition's own lock — "
                            f"`{_short(held)}` stays pinned until a "
                            f"notify, stalling (or deadlocking) every "
                            f"thread that needs it; release it before "
                            f"waiting",
                    key=f"{u.qual}:{_short(held)}:{_short(cond)}:wait"))
            for lock, line in u.manual_acquires:
                findings.append(Finding(
                    analyzer="lock-discipline", code="LK003",
                    path=u.module.relpath, line=line,
                    message=f"manual `.acquire()` on `{_short(lock)}` "
                            f"in `{u.qual}` escapes the static order "
                            f"analysis and leaks on exceptions; use a "
                            f"`with` block",
                    key=f"{u.qual}:{_short(lock)}:acquire"))

        findings.extend(_cycles(edges))
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))

    def _scan_module(self, module: Module) -> List[_Unit]:
        package = module.dotted.rsplit(".", 1)[0] \
            if "." in module.dotted else ""
        imports = collect_imports(module.tree, package)
        # the commit journal IS the sanctioned commit-lock file-I/O
        # seam: its units contribute no LK005 facts
        basename = module.relpath.replace("\\", "/").rsplit("/", 1)[-1]
        self._file_io_exempt = basename in FILE_IO_SEAM_BASENAMES

        # lock identities come from the SHARED index (tools/lint/locks):
        # own constructor assignments, same-module base-class
        # inheritance (Histogram's `with self._lock:` resolves to
        # `metrics._Metric._lock`), and the @guarded_by contract tables
        # resolve against the same owner walk — so the LK and GB
        # families can never disagree on what a lock IS
        idx = index_module(module)

        units: List[_Unit] = []
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                units.append(self._scan_unit(module, imports, None,
                                             node, idx))
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        units.append(self._scan_unit(
                            module, imports, node.name, sub, idx))
        return units

    def _scan_unit(self, module: Module, imports, cls: Optional[str],
                   fn, idx: ModuleLocks) -> _Unit:
        unit = _Unit(module=module, cls=cls, name=fn.name, node=fn)
        prefix = module.dotted

        def lock_id(expr: ast.AST) -> Optional[str]:
            if cls is not None and isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self":
                return idx.canonical(cls, expr.attr)
            if isinstance(expr, ast.Name):
                return idx.module_lock_id(expr.id)
            return None

        def cond_id(expr: ast.AST) -> Optional[Tuple[str, Optional[str]]]:
            """lock_id restricted to threading.Condition identities.
            Returns (id, wrapped-lock id or None): Condition(existing)
            releases the WRAPPED lock on wait, so LK004 exempts it."""
            if cls is not None and isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self":
                owner = idx.cond_owner(cls, expr.attr)
                if owner is None:
                    return None
                wrapped = idx.cond_wrapped(cls, expr.attr)
                wid = (idx.canonical(cls, wrapped)
                       or (idx.module_lock_id(wrapped)
                           if wrapped else None)) if wrapped else None
                return (f"{prefix}.{owner}.{expr.attr}", wid)
            if isinstance(expr, ast.Name) and expr.id in idx.module_conds:
                wrapped = idx.module_wraps.get(expr.id)
                return (f"{prefix}.{expr.id}",
                        f"{prefix}.{wrapped}" if wrapped else None)
            return None


        def walk(body: List[ast.stmt], held: Tuple[str, ...]) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, ast.With):
                    now = list(held)
                    for item in stmt.items:
                        lid = lock_id(item.context_expr)
                        if lid is not None:
                            unit.acquires.add(lid)
                            for h in now:
                                unit.edges.add((h, lid, stmt.lineno))
                            now.append(lid)
                        else:
                            # non-lock context managers (`with
                            # open(...)`) are calls made while the
                            # locks acquired SO FAR are held
                            self._scan_expr_calls(item.context_expr,
                                                  tuple(now), unit,
                                                  imports, lock_id,
                                                  cond_id)
                    walk(stmt.body, tuple(now))
                    continue
                subs = list(_bodies(stmt))
                if subs:
                    # compound statement: scan only its header
                    # expressions here — body calls get the right held
                    # set through the recursion
                    for header in _header_exprs(stmt):
                        self._scan_expr_calls(header, held, unit,
                                              imports, lock_id, cond_id)
                    for sub in subs:
                        walk(sub, held)
                else:
                    self._scan_expr_calls(stmt, held, unit, imports,
                                          lock_id, cond_id)

        walk(fn.body, ())
        return unit

    def _scan_expr_calls(self, root: ast.AST, held: Tuple[str, ...],
                         unit: _Unit, imports, lock_id,
                         cond_id=lambda expr: None) -> None:
        for node in ast.walk(root):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if not isinstance(node, ast.Call):
                continue
            # manual acquire (held or not)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("acquire",):
                lid = lock_id(node.func.value)
                if lid is not None:
                    unit.manual_acquires.add((lid, node.lineno))
                    continue
            # Condition wait under other held locks (LK004): wait
            # releases only the condition's OWN lock — itself, or the
            # existing lock a `Condition(self._lk)` ctor wrapped —
            # never the rest of the stack
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("wait", "wait_for"):
                got = cond_id(node.func.value)
                if got is not None:
                    cid, wrapped = got
                    for h in held:
                        if h != cid and h != wrapped:
                            unit.held_waits.add((h, cid, node.lineno))
                    continue
            target = self._blocking_target(node, imports)
            if target is not None:
                unit.blocking.add((target, node.lineno))
                # EVERY held lock stalls its waiters, not just the
                # innermost one
                for h in held:
                    unit.held_blocking.add((h, target, node.lineno))
                continue
            io_target = self._file_io_target(node, imports)
            if io_target is not None and not getattr(
                    self, "_file_io_exempt", False):
                unit.file_io.add((io_target, node.lineno))
                for h in held:
                    unit.held_file_io.add((h, io_target, node.lineno))
                continue
            callee = self._local_callee(node)
            if callee is not None:
                unit.calls.add(callee)
                for h in held:
                    unit.held_calls.add((h, callee, node.lineno))

    @staticmethod
    def _blocking_target(call: ast.Call, imports) -> Optional[str]:
        dotted = call_target(call)
        if dotted is not None:
            resolved = imports.resolve(dotted)
            if resolved in BLOCKING_DOTTED:
                return resolved
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in BLOCKING_ATTRS:
            return call.func.attr
        return None

    @staticmethod
    def _file_io_target(call: ast.Call, imports) -> Optional[str]:
        """LK005: builtin open(), the os/shutil file ops, and pathlib
        read/write methods."""
        if isinstance(call.func, ast.Name) and call.func.id == "open":
            return "open"
        dotted = call_target(call)
        if dotted is not None:
            resolved = imports.resolve(dotted)
            if resolved in FILE_IO_DOTTED:
                return resolved
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in FILE_IO_ATTRS:
            return call.func.attr
        return None

    @staticmethod
    def _local_callee(call: ast.Call) -> Optional[str]:
        """'name' for self.name(...) or bare name(...) calls."""
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
        return None


def _lk005(u: _Unit, held: str, target: str, line: int,
           via: Optional[str] = None) -> Finding:
    how = f"a call to `{via}` which reaches " if via else ""
    return Finding(
        analyzer="lock-discipline", code="LK005",
        path=u.module.relpath, line=line,
        message=f"`{u.qual}` holds commit lock `{_short(held)}` across "
                f"{how}file I/O `{target}`: disk latency under the "
                f"commit lock stalls every publish/ingest/schedule for "
                f"the full write+fsync; only the commit journal's "
                f"bounded append seam (scheduler/journal.py) may write "
                f"while committing — move the I/O outside the lock or "
                f"into the journal",
        key=f"{u.qual}:{_short(held)}:io:{target}"
            + (f":{via}" if via else ""))


def _close_summaries(units: List[_Unit]
                     ) -> Dict[Tuple[str, Optional[str], str],
                               Tuple[Set[str], Set[str], Set[str]]]:
    """(acquired locks, blocking targets, file-I/O targets) per unit,
    closed over same-module self./local calls (fixpoint)."""
    summaries = {
        (u.module.relpath, u.cls, u.name):
            (set(u.acquires), {t for t, _ in u.blocking},
             {t for t, _ in u.file_io})
        for u in units}
    changed = True
    while changed:
        changed = False
        for u in units:
            key = (u.module.relpath, u.cls, u.name)
            acq, blk, fio = summaries[key]
            for callee in u.calls:
                cs = summaries.get((u.module.relpath, u.cls, callee)) \
                    or summaries.get((u.module.relpath, None, callee))
                if cs is None:
                    continue
                if not cs[0] <= acq:
                    acq |= cs[0]
                    changed = True
                if not cs[1] <= blk:
                    blk |= cs[1]
                    changed = True
                if not cs[2] <= fio:
                    fio |= cs[2]
                    changed = True
            summaries[key] = (acq, blk, fio)
    return summaries


def _cycles(edges: Dict[Tuple[str, str], Tuple[str, int]]
            ) -> List[Finding]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    findings: List[Finding] = []
    reported: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str],
            on_path: Set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) >= 2:
                cyc = _canonical(tuple(path))
                if cyc in reported:
                    continue
                reported.add(cyc)
                a, b = path[0], path[1]
                rel, line = edges[(a, b)]
                pretty = " -> ".join(_short(x) for x in path + [path[0]])
                findings.append(Finding(
                    analyzer="lock-discipline", code="LK001",
                    path=rel, line=line,
                    message=f"lock-order cycle: {pretty}; two threads "
                            f"taking these locks in opposite order "
                            f"deadlock — pick one global order (the "
                            f"informers document commit -> view) and "
                            f"stick to it",
                    key="cycle:" + "->".join(_short(x) for x in cyc)))
            elif nxt not in on_path:
                on_path.add(nxt)
                dfs(start, nxt, path + [nxt], on_path)
                on_path.discard(nxt)

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return findings


def _canonical(cycle: Tuple[str, ...]) -> Tuple[str, ...]:
    i = cycle.index(min(cycle))
    return cycle[i:] + cycle[:i]
