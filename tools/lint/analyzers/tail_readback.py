"""tail-readback: flag blocking host syncs inside retry/tail loops on
the host side of the jit boundary.

The bug class: an adaptive straggler/retry loop that reads a device
value back EVERY iteration (`np.asarray(stats)`, `.item()`,
`jax.device_get`, `block_until_ready`). Each blocking transfer pays a
full device round-trip (~100 ms over a TPU tunnel), so a 10-pass tail
pays 10 of them — the exact pattern the device-resident compaction loop
(scheduler/core.tail_compaction_loop) deletes from bench.py. This
analyzer keeps it deleted: a host sync is fine BEFORE or AFTER such a
loop (the single stats readback), never per-iteration inside one.

Heuristic scope (syntactic, per-module): a `while`/`for` statement
counts as a retry/tail loop when the pattern ``tail|retry|straggl``
(case-insensitive) matches the enclosing function's name, a name read
in the loop condition/iterator, or a callee name inside the loop body.
Loops outside that vocabulary — ordinary data walks that materialize
arrays — are never flagged; a DELIBERATE per-pass readback (the
conformance oracle in bench host mode) carries an inline
``# koordlint: disable=HS006`` marker.

Code:
  HS006  blocking host sync inside a retry/tail loop
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from tools.lint.astutil import call_target
from tools.lint.callgraph import project_index
from tools.lint.framework import Analyzer, Finding, Module, Project, register

# vocabulary words must start at a name-segment boundary (start of the
# identifier or after a non-letter such as '_'), so `details`,
# `retailer` or `curtailed` never classify an innocent loop; snake_case
# is the repo convention, so segment starts are what we anchor on
TAIL_NAME_RE = re.compile(r"(?:^|[^A-Za-z])(?:tail|retry|straggl)",
                          re.IGNORECASE)
NUMPY_SINKS = {"numpy.asarray", "numpy.array"}
JAX_SINKS = {"jax.device_get", "jax.block_until_ready"}


def _names_under(node: ast.AST) -> Iterable[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


def _is_tail_loop(loop: ast.AST, func_names: Tuple[str, ...]) -> bool:
    """The loop vocabulary check (see module docstring)."""
    if any(TAIL_NAME_RE.search(n) for n in func_names):
        return True
    header = [loop.test] if isinstance(loop, ast.While) \
        else [loop.target, loop.iter]
    for node in header:
        if any(TAIL_NAME_RE.search(n) for n in _names_under(node)):
            return True
    for sub in ast.walk(loop):
        if isinstance(sub, ast.Call):
            target = call_target(sub)
            if target and TAIL_NAME_RE.search(target):
                return True
    return False


@register
class TailReadbackAnalyzer(Analyzer):
    name = "tail-readback"
    description = ("blocking host sync (np.asarray, .item(), device_get, "
                   "block_until_ready) inside a retry/tail loop — the "
                   "per-pass readback pattern the device-resident tail "
                   "compaction loop deletes")

    def run(self, project: Project) -> Iterable[Finding]:
        index = project_index(project)
        findings: Dict[Tuple[str, int, str], Finding] = {}
        for mod in project.modules:
            mi = index.index_of(mod)
            self._walk(mod.tree, mod, mi, (), findings)
        return sorted(findings.values(),
                      key=lambda f: (f.path, f.line, f.code))

    def _walk(self, node: ast.AST, mod: Module, mi,
              func_names: Tuple[str, ...], findings) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(child, mod, mi, func_names + (child.name,),
                           findings)
            elif isinstance(child, (ast.While, ast.For)):
                if _is_tail_loop(child, func_names):
                    self._flag_sinks(child, mod, mi, func_names, findings)
                else:
                    # nested loops/functions may still qualify
                    self._walk(child, mod, mi, func_names, findings)
            else:
                self._walk(child, mod, mi, func_names, findings)

    def _flag_sinks(self, loop: ast.AST, mod: Module, mi,
                    func_names: Tuple[str, ...], findings) -> None:
        qual = ".".join(func_names) or "<module>"
        for sub in ast.walk(loop):
            if not isinstance(sub, ast.Call):
                continue
            sink = self._sink_name(sub, mi)
            if sink is None:
                continue
            f = Finding(
                analyzer=self.name, code="HS006", path=mod.relpath,
                line=sub.lineno,
                message=(f"`{sink}` inside a retry/tail loop of `{qual}` "
                         f"blocks on a device->host transfer EVERY pass; "
                         f"keep the loop device-resident "
                         f"(core.tail_compaction_loop) and read stats back "
                         f"once after it — or mark a deliberate oracle "
                         f"with `# koordlint: disable=HS006`"),
                key=f"{qual}:{sink}")
            findings.setdefault((f.path, f.line, f.code), f)

    @staticmethod
    def _sink_name(call: ast.Call, mi) -> Optional[str]:
        if isinstance(call.func, ast.Attribute):
            if call.func.attr == "item" and not call.args:
                return ".item()"
            if call.func.attr == "block_until_ready":
                return "block_until_ready"
        dotted = call_target(call)
        resolved = mi.resolve_dotted(dotted) if dotted else ""
        if resolved in NUMPY_SINKS or resolved in JAX_SINKS:
            return dotted
        return None
