"""recompilation-hazard: jitted callables whose signatures or bodies
invite silent retracing.

Codes:
  RC001  parameter annotated/defaulted as a Python scalar, str, or dict
         but not named in static_argnames — every distinct value (str)
         or weak-type promotion (scalar) risks a retrace, and dicts
         aren't hashable as static either way
  RC002  `if`/`while` branching directly on a non-static parameter —
         a tracer has no truth value; this raises at trace time or, if
         the value is concrete, bakes the branch into the compiled
         program per value
  RC003  `if`/`while` branching on `<param>.shape` — per-shape
         specialization; intentional specialization should flow through
         a named local or a static argument so the dependence is
         explicit (the scheduler's `n_inst = ...shape[1]` idiom)
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from tools.lint.astutil import param_names
from tools.lint.callgraph import project_index, ProjectIndex
from tools.lint.framework import Analyzer, Finding, Project, register

# NOT `tuple`: a tuple-annotated parameter is an ordinary traced pytree
# (static_argnames on one would raise on unhashable arrays)
SCALAR_ANNOTATIONS = {"int", "bool", "str", "float", "dict"}
SCALAR_DEFAULTS = (int, bool, str, float)


def _scalar_annotation(node: Optional[ast.AST]) -> Optional[str]:
    """'int' for scalar-ish annotations, unwrapping Optional[...]/
    Union[...]; None when the annotation doesn't imply a Python value."""
    if node is None:
        return None
    if isinstance(node, ast.Name) and node.id in SCALAR_ANNOTATIONS:
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            return _scalar_annotation(
                ast.parse(node.value, mode="eval").body)
        except SyntaxError:
            return None
    if isinstance(node, ast.Subscript):
        base = node.value
        if isinstance(base, ast.Name) and base.id in ("Optional", "Union"):
            inner = node.slice
            elts = inner.elts if isinstance(inner, ast.Tuple) else [inner]
            for e in elts:
                s = _scalar_annotation(e)
                if s is not None:
                    return s
    return None


@register
class RecompileAnalyzer(Analyzer):
    name = "recompilation-hazard"
    description = ("jitted params taking Python scalars/strings/dicts "
                   "without static_argnames; Python branching on traced "
                   "values or parameter shapes")

    def run(self, project: Project) -> Iterable[Finding]:
        index = project_index(project)
        findings: List[Finding] = []
        for entry in index.jit_entries():
            fn = entry.fn.node
            rel = entry.fn.module.relpath
            qual = entry.fn.qualname
            statics = set(entry.static_argnames)
            findings.extend(self._check_signature(fn, rel, qual, statics))
            findings.extend(self._check_branches(
                fn, rel, qual, entry.traced_params))
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))

    @staticmethod
    def _check_signature(fn, rel: str, qual: str,
                         statics: Set[str]) -> Iterable[Finding]:
        args = fn.args
        pos = args.posonlyargs + args.args
        defaults: List[Optional[ast.AST]] = \
            [None] * (len(pos) - len(args.defaults)) + list(args.defaults)
        params = list(zip(pos, defaults)) + \
            list(zip(args.kwonlyargs, args.kw_defaults))
        for param, default in params:
            if param.arg in statics:
                continue
            why = _scalar_annotation(param.annotation)
            if why is None and isinstance(default, ast.Constant) \
                    and isinstance(default.value, SCALAR_DEFAULTS):
                why = type(default.value).__name__
            if why is None and isinstance(default, ast.Dict):
                why = "dict"
            if why is None:
                continue
            yield Finding(
                analyzer="recompilation-hazard", code="RC001",
                path=rel, line=param.lineno,
                message=f"jitted `{qual}` takes `{param.arg}` as a "
                        f"Python {why} but does not list it in "
                        f"static_argnames: each distinct value risks a "
                        f"silent retrace (strs/dicts always do)",
                key=f"{qual}:{param.arg}")

    @staticmethod
    def _check_branches(fn, rel: str, qual: str,
                        traced: frozenset) -> Iterable[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            test = node.test
            direct, shaped = _scan_test(test, traced)
            for name in sorted(shaped):
                yield Finding(
                    analyzer="recompilation-hazard", code="RC003",
                    path=rel, line=test.lineno,
                    message=f"jitted `{qual}` branches on "
                            f"`{name}.shape`: the program specializes "
                            f"per shape; bind the flag to a named local "
                            f"or a static argument to make the "
                            f"specialization explicit",
                    key=f"{qual}:shape:{name}")
            for name in sorted(direct):
                yield Finding(
                    analyzer="recompilation-hazard", code="RC002",
                    path=rel, line=test.lineno,
                    message=f"jitted `{qual}` branches on traced "
                            f"parameter `{name}`: tracers have no truth "
                            f"value — use jnp.where/lax.cond, or mark "
                            f"the parameter static",
                    key=f"{qual}:branch:{name}")


def _scan_test(test: ast.AST,
               traced: frozenset) -> Tuple[Set[str], Set[str]]:
    """Names branched on directly vs via `.shape`, limited to traced
    parameters; `.shape`/`.dtype`/len() sub-expressions don't count as
    direct branching."""
    direct: Set[str] = set()
    shaped: Set[str] = set()

    def walk(node: ast.AST, under_static: bool) -> None:
        if isinstance(node, ast.Compare) \
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops) \
                and all(isinstance(c, ast.Constant) and c.value is None
                        for c in node.comparators):
            # `param is (not) None` is a concrete Python bool under
            # trace — the standard optional-argument guard
            for child in ast.iter_child_nodes(node):
                walk(child, True)
            return
        if isinstance(node, ast.Attribute):
            if node.attr in ("shape", "ndim", "size", "dtype"):
                if isinstance(node.value, ast.Name) \
                        and node.value.id in traced:
                    shaped.add(node.value.id)
                walk(node.value, True)
                return
            walk(node.value, under_static)
            return
        if isinstance(node, ast.Call):
            fname = node.func.id if isinstance(node.func, ast.Name) else ""
            inner_static = under_static or fname == "len"
            for child in ast.iter_child_nodes(node):
                walk(child, inner_static)
            return
        if isinstance(node, ast.Name) and not under_static \
                and node.id in traced:
            direct.add(node.id)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, under_static)

    walk(test, False)
    return direct, shaped
