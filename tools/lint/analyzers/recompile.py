"""recompilation-hazard: jitted callables whose signatures or bodies
invite silent retracing.

Codes:
  RC001  parameter annotated/defaulted as a Python scalar, str, or dict
         but not named in static_argnames — every distinct value (str)
         or weak-type promotion (scalar) risks a retrace, and dicts
         aren't hashable as static either way
  RC002  `if`/`while` branching directly on a non-static parameter —
         a tracer has no truth value; this raises at trace time or, if
         the value is concrete, bakes the branch into the compiled
         program per value
  RC003  `if`/`while` branching on `<param>.shape` — per-shape
         specialization; intentional specialization should flow through
         a named local or a static argument so the dependence is
         explicit (the scheduler's `n_inst = ...shape[1]` idiom)
  RC004  a cache-busting static: a static_argnames parameter whose
         annotation/default is unhashable (list/set/dict — jit's cache
         key raises on it), or a call site feeding a static from a
         non-deterministic source (time.*/random.*/uuid.*/os.urandom)
         — every call mints a fresh cache key, so the "cached" program
         recompiles per call and a persistent compile cache can never
         hit
  RC005  a bare Python numeric literal passed as a TRACED argument at
         a jit-entry call site: the scalar enters the trace weak-typed,
         so the executable cache keys it differently from an array of
         the same value — alternating callers silently double-compile.
         Wrap it (jnp.asarray(v, dtype)) or mark the parameter static.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from tools.lint.astutil import call_target, positional_params
from tools.lint.callgraph import project_index, ProjectIndex
from tools.lint.framework import Analyzer, Finding, Project, register

# NOT `tuple`: a tuple-annotated parameter is an ordinary traced pytree
# (static_argnames on one would raise on unhashable arrays)
SCALAR_ANNOTATIONS = {"int", "bool", "str", "float", "dict"}
SCALAR_DEFAULTS = (int, bool, str, float)

# static_argnames values must be hashable; these annotations/literal
# defaults never are
UNHASHABLE_ANNOTATIONS = {"list", "set", "dict", "List", "Set", "Dict"}

# sources whose every call yields a fresh value: a static derived from
# one re-keys (and recompiles) the program per call
NONDET_MODULE_HEADS = {"time", "random", "uuid", "secrets"}
NONDET_CALLS = {"os.urandom", "os.getpid", "os.getrandom", "id"}


def _scalar_annotation(node: Optional[ast.AST]) -> Optional[str]:
    """'int' for scalar-ish annotations, unwrapping Optional[...]/
    Union[...]; None when the annotation doesn't imply a Python value."""
    if node is None:
        return None
    if isinstance(node, ast.Name) and node.id in SCALAR_ANNOTATIONS:
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            return _scalar_annotation(
                ast.parse(node.value, mode="eval").body)
        except SyntaxError:
            return None
    if isinstance(node, ast.Subscript):
        base = node.value
        if isinstance(base, ast.Name) and base.id in ("Optional", "Union"):
            inner = node.slice
            elts = inner.elts if isinstance(inner, ast.Tuple) else [inner]
            for e in elts:
                s = _scalar_annotation(e)
                if s is not None:
                    return s
    return None


@register
class RecompileAnalyzer(Analyzer):
    name = "recompilation-hazard"
    description = ("jitted params taking Python scalars/strings/dicts "
                   "without static_argnames; Python branching on traced "
                   "values or parameter shapes")

    def run(self, project: Project) -> Iterable[Finding]:
        index = project_index(project)
        findings: List[Finding] = []
        # decorator-form entries are callable by their own name; the
        # assignment form (g = jax.jit(f)) jits only calls through the
        # alias, so direct f(...) call sites are not jit dispatches
        entries = {}
        for entry in index.jit_entries():
            fn = entry.fn.node
            rel = entry.fn.module.relpath
            qual = entry.fn.qualname
            statics = set(entry.static_argnames)
            findings.extend(self._check_signature(fn, rel, qual, statics))
            findings.extend(self._check_branches(
                fn, rel, qual, entry.traced_params))
            if entry.alias_name is None:
                entries[id(fn)] = entry
        for mi in index.modules.values():
            findings.extend(self._check_call_sites(index, mi, entries))
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))

    @staticmethod
    def _check_signature(fn, rel: str, qual: str,
                         statics: Set[str]) -> Iterable[Finding]:
        args = fn.args
        pos = args.posonlyargs + args.args
        defaults: List[Optional[ast.AST]] = \
            [None] * (len(pos) - len(args.defaults)) + list(args.defaults)
        params = list(zip(pos, defaults)) + \
            list(zip(args.kwonlyargs, args.kw_defaults))
        for param, default in params:
            if param.arg in statics:
                why = _unhashable_static(param.annotation, default)
                if why is not None:
                    yield Finding(
                        analyzer="recompilation-hazard", code="RC004",
                        path=rel, line=param.lineno,
                        message=f"jitted `{qual}` marks `{param.arg}` "
                                f"static but its {why} is unhashable: "
                                f"jit's cache key requires hashable "
                                f"statics — pass a tuple/frozenset "
                                f"instead",
                        key=f"{qual}:static:{param.arg}")
                continue
            why = _scalar_annotation(param.annotation)
            if why is None and isinstance(default, ast.Constant) \
                    and isinstance(default.value, SCALAR_DEFAULTS):
                why = type(default.value).__name__
            if why is None and isinstance(default, ast.Dict):
                why = "dict"
            if why is None:
                continue
            yield Finding(
                analyzer="recompilation-hazard", code="RC001",
                path=rel, line=param.lineno,
                message=f"jitted `{qual}` takes `{param.arg}` as a "
                        f"Python {why} but does not list it in "
                        f"static_argnames: each distinct value risks a "
                        f"silent retrace (strs/dicts always do)",
                key=f"{qual}:{param.arg}")

    def _check_call_sites(self, index: ProjectIndex, mi,
                          entries) -> Iterable[Finding]:
        """RC004 (non-deterministic statics) / RC005 (weak-type scalar
        literals) at every resolvable call of a decorator-form jit
        entry. Each call site is visited exactly once: module-level
        statements with the module as scope, each function's own
        statements with its scope chain (nested defs excluded — they
        are their own FunctionInfo)."""
        scopes = [((mi.module.tree,), mi.module.tree)]
        for info in mi.functions:
            scopes.append((info.scope_chain + (info.node,), info.node))
        for chain, owner in scopes:
            for call in _own_calls(owner):
                callee = index.resolve_call(mi, chain, call)
                if callee is None:
                    continue
                entry = entries.get(id(callee.node))
                if entry is None:
                    continue
                statics = set(entry.static_argnames)
                traced = entry.traced_params
                qual = entry.fn.qualname
                for param, value in _bind_call_args(entry, call):
                    if param in statics:
                        src = _nondet_source(value)
                        if src is not None:
                            yield Finding(
                                analyzer="recompilation-hazard",
                                code="RC004", path=mi.module.relpath,
                                line=value.lineno,
                                message=f"call to jitted `{qual}` "
                                        f"derives static `{param}` "
                                        f"from non-deterministic "
                                        f"`{src}`: every call mints a "
                                        f"fresh cache key, so the "
                                        f"program recompiles per call "
                                        f"and a persistent compile "
                                        f"cache can never hit",
                                key=f"{qual}:nondet:{param}")
                    elif param in traced:
                        lit = _numeric_literal(value)
                        if lit is not None:
                            yield Finding(
                                analyzer="recompilation-hazard",
                                code="RC005", path=mi.module.relpath,
                                line=value.lineno,
                                message=f"call to jitted `{qual}` "
                                        f"passes bare Python {lit} "
                                        f"literal as traced "
                                        f"`{param}`: weak-typed "
                                        f"scalars key the executable "
                                        f"cache differently from "
                                        f"arrays of the same value — "
                                        f"wrap it (jnp.asarray(v, "
                                        f"dtype)) or mark the "
                                        f"parameter static",
                                key=f"{qual}:weak:{param}")

    @staticmethod
    def _check_branches(fn, rel: str, qual: str,
                        traced: frozenset) -> Iterable[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            test = node.test
            direct, shaped = _scan_test(test, traced)
            for name in sorted(shaped):
                yield Finding(
                    analyzer="recompilation-hazard", code="RC003",
                    path=rel, line=test.lineno,
                    message=f"jitted `{qual}` branches on "
                            f"`{name}.shape`: the program specializes "
                            f"per shape; bind the flag to a named local "
                            f"or a static argument to make the "
                            f"specialization explicit",
                    key=f"{qual}:shape:{name}")
            for name in sorted(direct):
                yield Finding(
                    analyzer="recompilation-hazard", code="RC002",
                    path=rel, line=test.lineno,
                    message=f"jitted `{qual}` branches on traced "
                            f"parameter `{name}`: tracers have no truth "
                            f"value — use jnp.where/lax.cond, or mark "
                            f"the parameter static",
                    key=f"{qual}:branch:{name}")


def _unhashable_static(annotation: Optional[ast.AST],
                       default: Optional[ast.AST]) -> Optional[str]:
    """'annotation `list`' / 'default literal' when a static parameter
    is declared or defaulted unhashable; None otherwise."""
    node = annotation
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Name) and node.id in UNHASHABLE_ANNOTATIONS:
        return f"annotation `{node.id}`"
    if isinstance(default, ast.List):
        return "default (a list literal)"
    if isinstance(default, ast.Set):
        return "default (a set literal)"
    if isinstance(default, ast.Dict):
        return "default (a dict literal)"
    return None


def _own_calls(owner: ast.AST):
    """Every ast.Call in `owner`'s own statements, NOT descending into
    nested function/lambda bodies (those are scanned as their own
    scopes)."""
    stack = list(ast.iter_child_nodes(owner))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _bind_call_args(entry, call: ast.Call):
    """(param_name, value_expr) for the call's explicit arguments
    (starred/dict-splat arguments can't be bound statically)."""
    pos = positional_params(entry.fn.node)
    bound = []
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        if i < len(pos):
            bound.append((pos[i], arg))
    for kw in call.keywords:
        if kw.arg is not None:
            bound.append((kw.arg, kw.value))
    return bound


def _nondet_source(expr: ast.AST) -> Optional[str]:
    """The dotted name of a non-deterministic call anywhere inside
    `expr` (time.monotonic(), np.random.random(), uuid.uuid4(), ...),
    or None."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        dotted = call_target(node)
        if dotted is None:
            continue
        parts = dotted.split(".")
        if parts[0] in NONDET_MODULE_HEADS \
                or (len(parts) > 1 and parts[1] == "random") \
                or dotted in NONDET_CALLS:
            return dotted
    return None


def _numeric_literal(expr: ast.AST) -> Optional[str]:
    """'int'/'float' when `expr` is a bare numeric literal (unary +/-
    included; bools excluded — a traced bool literal is the
    lax.cond-predicate idiom, not a dtype hazard)."""
    node = expr
    if isinstance(node, ast.UnaryOp) \
            and isinstance(node.op, (ast.UAdd, ast.USub)):
        node = node.operand
    if isinstance(node, ast.Constant) \
            and type(node.value) in (int, float):
        return type(node.value).__name__
    return None


def _scan_test(test: ast.AST,
               traced: frozenset) -> Tuple[Set[str], Set[str]]:
    """Names branched on directly vs via `.shape`, limited to traced
    parameters; `.shape`/`.dtype`/len() sub-expressions don't count as
    direct branching."""
    direct: Set[str] = set()
    shaped: Set[str] = set()

    def walk(node: ast.AST, under_static: bool) -> None:
        if isinstance(node, ast.Compare) \
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops) \
                and all(isinstance(c, ast.Constant) and c.value is None
                        for c in node.comparators):
            # `param is (not) None` is a concrete Python bool under
            # trace — the standard optional-argument guard
            for child in ast.iter_child_nodes(node):
                walk(child, True)
            return
        if isinstance(node, ast.Attribute):
            if node.attr in ("shape", "ndim", "size", "dtype"):
                if isinstance(node.value, ast.Name) \
                        and node.value.id in traced:
                    shaped.add(node.value.id)
                walk(node.value, True)
                return
            walk(node.value, under_static)
            return
        if isinstance(node, ast.Call):
            fname = node.func.id if isinstance(node.func, ast.Name) else ""
            inner_static = under_static or fname == "len"
            for child in ast.iter_child_nodes(node):
                walk(child, inner_static)
            return
        if isinstance(node, ast.Name) and not under_static \
                and node.id in traced:
            direct.add(node.id)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, under_static)

    walk(test, False)
    return direct, shaped
