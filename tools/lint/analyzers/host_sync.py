"""host-sync-in-jit: flag device->host synchronization reachable inside
a traced region.

A `.item()`, `float()`/`int()`/`bool()` coercion, `np.asarray`/`np.array`
of a traced value, `jax.device_get`, or `block_until_ready` inside a
jitted program either raises at trace time (scalar coercions on tracers)
or — worse for the <2s/100k-pod budget — silently forces a device
round-trip per call when the enclosing code later runs un-jitted in a
fallback path. The walk starts at every jax.jit /
functools.partial(jax.jit, ...) entry point and follows project-resolvable
calls, including callables handed to jax.lax control flow.

Codes:
  HS001  .item() on a traced value
  HS002  block_until_ready inside the traced region
  HS003  jax.device_get inside the traced region
  HS004  np.asarray/np.array of a traced value
  HS005  float()/int()/bool() coercion of a traced value
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Set, Tuple

from tools.lint.astutil import call_target, dotted_name, param_names
from tools.lint.callgraph import project_index, FunctionInfo, ProjectIndex, TaintEngine
from tools.lint.framework import Analyzer, Finding, Project, register

NUMPY_SINKS = {"numpy.asarray", "numpy.array"}
COERCIONS = {"float", "int", "bool"}


@register
class HostSyncAnalyzer(Analyzer):
    name = "host-sync-in-jit"
    description = ("host synchronization (.item, scalar coercions, "
                   "np.asarray, device_get, block_until_ready) reachable "
                   "from a jax.jit entry point")

    def run(self, project: Project) -> Iterable[Finding]:
        index = project_index(project)
        findings: Dict[Tuple[str, int, str], Finding] = {}
        # worklist of (function, traced param set); merge per function
        seen: Dict[int, Tuple[FunctionInfo, Set[str]]] = {}
        work: List[Tuple[FunctionInfo, FrozenSet[str]]] = []
        for entry in index.jit_entries():
            work.append((entry.fn, entry.traced_params))
        while work:
            info, traced = work.pop()
            prev = seen.get(id(info.node))
            if prev is not None and traced <= prev[1]:
                continue
            merged = set(traced) | (prev[1] if prev else set())
            seen[id(info.node)] = (info, merged)
            mi = index.index_of(info.module)
            engine = TaintEngine(index, mi)

            def check(call: ast.Call, env, eng,
                      info=info, mi=mi) -> None:
                f = self._check_call(call, env, eng, mi, info)
                if f is not None:
                    findings.setdefault((f.path, f.line, f.code), f)

            scan = engine.scan(info, frozenset(merged), sink_check=check)
            for callee, callee_traced in scan.calls:
                work.append((callee, callee_traced))
        return sorted(findings.values(),
                      key=lambda f: (f.path, f.line, f.code))

    @staticmethod
    def _check_call(call: ast.Call, env, engine, mi,
                    info: FunctionInfo):
        rel = info.module.relpath
        qual = info.qualname

        def finding(code: str, message: str, key_sink: str) -> Finding:
            return Finding(analyzer="host-sync-in-jit", code=code,
                           path=rel, line=call.lineno, message=message,
                           key=f"{qual}:{key_sink}")

        # attribute sinks: x.item(), x.block_until_ready()
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr == "item" and not call.args \
                    and engine.expr_taint(call.func.value, env):
                return finding(
                    "HS001",
                    f"`.item()` on a traced value inside jitted "
                    f"`{qual}` forces a device->host sync (or a trace "
                    f"error); keep the value on device or hoist the "
                    f"readback out of the jitted region", "item")
            if attr == "block_until_ready":
                return finding(
                    "HS002",
                    f"`block_until_ready` inside jitted `{qual}`: the "
                    f"traced region has no host to block; move the "
                    f"barrier to the caller", "block_until_ready")
        dotted = call_target(call)
        resolved = mi.resolve_dotted(dotted) if dotted else ""
        if resolved in ("jax.device_get", "jax.block_until_ready"):
            code = "HS003" if resolved.endswith("device_get") else "HS002"
            return finding(
                code,
                f"`{resolved}` inside jitted `{qual}` is a host sync; "
                f"return the value and fetch it at the call site",
                resolved.rsplit(".", 1)[1])
        if resolved in NUMPY_SINKS and call.args \
                and engine.expr_taint(call.args[0], env):
            return finding(
                "HS004",
                f"`{dotted}` of a traced value inside jitted `{qual}` "
                f"materializes on host mid-trace; use jnp.asarray or "
                f"keep the operand static", "np-asarray")
        if resolved in COERCIONS and len(call.args) == 1 \
                and engine.expr_taint(call.args[0], env):
            return finding(
                "HS005",
                f"`{resolved}()` coercion of a traced value inside "
                f"jitted `{qual}` raises TracerConversionError at trace "
                f"time; mark the argument static or use jnp ops",
                f"coerce-{resolved}")
        return None
