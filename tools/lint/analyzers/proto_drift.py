"""proto-drift: every checked-in `*_pb2.py` must carry a content stamp
matching its `.proto` source, so a proto edit without regeneration fails
CI instead of shipping a silently stale wire format.

Stamp line (anywhere in the pb2 file, written by
`python -m tools.lint --stamp-protos`):

    # koordlint: proto-sha256=<sha256 hex of the .proto file bytes>

Codes:
  PD001  pb2 file has no stamp
  PD002  stamp does not match the current .proto content (drift)
  PD003  pb2 file with no sibling .proto source (orphan generated code)
"""

from __future__ import annotations

import hashlib
import posixpath
import re
from typing import Iterable, List

from tools.lint.framework import Analyzer, Finding, Project, register

STAMP_RE = re.compile(
    r"^#\s*koordlint:\s*proto-sha256=([0-9a-f]{64})\s*$", re.MULTILINE)


def proto_digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def stamp_line(digest: str) -> str:
    return f"# koordlint: proto-sha256={digest}"


@register
class ProtoDriftAnalyzer(Analyzer):
    name = "proto-drift"
    description = ("checked-in *_pb2.py content stamps must match "
                   "their .proto sources")

    def run(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        protos = {p: proto_digest(project.read_bytes(p))
                  for p in project.proto_files}
        pb2_seen = set()
        for proto_rel, digest in sorted(protos.items()):
            pb2_rel = posixpath.join(
                posixpath.dirname(proto_rel),
                posixpath.basename(proto_rel)[:-len(".proto")] + "_pb2.py")
            mod = project.by_relpath.get(pb2_rel)
            if mod is None:
                # a proto without generated code is fine (e.g. docs-only
                # schema); drift needs both sides
                continue
            pb2_seen.add(pb2_rel)
            m = STAMP_RE.search(mod.source)
            if m is None:
                findings.append(Finding(
                    analyzer="proto-drift", code="PD001",
                    path=pb2_rel, line=1,
                    message=f"generated module carries no koordlint "
                            f"proto stamp for {proto_rel}; run "
                            f"`python -m tools.lint --stamp-protos` "
                            f"after regenerating",
                    key="missing-stamp"))
            elif m.group(1) != digest:
                findings.append(Finding(
                    analyzer="proto-drift", code="PD002",
                    path=pb2_rel, line=_line_of(mod.source, m.start()),
                    message=f"stamp {m.group(1)[:12]}… does not match "
                            f"{proto_rel} (now {digest[:12]}…): the "
                            f".proto changed without regenerating the "
                            f"pb2; regenerate, then re-stamp",
                    key="stale-stamp"))
        for mod in project.modules:
            if not mod.relpath.endswith("_pb2.py") \
                    or mod.relpath in pb2_seen:
                continue
            proto_rel = mod.relpath[:-len("_pb2.py")] + ".proto"
            findings.append(Finding(
                analyzer="proto-drift", code="PD003",
                path=mod.relpath, line=1,
                message=f"generated module has no sibling {proto_rel}: "
                        f"orphan generated code cannot be checked for "
                        f"drift; check in the source proto",
                key="orphan-pb2"))
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))


def _line_of(source: str, offset: int) -> int:
    return source.count("\n", 0, offset) + 1


def stamp_project(project: Project) -> List[str]:
    """Insert/refresh stamps in every pb2 with a sibling proto; returns
    the relpaths rewritten (the --stamp-protos helper)."""
    rewritten: List[str] = []
    for proto_rel in project.proto_files:
        pb2_rel = posixpath.join(
            posixpath.dirname(proto_rel),
            posixpath.basename(proto_rel)[:-len(".proto")] + "_pb2.py")
        mod = project.by_relpath.get(pb2_rel)
        if mod is None:
            continue
        digest = proto_digest(project.read_bytes(proto_rel))
        line = stamp_line(digest)
        if STAMP_RE.search(mod.source):
            new_source = STAMP_RE.sub(line, mod.source, count=1)
        else:
            lines = mod.source.splitlines(keepends=True)
            # after the leading comment block, before the first code line
            at = 0
            for i, text in enumerate(lines):
                stripped = text.strip()
                if stripped and not stripped.startswith("#"):
                    at = i
                    break
            lines.insert(at, line + "\n")
            new_source = "".join(lines)
        if new_source != mod.source:
            import os
            with open(os.path.join(project.root,
                                   pb2_rel.replace("/", os.sep)),
                      "w", encoding="utf-8") as f:
                f.write(new_source)
            rewritten.append(pb2_rel)
    return rewritten
