"""pad-soundness: the koordpad static tier — mask-provenance dataflow
over contracted kernels, stdlib-only.

Every padded capacity axis (spec.PADDED_DIMS) declares what its pad
region contains via `~pad:<predicate>` in the koordshape grammar. This
pass re-runs the symbolic shape interpreter (tools/lint/shapes/
abstract.py) with per-axis pad-fill tracking (tools/lint/shapes/
pads.py): parameter fills come from the declarations, flow through
recognized jnp ops (annihilators like `& False` / `* 0` survive
broadcasting; equal known fills combine exactly; everything else joins
to unknown and stays silent — never-guess), and three dataflow checks
plus two registry checks fire on proven violations only. The dynamic
twin — tools/padcheck.py — runs every contract concretely under two
paddings and asserts bit-identical real rows; this pass is the half
that needs no jax at all.

Codes:
  PS001  non-neutral reduction: sum/any/max/argmax/top_k/... over a
         padded axis whose pad fill would perturb the real rows'
         result (e.g. mean over zero-padded rows, sum over -1
         sentinels) — mask the pads first
  PS002  sentinel gather: indexing (take / take_along_axis / advanced
         indexing / .at updates) by an array whose padded axis carries
         the -1 'none' sentinel without clamping — jax wraps negative
         indices, so pad rows silently hit the last real row
  PS003  pad-contract drift: an argument passed to another contracted
         kernel, or a return value, whose derived pad fill contradicts
         the declared predicate (known-vs-known only)
  PS004  pad totality: a PADDED_DIMS axis in a registered struct field
         or contract spec with no ~pad: predicate — declare what the
         pad region holds so both koordpad tiers can police it
  PS005  malformed pad: a predicate on a non-padded/exempt dim or an
         int-literal dim, or a fill the declared dtype cannot carry
         (inf on i32/bool, false on non-bool, -1 on u32/bool)
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Optional, Tuple

from tools.lint.callgraph import ModuleIndex, ProjectIndex, project_index
from tools.lint.framework import Analyzer, Finding, Project, register
from tools.lint.analyzers.shape_contract import _ConstTable
from tools.lint.shapes.abstract import ShapeInterp
from tools.lint.shapes.contracts import (
    AstContract,
    ContractIndex,
    extract_contracts,
)
from tools.lint.shapes.spec import LeafSpec, PADDED_DIMS, Spec

_DEFECT_CODE = {"pad_reduce": "PS001", "pad_gather": "PS002",
                "pad_cross": "PS003"}

# predicates whose fill only some dtypes can carry; everything absent
# here (zero/one/unschedulable/invalid/any) is dtype-agnostic
_FILL_DTYPES = {
    "inf": {"f32"},
    "false": {"bool"},
    "-1": {"f32", "i32", "i8"},
}


def _leaves(spec: Optional[Spec]) -> Iterator[Tuple[int, LeafSpec]]:
    """Every LeafSpec in a spec tree, with a stable position index."""
    def walk(s, pos):
        if isinstance(s, LeafSpec):
            yield pos[0], s
            pos[0] += 1
        elif isinstance(s, tuple):
            for item in s:
                yield from walk(item, pos)
    if spec is not None:
        yield from walk(spec, [0])


@register
class PadSoundnessAnalyzer(Analyzer):
    name = "pad-soundness"
    description = ("koordpad static tier: pad/mask provenance dataflow "
                   "over contracted kernels, pad-predicate totality "
                   "and well-formedness (PS001-PS005)")

    def run(self, project: Project) -> Iterable[Finding]:
        pidx = project_index(project)
        cindex = extract_contracts(project)
        consts = _ConstTable(project, pidx)
        findings: List[Finding] = []

        findings.extend(self._registry_checks(cindex))

        for (rel, _), contract in sorted(cindex.contracts.items()):
            mi = pidx.modules.get(rel)
            if mi is None:
                continue
            findings.extend(self._interpret(pidx, mi, cindex, consts,
                                            contract))
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))

    # --- PS004 / PS005: the declarations themselves ----------------------

    def _registry_checks(self, cindex: ContractIndex
                         ) -> Iterator[Finding]:
        for sname in sorted(cindex.structs):
            rel, line = cindex.struct_sites[sname]
            for fname, spec in sorted(cindex.structs[sname].items()):
                for i, leaf in _leaves(spec):
                    yield from self._leaf_checks(
                        f"{sname}.{fname}", i, leaf, rel, line)
        for (rel, _), c in sorted(cindex.contracts.items()):
            for aname, spec in sorted(c.args.items()):
                for i, leaf in _leaves(spec):
                    yield from self._leaf_checks(
                        f"{c.name}({aname})", i, leaf, rel, c.line)
            for i, leaf in _leaves(c.returns):
                yield from self._leaf_checks(
                    f"{c.name} returns", i, leaf, rel, c.line)

    def _leaf_checks(self, owner: str, leaf_idx: int, leaf: LeafSpec,
                     rel: str, line: int) -> Iterator[Finding]:
        for ax, dim in enumerate(leaf.dims):
            pred = leaf.pad_for(ax)
            keybase = f"{owner}:{leaf_idx}:{ax}"
            if pred is None:
                if isinstance(dim, str) and dim in PADDED_DIMS:
                    yield Finding(
                        analyzer=self.name, code="PS004", path=rel,
                        line=line,
                        message=f"{owner}: padded dim `{dim}` carries "
                                f"no ~pad: predicate — declare what "
                                f"its pad region holds (PAD_VOCAB) so "
                                f"both koordpad tiers can police it",
                        key=f"{keybase}:missing-pad")
                continue
            if isinstance(dim, int):
                yield Finding(
                    analyzer=self.name, code="PS005", path=rel,
                    line=line,
                    message=f"{owner}: pad predicate `{pred}` on the "
                            f"int-literal dim {dim} — literal extents "
                            f"are exact, never padded",
                    key=f"{keybase}:literal-pad")
            elif dim not in PADDED_DIMS:
                yield Finding(
                    analyzer=self.name, code="PS005", path=rel,
                    line=line,
                    message=f"{owner}: pad predicate `{pred}` on "
                            f"`{dim}`, which is not a padded capacity "
                            f"(spec.PADDED_DIMS) — exempt dims are "
                            f"sized exactly",
                    key=f"{keybase}:exempt-pad")
            allowed = _FILL_DTYPES.get(pred)
            if allowed is not None and leaf.dtype not in allowed:
                yield Finding(
                    analyzer=self.name, code="PS005", path=rel,
                    line=line,
                    message=f"{owner}: pad predicate `{pred}` is "
                            f"unrepresentable in dtype "
                            f"`{leaf.dtype}` (allowed: "
                            f"{sorted(allowed)})",
                    key=f"{keybase}:dtype-pad")

    # --- PS001-PS003: the dataflow per contract --------------------------

    def _interpret(self, pidx: ProjectIndex, mi: ModuleIndex,
                   cindex: ContractIndex, consts: _ConstTable,
                   contract: AstContract) -> Iterable[Finding]:
        info = None
        for fi in mi.functions:
            if fi.node is contract.fn_node:
                info = fi
                break
        if info is None:
            return []
        scope = info.scope_chain + (info.node,)

        def resolve_contract(call: ast.Call) -> Optional[AstContract]:
            target = pidx.resolve_call(mi, scope, call)
            if target is None:
                return None
            c = cindex.contract_for(target.module.relpath,
                                    target.node.name)
            if c is contract:
                return None
            return c

        interp = ShapeInterp(
            contract,
            resolve_dotted=mi.resolve_dotted,
            resolve_const=consts.resolve,
            resolve_contract=resolve_contract,
            struct_field=lambda s, f: cindex.structs.get(s, {}).get(f),
            track_pads=True,
        )
        out: List[Finding] = []
        for d in interp.run():
            code = _DEFECT_CODE.get(d.kind)
            if code is None:
                continue      # shape defects belong to shape-contract
            out.append(Finding(
                analyzer=self.name, code=code,
                path=contract.relpath, line=d.line,
                message=f"`{contract.name}`: {d.detail}", key=d.key))
        return out
