"""race-guard: static enforcement of the `@guarded_by` concurrency
contracts (koordinator_tpu/utils/sync.py) — the Tier-A half of
koordrace, paired with the dynamic interleaving gate in
tools/racecheck.py.

A contract says which lock guards each mutable attribute; this analyzer
proves the code practices what it declares. Like every rung of the
contract ladder it NEVER GUESSES: an access whose lock context cannot
be resolved syntactically (an unresolvable context manager on the with
stack, a helper reachable through an unknown call path) joins "unknown"
and reports nothing. Only lock-attribute guards are enforced at access
sites; the `publish-once` / `confined` / `racy-monitor` / `external:`
vocabulary declares a discipline the static tier cannot see the edges
of, so its value is the declaration itself plus the GB004/GB005 checks
that keep the table honest — and the dynamic tier, which drives the
real interleavings.

Codes:
  GB001  guarded attribute read/written outside its declared lock: the
         access races every `with`-guarded access of the same
         attribute. Private helpers inherit the INTERSECTION of the
         lock sets held at their intra-class call sites (a meet, so
         one unguarded call site voids the inheritance); helpers
         reachable only from `__init__` (or not at all from inside the
         class) are exempt — construction precedes sharing.
  GB002  check-then-act: a guarded read in one `with` block and a
         dependent write of the same attribute under a RE-ACQUIRED
         lock in a later block of the same function. Between the two
         blocks another thread can act on the stale read (lost
         update). Exempt when some OTHER lock spans both blocks (the
         SnapshotStore.checkpoint pattern: `_ck_lock` held across two
         `_lock` windows).
  GB003  guarded mutable state escaping its lock scope: `return self.x`
         / `yield self.x` of an attribute the constructor binds to a
         mutable container hands the caller a live reference that the
         lock no longer covers; return a copy (`list(...)`,
         `dict(...)`, a slice) instead.
  GB004  declared-vs-actual drift and totality: a lock-owning class (or
         module) with no guarded-by contract; a contract guard naming a
         lock attribute no constructor assigns; a guard lock that no
         `with` block in the module ever acquires.
  GB005  malformed contract: non-literal or ** tables, guards outside
         the sync.py grammar, duplicate entries or decorations, empty
         tables. The static mirror of sync._validate.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from tools.lint.astutil import call_target
from tools.lint.framework import Analyzer, Finding, Module, Project, register
from tools.lint.locks import (
    ClassLocks,
    ModuleLocks,
    guard_kind,
    header_exprs,
    index_module,
    short,
    stmt_bodies,
)

# sentinel member of a held set: "something unresolvable is held here",
# which disables reporting (never-guess) without granting any guard
UNKNOWN = "<unknown>"

INIT_NAMES = ("__init__", "__post_init__")

# constructors whose result is a shared mutable container: returning
# the bare attribute leaks a reference the lock no longer covers
MUTABLE_CTORS = {
    "list", "dict", "set", "bytearray",
    "collections.defaultdict", "collections.OrderedDict",
    "collections.Counter", "collections.deque",
}

# held state: ((lock id, lineno of the acquiring `with`), ...) — the
# with line distinguishes re-acquisition (GB002) from one hold
_Held = Tuple[Tuple[str, int], ...]


def _ids(held: _Held) -> FrozenSet[str]:
    return frozenset(l for l, _ in held)


@dataclass
class _Scan:
    """Lock-relevant facts of one function/method body."""

    name: str
    accesses: List[Tuple[str, str, int, _Held]] = field(
        default_factory=list)               # attr/name, kind, line, held
    calls: List[Tuple[str, FrozenSet[str], int]] = field(
        default_factory=list)               # callee, held ids, line
    acquired: Set[str] = field(default_factory=set)
    escapes: List[Tuple[str, int]] = field(default_factory=list)


def _bare_self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _scan_callable(fn, lock_id, self_mode: bool,
                   names: Optional[Set[str]] = None) -> _Scan:
    """Walk one def: attribute (or module-name) accesses with the held
    lock stack at each, intra-scope calls, acquisitions, and bare
    return/yield escapes. `lock_id(expr)` resolves a with-item to a
    canonical lock id or None."""
    scan = _Scan(name=fn.name)
    watched = names or set()

    def visit_expr(root: ast.AST, held: _Held) -> None:
        held_ids = _ids(held)

        def rec(node: ast.AST) -> None:
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                return  # deferred execution: lock context unknowable
            if isinstance(node, ast.Call):
                f = node.func
                callee = None
                if self_mode and isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self":
                    callee = f.attr
                elif not self_mode and isinstance(f, ast.Name):
                    callee = f.id
                if callee is not None:
                    scan.calls.append((callee, held_ids, node.lineno))
                else:
                    rec(f)
                for a in node.args:
                    rec(a)
                for kw in node.keywords:
                    rec(kw.value)
                return
            if isinstance(node, ast.Yield) and node.value is not None:
                a = _bare_self_attr(node.value) if self_mode else None
                if a is not None:
                    scan.escapes.append((a, node.lineno))
            if self_mode:
                a = _bare_self_attr(node)
                if a is not None:
                    kind = "write" if isinstance(
                        node.ctx, (ast.Store, ast.Del)) else "read"
                    scan.accesses.append((a, kind, node.lineno, held))
                    return
            elif isinstance(node, ast.Name):
                if node.id in watched:
                    kind = "write" if isinstance(
                        node.ctx, (ast.Store, ast.Del)) else "read"
                    scan.accesses.append((node.id, kind, node.lineno,
                                          held))
                return
            for child in ast.iter_child_nodes(node):
                rec(child)

        rec(root)

    def walk(body: List[ast.stmt], held: _Held) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                now = list(held)
                for item in stmt.items:
                    lid = lock_id(item.context_expr)
                    if lid is None:
                        # a non-lock / unresolvable context manager:
                        # evaluate its expression under the locks so
                        # far, then poison the inner scope — never
                        # guess what an unknown CM synchronizes
                        visit_expr(item.context_expr, tuple(now))
                        now.append((UNKNOWN, stmt.lineno))
                    else:
                        scan.acquired.add(lid)
                        now.append((lid, stmt.lineno))
                walk(stmt.body, tuple(now))
                continue
            if isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    a = _bare_self_attr(stmt.value) if self_mode else None
                    if a is not None:
                        scan.escapes.append((a, stmt.lineno))
                    visit_expr(stmt.value, held)
                continue
            subs = list(stmt_bodies(stmt))
            if subs:
                for header in header_exprs(stmt):
                    visit_expr(header, held)
                for sub in subs:
                    walk(sub, held)
            else:
                visit_expr(stmt, held)

    walk(fn.body, ())
    return scan


def _entry_fixpoint(scans: List[_Scan]) -> Dict[str, Optional[FrozenSet[str]]]:
    """Entry-held lock set per method name. Public methods start (and
    stay) empty — any caller may enter them bare. Private helpers start
    at TOP (None: assume guarded) and take the meet over their
    intra-class call sites from non-`__init__` methods; a site whose
    caller is itself TOP, or whose held set contains UNKNOWN,
    contributes nothing (never-guess). No surviving site leaves the
    helper at TOP: reachable only from construction, or not from
    inside the class at all — both exempt."""
    entry: Dict[str, Optional[FrozenSet[str]]] = {}
    for s in scans:
        if s.name in entry:
            continue
        private = s.name.startswith("_") and not s.name.startswith("__")
        entry[s.name] = None if private else frozenset()
    changed = True
    while changed:
        changed = False
        for target, cur in list(entry.items()):
            if not (target.startswith("_")
                    and not target.startswith("__")):
                continue
            sites: List[FrozenSet[str]] = []
            for caller in scans:
                if caller.name in INIT_NAMES:
                    continue
                ce = entry.get(caller.name)
                if ce is None:
                    continue
                for callee, held_ids, _line in caller.calls:
                    if callee != target or UNKNOWN in held_ids:
                        continue
                    sites.append(ce | held_ids)
            new = None if not sites else frozenset.intersection(*sites)
            if new != cur:
                entry[target] = new
                changed = True
    return entry


def _mutable_init_attrs(info: ClassLocks, idx: ModuleLocks) -> Set[str]:
    out: Set[str] = set()
    for node in info.node.body:
        if isinstance(node, ast.FunctionDef) and node.name in INIT_NAMES:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                for t in sub.targets:
                    a = _bare_self_attr(t)
                    if a is not None and _is_mutable_ctor(sub.value, idx):
                        out.add(a)
    return out


def _is_mutable_ctor(node: ast.AST, idx: ModuleLocks) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        t = call_target(node)
        if t is None:
            return False
        return idx.imports.resolve(t) in MUTABLE_CTORS
    return False


@register
class RaceGuardAnalyzer(Analyzer):
    name = "race-guard"
    description = ("guarded-by contract enforcement: accesses outside "
                   "the declared lock, check-then-act windows, "
                   "lock-scope escapes, and contract/code drift")

    def run(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            idx = index_module(module)
            interesting = (idx.module_locks or idx.module_guard
                           or idx.extra_module_guards
                           or any(c.locks or c.guard or c.extra_guards
                                  for c in idx.classes.values()))
            if not interesting:
                continue
            self._check_module(idx, findings)
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))

    # ------------------------------------------------------------------

    def _check_module(self, idx: ModuleLocks,
                      findings: List[Finding]) -> None:
        module = idx.module

        def make_lock_id(cls: Optional[str]):
            def lock_id(expr: ast.AST) -> Optional[str]:
                if cls is not None:
                    a = _bare_self_attr(expr)
                    if a is not None:
                        return idx.canonical(cls, a)
                if isinstance(expr, ast.Name):
                    return idx.module_lock_id(expr.id)
                return None
            return lock_id

        # scan every class + module-level function once; acquisitions
        # feed the GB004 dead-guard check module-wide
        class_scans: Dict[str, List[_Scan]] = {}
        for name, info in idx.classes.items():
            lock_id = make_lock_id(name)
            class_scans[name] = [
                _scan_callable(n, lock_id, self_mode=True)
                for n in info.node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        guarded_names = set()
        if idx.module_guard is not None:
            guarded_names = set(idx.module_guard.table)
        mod_lock_id = make_lock_id(None)
        module_scans = [
            _scan_callable(n, mod_lock_id, self_mode=False,
                           names=guarded_names)
            for n in module.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        all_acquired: Set[str] = set()
        for scans in class_scans.values():
            for s in scans:
                all_acquired |= s.acquired
        for s in module_scans:
            all_acquired |= s.acquired

        for name, info in idx.classes.items():
            self._check_class(idx, info, class_scans[name], all_acquired,
                              findings)
        self._check_module_guard(idx, module_scans, all_acquired,
                                 findings)

    # ------------------------------------------------------------------

    def _check_class(self, idx: ModuleLocks, info: ClassLocks,
                     scans: List[_Scan], all_acquired: Set[str],
                     findings: List[Finding]) -> None:
        relpath = idx.module.relpath
        cls = info.name

        def emit(code: str, line: int, message: str, key: str) -> None:
            findings.append(Finding(
                analyzer=self.name, code=code, path=relpath, line=line,
                message=message, key=key))

        for gt in ([info.guard] if info.guard else []) + info.extra_guards:
            for line, slug, reason in gt.malformed:
                emit("GB005", line,
                     f"malformed guarded-by contract on `{cls}`: "
                     f"{reason}", f"{cls}:{slug}")
        for gt in info.extra_guards:
            emit("GB005", gt.line,
                 f"`{cls}` is decorated with guarded_by more than "
                 f"once; merge the tables — one class, one contract",
                 f"{cls}:duplicate-decoration")

        if info.guard is None:
            if info.locks:
                owned = ", ".join(sorted(info.locks))
                emit("GB004", info.node.lineno,
                     f"`{cls}` constructs lock(s) ({owned}) but "
                     f"declares no @guarded_by contract; every "
                     f"lock-owning class must say which attributes "
                     f"each lock guards (koordinator_tpu/utils/"
                     f"sync.py)", f"{cls}:contract-missing")
            return

        gt = info.guard
        # classify: attr -> (guard attr, canonical lock id)
        lock_guards: Dict[str, Tuple[str, str]] = {}
        bad_guards: Set[str] = set()
        for attr, guard in gt.table.items():
            if guard_kind(guard) != "lock":
                continue
            canon = idx.canonical(cls, guard)
            if canon is None:
                if guard not in bad_guards:
                    bad_guards.add(guard)
                    emit("GB004", gt.line,
                         f"`{cls}` contract guards attributes with "
                         f"`{guard}` but no `self.{guard} = "
                         f"threading.Lock()` exists in the class or "
                         f"its bases — the declaration drifted from "
                         f"the code", f"{cls}:{guard}:guard-unresolved")
                continue
            lock_guards[attr] = (guard, canon)
        for guard, canon in sorted({v for v in lock_guards.values()}):
            if canon not in all_acquired:
                emit("GB004", gt.line,
                     f"`{cls}` contract names guard `{guard}` but no "
                     f"`with self.{guard}:` in this module ever "
                     f"acquires it — the declared discipline is not "
                     f"practiced", f"{cls}:{guard}:guard-dead")

        entry = _entry_fixpoint(scans)
        mutable_attrs = _mutable_init_attrs(info, idx)
        seen: Set[str] = set()

        for scan in scans:
            if scan.name in INIT_NAMES:
                continue
            e = entry.get(scan.name)
            if e is None:
                continue  # helper reachable only via construction
            # GB001
            for attr, kind, line, held in scan.accesses:
                g = lock_guards.get(attr)
                if g is None:
                    continue
                guard_attr, canon = g
                held_ids = _ids(held)
                if UNKNOWN in held_ids:
                    continue
                if canon in held_ids or canon in e:
                    continue
                key = f"{cls}.{scan.name}:{attr}:{kind}"
                if key in seen:
                    continue
                seen.add(key)
                verb = "writes" if kind == "write" else "reads"
                emit("GB001", line,
                     f"`{cls}.{scan.name}` {verb} `self.{attr}` "
                     f"outside its declared guard `{short(canon)}`: "
                     f"wrap the access in `with self.{guard_attr}:` "
                     f"(or amend the contract if the discipline "
                     f"changed)", key)
            # GB002
            for attr, (guard_attr, canon) in lock_guards.items():
                if canon in e:
                    continue  # lock spans the whole body via entry
                key = f"{cls}.{scan.name}:{attr}:check-then-act"
                if key in seen:
                    continue
                pair = _check_then_act(scan, attr, canon, e)
                if pair is None:
                    continue
                seen.add(key)
                rl, wl = pair
                emit("GB002", wl,
                     f"`{cls}.{scan.name}` reads `self.{attr}` under "
                     f"`{short(canon)}` (line {rl}), releases it, then "
                     f"writes `self.{attr}` under a re-acquired "
                     f"`{short(canon)}`: another thread can act on "
                     f"the stale read in between (lost update) — do "
                     f"the read-check-write in ONE critical section, "
                     f"or hold a spanning lock across both", key)
            # GB003
            for attr, line in scan.escapes:
                g = lock_guards.get(attr)
                if g is None or attr not in mutable_attrs:
                    continue
                guard_attr, canon = g
                key = f"{cls}.{scan.name}:{attr}:escape"
                if key in seen:
                    continue
                seen.add(key)
                emit("GB003", line,
                     f"`{cls}.{scan.name}` returns `self.{attr}` — a "
                     f"live reference to mutable state guarded by "
                     f"`{short(canon)}` escapes its lock scope; hand "
                     f"out a copy (`list(...)`, `dict(...)`, a slice) "
                     f"so callers cannot race the guarded mutations",
                     key)

    # ------------------------------------------------------------------

    def _check_module_guard(self, idx: ModuleLocks,
                            scans: List[_Scan], all_acquired: Set[str],
                            findings: List[Finding]) -> None:
        module = idx.module
        relpath = module.relpath

        def emit(code: str, line: int, message: str, key: str) -> None:
            findings.append(Finding(
                analyzer=self.name, code=code, path=relpath, line=line,
                message=message, key=key))

        for gt in (([idx.module_guard] if idx.module_guard else [])
                   + idx.extra_module_guards):
            for line, slug, reason in gt.malformed:
                emit("GB005", line,
                     f"malformed guard_module contract: {reason}",
                     f"<module>:{slug}")
        for gt in idx.extra_module_guards:
            emit("GB005", gt.line,
                 "guard_module called more than once for this module; "
                 "merge the tables", "<module>:duplicate-guard-module")

        if idx.module_guard is None:
            if idx.module_locks:
                line = _first_module_lock_line(idx)
                owned = ", ".join(sorted(idx.module_locks))
                emit("GB004", line,
                     f"module-level lock(s) ({owned}) but no "
                     f"guard_module(...) contract; declare which "
                     f"globals each lock guards (koordinator_tpu/"
                     f"utils/sync.py)", "<module>:contract-missing")
            return

        gt = idx.module_guard
        lock_guards: Dict[str, Tuple[str, str]] = {}
        bad_guards: Set[str] = set()
        for name, guard in gt.table.items():
            if guard_kind(guard) != "lock":
                continue
            canon = idx.module_lock_id(guard)
            if canon is None:
                if guard not in bad_guards:
                    bad_guards.add(guard)
                    emit("GB004", gt.line,
                         f"guard_module names `{guard}` but no "
                         f"module-level `{guard} = threading.Lock()` "
                         f"exists — the declaration drifted from the "
                         f"code", f"<module>:{guard}:guard-unresolved")
                continue
            lock_guards[name] = (guard, canon)
        for guard, canon in sorted({v for v in lock_guards.values()}):
            if canon not in all_acquired:
                emit("GB004", gt.line,
                     f"guard_module names `{guard}` but no `with "
                     f"{guard}:` in this module ever acquires it",
                     f"<module>:{guard}:guard-dead")

        entry = _entry_fixpoint(scans)
        seen: Set[str] = set()
        for scan in scans:
            e = entry.get(scan.name)
            if e is None:
                continue
            for name, kind, line, held in scan.accesses:
                g = lock_guards.get(name)
                if g is None:
                    continue
                guard_name, canon = g
                held_ids = _ids(held)
                if UNKNOWN in held_ids:
                    continue
                if canon in held_ids or canon in e:
                    continue
                key = f"{scan.name}:{name}:{kind}"
                if key in seen:
                    continue
                seen.add(key)
                verb = "writes" if kind == "write" else "reads"
                emit("GB001", line,
                     f"`{scan.name}` {verb} module global `{name}` "
                     f"outside its declared guard `{guard_name}`: "
                     f"wrap the access in `with {guard_name}:`", key)


def _check_then_act(scan: _Scan, attr: str, canon: str,
                    entry: FrozenSet[str]) -> Optional[Tuple[int, int]]:
    """(read line, write line) of the first GB002 pair for `attr`, or
    None. Pairs a guarded read with a LATER guarded write whose
    acquiring `with` is a different statement, unless some other lock
    (or an entry-held lock) spans both windows."""
    reads: List[Tuple[int, _Held]] = []
    writes: List[Tuple[int, _Held]] = []
    for a, kind, line, held in scan.accesses:
        if a != attr:
            continue
        ids = _ids(held)
        if canon not in ids or UNKNOWN in ids:
            continue
        (writes if kind == "write" else reads).append((line, held))
    for rl, rh in reads:
        r_with = _with_line(rh, canon)
        for wl, wh in writes:
            if wl <= rl:
                continue
            if _with_line(wh, canon) == r_with:
                continue
            common = ((_ids(rh) | entry) & (_ids(wh) | entry)) \
                - {canon, UNKNOWN}
            if common:
                continue
            return rl, wl
    return None


def _with_line(held: _Held, lock: str) -> int:
    line = -1
    for lid, wl in held:
        if lid == lock:
            line = wl  # innermost (re-entrant) acquisition wins
    return line


def _first_module_lock_line(idx: ModuleLocks) -> int:
    for node in idx.module.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in idx.module_locks:
                    return node.lineno
    return 1
