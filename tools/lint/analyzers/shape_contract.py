"""shape-contract: the koordshape static tier — kernel shape/dtype
contracts checked against the code, stdlib-only.

Every jitted entry point declares its tensor contract via the
`@shape_contract` decorator registry (koordinator_tpu/snapshot/schema.py);
this pass reads the declarations straight from the AST
(tools/lint/shapes/contracts.py), binds each contracted function's
parameters to their declared symbolic dims, and abstractly interprets
the body (tools/lint/shapes/abstract.py). The dynamic twin —
tools/shapecheck.py — drives jax.eval_shape over the same registry in
CI; this pass is the half that needs no jax at all.

Codes:
  SH001  dim-symbol mismatch: two distinct contract dims forced equal
         by a broadcast / concatenate / matmul contraction /
         take_along_axis, or a return value disagreeing with the
         function's own declared dims
  SH002  undeclared broadcast: implicit rank growth between non-scalar
         operands — add [None] / jnp.broadcast_to so promoted axes are
         visible in the code
  SH003  cross-kernel contract drift: an argument passed to another
         CONTRACTED kernel disagreeing with the callee's declared spec,
         or one struct registered twice with different field tables
  SH004  a module-level jax.jit entry point with no @shape_contract
         (test trees exempt; nested jit closures in drivers exempt)
  SH005  malformed contract declaration: unparsable spec, undeclared
         dim symbol, or a spec for a parameter the function lacks
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple, Union

from tools.lint.astutil import dotted_name
from tools.lint.callgraph import (
    FunctionInfo,
    ModuleIndex,
    ProjectIndex,
    project_index,
)
from tools.lint.framework import Analyzer, Finding, Project, register
from tools.lint.shapes.abstract import (
    Defect,
    IntVal,
    ScalarVal,
    ShapeInterp,
    Val,
)
from tools.lint.shapes.contracts import (
    AstContract,
    ContractIndex,
    extract_contracts,
)

_DEFECT_CODE = {"conflict": "SH001", "rank_growth": "SH002",
                "cross": "SH003"}


@register
class ShapeContractAnalyzer(Analyzer):
    name = "shape-contract"
    description = ("kernel shape/dtype contracts: declared-dim abstract "
                   "interpretation, cross-kernel drift, uncontracted "
                   "jit entry points")

    def run(self, project: Project) -> Iterable[Finding]:
        pidx = project_index(project)
        cindex = extract_contracts(project)
        consts = _ConstTable(project, pidx)
        findings: List[Finding] = []

        for p in cindex.problems:
            findings.append(Finding(
                analyzer=self.name, code="SH005", path=p.relpath,
                line=p.line, message=p.message, key=p.key))
        for p in cindex.struct_drift:
            findings.append(Finding(
                analyzer=self.name, code="SH003", path=p.relpath,
                line=p.line, message=p.message, key=p.key))

        findings.extend(self._uncontracted_jits(pidx, cindex))

        for (rel, _), contract in sorted(cindex.contracts.items()):
            mi = pidx.modules.get(rel)
            if mi is None:
                continue
            findings.extend(self._interpret(pidx, mi, cindex, consts,
                                            contract))
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))

    # --- SH004 -----------------------------------------------------------

    def _uncontracted_jits(self, pidx: ProjectIndex,
                           cindex: ContractIndex) -> Iterable[Finding]:
        for entry in pidx.jit_entries():
            info = entry.fn
            rel = info.module.relpath
            if rel.startswith("tests/"):
                continue  # test helpers aren't kernel entry points
            if not isinstance(info.scope_chain[-1], ast.Module):
                continue  # nested driver closures (bench sweeps)
            if cindex.contract_for(rel, info.node.name) is not None:
                continue
            yield Finding(
                analyzer=self.name, code="SH004", path=rel,
                line=entry.decorator_line,
                message=f"jitted entry point `{info.qualname}` has no "
                        f"@shape_contract: declare its dims/dtypes in "
                        f"the schema registry so koordshape (both "
                        f"tiers) can police it",
                key=f"{info.qualname}:no-contract")

    # --- the abstract interpretation per contract ------------------------

    def _interpret(self, pidx: ProjectIndex, mi: ModuleIndex,
                   cindex: ContractIndex, consts: "_ConstTable",
                   contract: AstContract) -> Iterable[Finding]:
        info = None
        for fi in mi.functions:
            if fi.node is contract.fn_node:
                info = fi
                break
        if info is None:
            return []
        scope = info.scope_chain + (info.node,)

        def resolve_contract(call: ast.Call) -> Optional[AstContract]:
            target = pidx.resolve_call(mi, scope, call)
            if target is None:
                return None
            c = cindex.contract_for(target.module.relpath,
                                    target.node.name)
            # a contract never cross-checks against itself (recursion)
            if c is contract:
                return None
            return c

        interp = ShapeInterp(
            contract,
            resolve_dotted=mi.resolve_dotted,
            resolve_const=consts.resolve,
            resolve_contract=resolve_contract,
            struct_field=lambda s, f: cindex.structs.get(s, {}).get(f),
        )
        out: List[Finding] = []
        for d in interp.run():
            out.append(Finding(
                analyzer=self.name, code=_DEFECT_CODE[d.kind],
                path=contract.relpath, line=d.line,
                message=f"`{contract.name}`: {d.detail}", key=d.key))
        return out


class _ConstTable:
    """module-level numeric constants, resolvable as
    'pkg.module.NAME' — EPS, MAX_NODE_SCORE, POLICY_NONE and friends.
    `NAME = int(...)`/`len(...)` records a scalar of unknown value, so
    resource-kind column indices still drop axes cleanly."""

    _SCALAR_CALLS = {"int", "len", "float"}

    def __init__(self, project: Project, pidx: ProjectIndex):
        self._by_module: Dict[str, Dict[str, Val]] = {}
        for m in project.modules:
            table: Dict[str, Val] = {}
            for node in m.tree.body:
                if not isinstance(node, ast.Assign):
                    continue
                val = self._const_of(node.value)
                if val is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        table[t.id] = val
            self._by_module[m.dotted] = table

    def _const_of(self, node: ast.AST) -> Optional[Val]:
        if isinstance(node, ast.Constant) \
                and isinstance(node.value, (int, float)) \
                and not isinstance(node.value, bool):
            if isinstance(node.value, int):
                return IntVal(node.value)
            return ScalarVal()
        if isinstance(node, ast.UnaryOp) \
                and isinstance(node.op, ast.USub):
            inner = self._const_of(node.operand)
            if isinstance(inner, IntVal):
                return IntVal(-inner.dim)
            return inner
        if isinstance(node, ast.Call):
            fn = dotted_name(node.func)
            if fn in self._SCALAR_CALLS:
                return ScalarVal()
        return None

    def resolve(self, resolved: str) -> Optional[Val]:
        mod, _, name = resolved.rpartition(".")
        if not mod:
            return None
        table = self._by_module.get(mod)
        if table is None:
            return None
        return table.get(name)
