"""determinism: unordered-collection iteration must not materialize
into ordered artifacts.

Set iteration order depends on PYTHONHASHSEED for str/bytes elements
and on insertion/collision history for everything else — two processes
holding the SAME logical set can walk it in different orders. In this
tree that is not a style nit: the host side columnarizes cluster state
into dense arrays, journals commits, and digests snapshots for the
crash-replay path. A node table built by iterating a set lays out
DIFFERENT row indices per process, so replicas disagree on every array
that indexes by row, replay produces a different schedule than the
original run, and snapshot digests stop matching across restarts.

ND001 fires when a set-valued expression (literal, comprehension,
set()/frozenset() call, set-algebra of those, or a local/module name
bound only to such) reaches an ORDER-SENSITIVE sink:

  - list()/tuple()/enumerate() materialization
  - a list comprehension over it
  - np/jnp array construction (array/asarray/fromiter/stack/
    concatenate) or str.join
  - a `for` loop whose body appends/extends/writes/update()s —
    accumulation into an ordered artifact or a hash digest

`sorted(s)` is the fix, and needs no special pragma: sorted() returns
a list, so its result is simply not set-typed and no sink fires.
Order-insensitive consumption (membership, len, min/max, any/all,
set algebra) is untouched.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from tools.lint.framework import Analyzer, Finding, Project, register

_SET_CTORS = {"set", "frozenset"}
_SET_ANNOTATIONS = {"set", "frozenset", "Set", "FrozenSet", "AbstractSet",
                    "MutableSet"}
# binary set algebra keeps set-ness when either side is a set
_SET_BINOPS = (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
# set methods returning sets
_SET_RETURNING_METHODS = {"union", "intersection", "difference",
                          "symmetric_difference", "copy"}

_MATERIALIZERS = {"list", "tuple", "enumerate"}
_ARRAY_CTORS = {"array", "asarray", "fromiter", "stack", "concatenate"}
# loop-body calls that accumulate into an ordered artifact / digest
_ACCUMULATORS = {"append", "extend", "write", "update", "writerow"}


def _ann_is_set(ann: Optional[ast.expr]) -> bool:
    if ann is None:
        return False
    node = ann
    if isinstance(node, ast.Subscript):      # Set[str], frozenset[int]
        node = node.value
    name = node.attr if isinstance(node, ast.Attribute) else \
        node.id if isinstance(node, ast.Name) else None
    return name in _SET_ANNOTATIONS


class _SetTyper:
    """Flow-insensitive local inference: a name is set-typed iff EVERY
    binding of it in the scope is a set-valued expression (a single
    non-set rebinding clears it — never guess)."""

    def __init__(self, outer: Optional["_SetTyper"] = None):
        self.outer = outer
        self.is_set: Dict[str, bool] = {}

    def bind(self, name: str, value_is_set: bool) -> None:
        prev = self.is_set.get(name)
        self.is_set[name] = value_is_set if prev is None \
            else (prev and value_is_set)

    def query(self, name: str) -> bool:
        if name in self.is_set:
            return self.is_set[name]
        return self.outer.query(name) if self.outer else False

    def expr_is_set(self, node: ast.expr) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Name):
            return self.query(node.id)
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in _SET_CTORS:
                return True
            if isinstance(fn, ast.Attribute) \
                    and fn.attr in _SET_RETURNING_METHODS:
                return self.expr_is_set(fn.value)
            return False
        if isinstance(node, ast.BinOp) \
                and isinstance(node.op, _SET_BINOPS):
            return self.expr_is_set(node.left) \
                or self.expr_is_set(node.right)
        if isinstance(node, ast.IfExp):
            return self.expr_is_set(node.body) \
                and self.expr_is_set(node.orelse)
        return False


def _scope_nodes(scope_body: List[ast.stmt]) -> Iterable[ast.AST]:
    """Source-order walk of a scope's own statements, descending into
    control flow but NOT into nested def/class/lambda scopes."""
    queue: List[ast.AST] = list(scope_body)
    while queue:
        node = queue.pop(0)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        queue.extend(ast.iter_child_nodes(node))


def _collect_bindings(scope_body: List[ast.stmt],
                      typer: _SetTyper) -> None:
    """One pass over a scope's own statements recording name bindings
    (a name bound only to set expressions is set-typed)."""
    for node in _scope_nodes(scope_body):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    typer.bind(tgt.id, typer.expr_is_set(node.value))
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            is_set = _ann_is_set(node.annotation) or (
                node.value is not None
                and typer.expr_is_set(node.value))
            typer.bind(node.target.id, is_set)
        elif isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Name):
            if not isinstance(node.op, _SET_BINOPS):
                typer.bind(node.target.id, False)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            if isinstance(node.target, ast.Name):
                typer.bind(node.target.id, False)


def _loop_accumulates(body: List[ast.stmt]) -> Optional[str]:
    """The accumulator method name when a loop body feeds an ordered
    artifact (list.append, digest.update, file.write, ...)."""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _ACCUMULATORS:
                return node.func.attr
    return None


def _describe(node: ast.expr) -> str:
    if isinstance(node, ast.Name):
        return f"`{node.id}`"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "a set literal"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return f"a {node.func.id}() value"
    return "a set-valued expression"


@register
class DeterminismAnalyzer(Analyzer):
    name = "determinism"
    description = ("set iteration materialized into ordered artifacts "
                   "(arrays, lists, digests) — hash-seed-dependent "
                   "order breaks replay and cross-process agreement; "
                   "iterate sorted(...) instead")

    def run(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for mod in project.modules:
            if not mod.relpath.startswith("koordinator_tpu/"):
                continue
            module_typer = _SetTyper()
            _collect_bindings(mod.tree.body, module_typer)
            self._scan_scope(mod.tree.body, module_typer, mod.relpath,
                             findings)
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))

    def _scan_scope(self, body: List[ast.stmt], typer: _SetTyper,
                    relpath: str, findings: List[Finding]) -> None:
        for stmt in body:
            self._scan_node(stmt, typer, relpath, findings)

    def _scan_node(self, node: ast.AST, typer: _SetTyper, relpath: str,
                   findings: List[Finding]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = _SetTyper(outer=typer)
            for arg in (node.args.posonlyargs + node.args.args
                        + node.args.kwonlyargs):
                inner.bind(arg.arg, _ann_is_set(arg.annotation))
            _collect_bindings(node.body, inner)
            self._scan_scope(node.body, inner, relpath, findings)
            return
        if isinstance(node, ast.ClassDef):
            inner = _SetTyper(outer=typer)
            _collect_bindings(node.body, inner)
            self._scan_scope(node.body, inner, relpath, findings)
            return

        if isinstance(node, ast.Call):
            self._check_call(node, typer, relpath, findings)
        elif isinstance(node, ast.ListComp):
            for gen in node.generators:
                if typer.expr_is_set(gen.iter):
                    self._emit(findings, relpath, node.lineno, gen.iter,
                               "a list comprehension")
        elif isinstance(node, (ast.For, ast.AsyncFor)) \
                and typer.expr_is_set(node.iter):
            acc = _loop_accumulates(node.body)
            if acc is not None:
                self._emit(findings, relpath, node.lineno, node.iter,
                           f"a loop accumulating via .{acc}()")

        for child in ast.iter_child_nodes(node):
            self._scan_node(child, typer, relpath, findings)

    def _check_call(self, node: ast.Call, typer: _SetTyper,
                    relpath: str, findings: List[Finding]) -> None:
        fn = node.func
        args = [a for a in node.args
                if not isinstance(a, ast.Starred)]
        if isinstance(fn, ast.Name) and fn.id in _MATERIALIZERS \
                and args and typer.expr_is_set(args[0]):
            self._emit(findings, relpath, node.lineno, args[0],
                       f"{fn.id}()")
        elif isinstance(fn, ast.Attribute):
            if fn.attr in _ARRAY_CTORS \
                    and args and typer.expr_is_set(args[0]):
                self._emit(findings, relpath, node.lineno, args[0],
                           f".{fn.attr}() array construction")
            elif fn.attr == "join" and args \
                    and typer.expr_is_set(args[0]):
                self._emit(findings, relpath, node.lineno, args[0],
                           "str.join()")

    def _emit(self, findings: List[Finding], relpath: str, line: int,
              src: ast.expr, sink: str) -> None:
        what = _describe(src)
        name = src.id if isinstance(src, ast.Name) else "<expr>"
        findings.append(Finding(
            analyzer=self.name, code="ND001", path=relpath, line=line,
            message=f"{what} is materialized through {sink} — set "
                    f"order is hash-seed/insertion dependent, so the "
                    f"produced ordering differs across processes "
                    f"(breaks columnar layout, replay, and digests); "
                    f"iterate sorted({name if name != '<expr>' else '...'}) "
                    f"instead",
            key=f"{sink}:{name}"))
