"""metric-registry consistency: a cross-file pass over the per-component
`metrics_defs.py` catalogs and the shared name registry
(`koordinator_tpu/metrics/registry.py`).

Registrations are `r.counter/gauge/histogram(<name>, ...)` calls inside
any `metrics_defs.py`; the registry is any `registry.py` sitting in a
`metrics/` directory, holding `UPPER_NAME = "metric_name"` constants.

Codes:
  MN001  duplicate metric name across the catalogs — two components
         would fight over one family in the shared process registry
  MN002  bare string-literal metric name in a catalog while a shared
         registry module exists — names drift apart silently; import
         the constant
  MN003  registry constant never registered by any catalog (dead name,
         or a catalog forgot its series)
  MN004  metric name expression the pass cannot resolve (not a literal
         and not a registry constant)
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Tuple

from tools.lint.astutil import str_const
from tools.lint.framework import Analyzer, Finding, Module, Project, register

REGISTRATION_METHODS = {"counter", "gauge", "histogram"}


def _is_catalog(module: Module) -> bool:
    return module.relpath.endswith("metrics_defs.py")


def _is_registry(module: Module) -> bool:
    return module.relpath.endswith("metrics/registry.py")


@register
class MetricNamesAnalyzer(Analyzer):
    name = "metric-registry"
    description = ("duplicate/unregistered/unresolvable metric names "
                   "across the metrics_defs catalogs and the shared "
                   "name registry")

    def run(self, project: Project) -> Iterable[Finding]:
        catalogs = [m for m in project.modules if _is_catalog(m)]
        registries = [m for m in project.modules if _is_registry(m)]
        constants: Dict[str, Tuple[str, Module, int]] = {}
        for reg in registries:
            for node in reg.tree.body:
                if not isinstance(node, ast.Assign):
                    continue
                value = str_const(node.value)
                if value is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id.isupper():
                        constants[t.id] = (value, reg, node.lineno)

        findings: List[Finding] = []
        # name -> first registration (path, line)
        seen: Dict[str, Tuple[str, int]] = {}
        registered_constants: set = set()
        for cat in catalogs:
            for call in ast.walk(cat.tree):
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr in REGISTRATION_METHODS
                        and call.args):
                    continue
                name_node = call.args[0]
                literal = str_const(name_node)
                const_name = name_node.id \
                    if isinstance(name_node, ast.Name) else None
                if literal is not None:
                    resolved = literal
                    if registries:
                        findings.append(Finding(
                            analyzer="metric-registry", code="MN002",
                            path=cat.relpath, line=name_node.lineno,
                            message=f"metric name {literal!r} is a bare "
                                    f"string literal; import the "
                                    f"constant from the shared metrics "
                                    f"registry so the catalogs cannot "
                                    f"drift",
                            key=f"bare:{literal}"))
                elif const_name is not None \
                        and const_name in constants:
                    resolved = constants[const_name][0]
                    registered_constants.add(const_name)
                else:
                    findings.append(Finding(
                        analyzer="metric-registry", code="MN004",
                        path=cat.relpath, line=name_node.lineno,
                        message="metric name is neither a string "
                                "literal nor a shared-registry "
                                "constant; the cross-file consistency "
                                "pass cannot verify it",
                        key=f"unresolved:{ast.unparse(name_node)}"))
                    continue
                prev = seen.get(resolved)
                if prev is not None:
                    findings.append(Finding(
                        analyzer="metric-registry", code="MN001",
                        path=cat.relpath, line=call.lineno,
                        message=f"metric name {resolved!r} already "
                                f"registered at {prev[0]}:{prev[1]}; "
                                f"two catalogs sharing one family "
                                f"collide in the process registry",
                        key=f"dup:{resolved}"))
                else:
                    seen[resolved] = (cat.relpath, call.lineno)
        for const_name, (value, reg, line) in sorted(constants.items()):
            if const_name not in registered_constants:
                findings.append(Finding(
                    analyzer="metric-registry", code="MN003",
                    path=reg.relpath, line=line,
                    message=f"registry constant {const_name} "
                            f"({value!r}) is never registered by any "
                            f"metrics_defs catalog — dead name or "
                            f"missing series",
                    key=f"unregistered:{const_name}"))
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))
