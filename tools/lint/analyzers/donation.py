"""donation-aliasing: a buffer passed through `donate_argnums`/
`donate_argnames` is invalidated by the call — XLA may reuse its memory
for the outputs. Reading the donor variable afterwards returns garbage
(or raises on deleted-buffer access) only at runtime; this pass catches
it statically.

Code:
  DA001  donated variable read after the donating call before rebinding

The check is scoped to the enclosing function of each call site and is
loop-aware: for a call inside a loop both continuation paths are
checked — the wrap-around to the next iteration (which reaches the
loop-top statements with the buffer already donated) and the loop exit
(which reaches the post-loop statements with the LAST iteration's
buffer donated). The `snap = sweep(snap, ...)` rebind idiom passes; a
stale `jax.block_until_ready(snap)` at loop top or a `return snap`
after the loop does not. The assignment form `g = jax.jit(f,
donate_argnums=...)` attributes donation to calls through `g`; direct
`f(...)` calls stay plain.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.lint.astutil import dotted_name, positional_params
from tools.lint.callgraph import project_index, FunctionInfo, JitEntry, ProjectIndex
from tools.lint.framework import Analyzer, Finding, Project, register


@register
class DonationAnalyzer(Analyzer):
    name = "donation-aliasing"
    description = ("reads of a donated buffer after the jitted call "
                   "that consumed it")

    def run(self, project: Project) -> Iterable[Finding]:
        index = project_index(project)
        # decorator form: the raw def IS the jitted callable
        donating: Dict[int, JitEntry] = {}
        # assignment form (g = jax.jit(f, ...)): donation applies to
        # calls through the ALIAS, never to direct f(...) calls
        aliased: Dict[Tuple[str, str], JitEntry] = {}
        for entry in index.jit_entries():
            if not (entry.donate_argnums or entry.donate_argnames):
                continue
            if entry.alias_name is not None:
                aliased[(entry.alias_module_relpath,
                         entry.alias_name)] = entry
            else:
                donating[id(entry.fn.node)] = entry
        if not donating and not aliased:
            return []
        findings: List[Finding] = []
        for mi in index.modules.values():
            for info in mi.functions:
                findings.extend(self._scan_function(
                    index, mi, info, donating, aliased))
        return sorted(findings, key=lambda f: (f.path, f.line, f.code))

    def _scan_function(self, index: ProjectIndex, mi, info: FunctionInfo,
                       donating: Dict[int, JitEntry],
                       aliased: Dict[Tuple[str, str], JitEntry]
                       ) -> Iterable[Finding]:
        chain = info.scope_chain + (info.node,)
        for stmt, call, loop in _calls_with_context(info.node):
            entry = None
            if isinstance(call.func, ast.Name):
                entry = aliased.get((info.module.relpath, call.func.id))
            if entry is None:
                callee = index.resolve_call(mi, chain, call)
                if callee is None or id(callee.node) not in donating:
                    continue
                entry = donating[id(callee.node)]
            donated = _donated_names(entry, call)
            if not donated:
                continue
            paths = _paths_after(info.node, stmt, loop)
            for name in sorted(donated):
                hit = None
                for path in paths:
                    hit = _first_use_before_rebind(path, stmt, name)
                    if hit is not None:
                        break
                if hit is not None:
                    yield Finding(
                        analyzer="donation-aliasing", code="DA001",
                        path=info.module.relpath, line=hit.lineno,
                        message=f"`{name}` was donated to "
                                f"`{entry.fn.qualname}` on line "
                                f"{call.lineno} and is read here before "
                                f"rebinding: the buffer may already be "
                                f"reused for the outputs — rebind the "
                                f"name from the call's result or drop "
                                f"it from donate_argnums",
                        key=f"{info.qualname}:{entry.fn.qualname}:{name}")


def _donated_names(entry: JitEntry, call: ast.Call) -> Set[str]:
    """Plain-Name arguments sitting in donated positions/keywords."""
    pos = positional_params(entry.fn.node)
    donated_params = set(entry.donate_argnames)
    donated_params.update(pos[i] for i in entry.donate_argnums
                          if 0 <= i < len(pos))
    names: Set[str] = set()
    for i, arg in enumerate(call.args):
        if i < len(pos) and pos[i] in donated_params \
                and isinstance(arg, ast.Name):
            names.add(arg.id)
    for kw in call.keywords:
        if kw.arg in donated_params and isinstance(kw.value, ast.Name):
            names.add(kw.value.id)
    return names


def _calls_with_context(fn) -> Iterable[Tuple[ast.stmt, ast.Call,
                                              Optional[ast.stmt]]]:
    """(enclosing statement, call, innermost enclosing loop) for every
    call in `fn`, excluding nested function bodies; compound statements
    attribute body calls to the innermost simple statement."""
    seen: Set[int] = set()
    for stmt, call, loop in _walk_dedup(fn.body, None):
        if id(call) not in seen:
            seen.add(id(call))
            yield stmt, call, loop


def _walk_dedup(body: List[ast.stmt], loop: Optional[ast.stmt]):
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        inner_loop = stmt if isinstance(stmt, (ast.For, ast.While)) \
            else loop
        subs = list(_sub_bodies(stmt))
        if subs:
            for sub in subs:
                yield from _walk_dedup(sub, inner_loop)
            # calls in the statement header (test/iter) still belong here
            for node in _header_nodes(stmt):
                for c in ast.walk(node):
                    if isinstance(c, ast.Call):
                        yield stmt, c, inner_loop
        else:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(node, ast.Call):
                    yield stmt, node, inner_loop


def _sub_bodies(stmt: ast.stmt) -> Iterable[List[ast.stmt]]:
    for attr in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, attr, None)
        if isinstance(sub, list) and sub \
                and isinstance(sub[0], ast.stmt):
            yield sub
    for h in getattr(stmt, "handlers", []) or []:
        yield h.body


def _header_nodes(stmt: ast.stmt) -> Iterable[ast.AST]:
    for attr in ("test", "iter", "items", "value"):
        node = getattr(stmt, attr, None)
        if node is None:
            continue
        if isinstance(node, list):
            yield from node
        else:
            yield node


def _flatten(body: List[ast.stmt], out: List[ast.stmt]) -> None:
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        out.append(stmt)
        for sub in _sub_bodies(stmt):
            _flatten(sub, out)


def _paths_after(fn, call_stmt: ast.stmt,
                 loop: Optional[ast.stmt]) -> List[List[ast.stmt]]:
    """Execution paths (flattened statement lists) a donated buffer can
    flow along after `call_stmt`. Outside a loop there is one: the rest
    of the function. Inside a loop there are two, each checked
    independently because a rebind on one does not save the other:
    (A) next iteration — wrap once around the loop body back to the
    call; (B) loop exit — everything after the loop."""
    linear: List[ast.stmt] = []
    _flatten(fn.body, linear)
    try:
        at = linear.index(call_stmt)
    except ValueError:
        return []
    if loop is None:
        return [linear[at + 1:]]
    loop_linear: List[ast.stmt] = []
    _flatten(loop.body, loop_linear)
    if call_stmt not in loop_linear:
        return [linear[at + 1:]]
    i = loop_linear.index(call_stmt)
    wrap = loop_linear[i + 1:] + loop_linear[:i + 1]
    in_loop = {id(s) for s in loop_linear}
    post_loop = [s for s in linear[at + 1:] if id(s) not in in_loop]
    return [wrap, post_loop]


def _first_use_before_rebind(order: List[ast.stmt],
                             call_stmt: ast.stmt,
                             name: str) -> Optional[ast.AST]:
    """First statement in `order` that loads `name`; None if a store
    (rebind) comes first. The donating statement itself counts only as
    its stores (its loads fed the call) — the `x, y = f(x, y)` rebind
    idiom leaves nothing stale, in or out of a loop."""
    if _stores_name(call_stmt, name):
        return None
    for stmt in order:
        # the call statement can reappear via wrap-around: its argument
        # loads then belong to the NEXT iteration, re-donating a buffer
        # the previous iteration already consumed
        load = _loads_name(stmt, name)
        stores = _stores_name(stmt, name)
        if load is not None and not stores:
            return load
        if load is not None and stores:
            # `x = f(x)`-style single statement: the load feeds the
            # rebinding expression — treat as rebind-after-read hazard
            # only when the load is outside the defining statement's
            # value; keep it simple and treat store+load as a rebind
            return None
        if stores:
            return None
    return None


def _loads_name(stmt: ast.stmt, name: str) -> Optional[ast.AST]:
    for node in ast.walk(stmt):
        if isinstance(node, ast.Name) and node.id == name \
                and isinstance(node.ctx, ast.Load):
            return node
    return None


def _stores_name(stmt: ast.stmt, name: str) -> bool:
    for node in ast.walk(stmt):
        if isinstance(node, ast.Name) and node.id == name \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
    return False
