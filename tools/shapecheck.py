"""koordshape Tier B: the device-free jax.eval_shape CI gate.

Imports the runtime contract registry
(koordinator_tpu.snapshot.schema.SHAPE_CONTRACTS) and drives
jax.eval_shape over EVERY registered contract with symbolic-sized
ShapeDtypeStructs — abstract tracing only: no device, no XLA compile,
seconds on CPU. Two distinct size assignments run so a kernel that
accidentally couples two dims (uses N where the contract says P)
produces an output-shape drift in at least one of them.

Failure classes caught per contract:
  - output-shape drift vs the declared dims (under both assignments)
  - dtype promotion (declared f32 coming back f64/i32, bool masks
    silently promoted by arithmetic)
  - weak-type leaks (an output whose dtype still floats with context —
    one python scalar away from a silent promotion + retrace)
  - x64 upcasts (any 64-bit leaf anywhere in the output tree; the gate
    also refuses to run with jax_enable_x64 on)

`--self-test-mutation` proves the gate is live: it copies
koordinator_tpu/ to a temp dir, flips the mask dtype of
ops/feasibility.resource_fit (jnp.all -> jnp.sum: bool[P,N] becomes
i32[P,N]), re-runs this script against the mutated copy, and fails
unless the run FAILS. CI runs both stages (tools/ci.sh).
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
from typing import Dict, List, Optional

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# appended (not prepended) so a mutated tree earlier on PYTHONPATH wins
if REPO_ROOT not in sys.path:
    sys.path.append(REPO_ROOT)

from tools.lint.shapes.spec import (  # noqa: E402
    DimProp,
    LeafSpec,
    Spec,
    StructRef,
    parse_spec,
)

# every module that registers contracts or structs; importing populates
# the registry — keep in sync with new @shape_contract carriers
CONTRACT_MODULES = (
    "koordinator_tpu.snapshot.schema",
    "koordinator_tpu.snapshot.delta",
    "koordinator_tpu.ops.feasibility",
    "koordinator_tpu.ops.waterfill",
    "koordinator_tpu.ops.quota_demand",
    "koordinator_tpu.scheduler.cascade",
    "koordinator_tpu.scheduler.core",
    "koordinator_tpu.scheduler.guards",
    "koordinator_tpu.compilecache.precompile",
    "koordinator_tpu.parallel.shardops",
    "koordinator_tpu.scheduler.plugins.loadaware",
    "koordinator_tpu.scheduler.plugins.deviceshare",
    "koordinator_tpu.scheduler.plugins.numaaware",
    "koordinator_tpu.descheduler.lownodeload_device",
    "koordinator_tpu.slo_controller.noderesource",
)

# Two size assignments, each internally all-distinct, with the P/N
# order FLIPPED between them so pod/node coupling cannot hide. R stays
# NUM_RESOURCES in both (kernels index resource columns by ResourceKind
# constants, so R is a fixed axis in practice). Constraints honored:
# TC <= P (tail windows gather from the batch), Z small (the topology
# manager builds a 2^Z mask table). Assignment B additionally avoids
# every FIXED_DIMS value and NUM_RESOURCES, so coupling against a fixed
# axis is caught there even where A's small values collide.
ASSIGNMENT_A = {
    "P": 21, "N": 5, "I": 2, "Z": 3, "G": 4, "Q": 6, "V": 7,
    "S": 8, "L": 9, "T": 10, "TG": 12, "SG": 13, "AG": 14, "FG": 15,
    "DM": 16, "J": 17, "K": 18, "KC": 23, "TC": 19, "RD": 20, "NS": 22,
}
ASSIGNMENT_B = {
    "P": 26, "N": 23, "I": 8, "Z": 4, "G": 7, "Q": 9, "V": 10,
    "S": 13, "L": 14, "T": 15, "TG": 16, "SG": 17, "AG": 18, "FG": 19,
    "DM": 21, "J": 24, "K": 25, "KC": 30, "TC": 12, "RD": 27, "NS": 28,
}

_DTYPE_NAMES = {"f32": "float32", "i32": "int32", "i8": "int8",
                "u32": "uint32", "bool": "bool"}
_WIDE_DTYPES = ("float64", "int64", "uint64", "complex128")


class ShapeCheckError(Exception):
    pass


def _sizes(assignment: Dict[str, int]):
    from koordinator_tpu.api.extension import NUM_RESOURCES
    from koordinator_tpu.snapshot.schema import FIXED_DIMS
    out = dict(assignment)
    out["R"] = NUM_RESOURCES
    out.update(FIXED_DIMS)
    return out


def _resolve_dim(dim, sizes: Dict[str, int]) -> int:
    if isinstance(dim, int):
        return dim
    if dim in sizes:
        return sizes[dim]
    raise ShapeCheckError(f"no size assigned to dim {dim!r}")


def build_value(spec: Spec, sizes: Dict[str, int]):
    """A spec -> an abstract input: ShapeDtypeStruct leaves, struct
    instances for StructRefs (static fields keep their defaults)."""
    import jax
    import numpy as np
    from koordinator_tpu.snapshot.schema import STRUCT_CLASSES, STRUCT_SPECS

    if isinstance(spec, tuple):
        return tuple(build_value(s, sizes) for s in spec)
    if isinstance(spec, LeafSpec):
        shape = tuple(_resolve_dim(d, sizes) for d in spec.dims)
        return jax.ShapeDtypeStruct(shape,
                                    np.dtype(_DTYPE_NAMES[spec.dtype]))
    if isinstance(spec, StructRef):
        cls = STRUCT_CLASSES.get(spec.name)
        fields = STRUCT_SPECS.get(spec.name)
        if cls is None or fields is None:
            raise ShapeCheckError(f"unregistered struct {spec.name!r}")
        kwargs = {}
        for fname, raw in fields.items():
            fspec = parse_spec(raw)
            if isinstance(fspec, DimProp):
                continue  # symbolic-int property, not a field
            kwargs[fname] = build_value(fspec, sizes)
        return cls(**kwargs)
    raise ShapeCheckError(f"cannot build a value for spec {spec!r}")


def check_output(spec: Spec, got, sizes: Dict[str, int],
                 where: str, errors: List[str]) -> None:
    from koordinator_tpu.snapshot.schema import STRUCT_CLASSES, STRUCT_SPECS

    if spec is None:
        return
    if isinstance(spec, tuple):
        if not isinstance(got, (tuple, list)) or len(got) != len(spec):
            errors.append(f"{where}: expected a {len(spec)}-tuple, got "
                          f"{type(got).__name__}")
            return
        for i, (s, g) in enumerate(zip(spec, got)):
            check_output(s, g, sizes, f"{where}[{i}]", errors)
        return
    if isinstance(spec, LeafSpec):
        if got is None:
            if not spec.optional:
                errors.append(f"{where}: None where the contract "
                              f"requires a value")
            return
        shape = getattr(got, "shape", None)
        dtype = getattr(got, "dtype", None)
        if shape is None or dtype is None:
            errors.append(f"{where}: expected an array, got {got!r}")
            return
        want_shape = tuple(_resolve_dim(d, sizes) for d in spec.dims)
        if tuple(shape) != want_shape:
            decl = ",".join(str(d) for d in spec.dims)
            errors.append(
                f"{where}: shape drift — declared [{decl}] = "
                f"{want_shape} under this assignment, got "
                f"{tuple(shape)} (dim coupling or a mis-broadcast)")
        want_dtype = _DTYPE_NAMES[spec.dtype]
        if str(dtype) != want_dtype:
            errors.append(f"{where}: dtype drift — declared "
                          f"{want_dtype}, got {dtype} (promotion?)")
        if getattr(got, "weak_type", False):
            errors.append(f"{where}: weak-type leak — the output dtype "
                          f"still floats with context; anchor it with "
                          f"an explicit dtype")
        return
    if isinstance(spec, StructRef):
        cls = STRUCT_CLASSES.get(spec.name)
        fields = STRUCT_SPECS.get(spec.name, {})
        if cls is not None and not isinstance(got, cls):
            errors.append(f"{where}: expected {spec.name}, got "
                          f"{type(got).__name__}")
            return
        for fname, raw in fields.items():
            fspec = parse_spec(raw)
            if isinstance(fspec, DimProp):
                continue
            check_output(fspec, getattr(got, fname, None), sizes,
                         f"{where}.{fname}", errors)
        return
    errors.append(f"{where}: unhandled spec {spec!r}")


def _scan_wide_leaves(out, where: str, errors: List[str]) -> None:
    import jax
    for path, leaf in jax.tree_util.tree_flatten_with_path(out)[0]:
        dtype = getattr(leaf, "dtype", None)
        if dtype is not None and str(dtype) in _WIDE_DTYPES:
            errors.append(f"{where}{jax.tree_util.keystr(path)}: "
                          f"64-bit leaf ({dtype}) — x64 upcast")


def run_contract(contract, sizes: Dict[str, int],
                 label: str) -> List[str]:
    import jax
    from koordinator_tpu.snapshot.schema import SHAPE_CONTRACTS

    errors: List[str] = []
    kwargs = {}
    static_kwargs = {}
    for name, raw in contract.args.items():
        kwargs[name] = build_value(parse_spec(raw), sizes)
    for name, value in contract.static.items():
        if isinstance(value, str) and value in sizes:
            value = sizes[value]
        static_kwargs[name] = value
    for name, dotted in contract.callables.items():
        target = SHAPE_CONTRACTS.get(dotted)
        if target is None:
            return [f"{label}: _callable {name!r} names unregistered "
                    f"contract {dotted!r}"]
        static_kwargs[name] = target.fn
    fn = functools.partial(contract.fn, **static_kwargs) \
        if static_kwargs else contract.fn
    try:
        out = jax.eval_shape(fn, **kwargs)
    except Exception as exc:  # noqa: BLE001 — any trace failure fails CI
        return [f"{label}: eval_shape raised "
                f"{type(exc).__name__}: {exc}"]
    spec = parse_spec(contract.returns) \
        if contract.returns is not None else None
    check_output(spec, out, sizes, label, errors)
    _scan_wide_leaves(out, label, errors)
    return errors


def run_all(verbose: bool = False) -> int:
    import importlib

    import jax
    if jax.config.jax_enable_x64:
        print("shapecheck: refusing to run with jax_enable_x64 — the "
              "contracts pin 32-bit layouts", file=sys.stderr)
        return 2
    for mod in CONTRACT_MODULES:
        importlib.import_module(mod)
    from koordinator_tpu.snapshot.schema import SHAPE_CONTRACTS

    failures = 0
    for key in sorted(SHAPE_CONTRACTS):
        contract = SHAPE_CONTRACTS[key]
        errs: List[str] = []
        for tag, assignment in (("A", ASSIGNMENT_A),
                                ("B", ASSIGNMENT_B)):
            errs.extend(run_contract(contract, _sizes(assignment),
                                     f"{key}[{tag}]"))
        if errs:
            failures += 1
            for e in errs:
                print(f"FAIL {e}")
        elif verbose:
            print(f"ok   {key}")
    total = len(SHAPE_CONTRACTS)
    print(f"shapecheck: {total - failures}/{total} contracts clean "
          f"under 2 assignments")
    return 1 if failures else 0


# --- the seeded-mutation smoke (gate liveness proof) -----------------------

def self_test_mutation() -> int:
    """Flip resource_fit's mask dtype in a TEMP COPY of the package and
    assert the gate fails on it. Leaves the working tree untouched."""
    from tools.seedmut import Mutation, check_gate_catches
    return check_gate_catches(
        Mutation(
            relpath=os.path.join("koordinator_tpu", "ops",
                                 "feasibility.py"),
            anchor="return jnp.all(",
            replacement="return jnp.sum(",
            note="resource_fit mask flipped jnp.all -> jnp.sum "
                 "(bool[P,N] becomes i32[P,N])"),
        [sys.executable, os.path.abspath(__file__)],
        marker="dtype drift", label="shapecheck")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python tools/shapecheck.py",
        description="koordshape Tier B: device-free eval_shape gate "
                    "over the kernel contract registry")
    parser.add_argument("--self-test-mutation", action="store_true",
                        help="prove the gate live: flip one dtype in a "
                             "temp copy and assert the run fails")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    if args.self_test_mutation:
        return self_test_mutation()
    return run_all(verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
