"""Seeded-mutation harness shared by the contract gates.

Every koordshape/koordpad gate ships a `--self-test-mutation` mode that
proves the gate is LIVE: plant one known defect in a temp copy of the
package, re-run the gate against the mutated tree, and fail unless the
gate fails for the expected reason. This module is the one
implementation of that plant-and-rerun loop; the gates
(tools/shapecheck.py, tools/padcheck.py) supply only their anchors and
failure markers.

Two kinds of gate are supported by the same entry point:
  - import gates (shapecheck, padcheck): the temp tree is PREPENDED to
    PYTHONPATH so the mutated `koordinator_tpu` shadows the real one
    for the child process;
  - file gates (koordlint): any "{tree}" placeholder in the argv is
    substituted with the temp tree path, for tools that read source
    from a --root rather than importing it.

The working tree is never touched; the temp copy is deleted on exit.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile
from dataclasses import dataclass
from typing import Optional, Sequence

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PACKAGE = "koordinator_tpu"


@dataclass(frozen=True)
class Mutation:
    """One planted defect: replace the first occurrence of `anchor`
    with `replacement` in `relpath` (relative to the repo root)."""

    relpath: str
    anchor: str
    replacement: str
    note: str  # one-line description for the smoke report


def _run_mutated(mutation: Mutation, argv: Sequence[str], *,
                 label: str, repo_root: str, timeout: int):
    """Plant `mutation` in a temp copy of the package and run `argv`
    against it. Returns the completed process, or None when the anchor
    has drifted out of the tree (the smoke itself is stale)."""
    with tempfile.TemporaryDirectory(prefix="seedmut-") as td:
        shutil.copytree(os.path.join(repo_root, PACKAGE),
                        os.path.join(td, PACKAGE))
        target = os.path.join(td, mutation.relpath)
        with open(target, encoding="utf-8") as f:
            src = f.read()
        if mutation.anchor not in src:
            print(f"mutation smoke [{label}]: anchor "
                  f"{mutation.anchor!r} missing from {mutation.relpath}"
                  f" — refresh the smoke's anchor", file=sys.stderr)
            return None
        with open(target, "w", encoding="utf-8") as f:
            f.write(src.replace(mutation.anchor,
                                mutation.replacement, 1))
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [td, repo_root] + ([env["PYTHONPATH"]]
                               if env.get("PYTHONPATH") else []))
        cmd = [a.replace("{tree}", td) for a in argv]
        return subprocess.run(cmd, capture_output=True, text=True,
                              env=env, cwd=repo_root, timeout=timeout)


def check_gate_catches(mutation: Mutation, argv: Sequence[str], *,
                       marker: Optional[str] = None,
                       label: str = "gate",
                       repo_root: str = REPO_ROOT,
                       timeout: int = 1200) -> int:
    """Plant `mutation` in a temp copy of the package, run `argv`
    against it, and return 0 iff the gate FAILED (non-zero exit) with
    `marker` somewhere in its output — i.e. the gate caught the defect
    for the right reason. Returns 2 when the anchor has drifted out of
    the tree (the smoke itself is stale), 1 when the gate let the
    defect through or failed for an unrelated reason."""
    proc = _run_mutated(mutation, argv, label=label,
                        repo_root=repo_root, timeout=timeout)
    if proc is None:
        return 2
    if proc.returncode == 0:
        print(f"mutation smoke [{label}]: the gate PASSED a mutated "
              f"tree ({mutation.note}) — it is not protecting "
              f"anything", file=sys.stderr)
        print(proc.stdout, file=sys.stderr)
        return 1
    if marker is not None and marker not in proc.stdout + proc.stderr:
        print(f"mutation smoke [{label}]: the gate failed without the "
              f"expected marker {marker!r}:", file=sys.stderr)
        print(proc.stdout + proc.stderr, file=sys.stderr)
        return 1
    print(f"mutation smoke [{label}]: {mutation.note} — correctly "
          f"caught (gate is live)")
    return 0


def check_gate_passes(mutation: Mutation, argv: Sequence[str], *,
                      label: str = "gate",
                      repo_root: str = REPO_ROOT,
                      timeout: int = 1200) -> int:
    """The complement of check_gate_catches: return 0 iff the gate
    PASSES the mutated tree. Dual-tier smokes use this to prove the
    two tiers are complementary BY CONSTRUCTION — each planted defect
    must be caught by exactly its own tier, and demonstrably invisible
    to the other (a defect both tiers see proves redundancy, not
    coverage). Returns 2 on anchor drift, 1 when the gate failed (it
    can see the defect after all)."""
    proc = _run_mutated(mutation, argv, label=label,
                        repo_root=repo_root, timeout=timeout)
    if proc is None:
        return 2
    if proc.returncode != 0:
        print(f"mutation smoke [{label}]: expected the gate to MISS "
              f"this defect ({mutation.note}) but it failed — the "
              f"tiers overlap where they should complement:",
              file=sys.stderr)
        print(proc.stdout + proc.stderr, file=sys.stderr)
        return 1
    print(f"mutation smoke [{label}]: {mutation.note} — invisible to "
          f"this tier, as designed")
    return 0
