"""Randomized soak for the service auto-pack path.

For many seeds, schedule a full-gate batch through SchedulerService
(auto_pack on) and assert the per-row outcome invariants that would
break if the inverse permutation ever mapped results to the wrong
rows: sentinel-impossible pods unschedulable at THEIR rows, consumed
reservation slots only at owner rows with matching ids, NUMA zone
reports only on CPU-bind rows, GPU instance takes only on
device-requesting rows.

`--chaos` additionally injects ONE random fault per seed (column
corruption, runtime failure, or watchdog stall — the
koordinator_tpu.testing.faults catalog) and asserts the service
completes the cycle with the quarantined/faulted rows contained: the
per-row invariants must hold on the CLEAN rows regardless of the
fault.

Usage: JAX_PLATFORMS=cpu python tools/soak_service.py [n_seeds] [--chaos]
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from koordinator_tpu.scheduler.frameworkext import SchedulerService
from koordinator_tpu.utils import synthetic

P, N = 1_024, 256
CHAOS = "--chaos" in sys.argv[1:]
_counts = [a for a in sys.argv[1:] if not a.startswith("-")]
N_SEEDS = int(_counts[0]) if _counts else 100

# per-seed chaos menu: one of these fires each seed (seeded choice)
CHAOS_MENU = ("nan_metric_column", "negative_allocatable",
              "nan_pod_request", "bad_gang_id", "xla_oom",
              "xla_transient", "watchdog_stall", "none")


def apply_chaos(service, snap, pods, seed):
    """Inject one seeded fault; -> (snap, pods, quarantined pod rows)."""
    from koordinator_tpu.testing import faults

    inj = faults.FaultInjector(seed)
    fault = CHAOS_MENU[int(inj.rng.integers(len(CHAOS_MENU)))]
    quarantined = np.zeros((0,), np.int64)
    if fault in faults.SNAPSHOT_FAULTS:
        snap, _rows = inj.corrupt_snapshot(snap, fault, n_rows=2)
    elif fault in faults.BATCH_FAULTS:
        pods, quarantined = inj.corrupt_batch(pods, fault, n_rows=4)
    elif fault == "xla_oom":
        service.fault_injection = inj.oom_above(P // 2)
    elif fault == "xla_transient":
        service.fault_injection = inj.xla_transient(fail_attempts={1})
    elif fault == "watchdog_stall":
        inj.stall_watchdog(service)
    return snap, pods, quarantined


def main():
    bad = 0
    for i in range(N_SEEDS):
        rng = np.random.default_rng(i)
        service = SchedulerService(num_rounds=2, k_choices=4)
        service._sleep = lambda _s: None
        snap = synthetic.full_gate_cluster(
            N, seed=i, num_quotas=8, num_gangs=8)
        pods = synthetic.full_gate_pods(P, N, seed=i + 500,
                                        num_quotas=8, num_gangs=8)
        reqs = np.asarray(pods.requests).copy()
        impossible = rng.choice(P, 16, replace=False)
        reqs[impossible] = 1e9
        pods = pods.replace(requests=reqs)
        quarantined = np.zeros((0,), np.int64)
        if CHAOS:
            snap, pods, quarantined = apply_chaos(service, snap, pods, i)
        service.publish(snap)
        res = service.schedule(pods)
        a = np.asarray(res.assignment)
        slot = np.asarray(res.res_slot)
        zone = np.asarray(res.numa_zone)
        gpu_take = np.asarray(res.gpu_take)
        owner = np.asarray(pods.reservation_owner)
        numa = np.asarray(pods.numa_single)
        from koordinator_tpu.scheduler.plugins import deviceshare
        gpu = np.asarray(deviceshare.has_device_request(pods))
        ok = ((a[impossible] == -1).all()
              and (a[quarantined] == -1).all()
              and (slot[owner < 0] < 0).all()
              and (owner[slot >= 0] == slot[slot >= 0]).all()
              and (zone[~numa] < 0).all()
              and not gpu_take[~gpu].any()
              # capacity varies by seed; the floor only guards
              # against a degenerate all-unschedulable run
              and int((a >= 0).sum()) > P // 8)
        if not ok:
            print(f"seed {i}: ROW-CONSISTENCY VIOLATION", flush=True)
            bad += 1
        if (i + 1) % 20 == 0:
            print(f"{i + 1}/{N_SEEDS} seeds, {bad} violations",
                  flush=True)
    print(f"SERVICE SOAK DONE: {N_SEEDS} seeds, {bad} violations",
          flush=True)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
