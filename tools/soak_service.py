"""Randomized soak for the service auto-pack path.

For many seeds, schedule a full-gate batch through SchedulerService
(auto_pack on) and assert the per-row outcome invariants that would
break if the inverse permutation ever mapped results to the wrong
rows: sentinel-impossible pods unschedulable at THEIR rows, consumed
reservation slots only at owner rows with matching ids, NUMA zone
reports only on CPU-bind rows, GPU instance takes only on
device-requesting rows.

`--chaos` additionally injects ONE random fault per seed (column
corruption, runtime failure, or watchdog stall — the
koordinator_tpu.testing.faults catalog) and asserts the service
completes the cycle with the quarantined/faulted rows contained: the
per-row invariants must hold on the CLEAN rows regardless of the
fault.

`--kill` is the crash soak (ISSUE 14): each cycle SIGKILLs a
journaled, checkpointed child service at a SEEDED crash point
(faults.CRASH_POINTS x hit count, drawn per seed), then recovers in
this process and asserts the recovered placements are bit-identical to
the no-crash oracle with exactly one journal record per (epoch, chunk)
— the tools/crash_smoke.py machinery, randomized. Each cycle pays a
subprocess jax start, so the default seed count is small.

Usage: JAX_PLATFORMS=cpu python tools/soak_service.py [n_seeds]
           [--chaos | --kill]
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from koordinator_tpu.scheduler.frameworkext import SchedulerService
from koordinator_tpu.utils import synthetic

P, N = 1_024, 256
CHAOS = "--chaos" in sys.argv[1:]
KILL = "--kill" in sys.argv[1:]
_counts = [a for a in sys.argv[1:] if not a.startswith("-")]
N_SEEDS = int(_counts[0]) if _counts else (5 if KILL else 100)

# per-seed chaos menu: one of these fires each seed (seeded choice)
CHAOS_MENU = ("nan_metric_column", "negative_allocatable",
              "nan_pod_request", "bad_gang_id", "xla_oom",
              "xla_transient", "watchdog_stall", "none")


def apply_chaos(service, snap, pods, seed):
    """Inject one seeded fault; -> (snap, pods, quarantined pod rows)."""
    from koordinator_tpu.testing import faults

    inj = faults.FaultInjector(seed)
    fault = CHAOS_MENU[int(inj.rng.integers(len(CHAOS_MENU)))]
    quarantined = np.zeros((0,), np.int64)
    if fault in faults.SNAPSHOT_FAULTS:
        snap, _rows = inj.corrupt_snapshot(snap, fault, n_rows=2)
    elif fault in faults.BATCH_FAULTS:
        pods, quarantined = inj.corrupt_batch(pods, fault, n_rows=4)
    elif fault == "xla_oom":
        service.fault_injection = inj.oom_above(P // 2)
    elif fault == "xla_transient":
        service.fault_injection = inj.xla_transient(fail_attempts={1})
    elif fault == "watchdog_stall":
        inj.stall_watchdog(service)
    return snap, pods, quarantined


def main_kill():
    """The crash soak: one SIGKILLed child + recovery per seed, crash
    point and hit drawn from the seed so a failure reproduces from its
    seed alone."""
    from koordinator_tpu.testing import faults
    import tools.crash_smoke as crash

    bad = 0
    for i in range(N_SEEDS):
        rng = np.random.default_rng(i)
        point = faults.CRASH_POINTS[int(rng.integers(
            len(faults.CRASH_POINTS)))]
        # hits 1..4: before/while/after each of the 4 chunk commits
        hit = int(rng.integers(1, 5))
        if point == "mid_checkpoint":
            # checkpoint 1 is the initial publish; 2 the post-batch one
            hit = int(rng.integers(1, 3))
        try:
            verdict = crash.run_case(point, hit, seed=i)
            print(f"KILL OK   seed {i}: {verdict}", flush=True)
        except AssertionError as exc:
            bad += 1
            print(f"KILL FAIL seed {i} ({point}:{hit}): {exc}",
                  flush=True)
    print(f"KILL SOAK DONE: {N_SEEDS} seeds, {bad} violations",
          flush=True)
    return 1 if bad else 0


def main():
    bad = 0
    for i in range(N_SEEDS):
        rng = np.random.default_rng(i)
        service = SchedulerService(num_rounds=2, k_choices=4)
        service._sleep = lambda _s: None
        snap = synthetic.full_gate_cluster(
            N, seed=i, num_quotas=8, num_gangs=8)
        pods = synthetic.full_gate_pods(P, N, seed=i + 500,
                                        num_quotas=8, num_gangs=8)
        reqs = np.asarray(pods.requests).copy()
        impossible = rng.choice(P, 16, replace=False)
        reqs[impossible] = 1e9
        pods = pods.replace(requests=reqs)
        quarantined = np.zeros((0,), np.int64)
        if CHAOS:
            snap, pods, quarantined = apply_chaos(service, snap, pods, i)
        service.publish(snap)
        res = service.schedule(pods)
        a = np.asarray(res.assignment)
        slot = np.asarray(res.res_slot)
        zone = np.asarray(res.numa_zone)
        gpu_take = np.asarray(res.gpu_take)
        owner = np.asarray(pods.reservation_owner)
        numa = np.asarray(pods.numa_single)
        from koordinator_tpu.scheduler.plugins import deviceshare
        gpu = np.asarray(deviceshare.has_device_request(pods))
        ok = ((a[impossible] == -1).all()
              and (a[quarantined] == -1).all()
              and (slot[owner < 0] < 0).all()
              and (owner[slot >= 0] == slot[slot >= 0]).all()
              and (zone[~numa] < 0).all()
              and not gpu_take[~gpu].any()
              # capacity varies by seed; the floor only guards
              # against a degenerate all-unschedulable run
              and int((a >= 0).sum()) > P // 8)
        if not ok:
            print(f"seed {i}: ROW-CONSISTENCY VIOLATION", flush=True)
            bad += 1
        if (i + 1) % 20 == 0:
            print(f"{i + 1}/{N_SEEDS} seeds, {bad} violations",
                  flush=True)
    print(f"SERVICE SOAK DONE: {N_SEEDS} seeds, {bad} violations",
          flush=True)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main_kill() if KILL else main())
