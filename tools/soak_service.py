"""Randomized soak for the service auto-pack path.

For many seeds, schedule a full-gate batch through SchedulerService
(auto_pack on) and assert the per-row outcome invariants that would
break if the inverse permutation ever mapped results to the wrong
rows: sentinel-impossible pods unschedulable at THEIR rows, consumed
reservation slots only at owner rows with matching ids, NUMA zone
reports only on CPU-bind rows, GPU instance takes only on
device-requesting rows.

`--chaos` additionally injects ONE random fault per seed (column
corruption, runtime failure, or watchdog stall — the
koordinator_tpu.testing.faults catalog) and asserts the service
completes the cycle with the quarantined/faulted rows contained: the
per-row invariants must hold on the CLEAN rows regardless of the
fault.

`--kill` is the crash soak (ISSUE 14): each cycle SIGKILLs a
journaled, checkpointed child service at a SEEDED crash point
(faults.CRASH_POINTS x hit count, drawn per seed), then recovers in
this process and asserts the recovered placements are bit-identical to
the no-crash oracle with exactly one journal record per (epoch, chunk)
— the tools/crash_smoke.py machinery, randomized. Each cycle pays a
subprocess jax start, so the default seed count is small.

`--threads K` (koordrace Tier B's wall-clock complement) adds a
per-seed thread-stress phase: K REAL threads — duplicate-replaying
ingest drivers, a concurrent schedule driver, a checkpoint/reader
driver — hammer the seed's live service under genuine preemption, and
the SnapshotStore exactly-once ledger is then asserted via the SAME
invariant helper the deterministic battery uses
(tools/racecheck.store_accounting_invariants). Where racecheck
explores seeded schedules it can replay, this explores whatever the
OS scheduler does — cheap breadth on top of deterministic depth.
Composes with --chaos (the stress runs on the fault-injected service)
and with --kill (each crash-recovery seed gets its own stressed
service).

Usage: JAX_PLATFORMS=cpu python tools/soak_service.py [n_seeds]
           [--chaos | --kill] [--threads K]
"""

import os
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from koordinator_tpu.scheduler.frameworkext import SchedulerService
from koordinator_tpu.utils import synthetic

P, N = 1_024, 256
CHAOS = "--chaos" in sys.argv[1:]
KILL = "--kill" in sys.argv[1:]
_args = sys.argv[1:]
THREADS = int(_args[_args.index("--threads") + 1]) \
    if "--threads" in _args else 0
if "--threads" in _args:
    del _args[_args.index("--threads"):_args.index("--threads") + 2]
_counts = [a for a in _args if not a.startswith("-")]
N_SEEDS = int(_counts[0]) if _counts else (5 if KILL else 100)

# per-seed chaos menu: one of these fires each seed (seeded choice)
CHAOS_MENU = ("nan_metric_column", "negative_allocatable",
              "nan_pod_request", "bad_gang_id", "xla_oom",
              "xla_transient", "watchdog_stall", "none")


def apply_chaos(service, snap, pods, seed):
    """Inject one seeded fault; -> (snap, pods, quarantined pod rows)."""
    from koordinator_tpu.testing import faults

    inj = faults.FaultInjector(seed)
    fault = CHAOS_MENU[int(inj.rng.integers(len(CHAOS_MENU)))]
    quarantined = np.zeros((0,), np.int64)
    if fault in faults.SNAPSHOT_FAULTS:
        snap, _rows = inj.corrupt_snapshot(snap, fault, n_rows=2)
    elif fault in faults.BATCH_FAULTS:
        pods, quarantined = inj.corrupt_batch(pods, fault, n_rows=4)
    elif fault == "xla_oom":
        service.fault_injection = inj.oom_above(P // 2)
    elif fault == "xla_transient":
        service.fault_injection = inj.xla_transient(fail_attempts={1})
    elif fault == "watchdog_stall":
        inj.stall_watchdog(service)
    return snap, pods, quarantined


def _stress_delta(snap, version):
    """A real (tiny, all-zero) NodeMetricDelta stamped with `version`:
    the stress cares about the store's version-guard ledger, not the
    metric values, but the delta must be genuine so ingest runs the
    jitted apply kernel under the real locks."""
    from koordinator_tpu.snapshot.delta import NodeMetricDelta

    nodes = snap.nodes
    k = 4
    row = np.zeros((k,) + np.asarray(nodes.usage).shape[1:], np.float32)
    agg = np.zeros((k,) + np.asarray(nodes.agg_usage).shape[1:],
                   np.float32)
    return NodeMetricDelta(
        idx=np.arange(k, dtype=np.int32),
        metric_fresh=np.ones(k, bool),
        usage=row, prod_usage=row, agg_usage=agg,
        has_agg=np.zeros(k, bool),
        assigned_estimated=row, assigned_correction=row,
        prod_assigned_estimated=row, prod_assigned_correction=row,
        source_version=np.int32(version))


def stress_threads(service, pods, seed, k):
    """The --threads phase: k real threads race the seed's live service
    — ingest drivers all replaying the SAME delta version sequence
    (racing ghosts), a schedule driver committing a full batch through
    the commit lock mid-replay, a checkpoint/reader driver — then the
    store's exactly-once ledger is asserted with the invariant helper
    the deterministic racecheck battery uses. Returns 1 on violation."""
    from tools.racecheck import store_accounting_invariants

    store = service.store
    base_ver = store.version
    base_wm = store.applied_delta_version
    base_rej = store.delta_rejections
    n_versions = 4
    snap = store.current()
    deltas = [_stress_delta(snap, base_wm + 1 + j)
              for j in range(n_versions)]
    roles = [("ingest", "ingest", "schedule", "checkpoint")[t % 4]
             for t in range(k)]
    commits = []
    errors = []

    def ingest_driver():
        for d in deltas:
            service.ingest(d)

    def schedule_driver():
        res = service.schedule(pods)
        commits.append(int(np.asarray(res.assignment).shape[0]))

    def checkpoint_driver():
        for _ in range(n_versions):
            service.store.maybe_checkpoint()
            _ = store.version
            _ = store.applied_delta_version
            store.current()

    drivers = {"ingest": ingest_driver, "schedule": schedule_driver,
               "checkpoint": checkpoint_driver}

    def run(fn):
        try:
            fn()
        except Exception as exc:  # noqa: BLE001 — reported below
            errors.append(exc)

    threads = [threading.Thread(target=run, args=(drivers[role],),
                                name=f"stress-{role}-{t}")
               for t, role in enumerate(roles)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=300)

    fails = []
    if any(th.is_alive() for th in threads):
        fails.append("a stress driver is still running after 300s")
    for exc in errors:
        fails.append(f"driver raised {type(exc).__name__}: {exc}")
    # each successful schedule commits exactly one functional update
    store_accounting_invariants(
        store, base_version=base_ver, base_watermark=base_wm,
        base_rejections=base_rej, n_versions=n_versions,
        n_producers=roles.count("ingest"), n_updates=len(commits),
        report=fails.append)
    for msg in fails:
        print(f"seed {seed}: THREAD-STRESS {msg}", flush=True)
    return 1 if fails else 0


def main_kill():
    """The crash soak: one SIGKILLed child + recovery per seed, crash
    point and hit drawn from the seed so a failure reproduces from its
    seed alone."""
    from koordinator_tpu.testing import faults
    import tools.crash_smoke as crash

    bad = 0
    for i in range(N_SEEDS):
        rng = np.random.default_rng(i)
        point = faults.CRASH_POINTS[int(rng.integers(
            len(faults.CRASH_POINTS)))]
        # hits 1..4: before/while/after each of the 4 chunk commits
        hit = int(rng.integers(1, 5))
        if point == "mid_checkpoint":
            # checkpoint 1 is the initial publish; 2 the post-batch one
            hit = int(rng.integers(1, 3))
        try:
            verdict = crash.run_case(point, hit, seed=i)
            print(f"KILL OK   seed {i}: {verdict}", flush=True)
        except AssertionError as exc:
            bad += 1
            print(f"KILL FAIL seed {i} ({point}:{hit}): {exc}",
                  flush=True)
        if THREADS:
            # the crash cases run in child processes, so the thread
            # stress gets its own in-process service per seed
            service = make_service(num_rounds=2, k_choices=4)
            service.publish(synthetic.full_gate_cluster(
                N, seed=i, num_quotas=8, num_gangs=8))
            pods = synthetic.full_gate_pods(
                P, N, seed=i + 500, num_quotas=8, num_gangs=8)
            bad += stress_threads(service, pods, i, THREADS)
            bad += check_health(service, i,
                                lambda msg: print(msg, flush=True))
    print(f"KILL SOAK DONE: {N_SEEDS} seeds, {bad} violations",
          flush=True)
    return 1 if bad else 0


def make_service(**kw):
    """A soak service with the koordcost health plane attached: memwatch
    plus a LATENCY-ONLY SloTracker — the soak plants impossible pods on
    purpose, so the placement_success objective would burn its budget by
    design; cycle latency and the leak sentinel are the signals that
    must stay green across every seed."""
    from koordinator_tpu.metrics import Registry
    from koordinator_tpu.obs.slo import DEFAULT_OBJECTIVES, SloTracker
    from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics

    metrics = SchedulerMetrics(Registry())
    latency = tuple(o for o in DEFAULT_OBJECTIVES if o.kind == "latency")
    service = SchedulerService(
        metrics=metrics, memwatch=True,
        slo=SloTracker(metrics, objectives=latency), **kw)
    service._sleep = lambda _s: None
    return service


def check_health(service, seed, report):
    """One green-or-fail verdict per seed: every SLO objective inside
    budget and zero leak-sentinel events across the soak's cycles."""
    health = service.health()
    if health["ok"] and health["leakEvents"] == 0:
        return 0
    report(f"seed {seed}: HEALTH NOT GREEN: ok={health['ok']} "
           f"leaks={health['leakEvents']} "
           f"budget={health['budgetRemaining']}")
    return 1


def main():
    bad = 0
    for i in range(N_SEEDS):
        rng = np.random.default_rng(i)
        service = make_service(num_rounds=2, k_choices=4)
        snap = synthetic.full_gate_cluster(
            N, seed=i, num_quotas=8, num_gangs=8)
        pods = synthetic.full_gate_pods(P, N, seed=i + 500,
                                        num_quotas=8, num_gangs=8)
        reqs = np.asarray(pods.requests).copy()
        impossible = rng.choice(P, 16, replace=False)
        reqs[impossible] = 1e9
        pods = pods.replace(requests=reqs)
        quarantined = np.zeros((0,), np.int64)
        if CHAOS:
            snap, pods, quarantined = apply_chaos(service, snap, pods, i)
        service.publish(snap)
        res = service.schedule(pods)
        a = np.asarray(res.assignment)
        slot = np.asarray(res.res_slot)
        zone = np.asarray(res.numa_zone)
        gpu_take = np.asarray(res.gpu_take)
        owner = np.asarray(pods.reservation_owner)
        numa = np.asarray(pods.numa_single)
        from koordinator_tpu.scheduler.plugins import deviceshare
        gpu = np.asarray(deviceshare.has_device_request(pods))
        ok = ((a[impossible] == -1).all()
              and (a[quarantined] == -1).all()
              and (slot[owner < 0] < 0).all()
              and (owner[slot >= 0] == slot[slot >= 0]).all()
              and (zone[~numa] < 0).all()
              and not gpu_take[~gpu].any()
              # capacity varies by seed; the floor only guards
              # against a degenerate all-unschedulable run
              and int((a >= 0).sum()) > P // 8)
        if not ok:
            print(f"seed {i}: ROW-CONSISTENCY VIOLATION", flush=True)
            bad += 1
        if THREADS:
            bad += stress_threads(service, pods, i, THREADS)
        bad += check_health(service, i,
                            lambda msg: print(msg, flush=True))
        if (i + 1) % 20 == 0:
            print(f"{i + 1}/{N_SEEDS} seeds, {bad} violations",
                  flush=True)
    print(f"SERVICE SOAK DONE: {N_SEEDS} seeds, {bad} violations",
          flush=True)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main_kill() if KILL else main())
