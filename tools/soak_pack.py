"""Randomized soak for the round-5 batching layer.

For many workload seeds, schedule the SAME packed full-gate batch with
and without the batching specializations (all three nested prefixes +
domain classes) and require BIT-identical results — assignment, scores,
zone takes, GPU instance identity, aux, slots, gang rollback, and every
leaf of the post-commit snapshot. Bit-identity transfers every
invariant the full-width program already guarantees (tests/
test_invariants.py) to the packed program, seed by seed.

Shapes stay constant so both programs compile once; each seed is then
two cached executions. Usage:
    JAX_PLATFORMS=cpu python tools/soak_pack.py [n_seeds] [start]
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.utils import synthetic

P, N, CHUNK = 2_048, 256, 512
N_SEEDS = int(sys.argv[1]) if len(sys.argv) > 1 else 200
START = int(sys.argv[2]) if len(sys.argv) > 2 else 0


def main():
    cfg = LoadAwareConfig.make()
    kw = dict(num_rounds=2, k_choices=8, score_dims=(0, 1),
              tie_break=True, quota_depth=2, fit_dims=(0, 1, 2, 3),
              enable_numa=True, enable_devices=True)
    fields = core.PER_POD_RESULT_FIELDS + ("gang_failed",)
    bad = 0
    for i in range(START, START + N_SEEDS):
        pods = synthetic.full_gate_pods(P, N, seed=i, num_quotas=8,
                                        num_gangs=8)
        packed, prefixes, _ = synthetic.pack_gate_prefixes(pods, CHUNK)
        classes = synthetic.dom_classes(packed)
        snap = synthetic.full_gate_cluster(N, seed=i + 7, num_quotas=8,
                                           num_gangs=8)
        batch = synthetic.slice_batch(packed, (i % (P // CHUNK)) * CHUNK,
                                      CHUNK)
        full = core.schedule_batch(snap, batch, cfg, **kw)
        spec = core.schedule_batch(snap, batch, cfg,
                                   topo_prefix=prefixes["topo"],
                                   numa_prefix=prefixes["numa"],
                                   gpu_prefix=prefixes["gpu"],
                                   dom_classes=classes, **kw)
        ok = True
        for f in fields:
            if not np.array_equal(np.asarray(getattr(full, f)),
                                  np.asarray(getattr(spec, f))):
                print(f"seed {i}: MISMATCH in {f}", flush=True)
                ok = False
        for a, b in zip(jax.tree_util.tree_leaves(full.snapshot),
                        jax.tree_util.tree_leaves(spec.snapshot)):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                print(f"seed {i}: SNAPSHOT leaf mismatch", flush=True)
                ok = False
                break
        bad += not ok
        if (i - START + 1) % 25 == 0:
            print(f"{i - START + 1}/{N_SEEDS} seeds, {bad} mismatches",
                  flush=True)
    print(f"SOAK DONE: {N_SEEDS} seeds, {bad} mismatches", flush=True)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
