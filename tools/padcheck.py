"""koordpad Tier B: the differential pad-inertness gate.

Where tools/shapecheck.py proves every contracted kernel's SHAPES
abstractly (jax.eval_shape, no values), this gate runs every kernel
CONCRETELY on CPU, twice, over the same seeded real problem:

  run 0   arrays sized to the real extents exactly (zero extra pad)
  run X   every padded dim (schema.PADDED_DIMS) grown by +2/+3, pad
          regions materialized from the declared `~pad:` predicates
          (schema.PAD_FILL_VALUES); `invalid`/`any` regions get seeded
          well-typed garbage, because consumers promise not to read
          them

and then asserts, leaf by declared leaf of the output spec:

  - REAL-REGION INERTNESS: the padded run's outputs, sliced back to
    the real extents, are BIT-identical to run 0's. Any difference
    means pad rows leaked into real results — a non-neutral reduction,
    an unclamped sentinel gather, a mask conjunction dropped.
  - PAD-BAND DISCIPLINE: the padded run's own pad bands hold exactly
    the declared fill (skipped for `invalid`/`any`, which promise
    nothing). Producers must leave pads the way the contract says, or
    downstream annihilator reasoning (the pad-soundness lint) and the
    mesh repadder are built on sand.

The static twin is the `pad-soundness` koordlint pass: dataflow over
the same declarations, no jax. `--self-test-mutation` proves BOTH
tiers live by planting one defect each (tools/seedmut.py): dropping
the `& nodes.schedulable` conjunction in cascade.static_gates must
fail THIS gate, and dropping the index clamp in
feasibility.pod_ancestors must fail the lint pass (that one is
concretely masked afterwards, so only dataflow can see the hazard).
"""

from __future__ import annotations

import argparse
import os
import sys
import zlib
from typing import Dict, List, Optional, Tuple

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# appended (not prepended) so a mutated tree earlier on PYTHONPATH wins
if REPO_ROOT not in sys.path:
    sys.path.append(REPO_ROOT)

from tools.lint.shapes.spec import (  # noqa: E402
    DimProp,
    LeafSpec,
    PADDED_DIMS,
    Spec,
    StructRef,
    parse_spec,
)
from tools.shapecheck import (  # noqa: E402
    CONTRACT_MODULES,
    _DTYPE_NAMES,
    _resolve_dim,
)

# The real problem: every symbol all-distinct so cross-dim coupling
# cannot alias, every padded extent >= 4 so sliced comparisons see a
# real interior (Z stays 3: the topology manager builds a 2^Z table).
# TC <= P as in shapecheck. R/AGG/DEV/AX/QD come from the runtime.
REAL_SIZES = {
    "P": 11, "N": 5, "I": 6, "Z": 3, "G": 7, "Q": 8, "V": 9,
    "S": 10, "L": 12, "T": 13, "TG": 14, "SG": 15, "AG": 16, "FG": 17,
    "DM": 18, "J": 19, "K": 20, "KC": 21, "TC": 4, "RD": 22, "NS": 23,
}

# extra pad per padded dim in run X — deterministic, mixed +2/+3 so
# two padded dims never grow by amounts that re-alias their extents
PAD_EXTRA = {d: 2 + (i % 2)
             for i, d in enumerate(sorted(PADDED_DIMS))}

BASE_SEED = 0xC0FFEE


class PadCheckError(Exception):
    pass


def _sizes(padded: bool) -> Dict[str, int]:
    from koordinator_tpu.api.extension import NUM_RESOURCES
    from koordinator_tpu.snapshot.schema import FIXED_DIMS
    out = dict(REAL_SIZES)
    if padded:
        for d, extra in PAD_EXTRA.items():
            out[d] = out[d] + extra
    out["R"] = NUM_RESOURCES
    out.update(FIXED_DIMS)
    return out


def _rng(key: str, seed: int):
    import numpy as np
    return np.random.default_rng(
        (seed & 0xFFFFFFFF) << 32 | zlib.crc32(key.encode("utf-8")))


def _gen(dtype: str, shape: Tuple[int, ...], rng, index_cap: int):
    """Seeded real-region content. Integer leaves are index-like
    throughout the tree, so they draw from [-1, index_cap) — valid
    into every axis, including the -1 'none' sentinel (u32 cannot
    carry it and starts at 0)."""
    import numpy as np
    if dtype == "bool":
        return rng.random(shape) < 0.7
    if dtype == "f32":
        return rng.uniform(0.5, 2.0, shape).astype(np.float32)
    lo = 0 if dtype == "u32" else -1
    return rng.integers(lo, index_cap,
                        size=shape).astype(np.dtype(_DTYPE_NAMES[dtype]))


def _build_leaf(leaf: LeafSpec, real: Dict[str, int],
                padded: Dict[str, int], rng, grng, index_cap: int):
    """-> (array_real, array_padded): identical seeded real regions;
    the padded twin's pad bands hold the declared fills (or seeded
    garbage for `invalid`/`any`)."""
    import numpy as np
    from koordinator_tpu.snapshot.schema import PAD_FILL_VALUES
    real_shape = tuple(_resolve_dim(d, real) for d in leaf.dims)
    pad_shape = tuple(_resolve_dim(d, padded) for d in leaf.dims)
    base = _gen(leaf.dtype, real_shape, rng, index_cap)
    if pad_shape == real_shape:
        return base, base
    arr = np.zeros(pad_shape, dtype=base.dtype)
    for ax in range(len(leaf.dims)):
        if pad_shape[ax] == real_shape[ax]:
            continue
        sl = [slice(None)] * len(leaf.dims)
        sl[ax] = slice(real_shape[ax], None)
        fill = PAD_FILL_VALUES.get(leaf.pad_for(ax) or "")
        if fill is None:
            band_shape = tuple(pad_shape[i] if i != ax
                               else pad_shape[ax] - real_shape[ax]
                               for i in range(len(pad_shape)))
            arr[tuple(sl)] = _gen(leaf.dtype, band_shape, grng,
                                  index_cap)
        else:
            arr[tuple(sl)] = np.asarray(fill).astype(base.dtype)
    arr[tuple(slice(0, s) for s in real_shape)] = base
    return base, arr


def build_pair(spec: Spec, real: Dict[str, int], padded: Dict[str, int],
               rng, grng, index_cap: int):
    """A spec -> (value_real, value_padded), recursing through tuples
    and registered structs with ONE rng stream so the real regions are
    draw-for-draw identical."""
    from koordinator_tpu.snapshot.schema import STRUCT_CLASSES, STRUCT_SPECS
    if isinstance(spec, tuple):
        pairs = [build_pair(s, real, padded, rng, grng, index_cap)
                 for s in spec]
        return tuple(p[0] for p in pairs), tuple(p[1] for p in pairs)
    if isinstance(spec, LeafSpec):
        return _build_leaf(spec, real, padded, rng, grng, index_cap)
    if isinstance(spec, StructRef):
        cls = STRUCT_CLASSES.get(spec.name)
        fields = STRUCT_SPECS.get(spec.name)
        if cls is None or fields is None:
            raise PadCheckError(f"unregistered struct {spec.name!r}")
        kw0, kwx = {}, {}
        for fname, raw in fields.items():
            fspec = parse_spec(raw)
            if isinstance(fspec, DimProp):
                continue
            kw0[fname], kwx[fname] = build_pair(fspec, real, padded,
                                                rng, grng, index_cap)
        return cls(**kw0), cls(**kwx)
    raise PadCheckError(f"cannot build a value for spec {spec!r}")


def _compare_leaf(leaf: LeafSpec, o0, ox, real: Dict[str, int],
                  where: str, errors: List[str]) -> None:
    import numpy as np
    from koordinator_tpu.snapshot.schema import PAD_FILL_VALUES
    if o0 is None or ox is None:
        if leaf.optional and o0 is None and ox is None:
            return
        errors.append(f"{where}: output present in one run only "
                      f"(pad0={o0 is not None}, padX={ox is not None})")
        return
    a = np.asarray(o0)
    b = np.asarray(ox)
    real_shape = tuple(_resolve_dim(d, real) for d in leaf.dims)
    # shape drift is shapecheck's job; slicing to the real extents is
    # well-defined regardless
    sliced = b[tuple(slice(0, s) for s in real_shape)]
    if a.tobytes() != sliced.tobytes():
        with np.errstate(invalid="ignore"):
            ndrift = int(np.sum(a != sliced))
        errors.append(
            f"{where}: pad leak — real-region drift between the "
            f"zero-pad and padded runs ({ndrift} element(s) differ); "
            f"pad rows perturbed real results")
    for ax, dim in enumerate(leaf.dims):
        if b.shape[ax] == real_shape[ax]:
            continue
        pred = leaf.pad_for(ax)
        fill = PAD_FILL_VALUES.get(pred or "")
        if fill is None:
            continue  # invalid/any (or undeclared): contents free
        sl = [slice(0, real_shape[i]) for i in range(len(leaf.dims))]
        sl[ax] = slice(real_shape[ax], None)
        band = b[tuple(sl)]
        want = np.asarray(fill).astype(b.dtype)
        if not np.all(band == want):
            errors.append(
                f"{where}: pad-band drift on axis `{dim}` — declared "
                f"~pad:{pred} (fill {fill}), produced values "
                f"{sorted(set(np.asarray(band).ravel().tolist()))[:6]}")


def compare_outputs(spec: Optional[Spec], o0, ox, real: Dict[str, int],
                    where: str, errors: List[str]) -> None:
    from koordinator_tpu.snapshot.schema import STRUCT_SPECS
    if spec is None:
        return
    if isinstance(spec, tuple):
        if not isinstance(o0, (tuple, list)) or len(o0) != len(spec) \
                or not isinstance(ox, (tuple, list)) \
                or len(ox) != len(spec):
            errors.append(f"{where}: tuple arity drift vs the declared "
                          f"{len(spec)}-tuple")
            return
        for i, s in enumerate(spec):
            compare_outputs(s, o0[i], ox[i], real, f"{where}[{i}]",
                            errors)
        return
    if isinstance(spec, LeafSpec):
        _compare_leaf(spec, o0, ox, real, where, errors)
        return
    if isinstance(spec, StructRef):
        for fname, raw in STRUCT_SPECS.get(spec.name, {}).items():
            fspec = parse_spec(raw)
            if isinstance(fspec, DimProp):
                continue
            compare_outputs(fspec, getattr(o0, fname, None),
                            getattr(ox, fname, None), real,
                            f"{where}.{fname}", errors)
        return
    errors.append(f"{where}: unhandled spec {spec!r}")


def run_contract(key: str, contract, seed: int,
                 packed: bool = False) -> List[str]:
    import functools

    import jax
    from koordinator_tpu.snapshot.schema import SHAPE_CONTRACTS
    real = _sizes(padded=False)
    padded = _sizes(padded=True)
    index_cap = min(real.values())
    rng = _rng(key, seed)
    grng = _rng(key + "/garbage", seed)
    kw0, kwx = {}, {}
    for name, raw in contract.args.items():
        kw0[name], kwx[name] = build_pair(parse_spec(raw), real, padded,
                                          rng, grng, index_cap)
    static_kwargs = {}
    for name, value in contract.static.items():
        if isinstance(value, str) and value in real:
            if value in PADDED_DIMS:
                return [f"{key}: static {name!r} names padded dim "
                        f"{value!r} — a static cannot track padding"]
            value = real[value]
        static_kwargs[name] = value
    for name, dotted in contract.callables.items():
        target = SHAPE_CONTRACTS.get(dotted)
        if target is None:
            return [f"{key}: _callable {name!r} names unregistered "
                    f"contract {dotted!r}"]
        static_kwargs[name] = target.fn
    fn = functools.partial(contract.fn, **static_kwargs) \
        if static_kwargs else contract.fn
    # kernels use .at[] / while_loop carries: feed device arrays, not np
    import jax.numpy as jnp
    kw0 = jax.tree_util.tree_map(jnp.asarray, kw0)
    kwx = jax.tree_util.tree_map(jnp.asarray, kwx)
    if packed:
        # --packed: both runs consume bf16-round-tripped score/metric
        # columns (snapshot/packing.PACKABLE). The differential
        # assertions are unchanged — pad inertness and band discipline
        # must hold under packing exactly as they do at full f32
        # (packable pad fills are proven bf16-exact, so the bands stay
        # bit-exact through the round-trip).
        from koordinator_tpu.snapshot import packing
        kw0 = packing.roundtrip_tree(kw0)
        kwx = packing.roundtrip_tree(kwx)
    try:
        out0 = jax.device_get(fn(**kw0))
        outx = jax.device_get(fn(**kwx))
    except Exception as exc:  # noqa: BLE001 — any concrete failure fails CI
        return [f"{key}: concrete run raised "
                f"{type(exc).__name__}: {exc}"]
    errors: List[str] = []
    spec = parse_spec(contract.returns) \
        if contract.returns is not None else None
    compare_outputs(spec, out0, outx, real, key, errors)
    return errors


def run_all(seed: int = BASE_SEED, verbose: bool = False,
            only: Optional[str] = None, packed: bool = False) -> int:
    import importlib

    import jax
    if jax.config.jax_enable_x64:
        print("padcheck: refusing to run with jax_enable_x64 — the "
              "contracts pin 32-bit layouts", file=sys.stderr)
        return 2
    for mod in CONTRACT_MODULES:
        importlib.import_module(mod)
    from koordinator_tpu.snapshot.schema import SHAPE_CONTRACTS

    failures = 0
    total = 0
    for key in sorted(SHAPE_CONTRACTS):
        if only is not None and only not in key:
            continue
        total += 1
        errs = run_contract(key, SHAPE_CONTRACTS[key], seed,
                            packed=packed)
        if errs:
            failures += 1
            for e in errs:
                print(f"FAIL {e}")
        elif verbose:
            print(f"ok   {key}")
    mode = "bf16-packed inputs" if packed else "zero-pad vs padded runs"
    print(f"padcheck: {total - failures}/{total} contracts pad-inert "
          f"under {mode} (seed={seed:#x})")
    return 1 if failures else 0


# --- the seeded-mutation smoke: both koordpad tiers must be live -----------

def self_test_mutation() -> int:
    from tools.seedmut import Mutation, check_gate_catches
    rc = check_gate_catches(
        Mutation(
            relpath=os.path.join("koordinator_tpu", "scheduler",
                                 "cascade.py"),
            anchor="static_ok = la_ok & sel_ok "
                   "& nodes.schedulable[None, :]",
            replacement="static_ok = la_ok & sel_ok",
            note="static_gates no longer kills pad node columns "
                 "(schedulable conjunction dropped)"),
        [sys.executable, os.path.abspath(__file__)],
        marker="FAIL", label="padcheck")
    rc |= check_gate_catches(
        Mutation(
            relpath=os.path.join("koordinator_tpu", "ops",
                                 "feasibility.py"),
            anchor="quota_id = jnp.maximum(pods.quota_id, 0)",
            replacement="quota_id = pods.quota_id",
            note="pod_ancestors gathers through the raw -1 sentinel "
                 "(clamp dropped; concretely masked, so only the "
                 "static tier can see it)"),
        [sys.executable, "-m", "tools.lint", "--root", "{tree}",
         "--analyzers", "pad-soundness"],
        marker="PS002", label="pad-soundness")
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python tools/padcheck.py",
        description="koordpad Tier B: differential pad-inertness gate "
                    "over the kernel contract registry")
    parser.add_argument("--seed", type=lambda s: int(s, 0),
                        default=BASE_SEED,
                        help="base seed for the real problem draw")
    parser.add_argument("--only", help="substring filter on contract "
                                       "keys")
    parser.add_argument("--packed", action="store_true",
                        help="run both differential legs on bf16-"
                             "round-tripped score/metric columns "
                             "(snapshot/packing.PACKABLE)")
    parser.add_argument("--self-test-mutation", action="store_true",
                        help="prove both koordpad tiers live: plant "
                             "one defect per tier in a temp copy and "
                             "assert each gate fails")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    if args.self_test_mutation:
        return self_test_mutation()
    return run_all(seed=args.seed, verbose=args.verbose, only=args.only,
                   packed=args.packed)


if __name__ == "__main__":
    sys.exit(main())
