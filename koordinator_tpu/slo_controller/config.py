"""Colocation strategy config: the dynamic `slo-controller-config` ConfigMap
schema and its per-node-selector merge semantics.

Capability parity with apis/configuration/slo_controller_config.go
(ColocationCfg / ColocationStrategy) + pkg/util/sloconfig defaults and the
per-nodeSelector strategy merge in nodeslo/resource_strategy.go: the cluster
config carries a cluster-wide strategy plus an ordered list of node-selector
overrides; the first matching override (merged over the cluster strategy)
wins for a node.
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Dict, List, Optional

from koordinator_tpu.api.extension import (
    ANNOTATION_NODE_COLOCATION_STRATEGY,
    LABEL_CPU_RECLAIM_RATIO,
    LABEL_MEMORY_RECLAIM_RATIO,
    selector_matches,
)
from koordinator_tpu.utils.naming import camel_to_snake


class CalculatePolicy(enum.Enum):
    """Batch allocatable calculation policy (apis/configuration
    slo_controller_config.go CalculatePolicy)."""

    USAGE = "usage"
    REQUEST = "request"
    MAX_USAGE_REQUEST = "maxUsageRequest"


@dataclasses.dataclass
class ColocationStrategy:
    """Per-(cluster|node-group) overcommit strategy.

    Field parity with configuration.ColocationStrategy; defaults from
    pkg/util/sloconfig/colocation_config.go (DefaultColocationStrategy).
    """

    enable: bool = False
    metric_aggregate_duration_seconds: float = 300.0
    metric_report_interval_seconds: float = 60.0
    # percent of node capacity reclaimable for batch tier
    cpu_reclaim_threshold_percent: float = 60.0
    memory_reclaim_threshold_percent: float = 65.0
    # mid-tier caps as percent of node allocatable
    mid_cpu_threshold_percent: float = 10.0
    mid_memory_threshold_percent: float = 10.0
    # skip node update when relative diff below this
    resource_diff_threshold: float = 0.1
    # reset batch resources when NodeMetric is stale for this long
    degrade_time_minutes: float = 15.0
    update_time_threshold_seconds: float = 300.0
    cpu_calculate_policy: CalculatePolicy = CalculatePolicy.USAGE
    memory_calculate_policy: CalculatePolicy = CalculatePolicy.USAGE
    # node reservation percent applied to capacity before reclaim
    # (getNodeReservation: reserveRatio = (100-thresholdPercent)/100)

    def merged(self, override: "ColocationStrategyOverride") -> "ColocationStrategy":
        out = dataclasses.replace(self)
        for k, v in override.fields.items():
            if not hasattr(out, k):
                raise KeyError(f"unknown strategy field {k!r}")
            setattr(out, k, v)
        return out


@dataclasses.dataclass
class ColocationStrategyOverride:
    """NodeColocationCfg: a node-label selector plus partial strategy."""

    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    fields: Dict[str, object] = dataclasses.field(default_factory=dict)

    def matches(self, node_labels: Dict[str, str]) -> bool:
        return selector_matches(self.node_selector, node_labels)


@dataclasses.dataclass
class ColocationConfig:
    """slo-controller-config `colocation-config` entry (ColocationCfg)."""

    cluster_strategy: ColocationStrategy = dataclasses.field(
        default_factory=ColocationStrategy)
    node_overrides: List[ColocationStrategyOverride] = dataclasses.field(
        default_factory=list)

    def strategy_for(self, node_labels: Dict[str, str],
                     node_annotations: Optional[Dict[str, str]] = None
                     ) -> ColocationStrategy:
        """Per-node strategy resolution (sloconfig/colocation_config.go
        GetNodeColocationStrategy:102-155), precedence low to high:
        cluster strategy -> first matching node-selector override ->
        node annotation JSON partial -> reclaim-ratio labels. Illegal
        node metadata is ignored, never fatal (":142-154")."""
        out = self.cluster_strategy
        for ov in self.node_overrides:
            if ov.matches(node_labels):
                out = self.cluster_strategy.merged(ov)
                break
        anns = node_annotations or {}
        raw = anns.get(ANNOTATION_NODE_COLOCATION_STRATEGY)
        if raw:
            try:
                data = json.loads(raw)
            except ValueError:
                data = None  # illegal annotation ignored, never fatal
            if isinstance(data, dict):
                fields = {}
                for k, v in data.items():
                    snake = camel_to_snake(k)
                    coerced = self._coerce(snake, v)
                    if coerced is not None:
                        fields[snake] = coerced
                out = out.merged(ColocationStrategyOverride(fields=fields))
        out = dataclasses.replace(out)
        for label, attr in ((LABEL_CPU_RECLAIM_RATIO,
                             "cpu_reclaim_threshold_percent"),
                            (LABEL_MEMORY_RECLAIM_RATIO,
                             "memory_reclaim_threshold_percent")):
            raw = node_labels.get(label)
            if raw is None:
                continue
            try:
                ratio = float(raw)
            except ValueError:
                continue
            # the same [0,100]-percent invariant the ConfigMap webhook
            # enforces; an oversized ratio would overcommit the node
            if 0.0 <= ratio <= 1.0:
                setattr(out, attr, ratio * 100.0)
        return out

    @staticmethod
    def _coerce(field: str, value: object) -> Optional[object]:
        """Annotation values must land with the field's DECLARED type —
        the ConfigMap path coerces through the webhook validator; untyped
        node metadata must not sneak a str into arithmetic or a bogus
        policy into the kernel lowering. Dispatching on the declared type
        (not the current value's runtime type, which a prior int-valued
        override could have polluted) keeps valid values accepted.
        None = drop the field."""
        declared = _STRATEGY_FIELD_TYPES.get(field)
        if declared is None:
            return None  # unknown field
        if declared == "CalculatePolicy":
            try:
                return CalculatePolicy(value)
            except ValueError:
                return None
        if declared == "bool":
            return value if isinstance(value, bool) else None
        if declared == "float":
            return (float(value)
                    if isinstance(value, (int, float))
                    and not isinstance(value, bool) else None)
        # unhandled declared kinds reject rather than admit untyped data —
        # a future field must get an explicit branch here to be overridable
        return None


# declared field types (annotation strings under `from __future__ import
# annotations`) — the authority _coerce dispatches on
_STRATEGY_FIELD_TYPES: Dict[str, str] = {
    f.name: str(f.type) for f in dataclasses.fields(ColocationStrategy)}


def validate_colocation_config(cfg: ColocationConfig) -> List[str]:
    """ConfigMap-webhook-style validation (pkg/webhook/cm +
    sloconfig/colocation_validator.go). Returns a list of problems."""
    problems = []

    def check(s: ColocationStrategy, where: str):
        if not 0 <= s.cpu_reclaim_threshold_percent <= 100:
            problems.append(f"{where}: cpuReclaimThresholdPercent out of [0,100]")
        if not 0 <= s.memory_reclaim_threshold_percent <= 100:
            problems.append(f"{where}: memoryReclaimThresholdPercent out of [0,100]")
        if not 0 <= s.mid_cpu_threshold_percent <= 100:
            problems.append(f"{where}: midCPUThresholdPercent out of [0,100]")
        if not 0 <= s.mid_memory_threshold_percent <= 100:
            problems.append(f"{where}: midMemoryThresholdPercent out of [0,100]")
        if not 0 <= s.resource_diff_threshold <= 1:
            problems.append(f"{where}: resourceDiffThreshold out of [0,1]")
        if s.degrade_time_minutes <= 0:
            problems.append(f"{where}: degradeTimeMinutes must be positive")
        if s.metric_report_interval_seconds <= 0:
            problems.append(f"{where}: metricReportIntervalSeconds must be positive")

    check(cfg.cluster_strategy, "cluster")
    for i, ov in enumerate(cfg.node_overrides):
        if not ov.node_selector:
            problems.append(f"nodeOverride[{i}]: empty node selector")
        try:
            merged = cfg.cluster_strategy.merged(ov)
        except KeyError as e:
            problems.append(f"nodeOverride[{i}]: {e}")
            continue
        check(merged, f"nodeOverride[{i}]")
    return problems
