"""The colocation overcommit engine: batch/mid extended-resource calculation
for every node in one batched program.

Behavior parity with pkg/slo-controller/noderesource (SURVEY.md 2.3):
- batchresource plugin (plugins/batchresource/plugin.go:164-316, util.go:38-90):
    Batch[usage]          = Capacity − NodeReserved − max(SystemUsed, SystemReserved) − HPUsed
    Batch[request]        = Capacity − NodeReserved − SystemReserved − HPRequest
    Batch[maxUsageRequest]= Capacity − NodeReserved − max(SystemUsed, SystemReserved) − HPMaxUsedReq
  where HP (high-priority) spans every pod whose PriorityClass is not
  Batch/Free; a HP pod without a reported metric is counted at its request;
  LSE pods count max(request-mix, usage); dangling pod metrics (reported but
  no longer in the pod list) count at usage.
- midresource plugin (plugins/midresource/plugin.go:83-160):
    Mid = min(ProdReclaimable, NodeAllocatable × midThresholdPercent/100)
- degrade (plugin.go:467-484): NodeMetric staler than degradeTimeMinutes →
  batch/mid reset (encoded as −1).
- NeedSync diff gate (plugin.go:101-112 + util.IsResourceDiff).

TPU-native reading: the reference reconciles node-by-node on CR events; here
the whole cluster is one [N, 2] tensor program (columns cpu=millicores,
memory=MiB) recomputed per metric sync round — the natural shape for the
device-resident snapshot that feeds the scheduler's LoadAware columns.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.extension import PriorityClass, QoSClass, ResourceKind
from koordinator_tpu.api.types import Node, NodeMetric, Pod
from koordinator_tpu.slo_controller.config import CalculatePolicy, ColocationStrategy
from koordinator_tpu.slo_controller.metrics_defs import SloControllerMetrics
from koordinator_tpu.snapshot.schema import shape_contract

# Column order of the 2-dim resource axis used by this module.
CPU, MEM = 0, 1


@dataclasses.dataclass
class NodeResourceInputs:
    """Columnar inputs to the overcommit calculators, [N, 2] (cpu, mem).

    Host-aggregated from Node/NodeMetric/pod lists by `build_inputs`; all
    downstream math is jitted tensor ops.
    """

    capacity: np.ndarray          # f32[N, 2] node capacity
    allocatable: np.ndarray       # f32[N, 2] node allocatable
    system_used: np.ndarray       # f32[N, 2] NodeMetric systemUsage (+ HP host apps)
    system_reserved: np.ndarray   # f32[N, 2] max(kubelet reserved, node annotation)
    hp_request: np.ndarray        # f32[N, 2] Σ HP pod requests
    hp_used: np.ndarray           # f32[N, 2] Σ HP pod usages (req when no metric)
    hp_max_used_req: np.ndarray   # f32[N, 2] Σ max(req, usage) per HP pod
    prod_reclaimable: np.ndarray  # f32[N, 2] prediction (mid tier source)
    metric_age_seconds: np.ndarray  # f32[N] now − NodeMetric.updateTime (inf if none)
    valid: np.ndarray             # bool[N]
    names: Sequence[str] = ()     # node names (metric labels); "" rows OK


def _rl2(rl: Dict[ResourceKind, float]) -> np.ndarray:
    return np.array([rl.get(ResourceKind.CPU, 0.0),
                     rl.get(ResourceKind.MEMORY, 0.0)], np.float32)


def build_inputs(nodes: Sequence[Node],
                 metrics: Dict[str, NodeMetric],
                 pods_by_node: Dict[str, List[Pod]],
                 now: float,
                 node_reservations: Optional[Dict[str, Dict[ResourceKind, float]]] = None,
                 ) -> NodeResourceInputs:
    """Aggregate typed objects into calculator columns.

    Mirrors calculateOnNode's walk (batchresource/plugin.go:214-316): match
    pod-list entries against NodeMetric pod metrics, classify by priority
    class and QoS, and account dangling metrics.
    """
    n = len(nodes)
    z = lambda: np.zeros((n, 2), np.float32)
    cap, alloc, sys_used, sys_rsvd = z(), z(), z(), z()
    hp_req, hp_used, hp_max = z(), z(), z()
    reclaim = z()
    age = np.full((n,), np.inf, np.float32)
    valid = np.zeros((n,), bool)

    for i, node in enumerate(nodes):
        valid[i] = True
        alloc[i] = _rl2(node.allocatable)
        cap[i] = alloc[i]  # capacity ~= allocatable in canonical units
        if node_reservations and node.meta.name in node_reservations:
            sys_rsvd[i] = _rl2(node_reservations[node.meta.name])

        m = metrics.get(node.meta.name)
        pods = pods_by_node.get(node.meta.name, [])
        if m is None:
            # no metric: every HP pod counts at request; system unknown
            for pod in pods:
                if pod.phase not in ("Running", "Pending"):
                    continue
                if pod.priority_class in (PriorityClass.BATCH, PriorityClass.FREE):
                    continue
                r = _rl2(pod.requests)
                hp_req[i] += r
                hp_used[i] += r
                hp_max[i] += r
            continue

        age[i] = max(now - m.update_time, 0.0)
        sys_used[i] = _rl2(m.system_usage)
        reclaim[i] = _rl2(m.prod_reclaimable)
        pod_metrics = {pm.namespaced_name: pm for pm in m.pods_metric}
        dangling = dict(pod_metrics)

        for pod in pods:
            if pod.phase not in ("Running", "Pending"):
                continue
            key = pod.meta.namespaced_name
            pm = pod_metrics.get(key)
            if pm is not None:
                dangling.pop(key, None)
            if pod.priority_class in (PriorityClass.BATCH, PriorityClass.FREE):
                continue
            req = _rl2(pod.requests)
            hp_req[i] += req
            if pm is None:
                hp_used[i] += req  # not yet metered: count at request
            else:
                used = _rl2(pm.usage)
                if pod.qos is QoSClass.LSE:
                    # LSE never reclaims CPU: charge request on cpu, usage on mem
                    hp_used[i] += np.array([req[CPU], used[MEM]], np.float32)
                else:
                    hp_used[i] += used
                hp_max[i] += np.maximum(req, used)

        # dangling pod metrics: reported usage of pods no longer listed
        for pm in dangling.values():
            if pm.priority_class in (PriorityClass.BATCH, PriorityClass.FREE):
                continue
            used = _rl2(pm.usage)
            hp_used[i] += used
            hp_max[i] += used

    return NodeResourceInputs(
        capacity=cap, allocatable=alloc, system_used=sys_used,
        system_reserved=sys_rsvd, hp_request=hp_req, hp_used=hp_used,
        hp_max_used_req=hp_max, prod_reclaimable=reclaim,
        metric_age_seconds=age, valid=valid,
        names=[n.meta.name for n in nodes])


@shape_contract(
    capacity="f32[N~pad:zero,2]", node_reserved="f32[N~pad:zero,2]",
    system_reserved="f32[N~pad:zero,2]", system_used="f32[N~pad:zero,2]",
    hp_req="f32[N~pad:zero,2]", hp_used="f32[N~pad:zero,2]",
    hp_max="f32[N~pad:zero,2]",
    cpu_by_max="bool[N~pad:false]", mem_policy="i32[N~pad:zero]",
    _returns="f32[N~pad:zero,2]",
    _pad="columns are (cpu milli, mem MiB); clamped at 0, so padded "
         "zero-capacity rows return 0")
@jax.jit
def _batch_allocatable(capacity, node_reserved, system_reserved, system_used,
                       hp_req, hp_used, hp_max, cpu_by_max, mem_policy):
    """The three-policy batch formula (batchresource/util.go:38-90),
    vectorized over nodes. `mem_policy`: 0=usage, 1=request, 2=maxUsageRequest."""
    sys_eff = jnp.maximum(system_used, system_reserved)
    by_usage = jnp.maximum(capacity - node_reserved - sys_eff - hp_used, 0.0)
    by_request = jnp.maximum(
        capacity - node_reserved - system_reserved - hp_req, 0.0)
    by_max = jnp.maximum(capacity - node_reserved - sys_eff - hp_max, 0.0)

    cpu = jnp.where(cpu_by_max, by_max[:, CPU], by_usage[:, CPU])
    mem = jnp.where(mem_policy == 1, by_request[:, MEM],
                    jnp.where(mem_policy == 2, by_max[:, MEM],
                              by_usage[:, MEM]))
    return jnp.stack([cpu, mem], axis=-1)


@shape_contract(
    allocatable="f32[N~pad:zero,2]", prod_reclaimable="f32[N~pad:zero,2]",
    threshold_ratio="f32[N~pad:zero,2]",
    _returns="f32[N~pad:zero,2]",
    _pad="clamped at 0; degrade/invalid sentinels (-1) are applied "
         "host-side after the kernel")
@jax.jit
def _mid_allocatable(allocatable, prod_reclaimable, threshold_ratio):
    """Mid = min(ProdReclaimable, Allocatable × ratio), clamped at 0
    (midresource/plugin.go:130-160)."""
    cap = allocatable * threshold_ratio
    return jnp.maximum(jnp.minimum(prod_reclaimable, cap), 0.0)


_MEM_POLICY_CODE = {CalculatePolicy.USAGE: 0, CalculatePolicy.REQUEST: 1,
                    CalculatePolicy.MAX_USAGE_REQUEST: 2}


def compute_node_resources(inputs: NodeResourceInputs,
                           strategy: ColocationStrategy,
                           strategies: Optional[Sequence[ColocationStrategy]] = None,
                           ) -> Dict[str, np.ndarray]:
    """Run the full overcommit calculation for every node.

    `strategies`, when given, carries one (node-override-merged) strategy
    per node (ColocationConfig.strategy_for); thresholds and policies then
    vary per row. Returns {"batch": f32[N,2], "mid": f32[N,2],
    "degraded": bool[N]}; degraded rows carry −1 (the reference's Reset,
    plugin.go:153-162).
    """
    n = inputs.capacity.shape[0]
    per_node = list(strategies) if strategies is not None else [strategy] * n
    if len(per_node) != n:
        raise ValueError(f"{len(per_node)} strategies for {n} nodes")

    # per-node, per-dim reclaim ratios -> node reservation
    reserve_ratio = np.array(
        [[(100.0 - s.cpu_reclaim_threshold_percent) / 100.0,
          (100.0 - s.memory_reclaim_threshold_percent) / 100.0]
         for s in per_node], np.float32)
    node_reserved = inputs.capacity * reserve_ratio

    batch = np.asarray(_batch_allocatable(
        inputs.capacity, node_reserved, inputs.system_reserved,
        inputs.system_used, inputs.hp_request, inputs.hp_used,
        inputs.hp_max_used_req,
        jnp.asarray(np.array(
            [s.cpu_calculate_policy is CalculatePolicy.MAX_USAGE_REQUEST
             for s in per_node])),
        jnp.asarray(np.array(
            [_MEM_POLICY_CODE[s.memory_calculate_policy] for s in per_node],
            np.int32))))

    ratio = np.array([[s.mid_cpu_threshold_percent / 100.0,
                       s.mid_memory_threshold_percent / 100.0]
                      for s in per_node], np.float32)
    mid = np.asarray(_mid_allocatable(inputs.allocatable,
                                      inputs.prod_reclaimable, ratio))

    degrade_secs = np.array([s.degrade_time_minutes * 60.0 for s in per_node],
                            np.float32)
    degraded = inputs.metric_age_seconds >= degrade_secs
    batch = np.where(degraded[:, None], -1.0, batch)
    mid = np.where(degraded[:, None], -1.0, mid)
    batch[~inputs.valid] = -1.0
    mid[~inputs.valid] = -1.0
    return {"batch": batch, "mid": mid, "degraded": degraded & inputs.valid}


def need_sync(old: np.ndarray, new: np.ndarray,
              diff_threshold: float) -> np.ndarray:
    """bool[N]: relative diff of any dim exceeds the threshold
    (util.IsResourceDiff semantics: |new−old| / max(old, 1) > threshold;
    resets (−1) always sync when the old value differs)."""
    denom = np.maximum(np.abs(old), 1.0)
    diff = np.abs(new - old) / denom
    return np.any((diff > diff_threshold) | ((new < 0) != (old < 0)), axis=-1)


@dataclasses.dataclass
class NodeResourceController:
    """The reconcile loop: recompute overcommit columns and emit per-node
    updates, applying the NeedSync diff gate. Host-side shell around the
    jitted calculators (cmd/koord-manager noderesource controller)."""

    strategy: ColocationStrategy = dataclasses.field(
        default_factory=lambda: ColocationStrategy(enable=True))
    stats: Optional["SloControllerMetrics"] = None
    _last_batch: Optional[np.ndarray] = None
    _last_mid: Optional[np.ndarray] = None

    def reconcile(self, inputs: NodeResourceInputs,
                  strategies: Optional[Sequence[ColocationStrategy]] = None,
                  ) -> Dict[str, np.ndarray]:
        """Returns {"batch", "mid", "degraded", "sync_mask"}; callers fold
        `batch`/`mid` into Node allocatable (ResourceKind.BATCH_*/MID_*)
        for rows where sync_mask is set."""
        out = compute_node_resources(inputs, self.strategy, strategies)
        n = out["batch"].shape[0]
        if self._last_batch is None or self._last_batch.shape[0] != n:
            sync = np.ones((n,), bool)
            self._last_batch = out["batch"].copy()
            self._last_mid = out["mid"].copy()
        else:
            # honor per-node strategy overrides for the diff gate too, same
            # as the calculator does for the batch/mid math
            if strategies is not None:
                thr = np.asarray([s.resource_diff_threshold
                                  for s in strategies], np.float64)[:, None]
            else:
                thr = self.strategy.resource_diff_threshold
            sync = (need_sync(self._last_batch, out["batch"], thr)
                    | need_sync(self._last_mid, out["mid"], thr))
            # latch only rows that synced: the diff gate compares against the
            # last APPLIED value so sub-threshold drift accumulates until it
            # crosses the threshold (plugin.go NeedSync diffs vs node status)
            self._last_batch[sync] = out["batch"][sync]
            self._last_mid[sync] = out["mid"][sync]
        out["sync_mask"] = sync & inputs.valid
        if self.stats is None:
            self.stats = SloControllerMetrics()
        self.stats.node_resource_reconcile_count.labels("succeeded").inc()
        for plugin in ("batchresource", "midresource"):
            self.stats.node_resource_run_plugin_status.labels(
                plugin, "succeeded").inc()
        for i, name in enumerate(inputs.names):
            if not out["sync_mask"][i]:
                continue
            for tier, cols in (("batch", ((CPU, "batch-cpu"),
                                          (MEM, "batch-memory"))),
                               ("mid", ((CPU, "mid-cpu"),
                                        (MEM, "mid-memory")))):
                for col, kind in cols:
                    self.stats.node_extended_resource_allocatable.labels(
                        name, kind, "").set(float(out[tier][i, col]))
        return out
