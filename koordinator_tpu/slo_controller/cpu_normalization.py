"""CPU normalization: per-node performance ratio from the CPU model.

Capability parity with the noderesource CPUNormalization plugin
(`pkg/slo-controller/noderesource/plugins/cpunormalization/plugin.go`):
the cluster config maps CPU models to a performance ratio relative to
the fleet's basic model; the manager writes the node's ratio into the
`cpu-normalization-ratio` annotation, and koordlet's cpunormalization
runtime hook divides CFS quota by it so one requested millicore means
the same delivered compute on every machine generation. Ratios are
clamped to [1.0, 5.0] — scaling below the basic model is unsupported
(plugin.go defaultMinRatio/defaultMaxRatio).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    ANNOTATION_NODE_CPU_NORMALIZATION_RATIO,
)

MIN_RATIO = 1.0
MAX_RATIO = 5.0


@dataclasses.dataclass
class CPUNormalizationStrategy:
    """cpu-normalization-config ConfigMap entry: model -> ratio."""

    enable: bool = False
    ratio_model: Dict[str, float] = dataclasses.field(default_factory=dict)
    default_ratio: float = 1.0


def compute_ratio(strategy: CPUNormalizationStrategy,
                  cpu_model: str) -> float:
    ratio = strategy.ratio_model.get(cpu_model, strategy.default_ratio)
    return min(MAX_RATIO, max(MIN_RATIO, float(ratio)))


class CPUNormalizationPlugin:
    """Reconcile the ratio annotation from the node's CPU model (the
    model arrives through the koordlet nodeinfo collector's NodeCPUInfo;
    the reference reads it off the NodeResourceTopology annotations)."""

    name = "CPUNormalization"

    def __init__(self, strategy: Optional[CPUNormalizationStrategy] = None):
        self.strategy = strategy or CPUNormalizationStrategy()

    def reconcile(self, node: api.Node, cpu_model: str) -> bool:
        """Returns whether the node annotation changed."""
        anns = node.meta.annotations
        if not self.strategy.enable:
            return anns.pop(ANNOTATION_NODE_CPU_NORMALIZATION_RATIO,
                            None) is not None
        value = f"{compute_ratio(self.strategy, cpu_model):.2f}"
        if anns.get(ANNOTATION_NODE_CPU_NORMALIZATION_RATIO) == value:
            return False
        anns[ANNOTATION_NODE_CPU_NORMALIZATION_RATIO] = value
        return True


def node_ratio(node: Optional[api.Node]) -> float:
    """Parse the annotation; 1.0 (no scaling) on absence or bad value."""
    if node is None:
        return 1.0
    raw = node.meta.annotations.get(
        ANNOTATION_NODE_CPU_NORMALIZATION_RATIO, "")
    try:
        ratio = float(raw)
    except ValueError:
        return 1.0
    return ratio if MIN_RATIO <= ratio <= MAX_RATIO else 1.0
