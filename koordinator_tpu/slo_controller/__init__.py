"""slo-controller equivalent: the colocation overcommit engine, NodeMetric
lifecycle policy, and NodeSLO strategy rendering (SURVEY.md 2.3).

TPU-first design: instead of one controller-runtime reconcile per node, the
whole cluster's node columns go through batched calculators ([N, R] tensors,
jit-able) — one program updates every node's batch/mid allocatable per
NodeMetric sync round.
"""
