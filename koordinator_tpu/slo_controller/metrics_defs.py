"""slo-controller metric series — parity with pkg/slo-controller/metrics/
(common.go, metrics.go, node_resource.go)."""

from __future__ import annotations

from koordinator_tpu.metrics import Registry, global_registry


class SloControllerMetrics:
    def __init__(self, registry: Registry = None):
        r = registry if registry is not None else global_registry()
        self.nodemetric_reconcile_count = r.counter(
            "slo_controller_nodemetric_reconcile_count",
            "NodeMetric reconciliations by status",
            labels=("status",))
        self.nodemetric_spec_parse_count = r.counter(
            "slo_controller_nodemetric_spec_parse_count",
            "NodeMetric collect-policy config parses by status",
            labels=("status",))
        self.nodeslo_reconcile_count = r.counter(
            "slo_controller_nodeslo_reconcile_count",
            "NodeSLO reconciliations by status", labels=("status",))
        self.nodeslo_spec_parse_count = r.counter(
            "slo_controller_nodeslo_spec_parse_count",
            "NodeSLO strategy config parses by status", labels=("status",))
        self.node_resource_reconcile_count = r.counter(
            "slo_controller_node_resource_reconcile_count",
            "Node batch/mid resource reconciliations by status",
            labels=("status",))
        self.node_resource_run_plugin_status = r.counter(
            "slo_controller_node_resource_run_plugin_status",
            "Resource-calculate plugin runs by plugin and status",
            labels=("plugin", "status"))
        self.node_extended_resource_allocatable = r.gauge(
            "slo_controller_node_extended_resource_allocatable_internal",
            "Extended (batch/mid) allocatable the controller computed",
            labels=("node", "resource", "unit"))
