"""slo-controller metric series — parity with pkg/slo-controller/metrics/
(common.go, metrics.go, node_resource.go).

Family names come from the shared name registry
(koordinator_tpu/metrics/registry.py) and are re-exported here."""

from __future__ import annotations

from koordinator_tpu.metrics import Registry, global_registry
from koordinator_tpu.metrics.registry import (  # noqa: F401  (re-export)
    SLO_NODE_EXTENDED_RESOURCE_ALLOCATABLE,
    SLO_NODE_RESOURCE_RECONCILE_COUNT,
    SLO_NODE_RESOURCE_RUN_PLUGIN_STATUS,
    SLO_NODEMETRIC_RECONCILE_COUNT,
    SLO_NODEMETRIC_SPEC_PARSE_COUNT,
    SLO_NODESLO_RECONCILE_COUNT,
    SLO_NODESLO_SPEC_PARSE_COUNT,
)


class SloControllerMetrics:
    def __init__(self, registry: Registry = None):
        r = registry if registry is not None else global_registry()
        self.nodemetric_reconcile_count = r.counter(
            SLO_NODEMETRIC_RECONCILE_COUNT,
            "NodeMetric reconciliations by status",
            labels=("status",))
        self.nodemetric_spec_parse_count = r.counter(
            SLO_NODEMETRIC_SPEC_PARSE_COUNT,
            "NodeMetric collect-policy config parses by status",
            labels=("status",))
        self.nodeslo_reconcile_count = r.counter(
            SLO_NODESLO_RECONCILE_COUNT,
            "NodeSLO reconciliations by status", labels=("status",))
        self.nodeslo_spec_parse_count = r.counter(
            SLO_NODESLO_SPEC_PARSE_COUNT,
            "NodeSLO strategy config parses by status", labels=("status",))
        self.node_resource_reconcile_count = r.counter(
            SLO_NODE_RESOURCE_RECONCILE_COUNT,
            "Node batch/mid resource reconciliations by status",
            labels=("status",))
        self.node_resource_run_plugin_status = r.counter(
            SLO_NODE_RESOURCE_RUN_PLUGIN_STATUS,
            "Resource-calculate plugin runs by plugin and status",
            labels=("plugin", "status"))
        self.node_extended_resource_allocatable = r.gauge(
            SLO_NODE_EXTENDED_RESOURCE_ALLOCATABLE,
            "Extended (batch/mid) allocatable the controller computed",
            labels=("node", "resource", "unit"))
