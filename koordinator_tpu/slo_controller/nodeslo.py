"""NodeSLO rendering: turn cluster config + per-node overrides into the
per-node QoS strategy object the node agent enforces.

Capability parity with pkg/slo-controller/nodeslo (SURVEY.md 2.3): the
reference renders a NodeSLO CR per Node from the `slo-controller-config`
ConfigMap strategies (resourceThreshold / resourceQOS / cpuBurst / system),
each with per-nodeSelector overrides merged over the cluster default
(nodeslo/resource_strategy.go). Here the render is a pure function
node labels -> NodeSLO; the agent consumes it directly.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from koordinator_tpu.api.extension import selector_matches
from koordinator_tpu.api.types import (
    CPUBurstStrategy,
    NodeSLO,
    ResourceQOSStrategy,
    ResourceThresholdStrategy,
    SystemStrategy,
)
from koordinator_tpu.slo_controller.metrics_defs import SloControllerMetrics


@dataclasses.dataclass
class StrategyOverride:
    """One per-nodeSelector override entry: partial fields replacing the
    cluster default for matching nodes."""

    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    fields: Dict[str, object] = dataclasses.field(default_factory=dict)

    def matches(self, labels: Dict[str, str]) -> bool:
        return selector_matches(self.node_selector, labels)


@dataclasses.dataclass
class SLOControllerConfig:
    """The full dynamic config: cluster defaults + overrides per strategy
    family (apis/configuration/slo_controller_config.go)."""

    threshold: ResourceThresholdStrategy = dataclasses.field(
        default_factory=ResourceThresholdStrategy)
    threshold_overrides: List[StrategyOverride] = dataclasses.field(
        default_factory=list)
    cpu_burst: CPUBurstStrategy = dataclasses.field(
        default_factory=CPUBurstStrategy)
    cpu_burst_overrides: List[StrategyOverride] = dataclasses.field(
        default_factory=list)
    resource_qos: ResourceQOSStrategy = dataclasses.field(
        default_factory=ResourceQOSStrategy)
    resource_qos_overrides: List[StrategyOverride] = dataclasses.field(
        default_factory=list)
    system: SystemStrategy = dataclasses.field(default_factory=SystemStrategy)
    system_overrides: List[StrategyOverride] = dataclasses.field(
        default_factory=list)


def _merge(base, overrides: List[StrategyOverride],
           labels: Dict[str, str]):
    out = dataclasses.replace(base)
    for ov in overrides:
        if ov.matches(labels):
            for k, v in ov.fields.items():
                if not hasattr(out, k):
                    raise KeyError(f"unknown strategy field {k!r}")
                setattr(out, k, v)
            break  # first match wins (resource_strategy.go)
    return out


def render_node_slo(cfg: SLOControllerConfig, node_name: str,
                    node_labels: Optional[Dict[str, str]] = None,
                    stats: Optional["SloControllerMetrics"] = None) -> NodeSLO:
    """getNodeSLOSpec equivalent: cluster default + first matching override
    per strategy family."""
    labels = node_labels or {}
    try:
        qos = _merge(cfg.resource_qos, cfg.resource_qos_overrides, labels)
        qos = dataclasses.replace(
            qos, tiers={k: dict(v) for k, v in qos.tiers.items()})
        slo = NodeSLO(
            node_name=node_name,
            threshold=_merge(cfg.threshold, cfg.threshold_overrides, labels),
            cpu_burst=_merge(cfg.cpu_burst, cfg.cpu_burst_overrides, labels),
            resource_qos=qos,
            system=_merge(cfg.system, cfg.system_overrides, labels),
        )
    except Exception:
        if stats is not None:
            stats.nodeslo_reconcile_count.labels("failed").inc()
        raise
    if stats is not None:
        stats.nodeslo_reconcile_count.labels("succeeded").inc()
    return slo


@dataclasses.dataclass
class NodeMetricCollectPolicy:
    """NodeMetric spec collect policy distributed by the nodemetric
    controller (pkg/slo-controller/nodemetric/collect_policy.go)."""

    aggregate_duration_seconds: float = 300.0
    report_interval_seconds: float = 60.0
    node_aggregate_policy_durations: List[float] = dataclasses.field(
        default_factory=lambda: [300.0, 600.0, 1800.0])


def collect_policy_from_colocation(metric_aggregate_duration_seconds: float,
                                   metric_report_interval_seconds: float,
                                   ) -> NodeMetricCollectPolicy:
    """nodemetric controller: derive the collect policy from the colocation
    strategy fields (collect_policy.go getNodeMetricCollectPolicy)."""
    return NodeMetricCollectPolicy(
        aggregate_duration_seconds=metric_aggregate_duration_seconds,
        report_interval_seconds=metric_report_interval_seconds)
