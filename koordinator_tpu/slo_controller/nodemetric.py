"""NodeMetric controller: ensures one NodeMetric object per Node and keeps
its collect policy in sync with the dynamic config.

Capability parity with pkg/slo-controller/nodemetric (SURVEY.md 2.3,
collect_policy.go): the spec side of NodeMetric (report interval,
aggregation windows) is owned by the control plane; the node agent fills
status. The policy type is the SAME object the koordlet reporter consumes
(statesinformer.CollectPolicy) — the controller distributes it, the agent
obeys it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from koordinator_tpu.api import types as api
from koordinator_tpu.koordlet.statesinformer import CollectPolicy


class NodeMetricController:
    def __init__(self, policy: Optional[CollectPolicy] = None):
        self.policy = policy or CollectPolicy()
        self.metrics: Dict[str, api.NodeMetric] = {}

    def collect_policy(self) -> CollectPolicy:
        """The spec the agents should run with (NodeMetricSpec
        distribution)."""
        return self.policy

    def reconcile(self, nodes: Sequence[api.Node]) -> List[api.NodeMetric]:
        """Create missing NodeMetric shells, sync their report interval,
        and drop rows for deleted nodes; returns the live set."""
        names = {n.meta.name for n in nodes}
        for stale in set(self.metrics) - names:
            del self.metrics[stale]
        for node in nodes:
            m = self.metrics.get(node.meta.name)
            if m is None:
                m = self.metrics[node.meta.name] = api.NodeMetric(
                    node_name=node.meta.name)
            m.report_interval_seconds = self.policy.report_interval_seconds
        return [self.metrics[n.meta.name] for n in nodes]

    def observe_status(self, report: api.NodeMetric) -> None:
        """Fold a koordlet status report into the controller's view (the
        agent writes status; spec fields stay controller-owned)."""
        m = self.metrics.get(report.node_name)
        if m is None:
            self.metrics[report.node_name] = report
            return
        m.update_time = report.update_time
        m.node_usage = report.node_usage
        m.system_usage = report.system_usage
        m.aggregated = report.aggregated
        m.pods_metric = report.pods_metric
        m.prod_reclaimable = report.prod_reclaimable
