"""NodeMetric controller: ensures one NodeMetric object per Node and keeps
its collect policy in sync with the dynamic config.

Capability parity with pkg/slo-controller/nodemetric (SURVEY.md 2.3,
collect_policy.go): the spec side of NodeMetric (report interval,
aggregation windows) is owned by the control plane; the node agent fills
status. The policy type is the SAME object the koordlet reporter consumes
(statesinformer.CollectPolicy) — the controller distributes it, the agent
obeys it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from koordinator_tpu.api import types as api
from koordinator_tpu.koordlet.statesinformer import CollectPolicy
from koordinator_tpu.slo_controller.metrics_defs import SloControllerMetrics


class NodeMetricController:
    def __init__(self, policy: Optional[CollectPolicy] = None,
                 stats: Optional[SloControllerMetrics] = None):
        self.policy = policy or CollectPolicy()
        # `metrics` is the NodeMetric CR map; the series catalog is `stats`
        self.stats = stats if stats is not None else SloControllerMetrics()
        self.metrics: Dict[str, api.NodeMetric] = {}

    def parse_policy(self, metric_aggregate_duration_seconds: float,
                     metric_report_interval_seconds: float) -> CollectPolicy:
        """Derive the collect policy from colocation config fields
        (collect_policy.go getNodeMetricCollectPolicy), counting parse
        outcomes."""
        try:
            if metric_report_interval_seconds <= 0 or \
                    metric_aggregate_duration_seconds <= 0:
                raise ValueError("non-positive collect policy interval")
            policy = CollectPolicy(
                report_interval_seconds=metric_report_interval_seconds,
                aggregate_duration_seconds=metric_aggregate_duration_seconds)
        except Exception:
            self.stats.nodemetric_spec_parse_count.labels("failed").inc()
            raise
        self.stats.nodemetric_spec_parse_count.labels("succeeded").inc()
        self.policy = policy
        return policy

    def collect_policy(self) -> CollectPolicy:
        """The spec the agents should run with (NodeMetricSpec
        distribution)."""
        return self.policy

    def reconcile(self, nodes: Sequence[api.Node]) -> List[api.NodeMetric]:
        """Create missing NodeMetric shells, sync their report interval,
        and drop rows for deleted nodes; returns the live set."""
        names = {n.meta.name for n in nodes}
        for stale in set(self.metrics) - names:
            del self.metrics[stale]
        for node in nodes:
            m = self.metrics.get(node.meta.name)
            if m is None:
                m = self.metrics[node.meta.name] = api.NodeMetric(
                    node_name=node.meta.name)
            m.report_interval_seconds = self.policy.report_interval_seconds
        self.stats.nodemetric_reconcile_count.labels("succeeded").inc()
        return [self.metrics[n.meta.name] for n in nodes]

    def observe_status(self, report: api.NodeMetric) -> None:
        """Fold a koordlet status report into the controller's view (the
        agent writes status; spec fields stay controller-owned)."""
        m = self.metrics.get(report.node_name)
        if m is None:
            self.metrics[report.node_name] = report
            return
        m.update_time = report.update_time
        m.node_usage = report.node_usage
        m.system_usage = report.system_usage
        m.aggregated = report.aggregated
        m.pods_metric = report.pods_metric
        m.prod_reclaimable = report.prod_reclaimable
