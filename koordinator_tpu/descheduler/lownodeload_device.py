"""Device-resident LowNodeLoad plan (BASELINE config 5).

The host plugin (lownodeload.py) walks source nodes and their pods
sequentially — the faithful mirror of evictPodsFromSourceNodes
(/root/reference/pkg/descheduler/framework/plugins/loadaware/
low_node_load.go:232-305). That greedy is in fact PREFIX-STRUCTURED, so
the whole plan vectorizes with no per-pod loop at all:

- Within one source node, pods are evicted in sorted order while the
  node is still over its high threshold. Usage only decreases as pods
  leave, so "still over" is monotone: the evicted set is a PREFIX of
  the node's sorted removable pods — computable for every node at once
  with a segment exclusive-cumsum.
- Across nodes, the shared destination budget only decreases, and the
  reference stops as soon as any dimension is exhausted — so "budget
  still open" is ALSO monotone along the global eviction order: one
  exclusive cumsum over the would-be-evicted pods. Same for the
  per-cycle eviction cap.
- A pod is planned iff (node prefix holds) AND (budget prefix holds):
  two cumsums and a gather replace the reference's nested loop. This is
  the TPU-native shape of the "batched ILP relax" BASELINE.json names:
  the LP's greedy rounding collapses into prefix sums.

Classification (thresholds, deviation mode, freshness) and node_fit run
batched on device too. Host keeps only the typed->columnar flattening,
the anomaly counters (stateful across cycles), and offering the planned
pods to the evictor.

Per-node / per-namespace / per-cycle eviction caps (the
EvictionLimiter production configuration — migration arbitrator
blast-radius bounding, /root/reference/pkg/descheduler/controllers/
migration/arbitrator/filter.go) are ALSO modeled on device. Unlike the
uncapped plan they are not prefix-structured: the host loop SKIPS a
refused pod (no usage/budget subtraction) and continues, so acceptance
within a node is not a prefix of its sorted pods (ns-capped pods
interleave with accepted ones). The capped kernel therefore runs ONE
`lax.scan` along the global eviction order with a small carry (current
node's removed usage + count, global budget, total, per-namespace
counts) — still a single device program over the same columns, with
the classification/ordering prelude shared with the prefix kernel.

Narrowing (documented): the device plans predict the EvictionLimiter
exactly; a CUSTOM evictor that refuses arbitrary pods is honored by
filtering the returned selection on evict()'s result, but refusals do
not re-plan (the freed allowance is not re-offered to later pods until
the next cycle).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import NUM_RESOURCES, ResourceKind
from koordinator_tpu.descheduler.lownodeload import (
    LowNodeLoad,
    LowNodeLoadArgs,
)
from koordinator_tpu.snapshot.builder import resource_vec
from koordinator_tpu.snapshot.schema import shape_contract


def _plan_prelude(usage, capacity, fresh, source_mask,
                  pod_node, pod_usage_r, pod_req, pod_eligible,
                  low, high, weights, rdims_onehot,
                  use_deviation: bool, node_fit: bool, fit_dims: tuple):
    """Shared front half of both plan kernels: classification, budget,
    node_fit eligibility, and the global eviction order. Traced inside
    a jit, never called eagerly."""
    eps = 1e-9
    sel = lambda x: x @ rdims_onehot.T                    # [.., R]->[.., Rd]
    pct = 100.0 * sel(usage) / jnp.maximum(sel(capacity), eps)  # [N, Rd]
    if use_deviation:
        nf = jnp.maximum(fresh.sum(), 1)
        avg = jnp.where(fresh[:, None], pct, 0.0).sum(0) / nf
        low = jnp.clip(avg - low, 0.0, 100.0)
        high = jnp.clip(avg + high, 0.0, 100.0)
    low_mask = fresh & (pct < low[None, :]).all(1)        # [N]
    high_mask = fresh & (pct > high[None, :]).any(1)      # [N]
    high_abs = sel(capacity) * high[None, :] / 100.0      # [N, Rd]
    source = source_mask & high_mask                      # [N]

    # a -1 pod_node (pad rows, orphan pods) must not wrap to the last
    # node: clamp every gather through `pn` and gate on `on_node` so
    # such rows are never active and never charge a node
    on_node = pod_node >= 0                               # [P]
    pn = jnp.maximum(pod_node, 0)                         # [P]

    # budget: spare headroom under the HIGH threshold of destinations
    budget0 = jnp.where(low_mask[:, None],
                        high_abs - sel(usage), 0.0).sum(0)  # [Rd]

    # node_fit: pod must fit on >= 1 underutilized node, against
    # allocatable - Σ requests of that node's pods. `fit_dims` (static)
    # restricts the [P, N, R] comparison to dims ANY pod requests —
    # exact, because an unrequested dim compares 0 <= capacity + 0.5,
    # always true (the scheduler bench's fit_dims argument, same idea).
    if node_fit:
        node_req = jnp.zeros_like(capacity).at[pn].add(
            pod_req * on_node[:, None])
        dest_free = capacity - node_req                   # [N, R]
        fd = list(fit_dims) if fit_dims is not None else slice(None)
        fits_pn = (pod_req[:, None, fd] <= dest_free[None][:, :, fd]
                   + 0.5).all(-1)                         # [P, N]
        fits = (fits_pn & low_mask[None, :]).any(-1)      # [P]
        pod_eligible = pod_eligible & fits

    active = pod_eligible & on_node & source[pn]          # [P]

    # --- global eviction order: source nodes by weighted usage%% desc,
    # pods within a node by weighted usage desc (stable = list order) --
    node_w = (pct * weights[None, :]).sum(1)              # [N]
    n = usage.shape[0]
    src_rank = jnp.zeros((n,), jnp.int32).at[
        jnp.argsort(-jnp.where(source, node_w, -jnp.inf))].set(
        jnp.arange(n, dtype=jnp.int32))
    pod_w = (pod_usage_r * weights[None, :]).sum(1)       # [P]
    ord1 = jnp.argsort(-pod_w, stable=True)
    pod_rank = jnp.where(on_node, src_rank[pn], n)        # nodeless last
    order = ord1[jnp.argsort(pod_rank[ord1], stable=True)]
    return sel, active, order, budget0, high_abs


@shape_contract(
    usage="f32[N~pad:zero,R]", capacity="f32[N~pad:zero,R]",
    fresh="bool[N~pad:false]",
    source_mask="bool[N~pad:false]", pod_node="i32[P~pad:-1]",
    pod_usage_r="f32[P~pad:zero,RD]",
    pod_req="f32[P~pad:zero,R]", pod_eligible="bool[P~pad:false]",
    low="f32[RD]",
    high="f32[RD]", weights="f32[RD]", rdims_onehot="f32[RD,R]",
    max_evictions="i32[]",
    _returns=("bool[P~pad:false]", "i32[P~pad:any]"),
    _pad="pod_usage_r is pre-restricted to the RD threshold dims via "
         "rdims_onehot; ineligible pods are simply never taken")
@functools.partial(jax.jit, static_argnames=("use_deviation", "node_fit",
                                             "fit_dims"))
def plan_kernel(usage, capacity, fresh, source_mask,
                pod_node, pod_usage_r, pod_req, pod_eligible,
                low, high, weights, rdims_onehot,
                max_evictions,
                use_deviation: bool = False, node_fit: bool = True,
                fit_dims: tuple = None):
    """The full balance plan as one jitted program.

    Shapes: usage/capacity f32[N, R]; pod_* over P pods with
    pod_usage_r f32[P, Rd] already restricted to the threshold dims;
    rdims_onehot f32[Rd, R] selects those dims out of R columns;
    low/high/weights f32[Rd]. Returns (take bool[P], order i32[P]):
    take[p] marks planned pods, order is the global eviction order (the
    plan is `[int(i) for i in order if take[i]]`).
    """
    sel, active, order, budget0, high_abs = _plan_prelude(
        usage, capacity, fresh, source_mask, pod_node, pod_usage_r,
        pod_req, pod_eligible, low, high, weights, rdims_onehot,
        use_deviation, node_fit, fit_dims)

    ns = pod_node[order]                                  # sorted node ids
    x = jnp.where(active[order, None], pod_usage_r[order], 0.0)  # [P, Rd]

    # segment (per-node) EXCLUSIVE cumsum along the sorted order
    ex = jnp.cumsum(x, 0) - x
    p = x.shape[0]
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), ns[1:] != ns[:-1]])
    start_idx = lax_cummax(jnp.where(is_start,
                                     jnp.arange(p, dtype=jnp.int32), -1))
    seg_ex = ex - ex[jnp.maximum(start_idx, 0)]           # [P, Rd]

    # node prefix: evict while the node is STILL over before this pod
    still_over = ((sel(usage)[ns] - seg_ex) > high_abs[ns]).any(1)  # [P]
    take0 = active[order] & still_over

    # budget prefix (and per-cycle cap): both monotone along the order
    taken_x = jnp.where(take0[:, None], pod_usage_r[order], 0.0)
    cum_before = jnp.cumsum(taken_x, 0) - taken_x
    budget_ok = (budget0[None, :] - cum_before > 0.0).all(1)
    cnt_before = jnp.cumsum(take0.astype(jnp.int32)) - take0
    take_sorted = take0 & budget_ok & (cnt_before < max_evictions)

    take = jnp.zeros((p,), bool).at[order].set(take_sorted)
    return take, order


def lax_cummax(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.maximum, x)


@shape_contract(
    usage="f32[N~pad:zero,R]", capacity="f32[N~pad:zero,R]",
    fresh="bool[N~pad:false]",
    source_mask="bool[N~pad:false]", pod_node="i32[P~pad:-1]",
    pod_usage_r="f32[P~pad:zero,RD]",
    pod_req="f32[P~pad:zero,R]", pod_eligible="bool[P~pad:false]",
    low="f32[RD]",
    high="f32[RD]", weights="f32[RD]", rdims_onehot="f32[RD,R]",
    pod_ns="i32[P~pad:zero]", ns_counts0="i32[NS~pad:zero]",
    per_node0="i32[N~pad:zero]",
    max_evictions="i32[]", max_per_node="i32[]", max_per_ns="i32[]",
    _returns=("bool[P~pad:false]", "i32[P~pad:any]"),
    _pad="ns_counts0 is padded to a pow2 namespace table "
         "(columnarize_ns); unlimited caps ride _BIG sentinels")
@functools.partial(jax.jit, static_argnames=("use_deviation", "node_fit",
                                             "fit_dims"))
def plan_kernel_capped(usage, capacity, fresh, source_mask,
                       pod_node, pod_usage_r, pod_req, pod_eligible,
                       low, high, weights, rdims_onehot,
                       pod_ns, ns_counts0, per_node0,
                       max_evictions, max_per_node, max_per_ns,
                       use_deviation: bool = False, node_fit: bool = True,
                       fit_dims: tuple = None):
    """The balance plan under per-node / per-namespace / per-cycle caps.

    The host loop SKIPS a limiter-refused pod (no usage or budget
    subtraction) and keeps walking, so acceptance is not prefix-
    structured; this kernel replays that exact decision sequence as one
    `lax.scan` along the global eviction order. Carry: the CURRENT
    node's removed usage + eviction count (the order is node-contiguous,
    so one scalar pair suffices), the global budget/total, and the
    per-namespace counts (`ns_counts0`, padded — see columnarize_ns).
    `per_node0[n]` seeds node n's count from the limiter's existing
    state (mid-cycle reuse), as ns_counts0 does for namespaces.
    Returns (take bool[P], order i32[P]) like plan_kernel.
    """
    sel, active, order, budget0, high_abs = _plan_prelude(
        usage, capacity, fresh, source_mask, pod_node, pod_usage_r,
        pod_req, pod_eligible, low, high, weights, rdims_onehot,
        use_deviation, node_fit, fit_dims)

    ns = pod_node[order]
    usage_node = sel(usage)[ns]                           # [P, Rd]
    high_abs_s = high_abs[ns]                             # [P, Rd]
    pod_ns_s = pod_ns[order]                              # [P]
    u_s = pod_usage_r[order]                              # [P, Rd]
    active_s = active[order]
    p = u_s.shape[0]
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), ns[1:] != ns[:-1]])
    node_cnt0_s = per_node0[ns]                           # [P]

    def step(carry, xs):
        removed, node_cnt, budget, total, ns_counts = carry
        (start, u, un, ha, nsid, act, cnt0) = xs
        removed = jnp.where(start, jnp.zeros_like(removed), removed)
        node_cnt = jnp.where(start, cnt0, node_cnt)
        # host order: the still_over/budget break check runs BEFORE the
        # evict() limiter call; a limiter refusal subtracts nothing
        still_over = ((un - removed) > ha).any()
        budget_open = (budget > 0.0).all()
        want = act & still_over & budget_open
        allow = ((total < max_evictions)
                 & (node_cnt < max_per_node)
                 & (ns_counts[nsid] < max_per_ns))
        take = want & allow
        tf = take.astype(u.dtype)
        removed = removed + u * tf
        budget = budget - u * tf
        total = total + take.astype(total.dtype)
        node_cnt = node_cnt + take.astype(node_cnt.dtype)
        ns_counts = ns_counts.at[nsid].add(take.astype(ns_counts.dtype))
        return (removed, node_cnt, budget, total, ns_counts), take

    rd = u_s.shape[1]
    carry0 = (jnp.zeros((rd,), u_s.dtype), jnp.int32(0), budget0,
              jnp.int32(0), ns_counts0.astype(jnp.int32))
    _, take_sorted = jax.lax.scan(
        step, carry0,
        (is_start, u_s, usage_node, high_abs_s, pod_ns_s, active_s,
         node_cnt0_s))
    take = jnp.zeros((p,), bool).at[order].set(take_sorted)
    return take, order


def _pad_pow2(n: int, lo: int = 8) -> int:
    k = lo
    while k < n:
        k *= 2
    return k


def columnarize(nodes: Sequence[api.Node],
                metrics: Mapping[str, api.NodeMetric],
                pods_by_node: Mapping[str, Sequence[api.Pod]],
                args: LowNodeLoadArgs,
                usage: np.ndarray, capacity: np.ndarray,
                fresh: np.ndarray) -> Optional[dict]:
    """Typed host objects -> the kernel's POD columns (the node columns
    come in prebuilt from LowNodeLoad.node_columns, so flattening
    happens once). No per-pod decision logic here — that is the
    kernel's job. Pod usage is collected from EVERY NodeMetric,
    expired or not, matching the host plugin's pod_usage build (only
    node freshness gates classification)."""
    rdims = sorted({int(k) for k in args.high_thresholds})
    name_to_idx = {node.meta.name: i for i, node in enumerate(nodes)}
    pod_usage_map: Dict[str, np.ndarray] = {}
    for name in name_to_idx:
        m = metrics.get(name)
        if m is not None:
            for pm in m.pods_metric:
                pod_usage_map[pm.namespaced_name] = resource_vec(pm.usage)

    pods: List[api.Pod] = []
    pod_node_l: List[int] = []
    for name, plist in pods_by_node.items():
        i = name_to_idx.get(name)
        if i is None:
            continue
        for pod in plist:
            pods.append(pod)
            pod_node_l.append(i)
    p = len(pods)
    if p == 0:
        return None
    pod_node = np.asarray(pod_node_l, np.int32)
    pod_req = np.zeros((p, NUM_RESOURCES), np.float32)
    pod_usage_r = np.zeros((p, len(rdims)), np.float32)
    pod_eligible = np.zeros((p,), bool)
    for j, pod in enumerate(pods):
        pod_req[j] = resource_vec(pod.requests)
        u = pod_usage_map.get(pod.meta.namespaced_name)
        if u is None:
            u = pod_req[j]
        pod_usage_r[j] = u[rdims]
        pod_eligible[j] = not pod.is_daemonset and (
            args.pod_filter is None or args.pod_filter(pod))

    low = np.array([args.low_thresholds.get(ResourceKind(d), 0.0)
                    for d in rdims], np.float32)
    high = np.array([args.high_thresholds.get(ResourceKind(d), 100.0)
                     for d in rdims], np.float32)
    weights = np.array([args.resource_weights.get(ResourceKind(d), 0.0)
                        for d in rdims], np.float32)
    rdims_onehot = np.zeros((len(rdims), NUM_RESOURCES), np.float32)
    rdims_onehot[np.arange(len(rdims)), rdims] = 1.0
    fit_dims = tuple(int(d) for d in np.flatnonzero(pod_req.any(0)))
    return dict(usage=usage, capacity=capacity, fresh=fresh,
                pod_node=pod_node, pod_usage_r=pod_usage_r,
                pod_req=pod_req, pod_eligible=pod_eligible,
                low=low, high=high, weights=weights,
                rdims_onehot=rdims_onehot, pods=pods,
                fit_dims=fit_dims)


class DeviceLowNodeLoad(LowNodeLoad):
    """LowNodeLoad with the balance plan computed on device.

    Classification for the anomaly counters reuses the host classify()
    (cheap, stateful); the eviction selection — the O(N x P) part — is
    one jitted program. Per-cycle caps ride the prefix kernel; per-node
    / per-namespace caps (the production blast-radius configuration)
    switch to the scan kernel, which replays the limiter's exact
    skip-and-continue decisions. A custom evictor that refuses pods the
    limiter model did not predict is honored by filtering the returned
    selection on evict()'s result — refusals do not re-plan.
    """

    name = "LowNodeLoad"

    _BIG = 1 << 30

    def _limiter_caps(self):
        """(cycle_remaining, max_per_node, max_per_ns, limiter), with
        _BIG sentinels for unlimited dimensions."""
        limiter = getattr(self.evictor, "limiter", None)
        if limiter is None:
            return self._BIG, self._BIG, self._BIG, None
        cyc = (self._BIG if limiter.max_per_cycle is None
               else limiter.max_per_cycle - limiter._total)
        per_node = (self._BIG if limiter.max_per_node is None
                    else limiter.max_per_node)
        per_ns = (self._BIG if limiter.max_per_namespace is None
                  else limiter.max_per_namespace)
        return cyc, per_node, per_ns, limiter

    def balance_once(self, nodes, metrics, pods_by_node, now):
        args = self.args
        # the host plugin never consults the evictor in dry_run —
        # neither may the device caps (golden parity)
        if args.dry_run:
            cyc, per_node, per_ns, limiter = (self._BIG, self._BIG,
                                              self._BIG, None)
        else:
            cyc, per_node, per_ns, limiter = self._limiter_caps()
        if not nodes:
            return []
        # ONE flattening pass; anomaly gating stays host-side
        # (stateful across cycles)
        usage, capacity, fresh = self.node_columns(nodes, metrics, now)
        _, _, low_mask, high_mask, _ = self.classify_columns(
            usage, capacity, fresh)
        names = [nd.meta.name for nd in nodes]
        source_mask = self._gate_anomalies(names, high_mask)
        if not low_mask.any() or not source_mask.any():
            return []
        cols = columnarize(nodes, metrics, pods_by_node, args,
                           usage, capacity, fresh)
        if cols is None:
            return []
        pods = cols.pop("pods")
        pod_node = cols["pod_node"]
        if per_node < self._BIG or per_ns < self._BIG:
            # namespace ids + seeded limiter state (mid-cycle reuse)
            ns_names = sorted({p.meta.namespace for p in pods})
            ns_of = {s: j for j, s in enumerate(ns_names)}
            pod_ns = np.asarray([ns_of[p.meta.namespace] for p in pods],
                                np.int32)
            ns_counts0 = np.zeros((_pad_pow2(len(ns_names)),), np.int32)
            per_node0 = np.zeros((len(nodes),), np.int32)
            if limiter is not None:
                for s, j in ns_of.items():
                    ns_counts0[j] = limiter._per_ns.get(s, 0)
                for i, name in enumerate(names):
                    per_node0[i] = limiter._per_node.get(name, 0)
            take, order = plan_kernel_capped(
                source_mask=source_mask,
                pod_ns=pod_ns, ns_counts0=ns_counts0,
                per_node0=per_node0,
                max_evictions=np.int32(max(min(cyc, self._BIG), 0)),
                max_per_node=np.int32(min(per_node, self._BIG)),
                max_per_ns=np.int32(min(per_ns, self._BIG)),
                use_deviation=args.use_deviation_thresholds,
                node_fit=args.node_fit, **cols)
        else:
            take, order = plan_kernel(
                source_mask=source_mask,
                max_evictions=np.int32(max(min(cyc, self._BIG), 0)),
                use_deviation=args.use_deviation_thresholds,
                node_fit=args.node_fit, **cols)
        take = np.asarray(take)
        sel_idx = [int(i) for i in np.asarray(order) if take[int(i)]]
        if args.dry_run or self.evictor is None:
            return [pods[i] for i in sel_idx]
        selected = []
        for i in sel_idx:
            # honor the live verdict: a custom evictor may refuse pods
            # the limiter model did not predict (refused pods are NOT
            # re-planned — the host loop drops them the same way)
            if self.evictor.evict(
                    pods[i], f"node {names[int(pod_node[i])]} is "
                             f"overutilized"):
                selected.append(pods[i])
        return selected
