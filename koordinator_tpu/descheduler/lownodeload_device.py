"""Device-resident LowNodeLoad plan (BASELINE config 5).

The host plugin (lownodeload.py) walks source nodes and their pods
sequentially — the faithful mirror of evictPodsFromSourceNodes
(/root/reference/pkg/descheduler/framework/plugins/loadaware/
low_node_load.go:232-305). That greedy is in fact PREFIX-STRUCTURED, so
the whole plan vectorizes with no per-pod loop at all:

- Within one source node, pods are evicted in sorted order while the
  node is still over its high threshold. Usage only decreases as pods
  leave, so "still over" is monotone: the evicted set is a PREFIX of
  the node's sorted removable pods — computable for every node at once
  with a segment exclusive-cumsum.
- Across nodes, the shared destination budget only decreases, and the
  reference stops as soon as any dimension is exhausted — so "budget
  still open" is ALSO monotone along the global eviction order: one
  exclusive cumsum over the would-be-evicted pods. Same for the
  per-cycle eviction cap.
- A pod is planned iff (node prefix holds) AND (budget prefix holds):
  two cumsums and a gather replace the reference's nested loop. This is
  the TPU-native shape of the "batched ILP relax" BASELINE.json names:
  the LP's greedy rounding collapses into prefix sums.

Classification (thresholds, deviation mode, freshness) and node_fit run
batched on device too. Host keeps only the typed->columnar flattening,
the anomaly counters (stateful across cycles), and offering the planned
pods to the evictor.

Narrowing (documented): the plan assumes the evictor accepts every
offered pod. A per-cycle cap is modeled ON device (`max_evictions`);
per-node / per-namespace caps are not — `DeviceLowNodeLoad` falls back
to the host loop when those are configured, so plans never silently
diverge from the limiter.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import NUM_RESOURCES, ResourceKind
from koordinator_tpu.descheduler.lownodeload import (
    LowNodeLoad,
    LowNodeLoadArgs,
)
from koordinator_tpu.snapshot.builder import resource_vec


@functools.partial(jax.jit, static_argnames=("use_deviation", "node_fit",
                                             "fit_dims"))
def plan_kernel(usage, capacity, fresh, source_mask,
                pod_node, pod_usage_r, pod_req, pod_eligible,
                low, high, weights, rdims_onehot,
                max_evictions,
                use_deviation: bool = False, node_fit: bool = True,
                fit_dims: tuple = None):
    """The full balance plan as one jitted program.

    Shapes: usage/capacity f32[N, R]; pod_* over P pods with
    pod_usage_r f32[P, Rd] already restricted to the threshold dims;
    rdims_onehot f32[Rd, R] selects those dims out of R columns;
    low/high/weights f32[Rd]. Returns (take bool[P], order i32[P]):
    take[p] marks planned pods, order is the global eviction order (the
    plan is `[int(i) for i in order if take[i]]`).
    """
    eps = 1e-9
    sel = lambda x: x @ rdims_onehot.T                    # [.., R]->[.., Rd]
    pct = 100.0 * sel(usage) / jnp.maximum(sel(capacity), eps)  # [N, Rd]
    if use_deviation:
        nf = jnp.maximum(fresh.sum(), 1)
        avg = jnp.where(fresh[:, None], pct, 0.0).sum(0) / nf
        low = jnp.clip(avg - low, 0.0, 100.0)
        high = jnp.clip(avg + high, 0.0, 100.0)
    low_mask = fresh & (pct < low[None, :]).all(1)        # [N]
    high_mask = fresh & (pct > high[None, :]).any(1)      # [N]
    high_abs = sel(capacity) * high[None, :] / 100.0      # [N, Rd]
    source = source_mask & high_mask                      # [N]

    # budget: spare headroom under the HIGH threshold of destinations
    budget0 = jnp.where(low_mask[:, None],
                        high_abs - sel(usage), 0.0).sum(0)  # [Rd]

    # node_fit: pod must fit on >= 1 underutilized node, against
    # allocatable - Σ requests of that node's pods. `fit_dims` (static)
    # restricts the [P, N, R] comparison to dims ANY pod requests —
    # exact, because an unrequested dim compares 0 <= capacity + 0.5,
    # always true (the scheduler bench's fit_dims argument, same idea).
    if node_fit:
        node_req = jnp.zeros_like(capacity).at[pod_node].add(pod_req)
        dest_free = capacity - node_req                   # [N, R]
        fd = list(fit_dims) if fit_dims is not None else slice(None)
        fits_pn = (pod_req[:, None, fd] <= dest_free[None][:, :, fd]
                   + 0.5).all(-1)                         # [P, N]
        fits = (fits_pn & low_mask[None, :]).any(-1)      # [P]
        pod_eligible = pod_eligible & fits

    active = pod_eligible & source[pod_node]              # [P]

    # --- global eviction order: source nodes by weighted usage%% desc,
    # pods within a node by weighted usage desc (stable = list order) --
    node_w = (pct * weights[None, :]).sum(1)              # [N]
    n = usage.shape[0]
    src_rank = jnp.zeros((n,), jnp.int32).at[
        jnp.argsort(-jnp.where(source, node_w, -jnp.inf))].set(
        jnp.arange(n, dtype=jnp.int32))
    pod_w = (pod_usage_r * weights[None, :]).sum(1)       # [P]
    ord1 = jnp.argsort(-pod_w, stable=True)
    order = ord1[jnp.argsort(src_rank[pod_node[ord1]], stable=True)]

    ns = pod_node[order]                                  # sorted node ids
    x = jnp.where(active[order, None], pod_usage_r[order], 0.0)  # [P, Rd]

    # segment (per-node) EXCLUSIVE cumsum along the sorted order
    ex = jnp.cumsum(x, 0) - x
    p = x.shape[0]
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), ns[1:] != ns[:-1]])
    start_idx = lax_cummax(jnp.where(is_start,
                                     jnp.arange(p, dtype=jnp.int32), -1))
    seg_ex = ex - ex[jnp.maximum(start_idx, 0)]           # [P, Rd]

    # node prefix: evict while the node is STILL over before this pod
    still_over = ((sel(usage)[ns] - seg_ex) > high_abs[ns]).any(1)  # [P]
    take0 = active[order] & still_over

    # budget prefix (and per-cycle cap): both monotone along the order
    taken_x = jnp.where(take0[:, None], pod_usage_r[order], 0.0)
    cum_before = jnp.cumsum(taken_x, 0) - taken_x
    budget_ok = (budget0[None, :] - cum_before > 0.0).all(1)
    cnt_before = jnp.cumsum(take0.astype(jnp.int32)) - take0
    take_sorted = take0 & budget_ok & (cnt_before < max_evictions)

    take = jnp.zeros((p,), bool).at[order].set(take_sorted)
    return take, order


def lax_cummax(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.maximum, x)


def columnarize(nodes: Sequence[api.Node],
                metrics: Mapping[str, api.NodeMetric],
                pods_by_node: Mapping[str, Sequence[api.Pod]],
                args: LowNodeLoadArgs,
                usage: np.ndarray, capacity: np.ndarray,
                fresh: np.ndarray) -> Optional[dict]:
    """Typed host objects -> the kernel's POD columns (the node columns
    come in prebuilt from LowNodeLoad.node_columns, so flattening
    happens once). No per-pod decision logic here — that is the
    kernel's job. Pod usage is collected from EVERY NodeMetric,
    expired or not, matching the host plugin's pod_usage build (only
    node freshness gates classification)."""
    rdims = sorted({int(k) for k in args.high_thresholds})
    name_to_idx = {node.meta.name: i for i, node in enumerate(nodes)}
    pod_usage_map: Dict[str, np.ndarray] = {}
    for name in name_to_idx:
        m = metrics.get(name)
        if m is not None:
            for pm in m.pods_metric:
                pod_usage_map[pm.namespaced_name] = resource_vec(pm.usage)

    pods: List[api.Pod] = []
    pod_node_l: List[int] = []
    for name, plist in pods_by_node.items():
        i = name_to_idx.get(name)
        if i is None:
            continue
        for pod in plist:
            pods.append(pod)
            pod_node_l.append(i)
    p = len(pods)
    if p == 0:
        return None
    pod_node = np.asarray(pod_node_l, np.int32)
    pod_req = np.zeros((p, NUM_RESOURCES), np.float32)
    pod_usage_r = np.zeros((p, len(rdims)), np.float32)
    pod_eligible = np.zeros((p,), bool)
    for j, pod in enumerate(pods):
        pod_req[j] = resource_vec(pod.requests)
        u = pod_usage_map.get(pod.meta.namespaced_name)
        if u is None:
            u = pod_req[j]
        pod_usage_r[j] = u[rdims]
        pod_eligible[j] = not pod.is_daemonset and (
            args.pod_filter is None or args.pod_filter(pod))

    low = np.array([args.low_thresholds.get(ResourceKind(d), 0.0)
                    for d in rdims], np.float32)
    high = np.array([args.high_thresholds.get(ResourceKind(d), 100.0)
                     for d in rdims], np.float32)
    weights = np.array([args.resource_weights.get(ResourceKind(d), 0.0)
                        for d in rdims], np.float32)
    rdims_onehot = np.zeros((len(rdims), NUM_RESOURCES), np.float32)
    rdims_onehot[np.arange(len(rdims)), rdims] = 1.0
    fit_dims = tuple(int(d) for d in np.flatnonzero(pod_req.any(0)))
    return dict(usage=usage, capacity=capacity, fresh=fresh,
                pod_node=pod_node, pod_usage_r=pod_usage_r,
                pod_req=pod_req, pod_eligible=pod_eligible,
                low=low, high=high, weights=weights,
                rdims_onehot=rdims_onehot, pods=pods,
                fit_dims=fit_dims)


class DeviceLowNodeLoad(LowNodeLoad):
    """LowNodeLoad with the balance plan computed on device.

    Classification for the anomaly counters reuses the host classify()
    (cheap, stateful); the eviction selection — the O(N x P) part — is
    one jitted program. Falls back to the host loop when the evictor
    carries per-node/per-namespace limits the kernel does not model.
    """

    name = "LowNodeLoad"

    def _device_cap(self) -> Optional[int]:
        """max_per_cycle when device planning is sound, else None."""
        limiter = getattr(self.evictor, "limiter", None)
        if limiter is None:
            return 1 << 30
        if (limiter.max_per_node is not None
                or limiter.max_per_namespace is not None):
            return None
        if limiter.max_per_cycle is None:
            return 1 << 30
        return limiter.max_per_cycle - limiter._total

    def balance_once(self, nodes, metrics, pods_by_node, now):
        args = self.args
        # the host plugin never consults the evictor in dry_run —
        # neither may the device cap (golden parity)
        cap = (1 << 30) if args.dry_run else self._device_cap()
        if cap is None:
            return super().balance_once(nodes, metrics, pods_by_node,
                                        now)
        if not nodes:
            return []
        # ONE flattening pass; anomaly gating stays host-side
        # (stateful across cycles)
        usage, capacity, fresh = self.node_columns(nodes, metrics, now)
        _, _, low_mask, high_mask, _ = self.classify_columns(
            usage, capacity, fresh)
        names = [nd.meta.name for nd in nodes]
        source_mask = self._gate_anomalies(names, high_mask)
        if not low_mask.any() or not source_mask.any():
            return []
        cols = columnarize(nodes, metrics, pods_by_node, args,
                           usage, capacity, fresh)
        if cols is None:
            return []
        pods = cols.pop("pods")
        pod_node = cols["pod_node"]
        take, order = plan_kernel(
            source_mask=source_mask,
            max_evictions=np.int32(max(cap, 0)),
            use_deviation=args.use_deviation_thresholds,
            node_fit=args.node_fit, **cols)
        take = np.asarray(take)
        sel_idx = [int(i) for i in np.asarray(order) if take[int(i)]]
        selected = [pods[i] for i in sel_idx]
        if not args.dry_run and self.evictor is not None:
            for i in sel_idx:
                self.evictor.evict(
                    pods[i], f"node {names[int(pod_node[i])]} is "
                             f"overutilized")
        return selected
