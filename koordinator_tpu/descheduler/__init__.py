"""koord-descheduler equivalent: descheduling framework, LowNodeLoad
balance plugin, and the PodMigrationJob controller with arbitration
(SURVEY.md 2.4)."""

from koordinator_tpu.descheduler.framework import (  # noqa: F401
    BalancePlugin,
    CycleRunner,
    DeschedulePlugin,
    EvictionLimiter,
    Evictor,
    RecordingEvictor,
)
from koordinator_tpu.descheduler.lownodeload import (  # noqa: F401
    LowNodeLoadArgs,
    LowNodeLoad,
)
from koordinator_tpu.descheduler.lownodeload_device import (  # noqa: F401
    DeviceLowNodeLoad,
)
from koordinator_tpu.descheduler.migration import (  # noqa: F401
    Arbitrator,
    MigrationController,
    MigrationControllerArgs,
)
from koordinator_tpu.descheduler.compat import (  # noqa: F401
    COMPAT_PLUGINS,
    default_evictor_filter,
)
