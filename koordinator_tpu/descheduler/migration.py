"""PodMigrationJob controller + arbitration.

Capability parity with pkg/descheduler/controllers/migration (SURVEY.md
2.4, 3.5):
- The controller reconciles PodMigrationJob CRs: Pending jobs pass through
  the ARBITRATOR (group/sort/filter bounding blast radius per node /
  namespace / workload, arbitrator/{arbitrator,filter,sort}.go), then run:
  optionally reserve replacement capacity via a Reservation and wait for it
  to schedule (ReservationFirst, controller.go:241 doMigrate), then evict
  the pod; TTL-expired jobs fail.
- Filters (filter.go:133-360): one active job per pod; maxMigratingPerNode;
  maxMigratingPerNamespace; per-workload maxMigrating AND maxUnavailable
  (unavailable replicas + migrating replicas must stay under the limits).
- Sort (sort.go): stable order by creation time, then jobs whose workload
  already has migrations run LATER (SortJobsByMigratingNum), spreading
  disruption across workloads.

The reservation step is pluggable: the production edge hands the
Reservation to the TPU scheduler (reservations are virtual node columns,
scheduler/plugins/reservation.py) and reports back when it is Available.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from koordinator_tpu.api import types as api
from koordinator_tpu.descheduler.framework import Evictor
from koordinator_tpu.descheduler.metrics_defs import DeschedulerMetrics


def _limit(value, replicas: int) -> Optional[int]:
    """GetMaxMigrating/GetMaxUnavailable (pkg/util): int = absolute,
    float in (0,1] = fraction of replicas rounded up, None = unlimited."""
    if value is None:
        return None
    if isinstance(value, float) and 0.0 < value <= 1.0:
        return max(1, math.ceil(value * replicas))
    return int(value)


@dataclasses.dataclass
class MigrationControllerArgs:
    """MigrationControllerArgs (descheduler/apis/config/types.go) subset
    with reference defaults."""

    max_migrating_per_node: Optional[int] = 2
    max_migrating_per_namespace: Optional[int] = None
    max_migrating_per_workload: Optional[object] = 0.1   # 10% of replicas
    max_unavailable_per_workload: Optional[object] = 0.1
    ttl_seconds: float = 300.0
    default_mode: str = "ReservationFirst"  # | "EvictDirectly"


class Arbitrator:
    """Sort + filter over the pending job queue (arbitrator.go
    doOnceArbitrate)."""

    def __init__(self, args: MigrationControllerArgs):
        self.args = args

    def sort(self, jobs: Sequence[api.PodMigrationJob],
             pod_of_job: Mapping[str, api.Pod],
             migrating_per_workload: Mapping[str, int]
             ) -> List[api.PodMigrationJob]:
        def key(idx_job):
            idx, job = idx_job
            pod = pod_of_job.get(job.meta.name)
            wl = pod.owner_workload if pod is not None else ""
            return (migrating_per_workload.get(wl, 0), idx)
        return [j for _, j in sorted(enumerate(jobs), key=key)]

    def filter(self, pod: api.Pod,
               migrating_pods: Sequence[api.Pod],
               unavailable_per_workload: Mapping[str, int]) -> bool:
        """May this pod start migrating given the currently-migrating set?"""
        args = self.args
        if any(p.meta.namespaced_name == pod.meta.namespaced_name
               for p in migrating_pods):
            return False  # one active job per pod (filterExistingPodMigrationJob)
        if args.max_migrating_per_node is not None and pod.node_name:
            on_node = sum(1 for p in migrating_pods
                          if p.node_name == pod.node_name)
            if on_node >= args.max_migrating_per_node:
                return False
        if args.max_migrating_per_namespace is not None:
            in_ns = sum(1 for p in migrating_pods
                        if p.meta.namespace == pod.meta.namespace)
            if in_ns >= args.max_migrating_per_namespace:
                return False
        wl = pod.owner_workload
        if wl:
            replicas = pod.workload_replicas or 1
            migrating = sum(1 for p in migrating_pods
                            if p.owner_workload == wl)
            max_migrating = _limit(args.max_migrating_per_workload, replicas)
            if max_migrating is not None and migrating >= max_migrating:
                return False
            max_unavail = _limit(args.max_unavailable_per_workload, replicas)
            if max_unavail is not None:
                unavailable = unavailable_per_workload.get(wl, 0)
                if unavailable + migrating >= max_unavail:
                    return False
        return True


class MigrationController:
    """The PodMigrationJob reconciler (controllers/migration/controller.go).

    Callbacks:
    - reserve(pod) -> reservation name: create replacement capacity
      (ReservationFirst); return "" to proceed without one.
    - reservation_available(name) -> bool: has the reservation scheduled?
    - release_reservation(name): cancel reserved capacity when a job fails
      (controller.go abort path deletes the Reservation — without this the
      reserved virtual-node capacity would leak on every timeout).
    - get_pod(namespace/name) -> Pod | None
    - unavailable_per_workload() -> workload -> count of not-Running
      replicas (beyond those being migrated)
    """

    def __init__(self, evictor: Evictor,
                 args: Optional[MigrationControllerArgs] = None,
                 reserve: Optional[Callable[[api.Pod], str]] = None,
                 reservation_available: Optional[Callable[[str], bool]] = None,
                 release_reservation: Optional[Callable[[str], None]] = None,
                 get_pod: Optional[Callable[[str], Optional[api.Pod]]] = None,
                 unavailable_per_workload: Optional[
                     Callable[[], Mapping[str, int]]] = None,
                 stats: Optional["DeschedulerMetrics"] = None):
        self.evictor = evictor
        self.stats = stats
        self.args = args or MigrationControllerArgs()
        self.arbitrator = Arbitrator(self.args)
        self.reserve = reserve
        self.reservation_available = reservation_available
        self.release_reservation = release_reservation
        self.get_pod = get_pod or (lambda _key: None)
        self.unavailable_per_workload = unavailable_per_workload or dict
        self.jobs: Dict[str, api.PodMigrationJob] = {}
        self._created: Dict[str, float] = {}
        self._seq = itertools.count()

    def _phase(self, job: api.PodMigrationJob, phase: str) -> None:
        job.phase = phase
        if self.stats is not None:
            self.stats.migration_jobs.labels(phase).inc()

    # -- job intake ----------------------------------------------------------

    def submit_for_pod(self, pod: api.Pod, reason: str = "",
                       now: float = 0.0) -> api.PodMigrationJob:
        """What the descheduler's evictor edge does: an eviction request
        becomes a PodMigrationJob (evictor/evictor.go)."""
        name = f"pmj-{next(self._seq)}"
        job = api.PodMigrationJob(
            meta=api.ObjectMeta(name=name),
            pod_namespace=pod.meta.namespace, pod_name=pod.meta.name,
            mode=self.args.default_mode, ttl_seconds=self.args.ttl_seconds,
            phase="Pending", reason=reason)
        self.submit(job, now)
        return job

    def submit(self, job: api.PodMigrationJob, now: float = 0.0) -> None:
        self.jobs[job.meta.name] = job
        self._created[job.meta.name] = now

    # -- reconcile -----------------------------------------------------------

    def _migrating_pods(self) -> List[api.Pod]:
        out = []
        for job in self.jobs.values():
            if job.phase == "Running":
                pod = self.get_pod(f"{job.pod_namespace}/{job.pod_name}")
                if pod is not None:
                    out.append(pod)
        return out

    def reconcile_once(self, now: float) -> None:
        # TTL expiry applies to any non-terminal job (controller.go
        # abortJobIfTimeout)
        for job in self.jobs.values():
            if job.phase in ("Pending", "Running") and \
                    now - self._created[job.meta.name] > job.ttl_seconds:
                self._phase(job, "Failed")
                job.reason = "timeout"
                if job.reservation_name and self.release_reservation:
                    self.release_reservation(job.reservation_name)
                    job.reservation_name = ""

        pending = [j for j in self.jobs.values() if j.phase == "Pending"]
        pod_of_job = {
            j.meta.name: self.get_pod(f"{j.pod_namespace}/{j.pod_name}")
            for j in pending}
        migrating = self._migrating_pods()
        per_wl: Dict[str, int] = {}
        for p in migrating:
            if p.owner_workload:
                per_wl[p.owner_workload] = per_wl.get(p.owner_workload, 0) + 1
        unavailable = dict(self.unavailable_per_workload())

        for job in self.arbitrator.sort(pending, pod_of_job, per_wl):
            pod = pod_of_job.get(job.meta.name)
            if pod is None:
                self._phase(job, "Failed")
                job.reason = "pod not found"
                continue
            if not self.arbitrator.filter(pod, migrating, unavailable):
                continue  # stays Pending, retried next reconcile
            self._phase(job, "Running")
            migrating.append(pod)
            if pod.owner_workload:
                per_wl[pod.owner_workload] = \
                    per_wl.get(pod.owner_workload, 0) + 1
            if job.mode == "ReservationFirst" and self.reserve is not None:
                job.reservation_name = self.reserve(pod)

        for job in [j for j in self.jobs.values() if j.phase == "Running"]:
            pod = self.get_pod(f"{job.pod_namespace}/{job.pod_name}")
            if pod is None:
                self._phase(job, "Succeeded")  # already gone
                continue
            if job.reservation_name and self.reservation_available is not None:
                if not self.reservation_available(job.reservation_name):
                    continue  # wait for replacement capacity
            if self.evictor.evict(pod, job.reason or "migration"):
                self._phase(job, "Succeeded")
            # else: stays Running, retried (eviction limiter may admit later)

    def gc(self) -> None:
        """Drop terminal jobs (controller job GC)."""
        for name in [n for n, j in self.jobs.items()
                     if j.phase in ("Succeeded", "Failed")]:
            del self.jobs[name]
            self._created.pop(name, None)
