"""Upstream-descheduler-compatible plugins.

Capability parity with pkg/descheduler/framework/plugins/kubernetes
(SURVEY.md 2.4): wrappers of the sigs descheduler behaviors the reference
re-exports — evict pods violating node selection, plus the default evictor
filter (daemonsets, system QoS, non-preemptible pods, priority threshold).
"""

from __future__ import annotations

from typing import Callable, Mapping, Optional, Sequence

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import QoSClass, selector_matches
from koordinator_tpu.descheduler.framework import Evictor

ANNOTATION_PREEMPTIBLE = "scheduling.koordinator.sh/preemptible"


def default_evictor_filter(priority_threshold: Optional[int] = None,
                           evict_system_pods: bool = False
                           ) -> Callable[[api.Pod], bool]:
    """defaultevictor.Filter: True = evictable."""

    def allow(pod: api.Pod) -> bool:
        if pod.is_daemonset:
            return False
        if not evict_system_pods and pod.qos is QoSClass.SYSTEM:
            return False
        if pod.meta.annotations.get(ANNOTATION_PREEMPTIBLE) == "false":
            return False
        if priority_threshold is not None and \
                (pod.priority or 0) >= priority_threshold:
            return False
        return True

    return allow


class RemovePodsViolatingNodeSelector:
    """Deschedule plugin: evict pods whose nodeSelector no longer matches
    their node's labels (node relabeled after placement)."""

    name = "RemovePodsViolatingNodeSelector"

    def __init__(self, evictor: Evictor,
                 get_pods_by_node: Callable[[], Mapping[str,
                                                        Sequence[api.Pod]]],
                 pod_filter: Optional[Callable[[api.Pod], bool]] = None):
        self.evictor = evictor
        self.get_pods_by_node = get_pods_by_node
        self.pod_filter = pod_filter or default_evictor_filter()

    def deschedule(self, nodes: Sequence[api.Node]) -> None:
        labels = {n.meta.name: n.meta.labels for n in nodes}
        for node_name, pods in self.get_pods_by_node().items():
            node_labels = labels.get(node_name)
            if node_labels is None:
                continue
            for pod in pods:
                if not pod.node_selector:
                    continue
                if selector_matches(pod.node_selector, node_labels):
                    continue
                if self.pod_filter(pod):
                    self.evictor.evict(
                        pod, f"nodeSelector no longer matches {node_name}")


class RemovePodsOnUnschedulableNodes:
    """Deschedule plugin: drain evictable pods off cordoned nodes (the
    taint-violation behavior restricted to the unschedulable taint)."""

    name = "RemovePodsOnUnschedulableNodes"

    def __init__(self, evictor: Evictor,
                 get_pods_by_node: Callable[[], Mapping[str,
                                                        Sequence[api.Pod]]],
                 pod_filter: Optional[Callable[[api.Pod], bool]] = None):
        self.evictor = evictor
        self.get_pods_by_node = get_pods_by_node
        self.pod_filter = pod_filter or default_evictor_filter()

    def deschedule(self, nodes: Sequence[api.Node]) -> None:
        pods_by_node = self.get_pods_by_node()
        for node in nodes:
            if not node.unschedulable:
                continue
            for pod in pods_by_node.get(node.meta.name, ()):
                if self.pod_filter(pod):
                    self.evictor.evict(
                        pod, f"node {node.meta.name} is unschedulable")
