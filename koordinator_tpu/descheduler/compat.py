"""Upstream-descheduler-compatible plugins.

Capability parity with pkg/descheduler/framework/plugins/kubernetes
(SURVEY.md 2.4, plugin.go:62-130 registry): the sigs descheduler
behaviors the reference re-exports — PodLifeTime, RemoveFailedPods,
RemoveDuplicates, RemovePodsHavingTooManyRestarts, the node-selection/
taint/topology-spread violation evictors, the request-based
Low/HighNodeUtilization pair — plus the default evictor filter
(daemonsets, system QoS, non-preemptible pods, priority threshold).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Mapping, Optional, Sequence

import numpy as np

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    QoSClass,
    ResourceKind,
    selector_matches,
)
from koordinator_tpu.descheduler.framework import Evictor
from koordinator_tpu.snapshot.builder import resource_vec

ANNOTATION_PREEMPTIBLE = "scheduling.koordinator.sh/preemptible"


def default_evictor_filter(priority_threshold: Optional[int] = None,
                           evict_system_pods: bool = False
                           ) -> Callable[[api.Pod], bool]:
    """defaultevictor.Filter: True = evictable."""

    def allow(pod: api.Pod) -> bool:
        if pod.is_daemonset:
            return False
        if not evict_system_pods and pod.qos is QoSClass.SYSTEM:
            return False
        if pod.meta.annotations.get(ANNOTATION_PREEMPTIBLE) == "false":
            return False
        if priority_threshold is not None and \
                (pod.priority or 0) >= priority_threshold:
            return False
        return True

    return allow


class RemovePodsViolatingNodeSelector:
    """Deschedule plugin: evict pods whose nodeSelector no longer matches
    their node's labels (node relabeled after placement)."""

    name = "RemovePodsViolatingNodeSelector"

    def __init__(self, evictor: Evictor,
                 get_pods_by_node: Callable[[], Mapping[str,
                                                        Sequence[api.Pod]]],
                 pod_filter: Optional[Callable[[api.Pod], bool]] = None):
        self.evictor = evictor
        self.get_pods_by_node = get_pods_by_node
        self.pod_filter = pod_filter or default_evictor_filter()

    def deschedule(self, nodes: Sequence[api.Node]) -> None:
        labels = {n.meta.name: n.meta.labels for n in nodes}
        for node_name, pods in self.get_pods_by_node().items():
            node_labels = labels.get(node_name)
            if node_labels is None:
                continue
            for pod in pods:
                if not pod.node_selector:
                    continue
                if selector_matches(pod.node_selector, node_labels):
                    continue
                if self.pod_filter(pod):
                    self.evictor.evict(
                        pod, f"nodeSelector no longer matches {node_name}")


class RemovePodsOnUnschedulableNodes:
    """Deschedule plugin: drain evictable pods off cordoned nodes (the
    taint-violation behavior restricted to the unschedulable taint)."""

    name = "RemovePodsOnUnschedulableNodes"

    def __init__(self, evictor: Evictor,
                 get_pods_by_node: Callable[[], Mapping[str,
                                                        Sequence[api.Pod]]],
                 pod_filter: Optional[Callable[[api.Pod], bool]] = None):
        self.evictor = evictor
        self.get_pods_by_node = get_pods_by_node
        self.pod_filter = pod_filter or default_evictor_filter()

    def deschedule(self, nodes: Sequence[api.Node]) -> None:
        pods_by_node = self.get_pods_by_node()
        for node in nodes:
            if not node.unschedulable:
                continue
            for pod in pods_by_node.get(node.meta.name, ()):
                if self.pod_filter(pod):
                    self.evictor.evict(
                        pod, f"node {node.meta.name} is unschedulable")


class _CompatBase:
    """Shared wiring: evictor + pod source + evictability filter + clock."""

    def __init__(self, evictor: Evictor,
                 get_pods_by_node: Callable[[], Mapping[str,
                                                        Sequence[api.Pod]]],
                 pod_filter: Optional[Callable[[api.Pod], bool]] = None,
                 now_fn: Callable[[], float] = time.time):
        self.evictor = evictor
        self.get_pods_by_node = get_pods_by_node
        self.pod_filter = pod_filter or default_evictor_filter()
        self.now_fn = now_fn


class PodLifeTime(_CompatBase):
    """Evict pods older than maxPodLifeTimeSeconds, optionally only in
    the given phases (podlifetime.PodLifeTimeArgs)."""

    name = "PodLifeTime"

    def __init__(self, *args, max_pod_life_time_seconds: float = 86400.0,
                 states: Sequence[str] = (), **kw):
        super().__init__(*args, **kw)
        self.max_age = max_pod_life_time_seconds
        self.states = set(states)

    def deschedule(self, nodes: Sequence[api.Node]) -> None:
        now = self.now_fn()
        for pods in self.get_pods_by_node().values():
            for pod in pods:
                if self.states and pod.phase not in self.states:
                    continue
                if pod.start_time <= 0 or \
                        now - pod.start_time < self.max_age:
                    continue
                if self.pod_filter(pod):
                    self.evictor.evict(
                        pod, f"pod exceeded max lifetime {self.max_age}s")


class RemoveFailedPods(_CompatBase):
    """Evict Failed pods, optionally only past a minimum age
    (removefailedpods.RemoveFailedPodsArgs)."""

    name = "RemoveFailedPods"

    def __init__(self, *args, min_pod_lifetime_seconds: float = 0.0, **kw):
        super().__init__(*args, **kw)
        self.min_age = min_pod_lifetime_seconds

    def deschedule(self, nodes: Sequence[api.Node]) -> None:
        now = self.now_fn()
        for pods in self.get_pods_by_node().values():
            for pod in pods:
                if pod.phase != "Failed":
                    continue
                if self.min_age and pod.start_time > 0 and \
                        now - pod.start_time < self.min_age:
                    continue
                if self.pod_filter(pod):
                    self.evictor.evict(pod, "pod is in Failed phase")


class RemovePodsHavingTooManyRestarts(_CompatBase):
    """Evict pods whose container restart total crossed the threshold
    (removepodshavingtoomanyrestarts args)."""

    name = "RemovePodsHavingTooManyRestarts"

    def __init__(self, *args, pod_restart_threshold: int = 100, **kw):
        super().__init__(*args, **kw)
        self.threshold = pod_restart_threshold

    def deschedule(self, nodes: Sequence[api.Node]) -> None:
        for pods in self.get_pods_by_node().values():
            for pod in pods:
                if pod.restart_count < self.threshold:
                    continue
                if self.pod_filter(pod):
                    self.evictor.evict(
                        pod, f"{pod.restart_count} restarts >= "
                             f"{self.threshold}")


class RemoveDuplicates(_CompatBase):
    """One replica of a workload per node: evict the extras so the
    owner's pods spread (removeduplicates semantics — duplicates are
    same-owner pods colocated on one node)."""

    name = "RemoveDuplicates"

    def deschedule(self, nodes: Sequence[api.Node]) -> None:
        for node_name, pods in self.get_pods_by_node().items():
            seen: Dict[str, int] = {}
            for pod in pods:
                owner = pod.owner_workload
                if not owner:
                    continue
                seen[owner] = seen.get(owner, 0) + 1
                if seen[owner] > 1 and self.pod_filter(pod):
                    self.evictor.evict(
                        pod, f"duplicate of {owner} on {node_name}")


class RemovePodsViolatingNodeAffinity(RemovePodsViolatingNodeSelector):
    """requiredDuringSchedulingIgnoredDuringExecution re-check: the pod's
    node selection no longer matches the (relabeled) node. The typed Pod
    carries affinity pre-resolved into `node_selector`, so the check is
    the selector re-match."""

    name = "RemovePodsViolatingNodeAffinity"


class RemovePodsViolatingNodeTaints(_CompatBase):
    """Evict pods that do not tolerate their node's NoSchedule/NoExecute
    taints (taint added after placement)."""

    name = "RemovePodsViolatingNodeTaints"

    def deschedule(self, nodes: Sequence[api.Node]) -> None:
        pods_by_node = self.get_pods_by_node()
        for node in nodes:
            hard = [t for t in node.taints
                    if t.effect in ("NoSchedule", "NoExecute")]
            if not hard:
                continue
            for pod in pods_by_node.get(node.meta.name, ()):
                bad = [t for t in hard
                       if not any(tol.tolerates(t)
                                  for tol in pod.tolerations)]
                if bad and self.pod_filter(pod):
                    self.evictor.evict(
                        pod, f"untolerated taint {bad[0].key}="
                             f"{bad[0].value}:{bad[0].effect}")


class RemovePodsViolatingTopologySpreadConstraint(_CompatBase):
    """Rebalance workloads whose per-domain pod counts violate maxSkew.
    Domains come from the node label named by the pod's
    spread_topology_key; EMPTY domains count as targets only when some
    SCHEDULABLE node provides them (a cordoned/tainted-only domain must
    not drag the floor to zero and trigger churn the scheduler can never
    repair). Evictions are the MINIMAL move set that repairs the skew,
    assuming each evicted pod reschedules into the emptiest domain —
    the upstream plugin's balanceDomains simulation."""

    name = "RemovePodsViolatingTopologySpreadConstraint"

    def deschedule(self, nodes: Sequence[api.Node]) -> None:
        node_labels = {n.meta.name: n.meta.labels for n in nodes}
        schedulable = [
            n for n in nodes
            if not n.unschedulable and not any(
                t.effect in ("NoSchedule", "NoExecute") for t in n.taints)]
        # group pods by (owner, topology key)
        groups: Dict[tuple, List[tuple]] = {}
        for node_name, pods in self.get_pods_by_node().items():
            labels = node_labels.get(node_name, {})
            for pod in pods:
                key = pod.spread_topology_key
                if not key or not pod.owner_workload:
                    continue
                domain = labels.get(key)
                if domain is None:
                    continue
                groups.setdefault((pod.owner_workload, key), []).append(
                    (domain, pod))
        for (owner, key), members in groups.items():
            counts: Dict[str, int] = {}
            for n in schedulable:
                d = n.meta.labels.get(key)
                if d is not None:
                    counts[d] = 0
            for domain, _pod in members:
                counts[domain] = counts.get(domain, 0) + 1
            if len(counts) < 2:
                continue
            # clamp: skew < 1 is unsatisfiable between unequal domains
            # and would make the repair loop oscillate forever
            max_skew = max(1, max(p.spread_max_skew for _, p in members))
            # minimal repair: move one pod at a time from the fullest to
            # the emptiest domain until the skew constraint holds
            evict_from: Dict[str, int] = {}
            sim = dict(counts)
            while max(sim.values()) - min(sim.values()) > max_skew:
                hi = max(sim, key=sim.get)  # type: ignore[arg-type]
                lo = min(sim, key=sim.get)  # type: ignore[arg-type]
                sim[hi] -= 1
                sim[lo] += 1
                evict_from[hi] = evict_from.get(hi, 0) + 1
            for domain, n_evict in evict_from.items():
                victims = [p for d, p in members
                           if d == domain and self.pod_filter(p)]
                for pod in victims[:n_evict]:
                    self.evictor.evict(
                        pod, f"skew of {owner} over {key} exceeds "
                             f"{max_skew}")


class _RequestUtilization(_CompatBase):
    """Shared classification for the upstream nodeutilization pair: node
    utilization = Σ pod REQUESTS / allocatable (the upstream plugins are
    request-based; the koord LowNodeLoad plugin is the usage-based one).
    The pod listing is fetched ONCE per cycle and shared between
    classification and draining so both see one consistent snapshot."""

    rdims = (int(ResourceKind.CPU), int(ResourceKind.MEMORY))

    def _utilization(self, nodes: Sequence[api.Node],
                     pods_by_node: Mapping[str, Sequence[api.Pod]]
                     ) -> np.ndarray:
        pct = np.zeros((len(nodes), len(self.rdims)), np.float32)
        for i, node in enumerate(nodes):
            cap = resource_vec(node.allocatable)[list(self.rdims)]
            req = np.zeros_like(cap)
            for pod in pods_by_node.get(node.meta.name, ()):
                req += resource_vec(pod.requests)[list(self.rdims)]
            with np.errstate(divide="ignore", invalid="ignore"):
                pct[i] = np.where(cap > 0, 100.0 * req / cap, 0.0)
        return pct

    def _drain(self, node: api.Node,
               pods_by_node: Mapping[str, Sequence[api.Pod]],
               max_per_node: int, reason: str) -> None:
        evicted = 0
        # lowest-priority first — upstream eviction order
        for pod in sorted(pods_by_node.get(node.meta.name, ()),
                          key=lambda p: p.priority or 0):
            if evicted >= max_per_node:
                break
            if self.pod_filter(pod) and self.evictor.evict(pod, reason):
                evicted += 1


class LowNodeUtilization(_RequestUtilization):
    """Balance plugin: evict from request-overutilized nodes while
    underutilized targets exist (nodeutilization.LowNodeUtilizationArgs,
    request-based upstream variant)."""

    name = "LowNodeUtilization"

    def __init__(self, *args, thresholds: float = 20.0,
                 target_thresholds: float = 70.0,
                 max_evictions_per_node: int = 5, **kw):
        super().__init__(*args, **kw)
        self.low = thresholds
        self.high = target_thresholds
        self.max_per_node = max_evictions_per_node

    def balance(self, nodes: Sequence[api.Node]) -> None:
        pods_by_node = self.get_pods_by_node()
        pct = self._utilization(nodes, pods_by_node)
        low_mask = (pct < self.low).all(axis=1)
        high_mask = (pct > self.high).any(axis=1)
        if not low_mask.any():
            return  # nowhere to move pods to
        for i, node in enumerate(nodes):
            if high_mask[i]:
                self._drain(node, pods_by_node, self.max_per_node,
                            f"node {node.meta.name} request-overutilized")


class HighNodeUtilization(_RequestUtilization):
    """Balance plugin: bin-packing — drain UNDERutilized nodes so their
    workload compacts onto the rest (nodeutilization.
    HighNodeUtilizationArgs)."""

    name = "HighNodeUtilization"

    def __init__(self, *args, thresholds: float = 20.0,
                 max_evictions_per_node: int = 5, **kw):
        super().__init__(*args, **kw)
        self.low = thresholds
        self.max_per_node = max_evictions_per_node

    def balance(self, nodes: Sequence[api.Node]) -> None:
        pods_by_node = self.get_pods_by_node()
        pct = self._utilization(nodes, pods_by_node)
        low_mask = (pct < self.low).all(axis=1)
        if low_mask.all():
            return  # nowhere to compact onto
        for i, node in enumerate(nodes):
            if low_mask[i]:
                self._drain(node, pods_by_node, self.max_per_node,
                            f"draining underutilized {node.meta.name} "
                            f"for bin-packing")


# name -> class, the plugin.go:62-130 registry analogue
COMPAT_PLUGINS = {
    p.name: p for p in (
        RemovePodsViolatingNodeSelector,
        RemovePodsOnUnschedulableNodes,
        PodLifeTime,
        RemoveFailedPods,
        RemovePodsHavingTooManyRestarts,
        RemoveDuplicates,
        RemovePodsViolatingNodeAffinity,
        RemovePodsViolatingNodeTaints,
        RemovePodsViolatingTopologySpreadConstraint,
        LowNodeUtilization,
        HighNodeUtilization,
    )
}
