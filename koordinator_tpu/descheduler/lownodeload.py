"""LowNodeLoad balance plugin: classify nodes by ACTUAL usage (NodeMetric)
against low/high thresholds, then migrate pods off overutilized nodes until
they fall under the high threshold, bounded by the spare capacity of the
underutilized destinations.

Behavior parity with framework/plugins/loadaware/{low_node_load.go,
utilization_util.go} (SURVEY.md 2.4):
- classification: a node is UNDERutilized when every resource's usage%% is
  below the low threshold, OVERutilized when any exceeds the high
  threshold (lowThresholdFilter/highThresholdFilter,
  utilization_util.go:316-327).
- deviation thresholds: low/high become cluster-average ± threshold
  (newThresholds + calcAverageResourceUsagePercent).
- anomaly gating: a node must be overutilized `consecutive_abnormalities`
  detections in a row before eviction starts (nodeAnomalyDetectors,
  low_node_load.go:196-259).
- budget: Σ over destination nodes of (high_threshold_abs − usage) per
  resource; eviction stops when any dimension is exhausted or the source
  node falls under the high threshold (evictPodsFromSourceNodes
  :232-305, continueEvictionCond).
- ordering: source nodes and their removable pods by weighted usage,
  descending (sortNodesByUsage, sorter.SortPodsByUsage).
- node_fit: a removable pod must fit (requests vs allocatable-requested)
  on at least one destination node (PodFitsAnyNode).

The column math (usage%%, masks, budget) is vectorized numpy — the
descheduler runs every couple of minutes, so clarity beats device offload
here; the mirror-image scheduler-side LoadAware logic IS the device kernel
(scheduler/plugins/loadaware.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import NUM_RESOURCES, ResourceKind
from koordinator_tpu.descheduler.framework import Evictor
from koordinator_tpu.snapshot.builder import resource_vec


@dataclasses.dataclass
class LowNodeLoadArgs:
    """LowNodeLoadArgs (descheduler/apis/config/types.go) — the fields the
    balance pass consumes, with reference defaults."""

    low_thresholds: Dict[ResourceKind, float] = dataclasses.field(
        default_factory=lambda: {ResourceKind.CPU: 45.0,
                                 ResourceKind.MEMORY: 60.0})
    high_thresholds: Dict[ResourceKind, float] = dataclasses.field(
        default_factory=lambda: {ResourceKind.CPU: 65.0,
                                 ResourceKind.MEMORY: 80.0})
    use_deviation_thresholds: bool = False
    resource_weights: Dict[ResourceKind, float] = dataclasses.field(
        default_factory=lambda: {ResourceKind.CPU: 1.0,
                                 ResourceKind.MEMORY: 1.0})
    # LoadAnomalyCondition: this many consecutive overutilized detections
    # before eviction kicks in (default 5)
    consecutive_abnormalities: int = 5
    node_fit: bool = True
    node_metric_expiration_seconds: float = 180.0
    dry_run: bool = False
    # pods the default evictor refuses (defaultevictor subset)
    pod_filter: Optional[Callable[[api.Pod], bool]] = None


def _usage_pct(usage: np.ndarray, capacity: np.ndarray) -> np.ndarray:
    return 100.0 * usage / np.maximum(capacity, 1e-9)


class LowNodeLoad:
    """The Balance plugin. Stateful only for the anomaly counters.

    For the CycleRunner loop, inject the cluster-state providers
    (`get_metrics`, `get_pods_by_node`, `now_fn` — the informer lookups the
    reference plugin does through its handle) and the runner drives
    `balance(nodes)`; `balance_once` is the explicit-arguments form.
    """

    name = "LowNodeLoad"

    def __init__(self, args: Optional[LowNodeLoadArgs] = None,
                 evictor: Optional[Evictor] = None,
                 get_metrics: Optional[
                     Callable[[], Mapping[str, api.NodeMetric]]] = None,
                 get_pods_by_node: Optional[
                     Callable[[], Mapping[str, Sequence[api.Pod]]]] = None,
                 now_fn: Optional[Callable[[], float]] = None):
        self.args = args or LowNodeLoadArgs()
        self.evictor = evictor
        self.get_metrics = get_metrics
        self.get_pods_by_node = get_pods_by_node
        self.now_fn = now_fn
        self._abnormal_counts: Dict[str, int] = {}

    def balance(self, nodes: Sequence[api.Node]) -> None:
        """BalancePlugin protocol entry (framework.CycleRunner)."""
        if self.get_metrics is None or self.get_pods_by_node is None:
            raise RuntimeError(
                "LowNodeLoad.balance needs get_metrics/get_pods_by_node "
                "providers; use balance_once for explicit arguments")
        import time
        now = self.now_fn() if self.now_fn is not None else time.time()
        self.balance_once(nodes, self.get_metrics(),
                          self.get_pods_by_node(), now)

    # -- classification (vectorized) ----------------------------------------

    def node_columns(self, nodes: Sequence[api.Node],
                     metrics: Mapping[str, api.NodeMetric],
                     now: float) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]:
        """One flattening pass: (usage [N,R], capacity [N,R], fresh [N]);
        nodes with missing/expired NodeMetric are not fresh (getNodeUsage
        skips them). Shared with the device path so the typed->columnar
        work happens exactly once."""
        args = self.args
        n = len(nodes)
        usage = np.zeros((n, NUM_RESOURCES), np.float32)
        capacity = np.zeros((n, NUM_RESOURCES), np.float32)
        fresh = np.zeros((n,), bool)
        for i, node in enumerate(nodes):
            capacity[i] = resource_vec(node.allocatable)
            m = metrics.get(node.meta.name)
            if m is not None and not m.is_expired(
                    args.node_metric_expiration_seconds, now):
                usage[i] = resource_vec(m.node_usage)
                fresh[i] = True
        return usage, capacity, fresh

    def classify(self, nodes: Sequence[api.Node],
                 metrics: Mapping[str, api.NodeMetric],
                 now: float) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                      np.ndarray, List[int]]:
        """Returns (usage [N,R], capacity [N,R], low_mask [N], high_mask
        [N], rdims) over the given nodes."""
        return self.classify_columns(
            *self.node_columns(nodes, metrics, now))

    def classify_columns(self, usage: np.ndarray, capacity: np.ndarray,
                         fresh: np.ndarray
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                    np.ndarray, List[int]]:
        """The threshold math over prebuilt columns."""
        args = self.args
        rdims = sorted({int(k) for k in args.high_thresholds})
        pct = _usage_pct(usage, capacity)

        low = np.array([args.low_thresholds.get(ResourceKind(d), 0.0)
                        for d in rdims], np.float32)
        high = np.array([args.high_thresholds.get(ResourceKind(d), 100.0)
                         for d in rdims], np.float32)
        if args.use_deviation_thresholds:
            avg = pct[fresh][:, rdims].mean(axis=0) if fresh.any() else \
                np.zeros_like(low)
            low = np.clip(avg - low, 0.0, 100.0)
            high = np.clip(avg + high, 0.0, 100.0)
        sel = pct[:, rdims]
        low_mask = fresh & (sel < low[None, :]).all(axis=1)
        high_mask = fresh & (sel > high[None, :]).any(axis=1)
        self._high_abs = capacity[:, rdims] * high[None, :] / 100.0
        return usage, capacity, low_mask, high_mask, rdims

    # -- anomaly gating ------------------------------------------------------

    def _gate_anomalies(self, names: Sequence[str],
                        high_mask: np.ndarray) -> np.ndarray:
        """Track consecutive overutilized detections per node; only nodes
        past the threshold are eviction sources. Normal nodes reset."""
        out = np.zeros_like(high_mask)
        for i, name in enumerate(names):
            if high_mask[i]:
                c = self._abnormal_counts.get(name, 0) + 1
                self._abnormal_counts[name] = c
                out[i] = c >= self.args.consecutive_abnormalities
            else:
                self._abnormal_counts.pop(name, None)
        return out

    # -- the balance pass ----------------------------------------------------

    def balance_once(self, nodes: Sequence[api.Node],
                     metrics: Mapping[str, api.NodeMetric],
                     pods_by_node: Mapping[str, Sequence[api.Pod]],
                     now: float) -> List[api.Pod]:
        """One Balance invocation; returns the pods selected for migration
        (already offered to the evictor unless dry_run)."""
        args = self.args
        if not nodes:
            return []
        usage, capacity, low_mask, high_mask, rdims = self.classify(
            nodes, metrics, now)
        names = [nd.meta.name for nd in nodes]
        source_mask = self._gate_anomalies(names, high_mask)
        if not low_mask.any() or not source_mask.any():
            return []

        # pod usage per node from the NodeMetric pod breakdown; fall back
        # to requests when a pod has no reported usage
        pod_usage: Dict[str, np.ndarray] = {}
        for name in names:
            m = metrics.get(name)
            if m is not None:
                for pm in m.pods_metric:
                    pod_usage[pm.namespaced_name] = resource_vec(pm.usage)

        # budget: spare headroom under the HIGH threshold of destinations
        budget = (self._high_abs[low_mask] - usage[low_mask][:, rdims]) \
            .sum(axis=0)

        # destination free room for node_fit (allocatable - Σ requests)
        dest_free = []
        for i in np.nonzero(low_mask)[0]:
            reqs = sum((resource_vec(p.requests)
                        for p in pods_by_node.get(names[i], [])),
                       np.zeros(NUM_RESOURCES, np.float32))
            dest_free.append(capacity[i] - reqs)

        weights = np.zeros((len(rdims),), np.float32)
        for j, d in enumerate(rdims):
            weights[j] = args.resource_weights.get(ResourceKind(d), 0.0)

        def weighted(vec_r: np.ndarray) -> float:
            return float((vec_r * weights).sum())

        # source nodes by weighted usage%, descending
        pct = _usage_pct(usage, capacity)
        src_order = sorted(np.nonzero(source_mask)[0].tolist(),
                           key=lambda i: -weighted(pct[i, rdims]))

        selected: List[api.Pod] = []
        for i in src_order:
            node_usage_r = usage[i, rdims].copy()
            high_abs = self._high_abs[i]
            removable = []
            for pod in pods_by_node.get(names[i], []):
                if pod.is_daemonset:
                    continue
                if args.pod_filter is not None and not args.pod_filter(pod):
                    continue
                if args.node_fit:
                    req = resource_vec(pod.requests)
                    if not any((req <= f + 0.5).all() for f in dest_free):
                        continue
                removable.append(pod)
            if not removable:
                continue
            removable.sort(key=lambda p: -weighted(
                pod_usage.get(p.meta.namespaced_name,
                              resource_vec(p.requests))[rdims]))
            for pod in removable:
                still_over = (node_usage_r > high_abs).any()
                if not still_over or (budget <= 0).any():
                    break
                if not args.dry_run and self.evictor is not None:
                    if not self.evictor.evict(
                            pod, f"node {names[i]} is overutilized"):
                        continue
                u = pod_usage.get(pod.meta.namespaced_name,
                                  resource_vec(pod.requests))[rdims]
                node_usage_r -= u
                budget -= u
                selected.append(pod)
        return selected
