"""Descheduling framework: plugin protocol, eviction limiting, cycle loop.

Capability parity with pkg/descheduler/{descheduler.go,framework/,profile/}
(SURVEY.md 2.4): profiles of Deschedule/Balance plugins run every
descheduling interval; an EvictionLimiter caps evictions per cycle /
node / namespace; evictors are pluggable (the production edge turns an
eviction into a PodMigrationJob instead of a direct delete — controlled by
the MigrationController, migration.py).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Protocol, Sequence

from koordinator_tpu.api import types as api
from koordinator_tpu.descheduler.metrics_defs import DeschedulerMetrics


class Evictor(Protocol):
    def evict(self, pod: api.Pod, reason: str) -> bool:
        """Request eviction; False = refused (limit/filters)."""


@dataclasses.dataclass
class Eviction:
    pod: api.Pod
    reason: str
    node_name: str


class EvictionLimiter:
    """Caps evictions per descheduling cycle, per node, and per namespace
    (descheduler.go evictionLimiter semantics). None = unlimited."""

    def __init__(self, max_per_cycle: Optional[int] = None,
                 max_per_node: Optional[int] = None,
                 max_per_namespace: Optional[int] = None):
        self.max_per_cycle = max_per_cycle
        self.max_per_node = max_per_node
        self.max_per_namespace = max_per_namespace
        self.reset()

    def reset(self) -> None:
        self._total = 0
        self._per_node: Dict[str, int] = {}
        self._per_ns: Dict[str, int] = {}

    def allow(self, pod: api.Pod) -> bool:
        if self.max_per_cycle is not None and self._total >= self.max_per_cycle:
            return False
        node = pod.node_name
        ns = pod.meta.namespace
        if (self.max_per_node is not None
                and self._per_node.get(node, 0) >= self.max_per_node):
            return False
        if (self.max_per_namespace is not None
                and self._per_ns.get(ns, 0) >= self.max_per_namespace):
            return False
        return True

    def record(self, pod: api.Pod) -> None:
        self._total += 1
        self._per_node[pod.node_name] = self._per_node.get(pod.node_name, 0) + 1
        ns = pod.meta.namespace
        self._per_ns[ns] = self._per_ns.get(ns, 0) + 1


class RecordingEvictor:
    """Test/dry-run evictor honoring an EvictionLimiter."""

    def __init__(self, limiter: Optional[EvictionLimiter] = None,
                 stats: Optional["DeschedulerMetrics"] = None,
                 strategy: str = ""):
        self.limiter = limiter or EvictionLimiter()
        self.evictions: List[Eviction] = []
        self.stats = stats
        self.strategy = strategy

    def evict(self, pod: api.Pod, reason: str) -> bool:
        if not self.limiter.allow(pod):
            if self.stats is not None:
                self.stats.pods_evicted.labels(
                    "error", self.strategy, pod.node_name).inc()
            return False
        self.limiter.record(pod)
        self.evictions.append(Eviction(pod, reason, pod.node_name))
        if self.stats is not None:
            self.stats.pods_evicted.labels(
                "success", self.strategy, pod.node_name).inc()
        return True


class DeschedulePlugin(Protocol):
    name: str

    def deschedule(self, nodes: Sequence[api.Node]) -> None: ...


class BalancePlugin(Protocol):
    name: str

    def balance(self, nodes: Sequence[api.Node]) -> None: ...


class CycleRunner:
    """descheduler.go Run loop: every interval, run each profile's
    Deschedule plugins then Balance plugins.

    Per-cycle eviction caps live in the EvictionLimiters the EVICTORS
    hold; pass every limiter that should reset at cycle start in
    `limiters` (e.g. `[evictor.limiter]` for a RecordingEvictor, or the
    limiter of the MigrationController's evictor)."""

    def __init__(self, deschedule_plugins: Sequence[DeschedulePlugin] = (),
                 balance_plugins: Sequence[BalancePlugin] = (),
                 limiters: Sequence[EvictionLimiter] = (),
                 descheduling_interval_seconds: float = 120.0):
        self.deschedule_plugins = list(deschedule_plugins)
        self.balance_plugins = list(balance_plugins)
        self.limiters = list(limiters)
        self.interval = descheduling_interval_seconds

    def run_once(self, nodes: Sequence[api.Node]) -> None:
        for limiter in self.limiters:
            limiter.reset()
        for plugin in self.deschedule_plugins:
            plugin.deschedule(nodes)
        for plugin in self.balance_plugins:
            plugin.balance(nodes)

    def run(self, get_nodes: Callable[[], Sequence[api.Node]],
            stop: Callable[[], bool],
            sleep: Callable[[float], None] = time.sleep) -> None:
        while not stop():
            self.run_once(get_nodes())
            sleep(self.interval)
