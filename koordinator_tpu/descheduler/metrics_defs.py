"""descheduler metric series — parity with pkg/descheduler/metrics/
metrics.go (PodsEvicted and the migration-job counters).

Family names come from the shared name registry
(koordinator_tpu/metrics/registry.py) and are re-exported here."""

from __future__ import annotations

from koordinator_tpu.metrics import Registry, global_registry
from koordinator_tpu.metrics.registry import (  # noqa: F401  (re-export)
    DESCHEDULER_MIGRATION_JOBS,
    DESCHEDULER_PODS_EVICTED,
)


class DeschedulerMetrics:
    def __init__(self, registry: Registry = None):
        r = registry if registry is not None else global_registry()
        self.pods_evicted = r.counter(
            DESCHEDULER_PODS_EVICTED,
            "Evicted pods by result/strategy/node ('error' = eviction "
            "failed)", labels=("result", "strategy", "node"))
        self.migration_jobs = r.counter(
            DESCHEDULER_MIGRATION_JOBS,
            "PodMigrationJob transitions by phase",
            labels=("phase",))
