"""descheduler metric series — parity with pkg/descheduler/metrics/
metrics.go (PodsEvicted and the migration-job counters)."""

from __future__ import annotations

from koordinator_tpu.metrics import Registry, global_registry


class DeschedulerMetrics:
    def __init__(self, registry: Registry = None):
        r = registry if registry is not None else global_registry()
        self.pods_evicted = r.counter(
            "descheduler_pods_evicted",
            "Evicted pods by result/strategy/node ('error' = eviction "
            "failed)", labels=("result", "strategy", "node"))
        self.migration_jobs = r.counter(
            "descheduler_migration_jobs",
            "PodMigrationJob transitions by phase",
            labels=("phase",))
