"""Device mesh + sharding specs for the cluster snapshot.

Design (scaling-book recipe): pick a mesh, annotate shardings, let XLA
insert collectives.

- 1D mesh over axis "nodes": every per-node column ([N, ...]) is sharded on
  dim 0; pod batches, quota/gang state, and config are replicated. The
  [P, N] score matrix is then computed shard-locally ([P, N/dev] per chip);
  jax.lax.top_k over the sharded axis makes XLA emit an all-gather of the
  per-shard top-k candidates over ICI (the global "selectHost" reduce);
  scatter-commits to node columns land shard-locally.
- The equivalent of sequence/context parallelism for this workload is
  exactly this node-axis sharding (SURVEY.md 5 "long-context"): the scaling
  axis is cluster size, and the collective pattern (shard-local reduce +
  cross-chip top-k merge) mirrors ring-attention's shard-local softmax +
  global combine.

No shard_map is needed: `scheduler.core.schedule_batch` is pure jit, so
annotating the snapshot's placement is enough (GSPMD propagates).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.snapshot.schema import ClusterSnapshot

NODE_AXIS = "nodes"


def make_mesh(devices: Optional[list] = None) -> Mesh:
    """1D mesh over all (or the given) devices on the node axis."""
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def candidate_mask_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for the cascade's [P, N] stage-1 candidate mask
    (scheduler/cascade.stage1_mask): pods replicate, node columns shard
    — the mask follows the node-column layout of every other [.., N]
    operand, so stage 1 is shard-local with zero collectives. Inside
    `schedule_batch` GSPMD derives exactly this placement from the
    snapshot's sharding; the export exists for callers that build or
    inspect the mask OUTSIDE the jitted program (smoke tools, tests)."""
    return NamedSharding(mesh, P(None, NODE_AXIS))


def snapshot_sharding(mesh: Mesh) -> ClusterSnapshot:
    """A ClusterSnapshot-shaped pytree of NamedShardings: node columns
    sharded on dim 0, everything else replicated."""
    node_spec = NamedSharding(mesh, P(NODE_AXIS))
    repl = NamedSharding(mesh, P())

    def node_field(_):
        return node_spec

    # nodes.* / devices.* are all [N, ...] -> shard dim 0; other groups
    # replicate
    from koordinator_tpu.snapshot.schema import (
        DeviceState, GangState, NodeState, QuotaState, ReservationState,
    )
    nodes = jax.tree_util.tree_map(node_field,
                                   NodeState(*([0] * len(NodeState.__dataclass_fields__))))
    quotas = jax.tree_util.tree_map(lambda _: repl,
                                    QuotaState(*([0] * len(QuotaState.__dataclass_fields__))))
    gangs = jax.tree_util.tree_map(lambda _: repl,
                                   GangState(*([0] * len(GangState.__dataclass_fields__))))
    res = jax.tree_util.tree_map(lambda _: repl,
                                 ReservationState(*([0] * len(ReservationState.__dataclass_fields__))))
    devs = jax.tree_util.tree_map(node_field,
                                  DeviceState(*([0] * len(DeviceState.__dataclass_fields__))))
    return ClusterSnapshot(nodes=nodes, quotas=quotas, gangs=gangs,
                           reservations=res, devices=devs, version=repl)


def shard_snapshot(snap: ClusterSnapshot, mesh: Mesh) -> ClusterSnapshot:
    """Place a host snapshot onto the mesh (node axis sharded over ICI).

    The node count must be divisible by the mesh size (pad capacities
    accordingly; SnapshotBuilder's max_nodes is the padded size).
    """
    shardings = snapshot_sharding(mesh)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), snap, shardings)
