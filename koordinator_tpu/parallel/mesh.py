"""Device mesh + sharding specs for the cluster snapshot.

Design (scaling-book recipe): pick a mesh, annotate shardings, let XLA
insert collectives.

- 1D mesh over axis "nodes" (the default): every per-node column
  ([N, ...]) is sharded on dim 0; pod batches, quota/gang state, and
  config are replicated. The [P, N] score matrix is then computed
  shard-locally ([P, N/dev] per chip); jax.lax.top_k over the sharded
  axis makes XLA emit an all-gather of the per-shard top-k candidates
  over ICI (the global "selectHost" reduce); scatter-commits to node
  columns land shard-locally.
- 2D mesh over ("pods", "nodes") (`make_mesh(devices, pods_axis=m)`):
  the pod queue's [P, ...] columns additionally shard over the pods
  axis, so the [P, N] intermediates tile over BOTH axes — the option
  for meshes big enough that node-axis sharding alone leaves chips
  idle. `batch_sharding`/`shard_batch` place a PodBatch accordingly.
- The equivalent of sequence/context parallelism for this workload is
  exactly this node-axis sharding (SURVEY.md 5 "long-context"): the scaling
  axis is cluster size, and the collective pattern (shard-local reduce +
  cross-chip top-k merge) mirrors ring-attention's shard-local softmax +
  global combine.

Inside `scheduler.core.schedule_batch` (pure jit) annotating the
operand placements is enough — GSPMD propagates the node sharding
through every [.., N] intermediate, computes the cascade's stage-1 mask
shard-locally (zero collectives; tools/mesh_flagship_smoke.py pins that
structurally on the compiled HLO) and emits the ICI top-k merge for
lax.top_k. For stages composed OUTSIDE one jitted program — where GSPMD
propagation has nothing to propagate through — the explicit shard_map
kernels live in `parallel.shardops` (shard-local stage-1, per-shard
top-k + ICI merge with exact tie semantics).

Sharding specs are DERIVED from the koordshape `register_struct`
field-spec tables (snapshot/schema.py): a leaf whose declared spec
carries the node symbol `N` shards that axis over "nodes", a [P]-leading
pod column shards over "pods" when the mesh has that axis, everything
else replicates. Adding a snapshot field therefore cannot silently get
the wrong placement — the same table that feeds the shape checkers
feeds the mesh layout.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.snapshot.schema import (
    ClusterSnapshot,
    PAD_FILL_VALUES,
    PodBatch,
    STRUCT_CLASSES,
    STRUCT_SPECS,
)

NODE_AXIS = "nodes"
POD_AXIS = "pods"


def make_mesh(devices: Optional[list] = None, pods_axis: int = 1) -> Mesh:
    """Mesh over all (or the given) devices: 1D on the node axis by
    default; `pods_axis > 1` folds the devices into a 2D
    (pods, nodes) grid (pods_axis must divide the device count)."""
    devices = jax.devices() if devices is None else devices
    if pods_axis <= 1:
        return Mesh(np.asarray(devices), (NODE_AXIS,))
    if len(devices) % pods_axis:
        raise ValueError(f"pods_axis={pods_axis} must divide the device "
                         f"count {len(devices)}")
    grid = np.asarray(devices).reshape(pods_axis,
                                       len(devices) // pods_axis)
    return Mesh(grid, (POD_AXIS, NODE_AXIS))


def mesh_axis_sizes(mesh: Mesh) -> dict:
    """{axis name: size} — the self-describing mesh stamp bench lines
    carry (a 4-device line must say whether it was 1x4 or 2x2)."""
    return {name: int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)}


def node_shards(mesh: Mesh) -> int:
    return int(mesh.shape[NODE_AXIS])


# --- spec-derived sharding trees ----------------------------------------

def _leaf_dims(spec) -> Optional[tuple]:
    """Dim-symbol tuple of a leaf spec string, `~pad:` predicates
    stripped ("f32[N~pad:zero,R]" -> ("N", "R")); None for struct
    references and bare-symbol properties."""
    if not isinstance(spec, str) or "[" not in spec:
        return None
    body = spec[spec.index("[") + 1:spec.rindex("]")].strip()
    if not body:
        return ()
    return tuple(t.split("~")[0].strip() for t in body.split(","))


def _node_fill(spec: str):
    """The concrete pad fill for a leaf's node axis, read off the `N`
    dim's declared ~pad: predicate (PAD_FILL_VALUES); predicates with
    no canonical fill (invalid/any) and undeclared dims fill 0."""
    body = spec[spec.index("[") + 1:spec.rindex("]")]
    for tok in body.split(","):
        dim, _, anno = tok.strip().partition("~")
        if dim.strip() == "N" and anno.strip().startswith("pad:"):
            fill = PAD_FILL_VALUES.get(anno.strip()[len("pad:"):])
            return 0 if fill is None else fill
    return 0


def _leaf_partition(dims: tuple, mesh: Mesh, shard_pods: bool) -> P:
    """PartitionSpec for one leaf: any `N` axis shards over the node
    axis; a LEADING `P` shards over the pods axis when asked for and
    the mesh has one; everything else replicates."""
    axes = []
    for i, d in enumerate(dims):
        if d == "N":
            axes.append(NODE_AXIS)
        elif (d == "P" and i == 0 and shard_pods
              and POD_AXIS in mesh.axis_names):
            axes.append(POD_AXIS)
        else:
            axes.append(None)
    while axes and axes[-1] is None:  # P(None) is not P()
        axes.pop()
    return P(*axes)


def struct_sharding(name: str, mesh: Mesh, shard_pods: bool = False):
    """Build a struct-shaped pytree of NamedShardings from the
    registered field-spec table (bare-symbol properties are skipped;
    nested registered structs recurse). Works for ANY registered
    struct whose defining module is imported — e.g.
    struct_sharding("ScheduleResult", mesh) derives the out_shardings
    of a sharded schedule step."""
    fields = {}
    for fname, spec in STRUCT_SPECS[name].items():
        if isinstance(spec, str) and spec in STRUCT_SPECS:
            fields[fname] = struct_sharding(spec, mesh, shard_pods)
            continue
        dims = _leaf_dims(spec)
        if dims is None:
            continue  # symbolic-int property (num_nodes), not a field
        fields[fname] = NamedSharding(
            mesh, _leaf_partition(dims, mesh, shard_pods))
    return STRUCT_CLASSES[name](**fields)


def snapshot_sharding(mesh: Mesh) -> ClusterSnapshot:
    """A ClusterSnapshot-shaped pytree of NamedShardings, derived from
    the koordshape field-spec tables: node columns ([N, ...] leaves in
    nodes.*/devices.*) shard dim 0, everything else replicates."""
    return struct_sharding("ClusterSnapshot", mesh)


def batch_sharding(pods: PodBatch, mesh: Mesh) -> PodBatch:
    """A PodBatch-shaped pytree of NamedShardings for the 2D mesh path:
    per-pod [P, ...] columns shard over the pods axis (when the mesh
    has one), the batch-global [*, N] domain matrices shard their node
    axis, count surfaces and selector/toleration tables replicate.
    Built by `replace` on `pods` so the static gate switches
    (has_taints & co, pytree aux data) match the batch being placed."""
    upd = {}
    for fname, spec in STRUCT_SPECS["PodBatch"].items():
        dims = _leaf_dims(spec)
        if dims is None:
            continue
        part = _leaf_partition(dims, mesh, shard_pods=True)
        # degenerate compile-out extents (the [1, 1] domain matrices of
        # slim workloads) and any axis the mesh doesn't divide replicate
        shape = getattr(pods, fname).shape
        part = P(*(ax if ax is not None
                   and shape[i] % mesh.shape[ax] == 0 and shape[i] > 1
                   else None
                   for i, ax in enumerate(part)))
        upd[fname] = NamedSharding(mesh, part)
    return pods.replace(**upd)


def candidate_mask_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for the cascade's [P, N] stage-1 candidate mask
    (scheduler/cascade.stage1_mask): pods replicate, node columns shard
    — the mask follows the node-column layout of every other [.., N]
    operand, so stage 1 is shard-local with zero collectives. Inside
    `schedule_batch` GSPMD derives exactly this placement from the
    snapshot's sharding; the export exists for callers that build or
    inspect the mask OUTSIDE the jitted program (smoke tools, tests)."""
    return NamedSharding(mesh, P(None, NODE_AXIS))


def shard_snapshot(snap: ClusterSnapshot, mesh: Mesh) -> ClusterSnapshot:
    """Place a host snapshot onto the mesh (node axis sharded over ICI).

    The node count must be divisible by the mesh's node-axis size —
    run the snapshot through `pad_nodes_to_mesh` first when it isn't
    (SnapshotBuilder's max_nodes is the padded size on the typed path).
    """
    shardings = snapshot_sharding(mesh)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), snap, shardings)


def shard_batch(pods: PodBatch, mesh: Mesh) -> PodBatch:
    """Place a pod batch onto the mesh per `batch_sharding` (the 2D
    mesh path; on a 1D node mesh it replicates per-pod columns and
    shards only the [*, N] domain matrices)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), pods, batch_sharding(pods, mesh))


# --- node-axis padding ---------------------------------------------------
#
# Pad fills are DERIVED from the ~pad: predicates the field-spec tables
# declare (_node_fill above): cpu_amplification pads 1.0 (pad:one — a
# ratio column stays semantically well-formed), instance/domain topology
# pads -1 (pad:-1 — "unknown" / "node lacks the topology key"), and
# everything else pads 0. tools/padcheck.py asserts the fills; the
# pad-soundness lint pass asserts consumers respect them.


def padded_node_count(num_nodes: int, mesh: Mesh) -> int:
    """The node-axis size after padding to a multiple of the mesh's
    node-axis extent."""
    size = node_shards(mesh)
    return -(-num_nodes // size) * size


def _pad_leaf(x, dims: tuple, n_old: int, n_new: int, fill):
    """Pad every axis whose declared symbol is N (and whose runtime
    extent actually is the node count — degenerate [1, 1] compile-out
    matrices stay put) from n_old to n_new with `fill`."""
    for axis, d in enumerate(dims):
        if d != "N" or x.shape[axis] != n_old:
            continue
        lib = np if isinstance(x, np.ndarray) else jax.numpy
        shape = x.shape[:axis] + (n_new - n_old,) + x.shape[axis + 1:]
        x = lib.concatenate(
            [x, lib.full(shape, fill, dtype=x.dtype)], axis=axis)
    return x


def _pad_struct(obj, name: str, n_old: int, n_new: int):
    upd = {}
    for fname, spec in STRUCT_SPECS[name].items():
        if isinstance(spec, str) and spec in STRUCT_SPECS:
            upd[fname] = _pad_struct(getattr(obj, fname), spec,
                                     n_old, n_new)
            continue
        dims = _leaf_dims(spec)
        if dims is None or "N" not in dims:
            continue
        upd[fname] = _pad_leaf(getattr(obj, fname), dims, n_old, n_new,
                               _node_fill(spec))
    return obj.replace(**upd)


def pad_nodes_to_mesh(snap: ClusterSnapshot, mesh: Mesh) -> ClusterSnapshot:
    """Pad the snapshot's node axis to a multiple of the mesh's
    node-axis size with zero-capacity rows, so callers never hand-pad
    before `shard_snapshot`. Derived from the same field-spec tables as
    the shardings (every leaf with an `N` axis pads; numpy inputs stay
    on host).

    PAD-ROW CONTRACT: pad rows are PROVABLY unschedulable — schedulable
    is False (the static gates zero their columns, so the cascade's
    stage-1 mask kills them before any score is computed) and
    allocatable is zero (the resource-fit gate rejects them
    independently). They therefore can never be charged: `requested`
    stays zero and the overcommit invariant is checked on the real rows
    only (`core.overcommit_ok(snap, num_real_nodes)` — pad rows are
    excluded by construction, not by tolerance).
    """
    n_old = snap.num_nodes
    n_new = padded_node_count(n_old, mesh)
    if n_new == n_old:
        return snap
    return _pad_struct(snap, "ClusterSnapshot", n_old, n_new)


def unpad_nodes(snap: ClusterSnapshot, num_real: int) -> ClusterSnapshot:
    """Slice a `pad_nodes_to_mesh`-padded snapshot back to its real
    node count — the inverse walk over the same field-spec tables.

    The mesh-shrink ladder rung (frameworkext.DegradationLadder) pads
    and re-shards per cycle over whatever devices survive; committing
    the PADDED post-cycle snapshot to the store would make the stored
    shapes a function of the surviving-device count (a recompile per
    shrink event, and a shape mismatch the moment the full mesh
    returns). Unpadding is sound because pad rows are provably inert:
    schedulable=False + zero allocatable means they are never chosen
    and never charged (`core.overcommit_ok` pins that), so slicing
    them off loses nothing."""
    n_now = snap.num_nodes
    if n_now == num_real:
        return snap
    if n_now < num_real:
        raise ValueError(f"cannot unpad {n_now} nodes to {num_real}")

    def slice_leaf(x, dims):
        for axis, d in enumerate(dims):
            if d == "N" and x.shape[axis] == n_now:
                index = [slice(None)] * x.ndim
                index[axis] = slice(0, num_real)
                x = x[tuple(index)]
        return x

    def walk(obj, name):
        upd = {}
        for fname, spec in STRUCT_SPECS[name].items():
            if isinstance(spec, str) and spec in STRUCT_SPECS:
                upd[fname] = walk(getattr(obj, fname), spec)
                continue
            dims = _leaf_dims(spec)
            if dims is None or "N" not in dims:
                continue
            upd[fname] = slice_leaf(getattr(obj, fname), dims)
        return obj.replace(**upd)

    return walk(snap, "ClusterSnapshot")


def pad_batch_nodes(pods: PodBatch, num_nodes: int) -> PodBatch:
    """Pad the batch's node-indexed matrices (the [*, N] topology
    domain maps) to a padded snapshot's node count, filling -1 ("node
    lacks the key") so pad columns can never open or charge a domain.
    A no-op when nothing carries the real node count (the [1, 1]
    compile-out matrices of slim workloads)."""
    extents = set()
    for fname, spec in STRUCT_SPECS["PodBatch"].items():
        dims = _leaf_dims(spec)
        if dims is None or "N" not in dims:
            continue
        extents.add(getattr(pods, fname).shape[dims.index("N")])
    extents -= {1, num_nodes}
    if not extents:
        return pods
    if len(extents) > 1 or max(extents) > num_nodes:
        raise ValueError(f"inconsistent batch node extents {sorted(extents)} "
                         f"vs padded node count {num_nodes}")
    return _pad_struct(pods, "PodBatch", extents.pop(), num_nodes)
