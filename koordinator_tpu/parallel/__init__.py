"""Multi-chip scale-out: shard the node axis of the snapshot over a Mesh.

The reference scales Filter/Score with chunked goroutines over nodes
(k8s Parallelizer, SURVEY.md 2.9); the TPU-native analogue is sharding the
node dimension of every [N, ...] column across chips so each chip
filters/scores a node shard and the top-k select rides ICI collectives.
"""

from koordinator_tpu.parallel.mesh import (  # noqa: F401
    candidate_mask_sharding,
    make_mesh,
    snapshot_sharding,
    shard_snapshot,
)
