"""Multi-chip scale-out: shard the node axis of the snapshot over a Mesh.

The reference scales Filter/Score with chunked goroutines over nodes
(k8s Parallelizer, SURVEY.md 2.9); the TPU-native analogue is sharding the
node dimension of every [N, ...] column across chips so each chip
filters/scores a node shard and the top-k select rides ICI collectives.
`mesh` owns the mesh shapes, spec-derived shardings and node-axis
padding; `shardops` the explicit shard_map kernels (shard-local stage-1,
per-shard top-k + ICI merge) for stages composed outside one jitted
program.
"""

from koordinator_tpu.parallel.mesh import (  # noqa: F401
    NODE_AXIS,
    POD_AXIS,
    batch_sharding,
    candidate_mask_sharding,
    make_mesh,
    mesh_axis_sizes,
    node_shards,
    pad_batch_nodes,
    pad_nodes_to_mesh,
    padded_node_count,
    shard_batch,
    shard_snapshot,
    snapshot_sharding,
    struct_sharding,
    unpad_nodes,
)
from koordinator_tpu.parallel import shardops  # noqa: F401
