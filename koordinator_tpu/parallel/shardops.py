"""Explicit shard_map kernels for the node-sharded scheduling program.

Inside one jitted `schedule_batch` GSPMD propagation is enough: the
snapshot's node sharding flows through every [.., N] intermediate, the
cascade's stage-1 mask is computed shard-locally with zero collectives,
and `lax.top_k` over the sharded axis lowers to a per-shard top-k plus
an ICI merge (tools/mesh_flagship_smoke.py pins both structurally on
the compiled HLO). Where GSPMD has nothing to propagate through —
stages composed OUTSIDE one jitted program, such as smoke tools
building the stage-1 mask standalone, or custom pipelines that want the
candidate merge before a host-side commit — these shard_map kernels are
the explicit, conformance-pinned equivalents:

- `stage1_mask_sharded`: the cascade's stage-1 candidate mask computed
  per node shard (each chip sees only its node columns; the quota
  ceiling, a [P]-only term, is recomputed replicated per shard — cheap
  and collective-free).
- `shard_local_topk`: per-shard `lax.top_k` + all-gather of the
  (value, global index) candidates over ICI + `topk_merge`, the
  lexicographic (value desc, index asc) merge whose tie order is
  exactly `lax.top_k`'s — bit-identical to the global reduction
  (tests/test_mesh_flagship.py pins it, ties included).

Both run under `jax.jit` at the call site; nothing here is a
module-level jit entry.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from koordinator_tpu import obs
from koordinator_tpu.obs import phases as obs_phases
from koordinator_tpu.parallel.mesh import (
    NODE_AXIS,
    node_shards,
    snapshot_sharding,
)
from koordinator_tpu.scheduler.cascade import stage1_mask
from koordinator_tpu.snapshot.schema import (
    ClusterSnapshot,
    MAX_QUOTA_DEPTH,
    PodBatch,
    shape_contract,
)


def stage1_mask_sharded(mesh: Mesh, snap: ClusterSnapshot, pods: PodBatch,
                        static_ok: jnp.ndarray,
                        fit_dims: Optional[tuple] = None,
                        quota_depth: int = MAX_QUOTA_DEPTH) -> jnp.ndarray:
    """bool[P, N]: `cascade.stage1_mask` computed shard-locally — each
    chip evaluates batch-start resource fit over its own node columns
    only. Zero collectives by construction (the resource fit is
    elementwise over node columns; the quota-ceiling term reads no node
    state and is recomputed identically on every shard), and
    bit-identical to the global mask.

    `check_rep=False` because shard_map cannot prove the replicated
    quota term is shard-invariant; the conformance test does."""
    snap_spec = jax.tree_util.tree_map(lambda s: s.spec,
                                       snapshot_sharding(mesh))
    pods_spec = jax.tree_util.tree_map(lambda _: P(), pods)
    mask_spec = P(None, NODE_AXIS)

    fn = shard_map(
        lambda sn, pd, so: stage1_mask(sn, pd, so, fit_dims=fit_dims,
                                       quota_depth=quota_depth),
        mesh=mesh, in_specs=(snap_spec, pods_spec, mask_spec),
        out_specs=mask_spec, check_rep=False)
    return fn(snap, pods, static_ok)


@shape_contract(
    vals="f32[P~pad:any,KC]", idxs="i32[P~pad:any,KC]",
    _returns=("f32[P~pad:any,KC]", "i32[P~pad:any,KC]"),
    _pad="KC = gathered per-shard candidates (k x node shards); rows "
         "sort by (value desc, global index asc) — exactly lax.top_k's "
         "tie order, so [:, :k] of the output equals the global top-k")
def topk_merge(vals: jnp.ndarray, idxs: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Lexicographic merge of gathered per-shard top-k candidate rows:
    sort each row by (value descending, global index ascending). Every
    global top-k element survives its own shard's local top-k, so
    slicing the merged row to k is bit-identical to `lax.top_k` over
    the full row — including ties, which lax.top_k breaks toward the
    lowest index."""
    with obs.phase(obs_phases.PHASE_ICI_MERGE):
        order = jnp.lexsort((idxs, -vals), axis=-1)
        return (jnp.take_along_axis(vals, order, axis=-1),
                jnp.take_along_axis(idxs, order, axis=-1))


def shard_local_topk(mesh: Mesh, scores: jnp.ndarray, k: int
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(f32[P, k], i32[P, k]): the explicit form of the ICI top-k
    reduce — per-shard `lax.top_k` over the local node columns,
    all-gather of the (value, global index) candidates over the node
    axis, then `topk_merge`. Bit-identical to
    `jax.lax.top_k(scores, k)` on the unsharded operand.

    Requires the sharded axis divisible by the shard count (run node
    columns through `pad_nodes_to_mesh` first) and k <= the local
    width — the global top-k may live entirely in one shard, so a
    shard must be able to nominate k candidates.
    """
    n = scores.shape[-1]
    shards = node_shards(mesh)
    if n % shards:
        raise ValueError(f"column count {n} not divisible by the "
                         f"{shards}-way node axis (pad_nodes_to_mesh)")
    local = n // shards
    if k > local:
        raise ValueError(f"k={k} exceeds the local shard width {local}; "
                         "a single shard could hold the whole top-k")

    def per_shard(x):
        with obs.phase(obs_phases.PHASE_TOPK):
            v, i = jax.lax.top_k(x, k)
            off = jax.lax.axis_index(NODE_AXIS) * local
            i = (i + off).astype(jnp.int32)
        with obs.phase(obs_phases.PHASE_ICI_MERGE):
            v = jax.lax.all_gather(v, NODE_AXIS, axis=v.ndim - 1,
                                   tiled=True)
            i = jax.lax.all_gather(i, NODE_AXIS, axis=i.ndim - 1,
                                   tiled=True)
            mv, mi = topk_merge(v, i)
            return mv[..., :k], mi[..., :k]

    fn = shard_map(per_shard, mesh=mesh,
                   in_specs=P(None, NODE_AXIS),
                   out_specs=(P(), P()), check_rep=False)
    return fn(scores)
