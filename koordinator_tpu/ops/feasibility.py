"""Cheap vectorized feasibility kernels — stage 1 of the Filter->Score
gate cascade (scheduler/cascade.py).

These are the batched analogues of the reference Filter stage's cheapest
checks: batch-start resource fit (noderesources.Fit) and elastic-quota
ceiling admission (elasticquota PreFilter). Both read only BATCH-START
state, which within a commit batch is monotone — node `requested` and
quota `used` only grow as pods are accepted — so a (pod, node) pair that
fails here fails in every commit round, and pruning it up front cannot
change placements (the soundness argument the cascade relies on; see
cascade.stage1_mask).

Self-contained numerical ops: no scheduler imports beyond the shared EPS
tolerance, so plugin kernels and tools can reuse them without cycles.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from koordinator_tpu.scheduler.batching import EPS
from koordinator_tpu.snapshot.schema import (
    MAX_QUOTA_DEPTH,
    PodBatch,
    QuotaState,
    shape_contract,
)


def _dims(x: jnp.ndarray, fit_dims: Optional[tuple]) -> jnp.ndarray:
    """Restrict a [..., R] operand to the checked resource dims (the
    same rule as core.schedule_batch's fit_dims)."""
    return x if fit_dims is None else x[..., list(fit_dims)]


@shape_contract(
    allocatable="f32[N~pad:unschedulable,R]",
    requested="f32[N~pad:unschedulable,R]", requests="f32[P~pad:zero,R]",
    _returns="bool[P~pad:invalid,N~pad:false]",
    _pad="padded node rows carry allocatable 0 so no pod fits them; "
         "padded pod rows are masked later by pods.valid")
def resource_fit(allocatable: jnp.ndarray, requested: jnp.ndarray,
                 requests: jnp.ndarray,
                 fit_dims: Optional[tuple] = None) -> jnp.ndarray:
    """bool[P, N]: pod fits the node's batch-start headroom on every
    checked dim. Identical math (and EPS tolerance) to the first commit
    round's fit gate, so the mask is exactly that round's fit and an
    upper bound of every later round's."""
    return jnp.all(
        _dims(requests, fit_dims)[:, None, :]
        + _dims(requested, fit_dims)[None]
        <= _dims(allocatable, fit_dims)[None] + EPS, axis=-1)


@shape_contract(quotas="QuotaState", pods="PodBatch",
                _returns="i32[P~pad:-1,QD]",
                _pad="-1 rows past the leaf / for quota-less pods")
def pod_ancestors(quotas: QuotaState, pods: PodBatch) -> jnp.ndarray:
    """i32[P, D]: each pod's quota-tree ancestor chain per depth, -1 =
    none (quota-less pods get an all--1 row)."""
    quota_id = jnp.maximum(pods.quota_id, 0)
    return jnp.where(pods.quota_id[:, None] >= 0,
                     quotas.depth_ancestor[quota_id], -1)


@shape_contract(quotas="QuotaState", pods="PodBatch",
                _returns="bool[P~pad:one]",
                _pad="invalid quota rows carry runtime +inf and never "
                     "gate; quota-less pods pass every level")
def quota_ceiling_ok(quotas: QuotaState, pods: PodBatch,
                     quota_depth: int = MAX_QUOTA_DEPTH,
                     fit_dims: Optional[tuple] = None) -> jnp.ndarray:
    """bool[P]: batch-start elastic-quota admission — used + request <=
    runtime at every tree level of the pod's chain. A False row kills
    the pod's ENTIRE node row in the cascade mask: quota admission is
    node-independent, and used only grows within the batch."""
    pod_anc = pod_ancestors(quotas, pods)
    ok = jnp.ones((pods.num_pods,), bool)
    for d in range(quota_depth):
        anc = pod_anc[:, d]
        a = jnp.maximum(anc, 0)
        level_ok = jnp.all(
            _dims(quotas.used, fit_dims)[a] + _dims(pods.requests, fit_dims)
            <= _dims(quotas.runtime, fit_dims)[a] + EPS, axis=-1)
        ok &= (anc < 0) | level_ok
    return ok
