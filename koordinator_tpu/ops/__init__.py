"""Device kernels shared across components (top-k commit lives in
scheduler/core; this package holds self-contained numerical ops:
waterfill — elastic-quota runtime, quota_demand — demand aggregation,
feasibility — the gate cascade's cheap stage-1 fit/ceiling kernels)."""
