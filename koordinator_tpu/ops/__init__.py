"""Device kernels shared across components (top-k commit lives in
scheduler/core; this package holds self-contained numerical ops)."""
