"""Hierarchical elastic-quota runtime calculation (fair-share water-filling).

Behavior parity with elasticquota/core/runtime_quota_calculator.go:111-168
(`quotaTree.redistribution` + `iterationForRedistribution`), applied level by
level down the tree (each parent redistributes its own runtime to its
children, GroupQuotaManager semantics):

1. autoScaleMin = max(min, guarantee). A child whose demand (limitedRequest)
   exceeds autoScaleMin starts at runtime = autoScaleMin and participates in
   redistribution weighted by sharedWeight; a child under its min keeps
   runtime = demand (or min when allowLentResource is false).
2. The parent's remaining resource is handed out in rounds:
   delta = floor(weight * remaining / totalWeight + 0.5); children clamp at
   their demand; the next round re-partitions ONLY the excess returned by
   the children that clamped (iterationForRedistribution recursion —
   un-handed rounding remainder is dropped, which also guarantees
   termination: a round either returns excess from a newly-capped child or
   ends the group).

TPU-native formulation: all sibling groups x all resource dims iterate
simultaneously — the loop state is [Q, R] tensors with per-parent segment
sums, so one fixed-point solves the entire forest (the reference allocates
one recursive solver per parent per dim).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from koordinator_tpu.snapshot.schema import (
    MAX_QUOTA_DEPTH,
    QuotaState,
    shape_contract,
)


def _seg_sum(values: jnp.ndarray, seg: jnp.ndarray, num: int) -> jnp.ndarray:
    """Segment-sum rows of [Q, R] by seg id (clip invalid to a dump row)."""
    out = jnp.zeros((num + 1,) + values.shape[1:], values.dtype)
    return out.at[jnp.where(seg >= 0, seg, num)].add(values)[:num]


@shape_contract(quotas="QuotaState", _returns="f32[Q~pad:zero,R]",
                _pad="invalid rows carry depth -1 and contribute nothing")
def propagate_demand(quotas: QuotaState) -> jnp.ndarray:
    """f32[Q, R]: limitedRequest per quota, from DIRECT demand.

    Bottom-up walk with the reference's per-level clamp
    (group_quota_manager.go:184-214 recursiveUpdateGroupTreeWithDeltaRequest
    + quota_info.go:196-211 getLimitRequestNoLock): each quota's request is
    its own pods' demand plus Σ children's *limited* requests; a quota that
    does not lend floors its request at min; the value passed upward is
    min(request, max). One unrolled level loop (depth is static)."""
    q = quotas.min.shape[0]
    depth = jnp.sum(quotas.depth_ancestor >= 0, axis=-1) - 1  # [Q]

    def clamp(subtree):
        floored = jnp.where(quotas.allow_lent[:, None], subtree,
                            jnp.maximum(subtree, quotas.min))
        return jnp.minimum(floored, quotas.max)

    subtree = quotas.demand
    for d in range(MAX_QUOTA_DEPTH - 1, 0, -1):
        at_d = (depth == d)[:, None]
        contrib = _seg_sum(jnp.where(at_d, clamp(subtree), 0.0),
                           jnp.where(at_d[:, 0], quotas.parent, -1), q)
        subtree = subtree + contrib
    return clamp(subtree)


def _redistribute_level(level_mask: jnp.ndarray, parent: jnp.ndarray,
                        parent_total: jnp.ndarray, demand: jnp.ndarray,
                        min_eff: jnp.ndarray, weight: jnp.ndarray,
                        allow_lent: jnp.ndarray, num_quotas: int,
                        max_iters: int) -> jnp.ndarray:
    """Runtime for all quotas of one level, vectorized over sibling groups
    and resource dims. Inputs are full [Q, ...] tensors; rows outside
    `level_mask` contribute nothing and return 0."""
    m = level_mask[:, None]                       # [Q, 1]
    adjusting = m & (demand > min_eff)            # [Q, R]
    runtime0 = jnp.where(
        adjusting, min_eff,
        jnp.where(allow_lent[:, None], jnp.minimum(demand, min_eff), min_eff))
    runtime0 = jnp.where(m, runtime0, 0.0)

    # remaining per parent = parent_total - Σ children initial runtime
    spent = _seg_sum(runtime0, parent, num_quotas)          # [Q, R]
    remaining = jnp.maximum(parent_total - spent, 0.0)      # [Q, R] (by parent row)

    def cond(state):
        it, runtime, adjusting, remaining = state
        total_w = _seg_sum(jnp.where(adjusting, weight, 0.0),
                           parent, num_quotas)
        want = (remaining > 0.5) & (total_w > 0)
        return (it < max_iters) & jnp.any(want)

    def body(state):
        it, runtime, adjusting, remaining = state
        w = jnp.where(adjusting, weight, 0.0)               # [Q, R]
        total_w = _seg_sum(w, parent, num_quotas)           # [Q, R] per parent
        group_live = (remaining > 0.5) & (total_w > 0)      # [Q, R] parent rows
        tw = jnp.take(total_w, jnp.maximum(parent, 0), axis=0)
        rem = jnp.take(remaining, jnp.maximum(parent, 0), axis=0)
        live = adjusting & (tw > 0) & (rem > 0.5)
        delta = jnp.where(live,
                          jnp.floor(w * rem / jnp.maximum(tw, 1e-9) + 0.5),
                          0.0)
        new_runtime = runtime + delta
        over = live & (new_runtime >= demand)
        excess = jnp.where(over, new_runtime - demand, 0.0)
        new_runtime = jnp.where(over, demand, new_runtime)
        # the next round re-partitions only the excess returned by children
        # that hit their demand; a live group's un-handed rounding remainder
        # is dropped (iterationForRedistribution recursion passes
        # toPartitionResource = Σ(runtime − request)) — this both matches the
        # reference and guarantees termination when every delta rounds to 0
        returned = _seg_sum(excess, parent, num_quotas)
        remaining = jnp.where(group_live, returned, remaining)
        adjusting = adjusting & ~over
        return (it + 1, new_runtime, adjusting, remaining)

    state = (jnp.int32(0), runtime0, adjusting, remaining)
    _, runtime, _, _ = jax.lax.while_loop(cond, body, state)
    return jnp.where(m, runtime, 0.0)


@shape_contract(quotas="QuotaState", cluster_total="f32[R]",
                _returns="f32[Q~pad:inf,R]",
                _static={"max_iters": 8},
                _pad="invalid quota rows return +inf (never gate)")
@functools.partial(jax.jit, static_argnames=("max_iters",))
def compute_runtime(quotas: QuotaState, cluster_total: jnp.ndarray,
                    max_iters: int = 64) -> jnp.ndarray:
    """f32[Q, R]: runtime entitlement for every quota in the forest.

    Top-down over tree levels: roots partition `cluster_total` [R], each
    lower level partitions its parent's freshly computed runtime. Invalid
    quota rows get +inf (no gating), preserving schedule_batch's "no quota"
    fast path.
    """
    q = quotas.min.shape[0]
    min_eff = quotas.min                           # guarantee folded upstream
    demand = propagate_demand(quotas)              # limitedRequest per quota
    # per-dim sharedWeight, defaulting to max (quota_info.go semantics)
    weight = jnp.where(quotas.shared_weight > 0, quotas.shared_weight,
                       quotas.max)
    weight = jnp.where(jnp.isfinite(weight), weight, 1.0)

    depth = jnp.sum(quotas.depth_ancestor >= 0, axis=-1) - 1  # [Q], -1 invalid
    runtime = jnp.zeros_like(quotas.min)

    for d in range(MAX_QUOTA_DEPTH):
        level = quotas.valid & (depth == d)
        if d == 0:
            # Each root owns a whole quota tree against the cluster total
            # (multi-quota-tree: one RuntimeQuotaCalculator per tree);
            # a root's runtime is the tree capacity, capped by its max.
            rt = jnp.minimum(quotas.max, cluster_total[None, :])
            runtime = jnp.where(level[:, None], rt, runtime)
            continue
        parent_total = runtime                      # [Q, R] indexed by parent
        rt = _redistribute_level(level, quotas.parent, parent_total,
                                 demand, min_eff, weight, quotas.allow_lent,
                                 q, max_iters)
        runtime = jnp.where(level[:, None], rt, runtime)

    # clamp by max everywhere; invalid rows never gate
    runtime = jnp.minimum(runtime, quotas.max)
    return jnp.where(quotas.valid[:, None], runtime, jnp.inf)
