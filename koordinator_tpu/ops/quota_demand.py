"""Quota demand accounting: fold a pending pod batch into the quota tree's
DIRECT demand column before the water-filling solve.

Mirrors GroupQuotaManager.updatePodRequest: a pod's request charges its own
quota; ancestor propagation happens inside ops.waterfill with the reference's
per-level min/max clamp (group_quota_manager.go
recursiveUpdateGroupTreeWithDeltaRequest)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from koordinator_tpu.snapshot.schema import PodBatch, QuotaState, shape_contract


@shape_contract(quotas="QuotaState", pods="PodBatch",
                _returns="QuotaState",
                _pad="invalid pod rows (valid False) and quota-less pods "
                     "(quota_id -1) charge the drop row, not the tree")
@jax.jit
def add_pending_demand(quotas: QuotaState, pods: PodBatch) -> QuotaState:
    q = quotas.min.shape[0]
    req = pods.requests * pods.valid[:, None]
    tgt = jnp.where(pods.quota_id >= 0, pods.quota_id, q)
    demand = quotas.demand.at[tgt].add(req, mode="drop")
    return quotas.replace(demand=demand)
