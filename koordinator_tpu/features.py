"""Feature gates.

Capability parity with pkg/features (SURVEY.md 2.7): k8s-featuregate-style
machinery — a registry of named gates with defaults and maturity stages,
`--feature-gates=A=true,B=false` string parsing, and per-component gate
catalogs (webhook gates features.go:28-52, koordlet QoS gates
koordlet_features.go:33-143, scheduler gates scheduler_features.go).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Iterable, Mapping

from koordinator_tpu.utils.sync import guarded_by


@dataclasses.dataclass(frozen=True)
class FeatureSpec:
    default: bool
    stage: str = "ALPHA"          # ALPHA | BETA | GA
    lock_to_default: bool = False


@guarded_by(_specs="_lock", _overrides="_lock")
class FeatureGate:
    """Mutable view over a spec registry (featuregate.MutableFeatureGate)."""

    def __init__(self, specs: Mapping[str, FeatureSpec]):
        self._specs = dict(specs)
        self._overrides: Dict[str, bool] = {}
        self._lock = threading.Lock()

    def add(self, specs: Mapping[str, FeatureSpec]) -> None:
        with self._lock:
            for name, spec in specs.items():
                existing = self._specs.get(name)
                if existing is not None and existing != spec:
                    raise ValueError(f"feature gate {name} redefined")
                self._specs[name] = spec

    def known(self) -> Iterable[str]:
        with self._lock:
            return sorted(self._specs)

    def enabled(self, name: str) -> bool:
        with self._lock:
            spec = self._specs.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name!r}")
            return self._overrides.get(name, spec.default)

    def set(self, name: str, value: bool) -> None:
        with self._lock:
            spec = self._specs.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name!r}")
            if spec.lock_to_default and value != spec.default:
                raise ValueError(f"feature gate {name} is locked to "
                                 f"{spec.default}")
            self._overrides[name] = value

    def set_from_map(self, values: Mapping[str, bool]) -> None:
        for name, value in values.items():
            self.set(name, value)

    def parse(self, flag: str) -> None:
        """--feature-gates=A=true,B=false"""
        for part in filter(None, (p.strip() for p in flag.split(","))):
            name, _, raw = part.partition("=")
            lowered = raw.strip().lower()
            if lowered not in ("true", "false"):
                raise ValueError(
                    f"invalid feature gate value {part!r} (want "
                    f"name=true|false)")
            self.set(name.strip(), lowered == "true")


def _specs(**kw: FeatureSpec) -> Dict[str, FeatureSpec]:
    return kw


_on = lambda stage="BETA": FeatureSpec(default=True, stage=stage)   # noqa: E731
_off = lambda stage="ALPHA": FeatureSpec(default=False, stage=stage)  # noqa: E731

# Webhook / manager gates (pkg/features/features.go:28-52).
MANAGER_GATES = _specs(
    PodMutatingWebhook=_on(),
    PodValidatingWebhook=_on(),
    ElasticQuotaIgnorePodOverhead=_off(),
    ElasticQuotaGuaranteePercent=_off(),
    DisableDefaultQuota=_off(),
    SupportParentQuotaSubmitPod=_off(),
    WebhookFramework=_on("BETA"),
    ColocationProfileSkipMutatingResources=_off(),
    MultiQuotaTree=_off(),
    ElasticQuotaProfile=_off(),
)

# koordlet QoS gates (pkg/features/koordlet_features.go:33-143).
KOORDLET_GATES = _specs(
    AuditEvents=_off(),
    AuditEventsHTTPHandler=_off(),
    BECFSQuotaBurst=_off(),
    BECPUEvict=_off(),
    BEMemoryEvict=_off(),
    BECPUSuppress=_on(),
    BECPUManager=_off(),
    CPUBurst=_on(),
    SystemConfig=_off(),
    RdtResctrl=_on(),
    CgroupReconcile=_off(),
    NodeTopologyReport=_on(),
    Libpfm4=_off(),
    CPICollector=_off(),
    PSICollector=_on(),
    CPUSuppress=_on(),
    CgroupV2=_on("BETA"),
    ColdPageCollector=_off(),
    Accelerators=_off(),
    CoreSched=_off(),
    BlkIOReconcile=_off(),
)

# Scheduler gates (pkg/features/scheduler_features.go).
SCHEDULER_GATES = _specs(
    CompatibleCSIStorageCapacity=_off(),
    DisableCSIStorageCapacityInformer=_off(),
    CompatiblePodDisruptionBudget=_off(),
    DisablePodDisruptionBudgetInformer=_off(),
    ResizePod=_off(),
    EnableACKGPUShareScheduling=_off(),
)

DEFAULT_FEATURE_GATE = FeatureGate({**MANAGER_GATES, **KOORDLET_GATES,
                                    **SCHEDULER_GATES})


def new_default_gate() -> FeatureGate:
    """A FRESH gate with every catalog — one per process/daemon instance.
    Each binary owns its own mutable gate (cmd/*/options in the
    reference); sharing the module-global DEFAULT_FEATURE_GATE across
    in-process components would leak --feature-gates overrides between
    them."""
    return FeatureGate({**MANAGER_GATES, **KOORDLET_GATES,
                        **SCHEDULER_GATES})
