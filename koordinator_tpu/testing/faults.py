"""Seeded, deterministic fault injectors for the resilience layer.

Each injector produces exactly one fault instance from a seeded RNG, so
a failing chaos run reproduces from its seed alone. Three families:

- column corruptions: host-side edits of snapshot/batch columns (the
  poison the device health guards in scheduler/guards.py must catch);
- delta replay: stale/duplicate `source_version` stamps (the store's
  version guard);
- runtime failures: hooks for `SchedulerService.fault_injection` that
  raise real `XlaRuntimeError`s (OOM above a width threshold, fail the
  Nth program attempt) or trip the cycle watchdog — driving the typed
  classifier and the degradation ladder.

Consumed by tools/chaos_smoke.py (the CI matrix), tools/soak_service.py
--chaos, and tests/test_degradation.py.
"""

from __future__ import annotations

import os
import signal
from typing import Callable, Optional, Tuple

import numpy as np

from koordinator_tpu.scheduler import guards

# every fault class the chaos matrix exercises; tools/chaos_smoke.py
# asserts detection + quarantine + service-up + clean-row conformance
# for each one
SNAPSHOT_FAULTS = ("nan_metric_column", "negative_allocatable",
                   "overcommit_row", "numa_free_above_cap")
BATCH_FAULTS = ("nan_pod_request", "negative_pod_request",
                "bad_gang_id", "bad_domain_index")
RUNTIME_FAULTS = ("xla_oom", "xla_transient", "device_lost",
                  "watchdog_stall", "device_lost_mid_chunk")
DELTA_FAULTS = ("stale_delta",)
ALL_FAULTS = SNAPSHOT_FAULTS + BATCH_FAULTS + RUNTIME_FAULTS + DELTA_FAULTS

# the named crash points of the kill-injected soak (ISSUE 14): the
# first three are the commit journal's append seam
# (scheduler/journal.py POINT_*), the fourth is the store's checkpoint
# writer. tools/crash_smoke.py SIGKILLs the service at each one and
# asserts the restarted service converges bit-identical to the
# no-crash oracle.
CRASH_POINTS = ("post_dispatch_pre_append", "mid_append_torn",
                "post_append_pre_publish", "mid_checkpoint")

# fault class -> guard-word bit the detection assertion checks
EXPECTED_BIT = {
    "nan_metric_column": guards.NODE_METRIC_NONFINITE,
    "negative_allocatable": guards.NODE_BAD_ALLOCATABLE,
    "overcommit_row": guards.NODE_OVERCOMMIT,
    "numa_free_above_cap": guards.NODE_NUMA_INVALID,
    "nan_pod_request": guards.POD_NONFINITE,
    "negative_pod_request": guards.POD_NEGATIVE,
    "bad_gang_id": guards.POD_ID_RANGE,
    "bad_domain_index": guards.POD_DOMAIN_RANGE,
}


def make_xla_error(message: str) -> Exception:
    """A REAL XlaRuntimeError when the runtime exposes one (it is the
    exception class device programs actually raise), else a stand-in
    with the same type name so `classify_failure`'s mro-name fallback
    still engages."""
    try:
        from jax.errors import JaxRuntimeError
        return JaxRuntimeError(message)
    except Exception:  # pragma: no cover - jaxlib layout drift
        err_type = type("XlaRuntimeError", (RuntimeError,), {})
        return err_type(message)


class FaultInjector:
    """One seeded source of faults; every choice draws from the seed."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = np.random.default_rng(seed)

    # --- column corruptions ------------------------------------------------

    def corrupt_snapshot(self, snap, kind: str,
                         n_rows: int = 1) -> Tuple[object, np.ndarray]:
        """-> (corrupted snapshot, corrupted node row indices)."""
        import jax.numpy as jnp

        nodes = snap.nodes
        n = int(np.asarray(nodes.schedulable).shape[0])
        rows = np.sort(self.rng.choice(n, size=min(n_rows, n),
                                       replace=False))
        if kind == "nan_metric_column":
            usage = np.asarray(nodes.usage).copy()
            usage[rows, self.rng.integers(usage.shape[1])] = np.nan
            nodes = nodes.replace(usage=jnp.asarray(usage))
        elif kind == "negative_allocatable":
            alloc = np.asarray(nodes.allocatable).copy()
            alloc[rows, self.rng.integers(alloc.shape[1])] = -1.0
            nodes = nodes.replace(allocatable=jnp.asarray(alloc))
        elif kind == "overcommit_row":
            req = np.asarray(nodes.requested).copy()
            req[rows] = np.asarray(nodes.allocatable)[rows] \
                + guards.OVERCOMMIT_TOL + 50.0
            nodes = nodes.replace(requested=jnp.asarray(req))
        elif kind == "numa_free_above_cap":
            free = np.asarray(nodes.numa_free).copy()
            valid = np.asarray(nodes.numa_valid)
            # only a VALID zone counts as inconsistent; force one
            free[rows, 0, 0] = np.asarray(nodes.numa_cap)[rows, 0, 0] \
                + guards.OVERCOMMIT_TOL + 10.0
            nv = valid.copy()
            nv[rows, 0] = True
            nodes = nodes.replace(numa_free=jnp.asarray(free),
                                  numa_valid=jnp.asarray(nv))
        else:
            raise ValueError(f"unknown snapshot fault {kind!r}")
        return snap.replace(nodes=nodes), rows

    def corrupt_batch(self, pods, kind: str,
                      n_rows: int = 1) -> Tuple[object, np.ndarray]:
        """-> (corrupted batch, quarantine-expected pod row indices)."""
        import jax.numpy as jnp

        p = int(np.asarray(pods.valid).shape[0])
        rows = np.sort(self.rng.choice(p, size=min(n_rows, p),
                                       replace=False))
        if kind == "nan_pod_request":
            req = np.asarray(pods.requests).copy()
            req[rows, self.rng.integers(req.shape[1])] = np.nan
            return pods.replace(requests=jnp.asarray(req)), rows
        if kind == "negative_pod_request":
            req = np.asarray(pods.requests).copy()
            req[rows, self.rng.integers(req.shape[1])] = -100.0
            return pods.replace(requests=jnp.asarray(req)), rows
        if kind == "bad_gang_id":
            gid = np.asarray(pods.gang_id).copy()
            gid[rows] = 1_000_000
            return pods.replace(gang_id=jnp.asarray(gid)), rows
        if kind == "bad_domain_index":
            if not pods.has_spread:
                raise ValueError("bad_domain_index needs a spread-"
                                 "modeling batch")
            dom = np.asarray(pods.spread_domain).copy()
            g = int(self.rng.integers(dom.shape[0]))
            dom[g, self.rng.integers(dom.shape[1])] = \
                np.asarray(pods.spread_count0).shape[1] + 3
            carriers = np.where(np.asarray(pods.spread_carrier)[:, g])[0]
            return pods.replace(spread_domain=jnp.asarray(dom)), carriers
        raise ValueError(f"unknown batch fault {kind!r}")

    # --- delta replay ------------------------------------------------------

    def stale_delta(self, delta, applied_version: Optional[int] = None):
        """Re-stamp a delta so it replays at/below the applied version
        (<= the high-water mark -> the store must no-op it)."""
        cur = applied_version
        if cur is None:
            cur = int(np.asarray(delta.source_version))
        stale = int(self.rng.integers(0, max(cur, 1)))
        return delta.replace(source_version=np.asarray(stale, np.int32))

    # --- runtime failures (SchedulerService.fault_injection hooks) ---------

    def oom_above(self, width: int) -> Callable:
        """OOM whenever the program's batch is wider than `width` — the
        allocator model chunk-halving degrades past."""

        def hook(_state, batch):
            if int(np.asarray(batch.valid).shape[0]) > width:
                raise make_xla_error(
                    "RESOURCE_EXHAUSTED: Out of memory while trying to "
                    "allocate 9182736455 bytes.")

        return hook

    def fail_nth_calls(self, fail_attempts, message: str) -> Callable:
        """Raise on the given (1-based) program attempts, succeed on the
        rest — the transient-failure model bounded retry must absorb."""
        fail = set(int(i) for i in fail_attempts)
        counter = {"n": 0}

        def hook(_state, _batch):
            counter["n"] += 1
            if counter["n"] in fail:
                raise make_xla_error(message)

        return hook

    def device_lost(self, fail_attempts) -> Callable:
        return self.fail_nth_calls(
            fail_attempts, "UNAVAILABLE: device lost; socket closed")

    def xla_transient(self, fail_attempts) -> Callable:
        return self.fail_nth_calls(
            fail_attempts, "INTERNAL: ran out of program cache slots")

    @staticmethod
    def stall_watchdog(service) -> None:
        """Force every cycle over the watchdog budget: the stall is
        classified and the NEXT cycle runs one rung down."""
        service.monitor.timeout = 0.0

    def lost_device_until_shrunk(self, after_calls: int) -> Callable:
        """A device that dies after `after_calls` program invocations
        and STAYS dead until the service stops scheduling onto it —
        i.e. every attempt keeps failing until the ladder reaches the
        mesh-shrink (or single-device) rung, exactly like a real bricked
        chip. The in-place transient retries must exhaust before the
        rung change, so this drives the full detect -> retry ->
        shrink -> resume path."""
        counter = {"n": 0}

        def hook(state, _batch):
            counter["n"] += 1
            if counter["n"] > after_calls and not state.mesh_shrink \
                    and not state.single_device:
                raise make_xla_error(
                    "UNAVAILABLE: device lost; socket closed")

        return hook


# --- kill-injected crash points (tools/crash_smoke.py) ---------------------


def sigkill_at(point: str, hit: int = 1) -> Callable[[str], None]:
    """Crash hook for the CommitJournal / SnapshotStore checkpoint
    seams: SIGKILL this process the `hit`-th time the named crash point
    is reached. A real SIGKILL — no atexit, no buffer flush, no
    finally blocks — so the on-disk state is exactly what a power cut
    would leave."""
    if point not in CRASH_POINTS:
        raise ValueError(f"unknown crash point {point!r} "
                         f"(known: {CRASH_POINTS})")
    count = {"n": 0}

    def hook(name: str) -> None:
        if name != point:
            return
        count["n"] += 1
        if count["n"] == hit:
            os.kill(os.getpid(), signal.SIGKILL)

    return hook
