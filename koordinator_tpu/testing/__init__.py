"""Deterministic test scaffolding shared by the chaos CI stage
(tools/chaos_smoke.py), the soak harness (tools/soak_service.py
--chaos), and the fault-injection test batteries."""
