"""koordinator_tpu — a TPU-native colocation scheduling framework.

A from-scratch rebuild of the capabilities of Koordinator (QoS-based colocation
scheduling for Kubernetes) designed TPU-first:

- Cluster state (nodes, pods, NUMA topology, quota trees, gangs, reservations,
  devices) lives in columnar, device-resident tensors (`snapshot/`).
- The scheduler's per-pod Filter/Score hot loop becomes batched JAX kernels
  emitting a pods x nodes score matrix reduced with top-k (`scheduler/`, `ops/`).
- Scale-out is sharding the node axis of the snapshot over a `jax.sharding.Mesh`
  (ICI collectives for the global top-k reduce), see `parallel/`.
- The node agent (koordlet), SLO controller, descheduler, webhook, and runtime
  hook components exist as capability-equivalent host-side subsystems feeding
  the device snapshot (`koordlet/`, `slo_controller/`, `descheduler/`,
  `webhook/`, `runtimeproxy/`).

Reference: hhyasdf/koordinator (see SURVEY.md at the repo root). Reference
file:line citations appear in docstrings throughout so behavior parity can be
checked; the implementation is original and TPU-native.
"""

__version__ = "0.4.0"
