"""ctypes bindings for the native C++ shims.

The reference builds its perf-group reader as cgo against libpfm4
(perf_group_linux.go:38-41, hack/libpfm.sh); here the equivalent is a small
C++ library (perf_group.cpp) built with `make -C koordinator_tpu/native` and
loaded via ctypes (no pybind11 in the image). Everything degrades
gracefully: if the .so is missing and cannot be built, or perf_event_open
is denied (container without CAP_PERFMON), callers get None — mirroring the
reference's Libpfm4 feature gate defaulting off (koordlet_features.go:117).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, Optional, Sequence, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libperf_group.so")

# perf_event_open(2) portable event encodings (the subset libpfm4 resolves
# these names to): name -> (perf type, config). Hardware events need a PMU
# (absent in many VMs -> ENOENT); software events always work and exercise
# the same grouped-read machinery.
PERF_TYPE_HARDWARE = 0
PERF_TYPE_SOFTWARE = 1
EVENTS = {
    "cycles": (PERF_TYPE_HARDWARE, 0),        # PERF_COUNT_HW_CPU_CYCLES
    "instructions": (PERF_TYPE_HARDWARE, 1),  # PERF_COUNT_HW_INSTRUCTIONS
    "cache-references": (PERF_TYPE_HARDWARE, 2),
    "cache-misses": (PERF_TYPE_HARDWARE, 3),
    "branches": (PERF_TYPE_HARDWARE, 4),
    "branch-misses": (PERF_TYPE_HARDWARE, 5),
    "sw-cpu-clock": (PERF_TYPE_SOFTWARE, 0),
    "sw-task-clock": (PERF_TYPE_SOFTWARE, 1),
    "sw-page-faults": (PERF_TYPE_SOFTWARE, 2),
    "sw-context-switches": (PERF_TYPE_SOFTWARE, 3),
}

_lib = None
_lib_error: Optional[str] = None


def _load_shim(so_path: str) -> Tuple[Optional[ctypes.CDLL], Optional[str]]:
    """Build-if-missing (one `make` covers all shims) then dlopen.
    Returns (lib, None) or (None, error)."""
    if not os.path.exists(so_path):
        try:
            subprocess.run(["make", "-C", _DIR, "-s"], check=True,
                           capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError) as e:
            return None, f"native build failed: {e}"
    try:
        return ctypes.CDLL(so_path), None
    except OSError as e:
        return None, f"load failed: {e}"


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    lib, _lib_error = _load_shim(_SO)
    if lib is None:
        return None
    lib.pg_open.restype = ctypes.c_void_p
    lib.pg_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint), ctypes.POINTER(ctypes.c_ulonglong),
        ctypes.c_int]
    lib.pg_read.restype = ctypes.c_int
    lib.pg_read.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_double)]
    lib.pg_close.restype = None
    lib.pg_close.argtypes = [ctypes.c_void_p]
    lib.pg_last_error.restype = ctypes.c_char_p
    lib.pg_last_error.argtypes = []
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def last_error() -> str:
    lib = _load()
    if lib is None:
        return _lib_error or ""
    return lib.pg_last_error().decode(errors="replace")


class PerfGroupCollector:
    """Grouped hardware counters for one cgroup (or one pid).

    Mirrors PerfGroupCollector (perf_group_linux.go:104-262): one event
    group per CPU, counts summed across CPUs with multiplexing correction.
    Raises OSError when the kernel refuses (no perf permission, bad
    cgroup) — callers treat that as "CPI collection unavailable".
    """

    def __init__(self, cgroup_dir: Optional[str] = None, pid: int = 0,
                 events: Sequence[str] = ("cycles", "instructions"),
                 cpus: Optional[Sequence[int]] = None):
        lib = _load()
        if lib is None:
            raise OSError(_lib_error or "native shim unavailable")
        self._lib = lib
        self.events = list(events)
        n = len(self.events)
        try:
            enc = [EVENTS[e] for e in self.events]
        except KeyError as e:
            raise ValueError(f"unknown perf event {e}") from None
        types = (ctypes.c_uint * n)(*(t for t, _ in enc))
        configs = (ctypes.c_ulonglong * n)(*(c for _, c in enc))
        if cpus is None:
            cpu_arr, n_cpus = None, 0
        else:
            cpu_arr = (ctypes.c_int * len(cpus))(*cpus)
            n_cpus = len(cpus)
        self._h = lib.pg_open(
            cgroup_dir.encode() if cgroup_dir is not None else None,
            pid, cpu_arr, n_cpus, types, configs, n)
        if not self._h:
            raise OSError(lib.pg_last_error().decode(errors="replace"))

    def read(self) -> Dict[str, float]:
        out = (ctypes.c_double * len(self.events))()
        if self._lib.pg_read(self._h, out) != 0:
            raise OSError(self._lib.pg_last_error().decode(errors="replace"))
        return dict(zip(self.events, out))

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.pg_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def cycles_instructions_reader() -> Optional[callable]:
    """Factory for the metricsadvisor PerformanceCollector's perf_reader
    (performance_collector_linux.go:85-120): returns
    `reader(cgroup_dir) -> (cycles, instructions) | None`, or None when
    perf is unavailable on this host (shim missing, or a probe open of the
    calling process's events is denied).

    A collector stays open per cgroup between ticks (each group is a pair
    of fds per CPU, so leaking them across pod churn would exhaust fd
    limits); the first call per cgroup primes the baseline and returns
    None, each later call returns the DELTA over the elapsed window. A
    collector is evicted as soon as its cgroup directory disappears
    (reading a removed cgroup's perf fds never errors — counters just
    freeze — so liveness must be checked on the filesystem)."""
    try:
        with PerfGroupCollector(pid=0, cpus=[0]) as probe:
            probe.read()
    except (OSError, ValueError):
        return None

    collectors: Dict[str, PerfGroupCollector] = {}
    last: Dict[str, Dict[str, float]] = {}

    def evict(cgroup_dir: str) -> None:
        c = collectors.pop(cgroup_dir, None)
        if c is not None:
            c.close()
        last.pop(cgroup_dir, None)

    def reader(cgroup_dir: str) -> Optional[Tuple[float, float]]:
        # drop collectors of vanished cgroups (exited pods) every call so
        # fds never accumulate past the live pod set
        for known in list(collectors):
            if not os.path.isdir(known):
                evict(known)
        if not os.path.isdir(cgroup_dir):
            return None
        c = collectors.get(cgroup_dir)
        first = c is None
        if first:
            try:
                c = PerfGroupCollector(cgroup_dir=cgroup_dir)
            except OSError:
                return None
            collectors[cgroup_dir] = c
        try:
            v = c.read()
        except OSError:
            evict(cgroup_dir)
            return None
        prev = last.get(cgroup_dir)
        last[cgroup_dir] = v
        if first or prev is None:
            return None  # baseline primed; first delta next tick
        return (v["cycles"] - prev["cycles"],
                v["instructions"] - prev["instructions"])

    return reader


# --- core scheduling (prctl PR_SCHED_CORE) ----------------------------------

_CS_SO = os.path.join(_DIR, "libcore_sched.so")
_cs_lib = None
_cs_error: Optional[str] = None

# prctl arg4 scope values (linux PIDTYPE_*; CoreSchedScopeType,
# core_sched.go:34-44)
SCOPE_THREAD = 0
SCOPE_PROCESS = 1       # thread group
SCOPE_PROCESS_GROUP = 2


def _load_cs() -> Optional[ctypes.CDLL]:
    global _cs_lib, _cs_error
    if _cs_lib is not None or _cs_error is not None:
        return _cs_lib
    lib, _cs_error = _load_shim(_CS_SO)
    if lib is None:
        return None
    lib.cs_supported.restype = ctypes.c_int
    lib.cs_get.restype = ctypes.c_int
    lib.cs_get.argtypes = [ctypes.c_uint, ctypes.c_int,
                           ctypes.POINTER(ctypes.c_ulonglong)]
    for fn in (lib.cs_create, lib.cs_share_to, lib.cs_share_from):
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_uint, ctypes.c_int]
    lib.cs_assign.restype = ctypes.c_int
    lib.cs_assign.argtypes = [ctypes.c_uint, ctypes.POINTER(ctypes.c_uint),
                              ctypes.c_int, ctypes.c_int,
                              ctypes.POINTER(ctypes.c_uint)]
    lib.cs_clear.restype = ctypes.c_int
    lib.cs_clear.argtypes = [ctypes.POINTER(ctypes.c_uint), ctypes.c_int,
                             ctypes.c_int, ctypes.POINTER(ctypes.c_uint)]
    lib.cs_last_error.restype = ctypes.c_char_p
    _cs_lib = lib
    return _cs_lib


class CoreSched:
    """prctl(PR_SCHED_CORE) operations (core_sched_linux.go:40-176).

    get/create/share_to/share_from are the raw prctl verbs; assign and
    clear are the compound helper-thread ops (the reference's
    CoreSchedExtendedInterface). All raise OSError on kernel refusal;
    construct only after `core_sched_supported()` says the kernel has
    CONFIG_SCHED_CORE."""

    def __init__(self) -> None:
        lib = _load_cs()
        if lib is None:
            raise OSError(_cs_error or "core-sched shim unavailable")
        self._lib = lib

    def _check(self, ret: int) -> None:
        if ret < 0:
            raise OSError(-ret,
                          self._lib.cs_last_error().decode(errors="replace"))

    def get(self, pid: int) -> int:
        """Cookie of a thread (0 = none). pid 0 = self."""
        cookie = ctypes.c_ulonglong(0)
        self._check(self._lib.cs_get(pid, SCOPE_THREAD,
                                     ctypes.byref(cookie)))
        return cookie.value

    def create(self, pid: int, scope: int = SCOPE_PROCESS) -> None:
        """Give pid (and, with SCOPE_PROCESS, its whole thread group) a
        fresh unique cookie."""
        self._check(self._lib.cs_create(pid, scope))

    def share_to(self, pid: int, scope: int = SCOPE_PROCESS) -> None:
        self._check(self._lib.cs_share_to(pid, scope))

    def share_from(self, pid: int) -> None:
        """Pull pid's cookie onto the CALLING THREAD. This tags the agent
        thread itself (it becomes SMT-isolated and clear() will refuse
        with EBUSY from it) — prefer assign(), which confines the pull to
        a throwaway helper thread."""
        self._check(self._lib.cs_share_from(pid, SCOPE_THREAD))

    def assign(self, pid_from: int, pids_to: Sequence[int],
               scope: int = SCOPE_PROCESS) -> Tuple[int, ...]:
        """Copy pid_from's cookie onto every pids_to; returns the pids
        that failed (dead pids etc. — partial failure is normal during
        pod churn)."""
        n = len(pids_to)
        if n == 0:
            return ()
        arr = (ctypes.c_uint * n)(*pids_to)
        failed = (ctypes.c_uint * n)()
        ret = self._lib.cs_assign(pid_from, arr, n, scope, failed)
        self._check(ret)
        return tuple(failed[i] for i in range(ret))

    def clear(self, pids: Sequence[int],
              scope: int = SCOPE_PROCESS) -> Tuple[int, ...]:
        """Reset cookies to 0; returns the pids that failed."""
        n = len(pids)
        if n == 0:
            return ()
        arr = (ctypes.c_uint * n)(*pids)
        failed = (ctypes.c_uint * n)()
        ret = self._lib.cs_clear(arr, n, scope, failed)
        self._check(ret)
        return tuple(failed[i] for i in range(ret))


def core_sched_supported() -> bool:
    """True when the shim loads AND the kernel accepts PR_SCHED_CORE
    (EnableCoreSchedIfSupported's probe)."""
    lib = _load_cs()
    return bool(lib) and bool(lib.cs_supported())
