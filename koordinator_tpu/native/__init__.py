"""ctypes bindings for the native C++ shims.

The reference builds its perf-group reader as cgo against libpfm4
(perf_group_linux.go:38-41, hack/libpfm.sh); here the equivalent is a small
C++ library (perf_group.cpp) built with `make -C koordinator_tpu/native` and
loaded via ctypes (no pybind11 in the image). Everything degrades
gracefully: if the .so is missing and cannot be built, or perf_event_open
is denied (container without CAP_PERFMON), callers get None — mirroring the
reference's Libpfm4 feature gate defaulting off (koordlet_features.go:117).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, Optional, Sequence, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libperf_group.so")

# perf_event_open(2) portable event encodings (the subset libpfm4 resolves
# these names to): name -> (perf type, config). Hardware events need a PMU
# (absent in many VMs -> ENOENT); software events always work and exercise
# the same grouped-read machinery.
PERF_TYPE_HARDWARE = 0
PERF_TYPE_SOFTWARE = 1
EVENTS = {
    "cycles": (PERF_TYPE_HARDWARE, 0),        # PERF_COUNT_HW_CPU_CYCLES
    "instructions": (PERF_TYPE_HARDWARE, 1),  # PERF_COUNT_HW_INSTRUCTIONS
    "cache-references": (PERF_TYPE_HARDWARE, 2),
    "cache-misses": (PERF_TYPE_HARDWARE, 3),
    "branches": (PERF_TYPE_HARDWARE, 4),
    "branch-misses": (PERF_TYPE_HARDWARE, 5),
    "sw-cpu-clock": (PERF_TYPE_SOFTWARE, 0),
    "sw-task-clock": (PERF_TYPE_SOFTWARE, 1),
    "sw-page-faults": (PERF_TYPE_SOFTWARE, 2),
    "sw-context-switches": (PERF_TYPE_SOFTWARE, 3),
}

_lib = None
_lib_error: Optional[str] = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    if not os.path.exists(_SO):
        try:
            subprocess.run(["make", "-C", _DIR, "-s"], check=True,
                           capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError) as e:
            _lib_error = f"native build failed: {e}"
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        _lib_error = f"load failed: {e}"
        return None
    lib.pg_open.restype = ctypes.c_void_p
    lib.pg_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint), ctypes.POINTER(ctypes.c_ulonglong),
        ctypes.c_int]
    lib.pg_read.restype = ctypes.c_int
    lib.pg_read.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_double)]
    lib.pg_close.restype = None
    lib.pg_close.argtypes = [ctypes.c_void_p]
    lib.pg_last_error.restype = ctypes.c_char_p
    lib.pg_last_error.argtypes = []
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def last_error() -> str:
    lib = _load()
    if lib is None:
        return _lib_error or ""
    return lib.pg_last_error().decode(errors="replace")


class PerfGroupCollector:
    """Grouped hardware counters for one cgroup (or one pid).

    Mirrors PerfGroupCollector (perf_group_linux.go:104-262): one event
    group per CPU, counts summed across CPUs with multiplexing correction.
    Raises OSError when the kernel refuses (no perf permission, bad
    cgroup) — callers treat that as "CPI collection unavailable".
    """

    def __init__(self, cgroup_dir: Optional[str] = None, pid: int = 0,
                 events: Sequence[str] = ("cycles", "instructions"),
                 cpus: Optional[Sequence[int]] = None):
        lib = _load()
        if lib is None:
            raise OSError(_lib_error or "native shim unavailable")
        self._lib = lib
        self.events = list(events)
        n = len(self.events)
        try:
            enc = [EVENTS[e] for e in self.events]
        except KeyError as e:
            raise ValueError(f"unknown perf event {e}") from None
        types = (ctypes.c_uint * n)(*(t for t, _ in enc))
        configs = (ctypes.c_ulonglong * n)(*(c for _, c in enc))
        if cpus is None:
            cpu_arr, n_cpus = None, 0
        else:
            cpu_arr = (ctypes.c_int * len(cpus))(*cpus)
            n_cpus = len(cpus)
        self._h = lib.pg_open(
            cgroup_dir.encode() if cgroup_dir is not None else None,
            pid, cpu_arr, n_cpus, types, configs, n)
        if not self._h:
            raise OSError(lib.pg_last_error().decode(errors="replace"))

    def read(self) -> Dict[str, float]:
        out = (ctypes.c_double * len(self.events))()
        if self._lib.pg_read(self._h, out) != 0:
            raise OSError(self._lib.pg_last_error().decode(errors="replace"))
        return dict(zip(self.events, out))

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.pg_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def cycles_instructions_reader() -> Optional[callable]:
    """Factory for the metricsadvisor PerformanceCollector's perf_reader
    (performance_collector_linux.go:85-120): returns
    `reader(cgroup_dir) -> (cycles, instructions) | None`, or None when
    perf is unavailable on this host (shim missing, or a probe open of the
    calling process's events is denied).

    A collector stays open per cgroup between ticks (each group is a pair
    of fds per CPU, so leaking them across pod churn would exhaust fd
    limits); the first call per cgroup primes the baseline and returns
    None, each later call returns the DELTA over the elapsed window. A
    collector is evicted as soon as its cgroup directory disappears
    (reading a removed cgroup's perf fds never errors — counters just
    freeze — so liveness must be checked on the filesystem)."""
    try:
        with PerfGroupCollector(pid=0, cpus=[0]) as probe:
            probe.read()
    except (OSError, ValueError):
        return None

    collectors: Dict[str, PerfGroupCollector] = {}
    last: Dict[str, Dict[str, float]] = {}

    def evict(cgroup_dir: str) -> None:
        c = collectors.pop(cgroup_dir, None)
        if c is not None:
            c.close()
        last.pop(cgroup_dir, None)

    def reader(cgroup_dir: str) -> Optional[Tuple[float, float]]:
        # drop collectors of vanished cgroups (exited pods) every call so
        # fds never accumulate past the live pod set
        for known in list(collectors):
            if not os.path.isdir(known):
                evict(known)
        if not os.path.isdir(cgroup_dir):
            return None
        c = collectors.get(cgroup_dir)
        first = c is None
        if first:
            try:
                c = PerfGroupCollector(cgroup_dir=cgroup_dir)
            except OSError:
                return None
            collectors[cgroup_dir] = c
        try:
            v = c.read()
        except OSError:
            evict(cgroup_dir)
            return None
        prev = last.get(cgroup_dir)
        last[cgroup_dir] = v
        if first or prev is None:
            return None  # baseline primed; first delta next tick
        return (v["cycles"] - prev["cycles"],
                v["instructions"] - prev["instructions"])

    return reader
