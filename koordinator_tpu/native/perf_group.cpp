// Grouped perf-event reader for per-cgroup hardware counters (CPI).
//
// C++ equivalent of the reference's cgo/libpfm4 component
// (pkg/koordlet/util/perf_group/perf_group_linux.go:140-262): one event
// GROUP per CPU opened against a cgroup fd with PERF_FLAG_PID_CGROUP,
// leader + members sharing a group so the counters are scheduled
// atomically; read returns PERF_FORMAT_GROUP records with
// time_enabled/time_running multiplexing correction. Event encoding uses
// perf's portable PERF_TYPE_HARDWARE ids directly (the subset libpfm4
// resolves "cycles"/"instructions" to), so no external library is needed.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <linux/perf_event.h>
#include <sys/ioctl.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

thread_local std::string g_last_error;

void set_error(const char* what) {
  g_last_error = std::string(what) + ": " + std::strerror(errno);
}

long perf_event_open(perf_event_attr* attr, pid_t pid, int cpu, int group_fd,
                     unsigned long flags) {
  return syscall(SYS_perf_event_open, attr, pid, cpu, group_fd, flags);
}

// PERF_FORMAT_GROUP read layout (perf_event_open(2) "Reading results").
struct ReadValue {
  uint64_t value;
  uint64_t id;
};
struct ReadFormat {
  uint64_t nr;
  uint64_t time_enabled;
  uint64_t time_running;
  ReadValue values[];  // nr entries
};

struct CpuGroup {
  int leader = -1;
  std::vector<int> fds;  // leader first, then members (open order = event order)
};

}  // namespace

struct pg_collector {
  std::vector<CpuGroup> groups;
  int n_events = 0;
  int cgroup_fd = -1;
};

extern "C" {

void pg_close(pg_collector* col);

const char* pg_last_error() { return g_last_error.c_str(); }

// Open one perf group per cpu for `n_events` events given by
// (types[i], configs[i]); target is a cgroup directory fd when
// cgroup_dir != NULL (PERF_FLAG_PID_CGROUP) or a pid otherwise
// (pid 0 = self — used by the self-test path where cgroup perms are
// unavailable). cpus == NULL means all online CPUs. Returns NULL on error.
pg_collector* pg_open(const char* cgroup_dir, int pid, const int* cpus,
                      int n_cpus, const unsigned* types,
                      const unsigned long long* configs, int n_events) {
  if (n_events <= 0) {
    g_last_error = "no events";
    return nullptr;
  }
  std::vector<int> cpu_list;
  bool tolerate_offline = false;
  if (cpus == nullptr || n_cpus <= 0) {
    // enumerate CONFIGURED cpu ids (online ids may be non-contiguous with
    // hotplug) and tolerate per-CPU open failures on the offline ones —
    // failing the whole collector because cpu 2 is offline would disable
    // CPI collection node-wide
    int n = static_cast<int>(sysconf(_SC_NPROCESSORS_CONF));
    for (int c = 0; c < n; c++) cpu_list.push_back(c);
    tolerate_offline = true;
  } else {
    cpu_list.assign(cpus, cpus + n_cpus);
  }

  pg_collector* col = new pg_collector();
  col->n_events = n_events;
  pid_t target = pid;
  unsigned long flags = PERF_FLAG_FD_CLOEXEC;
  if (cgroup_dir != nullptr) {
    col->cgroup_fd = open(cgroup_dir, O_RDONLY | O_DIRECTORY | O_CLOEXEC);
    if (col->cgroup_fd < 0) {
      set_error("open cgroup");
      delete col;
      return nullptr;
    }
    target = col->cgroup_fd;
    flags |= PERF_FLAG_PID_CGROUP;
  }

  for (int cpu : cpu_list) {
    CpuGroup group;
    bool skip_cpu = false;
    for (int e = 0; e < n_events; e++) {
      perf_event_attr attr;
      std::memset(&attr, 0, sizeof(attr));
      attr.size = sizeof(attr);
      attr.type = types[e];
      attr.config = configs[e];
      attr.read_format = PERF_FORMAT_GROUP | PERF_FORMAT_TOTAL_TIME_ENABLED |
                         PERF_FORMAT_TOTAL_TIME_RUNNING | PERF_FORMAT_ID;
      attr.sample_type = PERF_SAMPLE_IDENTIFIER;
      attr.disabled = (e == 0) ? 1 : 0;  // enable whole group via leader
      attr.inherit = 1;
      attr.exclude_hv = 1;
      long fd = perf_event_open(&attr, target, cpu, group.leader, flags);
      if (fd < 0) {
        if (tolerate_offline && e == 0 &&
            (errno == ENODEV || errno == ENXIO || errno == EINVAL)) {
          skip_cpu = true;  // offline/nonexistent cpu in the CONF range
          break;
        }
        set_error("perf_event_open");
        for (int f : group.fds) close(f);
        pg_close(col);
        return nullptr;
      }
      if (e == 0) group.leader = static_cast<int>(fd);
      group.fds.push_back(static_cast<int>(fd));
    }
    if (skip_cpu) continue;
    if (ioctl(group.leader, PERF_EVENT_IOC_RESET, PERF_IOC_FLAG_GROUP) < 0 ||
        ioctl(group.leader, PERF_EVENT_IOC_ENABLE, PERF_IOC_FLAG_GROUP) < 0) {
      set_error("ioctl enable");
      for (int f : group.fds) close(f);
      pg_close(col);
      return nullptr;
    }
    col->groups.push_back(std::move(group));
  }
  if (col->groups.empty()) {
    g_last_error = "no usable CPUs for perf group";
    pg_close(col);
    return nullptr;
  }
  return col;
}

// Sum each event's counts across all CPU groups into out_values[n_events],
// applying the time_enabled/time_running multiplexing correction per group
// (GetContainerPerfResult semantics). Returns 0 on success.
int pg_read(pg_collector* col, double* out_values) {
  if (col == nullptr) return -1;
  for (int e = 0; e < col->n_events; e++) out_values[e] = 0.0;
  std::vector<char> buf(sizeof(ReadFormat) +
                        sizeof(ReadValue) * col->n_events);
  for (const CpuGroup& group : col->groups) {
    ssize_t n = read(group.leader, buf.data(), buf.size());
    if (n < 0) {
      set_error("read");
      return -1;
    }
    const ReadFormat* rf = reinterpret_cast<const ReadFormat*>(buf.data());
    double scale = 1.0;
    if (rf->time_running > 0 && rf->time_running < rf->time_enabled) {
      scale = static_cast<double>(rf->time_enabled) /
              static_cast<double>(rf->time_running);
    }
    uint64_t nr = rf->nr;
    if (nr > static_cast<uint64_t>(col->n_events)) nr = col->n_events;
    for (uint64_t i = 0; i < nr; i++) {
      out_values[i] += static_cast<double>(rf->values[i].value) * scale;
    }
  }
  return 0;
}

void pg_close(pg_collector* col) {
  if (col == nullptr) return;
  for (const CpuGroup& group : col->groups) {
    if (group.leader >= 0)
      ioctl(group.leader, PERF_EVENT_IOC_DISABLE, PERF_IOC_FLAG_GROUP);
    for (int fd : group.fds) close(fd);
  }
  if (col->cgroup_fd >= 0) close(col->cgroup_fd);
  delete col;
}

}  // extern "C"
