// Core-scheduling prctl(PR_SCHED_CORE) shim.
//
// Capability parity with the reference's golang.org/x/sys/unix prctl
// wrapper (pkg/koordlet/util/system/core_sched_linux.go:40-176): get /
// create / share_to / share_from plus the compound assign and clear ops,
// which must run from a helper thread holding the right cookie — prctl
// SHARE_TO pushes the CALLING THREAD's cookie onto the target, so
//  - assign: helper thread pulls the source pid's cookie (SHARE_FROM),
//    then pushes it to each target (the reference's GoWithNewThread
//    at core_sched_linux.go:153-165);
//  - clear: a fresh thread starts with the spawner's cookie-0, so
//    pushing ITS cookie resets targets to 0 (":110-131").
// The helper thread dies afterwards, taking its cookie with it.
//
// Errors: ops return 0 on success or -errno; compound ops return the
// number of failed pids and record them in failed_out.

#include <errno.h>
#include <string.h>
#include <sys/prctl.h>

#include <cstdint>
#include <cstdio>
#include <new>
#include <system_error>
#include <thread>

#ifndef PR_SCHED_CORE
#define PR_SCHED_CORE 62
#endif
#ifndef PR_SCHED_CORE_GET
#define PR_SCHED_CORE_GET 0
#define PR_SCHED_CORE_CREATE 1
#define PR_SCHED_CORE_SHARE_TO 2
#define PR_SCHED_CORE_SHARE_FROM 3
#endif

// prctl arg4 scope (linux/sched.h PIDTYPE_*): 0=thread, 1=thread group
// (process), 2=process group — CoreSchedScopeType in core_sched.go:34-44.

// Error text is PER CALLING THREAD (ctypes releases the GIL across
// foreign calls, so tick-loop and hook-server threads can fail
// concurrently — a shared buffer would mis-attribute one thread's
// failure to another). Helper threads write into a stack buffer their
// spawner copies back after join, so attribution survives the join.
static thread_local char g_err[256];

static void set_err_buf(char* buf, const char* op, unsigned pid, int err) {
    snprintf(buf, 256, "%s pid=%u failed: %s (errno %d)",
             op, pid, strerror(err), err);
}

static void set_err(const char* op, unsigned pid, int err) {
    set_err_buf(g_err, op, pid, err);
}

// run fn on a fresh joined thread; -EAGAIN instead of std::terminate when
// thread creation itself fails (pid/pthread exhaustion on a loaded node)
template <typename Fn>
static int with_helper_thread(Fn&& fn) {
    try {
        std::thread helper(fn);
        helper.join();
        return 0;
    } catch (const std::system_error&) {
        set_err("helper_thread", 0, EAGAIN);
        return -EAGAIN;
    } catch (const std::bad_alloc&) {
        set_err("helper_thread", 0, ENOMEM);
        return -ENOMEM;
    }
}

extern "C" {

const char* cs_last_error() { return g_err; }

// 1 when the kernel supports PR_SCHED_CORE (CONFIG_SCHED_CORE and SMT
// active enough for the prctl to exist); probing GET on self is free.
int cs_supported() {
    unsigned long long cookie = 0;
    int ret = prctl(PR_SCHED_CORE, PR_SCHED_CORE_GET, 0, 0,
                    (unsigned long)&cookie);
    return ret == 0 ? 1 : 0;
}

int cs_get(unsigned pid, int pid_type, unsigned long long* cookie) {
    // NOTE: GET only supports thread scope (core_sched_linux.go:41)
    (void)pid_type;
    int ret = prctl(PR_SCHED_CORE, PR_SCHED_CORE_GET, pid, 0,
                    (unsigned long)cookie);
    if (ret != 0) { set_err("get", pid, errno); return -errno; }
    return 0;
}

int cs_create(unsigned pid, int pid_type) {
    int ret = prctl(PR_SCHED_CORE, PR_SCHED_CORE_CREATE, pid, pid_type, 0);
    if (ret != 0) { set_err("create", pid, errno); return -errno; }
    return 0;
}

int cs_share_to(unsigned pid, int pid_type) {
    int ret = prctl(PR_SCHED_CORE, PR_SCHED_CORE_SHARE_TO, pid, pid_type, 0);
    if (ret != 0) { set_err("share_to", pid, errno); return -errno; }
    return 0;
}

int cs_share_from(unsigned pid, int pid_type) {
    // NOTE: SHARE_FROM only supports thread scope on the source
    (void)pid_type;
    int ret = prctl(PR_SCHED_CORE, PR_SCHED_CORE_SHARE_FROM, pid, 0, 0);
    if (ret != 0) { set_err("share_from", pid, errno); return -errno; }
    return 0;
}

// Pull pid_from's cookie and push it onto every pid in pids_to (scope
// pid_type_to). Returns the number of failures (their pids in
// failed_out, sized >= n), or -errno when the initial SHARE_FROM fails.
int cs_assign(unsigned pid_from, const unsigned* pids_to, int n,
              int pid_type_to, unsigned* failed_out) {
    int n_failed = 0;
    int from_err = 0;
    char herr[256] = "";
    int spawn = with_helper_thread([&] {
        int ret = prctl(PR_SCHED_CORE, PR_SCHED_CORE_SHARE_FROM, pid_from,
                        0, 0);
        if (ret != 0) {
            from_err = errno;
            set_err_buf(herr, "assign/share_from", pid_from, errno);
            return;
        }
        for (int i = 0; i < n; i++) {
            ret = prctl(PR_SCHED_CORE, PR_SCHED_CORE_SHARE_TO, pids_to[i],
                        pid_type_to, 0);
            if (ret != 0) {
                set_err_buf(herr, "assign/share_to", pids_to[i], errno);
                failed_out[n_failed++] = pids_to[i];
            }
        }
    });
    if (herr[0]) snprintf(g_err, sizeof(g_err), "%s", herr);
    if (spawn != 0) return spawn;
    if (from_err != 0) return -from_err;
    return n_failed;
}

// Reset every pid's cookie to 0 by pushing a fresh thread's inherited
// cookie-0. Valid only while the SPAWNING thread holds cookie 0 — the
// helper CHECKS this (its inherited cookie) and refuses with -EBUSY
// rather than silently stamping a stale cookie onto the targets (e.g.
// after a caller misused share_from on its own thread).
int cs_clear(const unsigned* pids, int n, int pid_type,
             unsigned* failed_out) {
    int n_failed = 0;
    int guard_err = 0;
    char herr[256] = "";
    int spawn = with_helper_thread([&] {
        unsigned long long own = 0;
        if (prctl(PR_SCHED_CORE, PR_SCHED_CORE_GET, 0, 0,
                  (unsigned long)&own) == 0 && own != 0) {
            guard_err = EBUSY;
            set_err_buf(herr, "clear/guard: calling thread holds a cookie",
                        0, EBUSY);
            return;
        }
        for (int i = 0; i < n; i++) {
            int ret = prctl(PR_SCHED_CORE, PR_SCHED_CORE_SHARE_TO, pids[i],
                            pid_type, 0);
            if (ret != 0) {
                set_err_buf(herr, "clear/share_to", pids[i], errno);
                failed_out[n_failed++] = pids[i];
            }
        }
    });
    if (herr[0]) snprintf(g_err, sizeof(g_err), "%s", herr);
    if (spawn != 0) return spawn;
    if (guard_err != 0) return -guard_err;
    return n_failed;
}

}  // extern "C"
