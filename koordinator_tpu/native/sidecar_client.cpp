// Minimal C++ sidecar wire client — proves the Go-callable claim of the
// scheduler sidecar seam from a second language with zero dependencies
// beyond POSIX sockets (the reference keeps this seam in Go:
// /root/reference/pkg/scheduler/frameworkext/framework_extender.go:167-292;
// docs/SIDECAR_WIRE.md specifies the bytes this file speaks).
//
// Usage: sidecar_client <unix-socket-path> <fixture-dir>
//
// Replays the frozen conformance frames (tests/fixtures/sidecar/*.bin)
// against a live server in the documented order — PublishSnapshot,
// IngestDelta, IngestTopology, Schedule, Summary — one connection per
// RPC, and checks each response: status byte 0, a well-formed protobuf
// body, monotonically non-decreasing commit versions, a 2-pod Schedule
// assignment with in-range node indexes, and a Summary JSON object.
// Exit 0 = full round-trip OK; non-zero prints the failure.

#include <arpa/inet.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

namespace {

[[noreturn]] void die(const std::string &msg) {
  std::fprintf(stderr, "sidecar_client: FAIL: %s\n", msg.c_str());
  std::exit(1);
}

std::vector<uint8_t> read_file(const std::string &path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) die("cannot read fixture " + path);
  return std::vector<uint8_t>(std::istreambuf_iterator<char>(f),
                              std::istreambuf_iterator<char>());
}

void write_all(int fd, const uint8_t *buf, size_t n) {
  while (n) {
    ssize_t w = ::write(fd, buf, n);
    if (w <= 0) die("short write to socket");
    buf += w;
    n -= static_cast<size_t>(w);
  }
}

void read_all(int fd, uint8_t *buf, size_t n) {
  while (n) {
    ssize_t r = ::read(fd, buf, n);
    if (r <= 0) die("short read from socket");
    buf += r;
    n -= static_cast<size_t>(r);
  }
}

// One RPC per connection (SIDECAR_WIRE.md §1): send the pre-framed
// request verbatim, return the response body after the status byte.
std::vector<uint8_t> rpc(const std::string &sock_path,
                         const std::vector<uint8_t> &frame) {
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) die("socket()");
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  if (sock_path.size() >= sizeof(addr.sun_path)) die("socket path too long");
  std::memcpy(addr.sun_path, sock_path.c_str(), sock_path.size() + 1);
  if (::connect(fd, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) != 0)
    die("connect(" + sock_path + ")");
  write_all(fd, frame.data(), frame.size());
  uint8_t len_be[4];
  read_all(fd, len_be, 4);
  uint32_t len;
  std::memcpy(&len, len_be, 4);
  len = ntohl(len);
  if (len == 0 || len > (64u << 20)) die("bad response frame length");
  std::vector<uint8_t> payload(len);
  read_all(fd, payload.data(), len);
  ::close(fd);
  if (payload[0] != 0)
    die("status=1 error: " + std::string(payload.begin() + 1, payload.end()));
  return std::vector<uint8_t>(payload.begin() + 1, payload.end());
}

// --- minimal protobuf wire walker (proto3) --------------------------------

bool get_varint(const std::vector<uint8_t> &b, size_t &i, uint64_t *out) {
  uint64_t v = 0;
  for (int shift = 0; shift < 64; shift += 7) {
    if (i >= b.size()) return false;
    uint8_t byte = b[i++];
    v |= static_cast<uint64_t>(byte & 0x7f) << shift;
    if (!(byte & 0x80)) {
      *out = v;
      return true;
    }
  }
  return false;
}

struct Field {
  uint32_t number;
  uint32_t wire_type;          // 0 varint, 1 fixed64, 2 bytes, 5 fixed32
  uint64_t varint;             // wire type 0
  const uint8_t *data;         // wire type 2
  size_t size;
};

// Walks every field; returns false on malformed wire data. `fields`
// collects them in order (repeated fields appear repeatedly).
bool walk(const std::vector<uint8_t> &b, std::vector<Field> *fields) {
  size_t i = 0;
  while (i < b.size()) {
    uint64_t key;
    if (!get_varint(b, i, &key)) return false;
    Field f{};
    f.number = static_cast<uint32_t>(key >> 3);
    f.wire_type = static_cast<uint32_t>(key & 7);
    if (f.number == 0) return false;
    switch (f.wire_type) {
      case 0:
        if (!get_varint(b, i, &f.varint)) return false;
        break;
      case 1:
        if (i + 8 > b.size()) return false;
        i += 8;
        break;
      case 2: {
        uint64_t len;
        // len > size - i (not i + len > size): a near-2^64 varint must
        // fail cleanly, not wrap the addition past the bounds check
        if (!get_varint(b, i, &len) || len > b.size() - i) return false;
        f.data = b.data() + i;
        f.size = static_cast<size_t>(len);
        i += len;
        break;
      }
      case 5:
        if (i + 4 > b.size()) return false;
        i += 4;
        break;
      default:
        return false;
    }
    fields->push_back(f);
  }
  return true;
}

int64_t version_field(const std::vector<uint8_t> &body, const char *method) {
  std::vector<Field> fields;
  if (!walk(body, &fields))
    die(std::string(method) + ": response is not well-formed protobuf");
  for (const Field &f : fields)
    if (f.number == 1 && f.wire_type == 0)
      return static_cast<int64_t>(f.varint);
  die(std::string(method) + ": no version field in response");
}

}  // namespace

int main(int argc, char **argv) {
  if (argc != 3) {
    std::fprintf(stderr, "usage: %s <socket> <fixture-dir>\n", argv[0]);
    return 2;
  }
  const std::string sock = argv[1];
  const std::string dir = argv[2];

  int64_t last_version = -1;
  const char *versioned[][2] = {
      {"PublishSnapshot", "publish_request.bin"},
      {"IngestDelta", "ingest_request.bin"},
      {"IngestTopology", "ingest_topology_request.bin"},
  };
  for (auto &m : versioned) {
    std::vector<uint8_t> body = rpc(sock, read_file(dir + "/" + m[1]));
    int64_t v = version_field(body, m[0]);
    if (v < last_version)
      die(std::string(m[0]) + ": commit version went backwards");
    last_version = v;
    std::printf("sidecar_client: %s -> version %lld\n", m[0],
                static_cast<long long>(v));
  }

  // Schedule: 2-pod canonical batch against the 2-node snapshot
  std::vector<uint8_t> body =
      rpc(sock, read_file(dir + "/schedule_request.bin"));
  std::vector<Field> fields;
  if (!walk(body, &fields)) die("Schedule: malformed protobuf response");
  std::vector<int32_t> assignment;
  int64_t snap_version = -1;
  for (const Field &f : fields) {
    if (f.number == 1 && f.wire_type == 2) {  // packed repeated int32
      std::vector<uint8_t> packed(f.data, f.data + f.size);
      size_t i = 0;
      while (i < packed.size()) {
        uint64_t v;
        if (!get_varint(packed, i, &v))
          die("Schedule: malformed packed assignment");
        assignment.push_back(static_cast<int32_t>(v));
      }
    } else if (f.number == 1 && f.wire_type == 0) {  // unpacked fallback
      assignment.push_back(static_cast<int32_t>(f.varint));
    } else if (f.number == 5 && f.wire_type == 0) {
      snap_version = static_cast<int64_t>(f.varint);
    }
  }
  if (assignment.size() != 2)
    die("Schedule: expected 2 assignment entries, got " +
        std::to_string(assignment.size()));
  for (int32_t a : assignment)
    if (a < -1 || a >= 2)
      die("Schedule: assignment " + std::to_string(a) +
          " out of range for the 2-node snapshot");
  if (snap_version < last_version)
    die("Schedule: post-commit version went backwards");
  std::printf("sidecar_client: Schedule -> assignment [%d, %d], version %lld\n",
              assignment[0], assignment[1],
              static_cast<long long>(snap_version));

  // Summary: JSON counters reflecting the schedule we just ran
  body = rpc(sock, read_file(dir + "/summary_request.bin"));
  fields.clear();
  if (!walk(body, &fields)) die("Summary: malformed protobuf response");
  std::string json;
  for (const Field &f : fields)
    if (f.number == 1 && f.wire_type == 2)
      json.assign(reinterpret_cast<const char *>(f.data), f.size);
  if (json.empty() || json.front() != '{')
    die("Summary: body is not a JSON object: " + json);
  if (json.find("podsPlaced") == std::string::npos)
    die("Summary: missing podsPlaced counter: " + json);
  std::printf("sidecar_client: Summary -> %s\n", json.c_str());
  std::puts("sidecar_client: OK (5/5 RPCs round-tripped)");
  return 0;
}
