"""Pod/container metadata checkpoint for the proxy.

The proxy must attach pod context (labels/annotations/cgroup parent) to
container-level hook calls whose CRI requests only carry a sandbox id —
the reference checkpoints this in runtimeproxy/store (SURVEY.md 2.5).
Persistence is optional: `save`/`load` round-trip through a JSON file so a
restarted proxy keeps serving in-flight pods (store checkpoint dir).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Optional


@dataclasses.dataclass
class PodSandboxInfo:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    cgroup_parent: str = ""


@dataclasses.dataclass
class ContainerInfo:
    name: str = ""
    pod_sandbox_id: str = ""


class MetaStore:
    def __init__(self, checkpoint_path: str = ""):
        self.pods: Dict[str, PodSandboxInfo] = {}
        self.containers: Dict[str, ContainerInfo] = {}
        self.checkpoint_path = checkpoint_path

    def put_pod(self, sandbox_id: str, info: PodSandboxInfo) -> None:
        self.pods[sandbox_id] = info
        self._save()

    def put_container(self, container_id: str, info: ContainerInfo) -> None:
        self.containers[container_id] = info
        self._save()

    def pod_of_container(self, container_id: str) -> Optional[PodSandboxInfo]:
        c = self.containers.get(container_id)
        return self.pods.get(c.pod_sandbox_id) if c else None

    def delete_pod(self, sandbox_id: str) -> None:
        self.pods.pop(sandbox_id, None)
        for cid in [cid for cid, c in self.containers.items()
                    if c.pod_sandbox_id == sandbox_id]:
            del self.containers[cid]
        self._save()

    def delete_container(self, container_id: str) -> None:
        self.containers.pop(container_id, None)
        self._save()

    # -- checkpoint ----------------------------------------------------------

    def _save(self) -> None:
        if not self.checkpoint_path:
            return
        data = {
            "pods": {k: dataclasses.asdict(v) for k, v in self.pods.items()},
            "containers": {k: dataclasses.asdict(v)
                           for k, v in self.containers.items()},
        }
        tmp = self.checkpoint_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self.checkpoint_path)

    def load(self) -> None:
        if not self.checkpoint_path or \
                not os.path.exists(self.checkpoint_path):
            return
        with open(self.checkpoint_path) as f:
            data = json.load(f)
        self.pods = {k: PodSandboxInfo(**v)
                     for k, v in data.get("pods", {}).items()}
        self.containers = {k: ContainerInfo(**v)
                           for k, v in data.get("containers", {}).items()}
