"""Docker-engine variant of the CRI-interposing proxy.

Capability parity with pkg/runtimeproxy/server/docker (SURVEY.md 2.5): the
reference interposes the Docker Engine HTTP API between kubelet's
dockershim and dockerd, pattern-matching /containers/create, .../start,
.../update and .../stop (docker/server.go:63-66) and translating the
request's HostConfig resources through the same RuntimeHookService
protocol the CRI variant uses. Pod identity rides docker labels: a
sandbox is `io.kubernetes.docker.type == "podsandbox"`, containers point
at their sandbox via `io.kubernetes.sandbox.id`
(docker/docker_types.go:27-30), and annotation-prefixed labels are split
back out into annotations (docker/utils.go:123 splitLabelsAndAnnotations).

Here the same interposition is a JSON-body transform layer: `handle(path,
body)` routes exactly the reference's four endpoints, calls the hook
server before forwarding to the `DockerBackend`, and merges the hook's
LinuxContainerResources into the body's HostConfig — so a koordlet hook
(batchresource, cpuset, groupidentity via unified) shapes docker
containers the same way it shapes CRI ones.
"""

from __future__ import annotations

import dataclasses
import logging
import re
from typing import Dict, Optional, Protocol

from koordinator_tpu.runtimeproxy import api_pb2 as pb
from koordinator_tpu.runtimeproxy.rpc import RpcClient, RpcError
from koordinator_tpu.runtimeproxy.server import FailurePolicy
from koordinator_tpu.runtimeproxy.store import (
    ContainerInfo,
    MetaStore,
    PodSandboxInfo,
)

log = logging.getLogger(__name__)

CONTAINER_TYPE_LABEL = "io.kubernetes.docker.type"
CONTAINER_TYPE_SANDBOX = "podsandbox"
SANDBOX_ID_LABEL = "io.kubernetes.sandbox.id"
POD_NAME_LABEL = "io.kubernetes.pod.name"
POD_NAMESPACE_LABEL = "io.kubernetes.pod.namespace"
POD_UID_LABEL = "io.kubernetes.pod.uid"
ANNOTATION_PREFIX = "annotation."

# container references may be ids OR names: [a-zA-Z0-9][a-zA-Z0-9_.-]*
# (docker's reference grammar) — \w+ would silently pass-through legal
# by-name addressing like "my-app.1". Every route tolerates a query
# string: kubelet's dockershim always creates with ?name=k8s_..., and a
# $-anchored create pattern would pass the REAL traffic through
# uninterposed.
_REF = r"(?P<id>[a-zA-Z0-9][a-zA-Z0-9_.-]*)"
_Q = r"(\?(?P<query>.*))?$"
_ROUTES = (
    (re.compile(r"^/(v\d\.\d+/)?containers/create" + _Q), "create"),
    (re.compile(r"^/(v\d\.\d+/)?containers/" + _REF + r"/start" + _Q),
     "start"),
    (re.compile(r"^/(v\d\.\d+/)?containers/" + _REF + r"/update" + _Q),
     "update"),
    (re.compile(r"^/(v\d\.\d+/)?containers/" + _REF + r"/stop" + _Q),
     "stop"),
)


class DockerBackend(Protocol):
    """The real dockerd (stand-in): receives the merged body."""

    def create(self, body: dict) -> str: ...       # returns container id
    def start(self, container_id: str) -> None: ...
    def update(self, container_id: str, body: dict) -> None: ...
    def stop(self, container_id: str) -> None: ...


def split_labels_and_annotations(configs: Dict[str, str]
                                 ) -> (dict, dict):
    """docker labels carry annotations under the `annotation.` prefix
    (utils.go splitLabelsAndAnnotations)."""
    labels, annos = {}, {}
    for k, v in (configs or {}).items():
        if k.startswith(ANNOTATION_PREFIX):
            annos[k[len(ANNOTATION_PREFIX):]] = v
        else:
            labels[k] = v
    return labels, annos


def _host_config_to_pb(host_config: dict) -> pb.LinuxContainerResources:
    res = pb.LinuxContainerResources(
        cpu_shares=int(host_config.get("CpuShares", 0) or 0),
        cpu_quota=int(host_config.get("CpuQuota", 0) or 0),
        cpu_period=int(host_config.get("CpuPeriod", 0) or 0),
        memory_limit_in_bytes=int(host_config.get("Memory", 0) or 0),
        cpuset_cpus=str(host_config.get("CpusetCpus", "") or ""),
        cpuset_mems=str(host_config.get("CpusetMems", "") or ""))
    for k, v in (host_config.get("Unified") or {}).items():
        res.unified[k] = v
    return res


def _merge_pb_into_host_config(res: pb.LinuxContainerResources,
                               host_config: dict) -> None:
    """Hook response resources override the forwarded HostConfig where set
    (docker/utils.go UpdateHostConfigByResource)."""
    if res.cpu_shares:
        host_config["CpuShares"] = int(res.cpu_shares)
    if res.cpu_quota:
        host_config["CpuQuota"] = int(res.cpu_quota)
    if res.cpu_period:
        host_config["CpuPeriod"] = int(res.cpu_period)
    if res.memory_limit_in_bytes:
        host_config["Memory"] = int(res.memory_limit_in_bytes)
    if res.cpuset_cpus:
        host_config["CpusetCpus"] = str(res.cpuset_cpus)
    if res.cpuset_mems:
        host_config["CpusetMems"] = str(res.cpuset_mems)
    if res.unified:
        unified = dict(host_config.get("Unified") or {})
        unified.update(dict(res.unified))
        host_config["Unified"] = unified


@dataclasses.dataclass
class DockerResponse:
    ok: bool = True
    container_id: str = ""
    error: str = ""


class DockerProxy:
    """The RuntimeManagerDockerServer equivalent over typed JSON bodies."""

    def __init__(self, backend: DockerBackend,
                 hook_client: Optional[RpcClient] = None,
                 failure_policy: FailurePolicy = FailurePolicy.IGNORE,
                 store: Optional[MetaStore] = None):
        self.backend = backend
        self.hooks = hook_client
        self.failure_policy = failure_policy
        self.store = store or MetaStore()
        # container id -> last create body (docker /update bodies carry
        # only the resource fields; identity comes from the create)
        self._bodies: Dict[str, dict] = {}
        # container NAME (?name= on create) -> docker id, so by-name
        # lifecycle addressing resolves to the same store/_bodies keys
        self._names: Dict[str, str] = {}

    def _resolve_ref(self, ref: str) -> str:
        """A route reference may be the docker id or the create name."""
        return self._names.get(ref, ref)

    # -- routing (docker/server.go:63-66) ------------------------------------

    def handle(self, path: str, body: Optional[dict] = None,
               ) -> DockerResponse:
        for pattern, op in _ROUTES:
            m = pattern.match(path)
            if m:
                gd = m.groupdict()
                cid = self._resolve_ref(gd.get("id") or "")
                if op == "create":
                    name = ""
                    for part in (gd.get("query") or "").split("&"):
                        if part.startswith("name="):
                            name = part[len("name="):]
                    return self.create(body or {}, name=name)
                if op == "start":
                    return self.start(cid)
                if op == "update":
                    return self.update(cid, body or {})
                return self.stop(cid)
        # everything else passes through untouched (the reference reverse-
        # proxies unmatched paths directly to dockerd)
        return DockerResponse(ok=True)

    # -- hook plumbing --------------------------------------------------------

    def _call_hook(self, method: str, request, response_cls):
        if self.hooks is None:
            return None
        try:
            return self.hooks.call(method, request, response_cls)
        except (RpcError, OSError) as e:
            if self.failure_policy is FailurePolicy.FAIL:
                raise
            log.warning("docker hook %s failed (policy Ignore): %s",
                        method, e)
            return None

    # -- endpoints ------------------------------------------------------------

    def create(self, body: dict, name: str = "") -> DockerResponse:
        labels, annos = split_labels_and_annotations(body.get("Labels"))
        host_config = body.setdefault("HostConfig", {})
        is_sandbox = labels.get(CONTAINER_TYPE_LABEL) == CONTAINER_TYPE_SANDBOX
        try:
            if is_sandbox:
                req = pb.PodSandboxHookRequest(
                    pod_meta=pb.PodSandboxMetadata(
                        name=labels.get(POD_NAME_LABEL, ""),
                        namespace=labels.get(POD_NAMESPACE_LABEL, ""),
                        uid=labels.get(POD_UID_LABEL, "")),
                    cgroup_parent=host_config.get("CgroupParent", ""),
                    resources=_host_config_to_pb(host_config))
                for k, v in labels.items():
                    req.labels[k] = v
                for k, v in annos.items():
                    req.annotations[k] = v
                resp = self._call_hook("PreRunPodSandboxHook", req,
                                       pb.PodSandboxHookResponse)
                if resp is not None:
                    if resp.cgroup_parent:
                        host_config["CgroupParent"] = resp.cgroup_parent
                    _merge_pb_into_host_config(resp.resources, host_config)
            else:
                sandbox = self.store.pods.get(
                    labels.get(SANDBOX_ID_LABEL, "")) or PodSandboxInfo()
                req = pb.ContainerResourceHookRequest(
                    pod_meta=pb.PodSandboxMetadata(
                        name=sandbox.name or labels.get(POD_NAME_LABEL, ""),
                        namespace=sandbox.namespace
                        or labels.get(POD_NAMESPACE_LABEL, ""),
                        uid=sandbox.uid or labels.get(POD_UID_LABEL, "")),
                    container_resources=_host_config_to_pb(host_config),
                    pod_cgroup_parent=sandbox.cgroup_parent)
                for k, v in annos.items():
                    req.container_annotations[k] = v
                for k, v in sandbox.labels.items():
                    req.pod_labels[k] = v
                for k, v in sandbox.annotations.items():
                    req.pod_annotations[k] = v
                resp = self._call_hook("PreCreateContainerHook", req,
                                       pb.ContainerResourceHookResponse)
                if resp is not None:
                    _merge_pb_into_host_config(resp.container_resources,
                                               host_config)
        except (RpcError, OSError) as e:
            return DockerResponse(ok=False, error=str(e))
        cid = self.backend.create(body)
        self._bodies[cid] = body
        if name:
            self._names[name] = cid
        if is_sandbox:
            self.store.put_pod(cid, PodSandboxInfo(
                name=labels.get(POD_NAME_LABEL, ""),
                namespace=labels.get(POD_NAMESPACE_LABEL, ""),
                uid=labels.get(POD_UID_LABEL, ""),
                cgroup_parent=host_config.get("CgroupParent", ""),
                labels=labels, annotations=annos))
        else:
            self.store.put_container(cid, ContainerInfo(
                name=labels.get("io.kubernetes.container.name", ""),
                pod_sandbox_id=labels.get(SANDBOX_ID_LABEL, "")))
        return DockerResponse(ok=True, container_id=cid)

    def start(self, container_id: str) -> DockerResponse:
        self.backend.start(container_id)
        body = self._bodies.get(container_id, {})
        labels, _ = split_labels_and_annotations(body.get("Labels"))
        if labels.get(CONTAINER_TYPE_LABEL) != CONTAINER_TYPE_SANDBOX:
            # PostStartContainerHook is a notification: failures never
            # fail the already-started container
            try:
                self._call_hook(
                    "PostStartContainerHook",
                    pb.ContainerResourceHookRequest(
                        container_meta=pb.ContainerMetadata(
                            id=container_id)),
                    pb.ContainerResourceHookResponse)
            except (RpcError, OSError):
                pass
        return DockerResponse(ok=True, container_id=container_id)

    def update(self, container_id: str, body: dict) -> DockerResponse:
        host_config = body  # docker /update bodies ARE the resource set
        try:
            resp = self._call_hook(
                "PreUpdateContainerResourcesHook",
                pb.ContainerResourceHookRequest(
                    container_meta=pb.ContainerMetadata(id=container_id),
                    container_resources=_host_config_to_pb(host_config)),
                pb.ContainerResourceHookResponse)
        except (RpcError, OSError) as e:
            return DockerResponse(ok=False, error=str(e))
        if resp is not None:
            _merge_pb_into_host_config(resp.container_resources, host_config)
        self.backend.update(container_id, body)
        return DockerResponse(ok=True, container_id=container_id)

    def stop(self, container_id: str) -> DockerResponse:
        self.backend.stop(container_id)
        body = self._bodies.pop(container_id, {})
        labels, _ = split_labels_and_annotations(body.get("Labels"))
        method = ("PostStopPodSandboxHook"
                  if labels.get(CONTAINER_TYPE_LABEL)
                  == CONTAINER_TYPE_SANDBOX else "PostStopContainerHook")
        # post-stop hooks are cleanup notifications — always Ignore
        try:
            if method == "PostStopPodSandboxHook":
                self._call_hook(method, pb.PodSandboxHookRequest(),
                                pb.PodSandboxHookResponse)
            else:
                self._call_hook(
                    method,
                    pb.ContainerResourceHookRequest(
                        container_meta=pb.ContainerMetadata(
                            id=container_id)),
                    pb.ContainerResourceHookResponse)
        except (RpcError, OSError):
            pass
        if labels.get(CONTAINER_TYPE_LABEL) == CONTAINER_TYPE_SANDBOX:
            self.store.delete_pod(container_id)
        else:
            self.store.delete_container(container_id)
        self._names = {n: i for n, i in self._names.items()
                       if i != container_id}
        return DockerResponse(ok=True, container_id=container_id)
