"""The CRI-interposing runtime proxy (RuntimeManager).

Capability parity with pkg/runtimeproxy/server (SURVEY.md 2.5): the proxy
sits between the kubelet-facing CRI socket and the real runtime; before and
after forwarding each lifecycle operation it calls the registered hook
server (the node agent) over the RuntimeHookService protocol, merging the
hook response into the forwarded request so QoS adjustments (cgroup
parent, cpu shares/quota/cpuset, memory limits, env injection) reach the
runtime atomically with the operation. Hook failures follow the configured
failure policy: Fail rejects the CRI op, Ignore forwards unmodified
(runtimeproxy/config failure policies).

The CRI surface is a typed subset (this framework's kubelet edge is
internal); the hook wire protocol is the protoc-generated api_pb2.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
from typing import Dict, Optional, Protocol

from koordinator_tpu.runtimeproxy import api_pb2 as pb
from koordinator_tpu.runtimeproxy.rpc import RpcClient, RpcError
from koordinator_tpu.runtimeproxy.store import (
    ContainerInfo,
    MetaStore,
    PodSandboxInfo,
)

log = logging.getLogger(__name__)


class FailurePolicy(enum.Enum):
    FAIL = "Fail"
    IGNORE = "Ignore"


@dataclasses.dataclass
class PodSandboxRequest:
    """CRI RunPodSandbox/StopPodSandbox subset (incl. the sandbox-level
    cgroup resources the hook response can adjust)."""

    sandbox_id: str = ""
    name: str = ""
    namespace: str = ""
    uid: str = ""
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    cgroup_parent: str = ""
    runtime_handler: str = ""
    cpu_shares: int = 0
    cpu_quota: int = 0
    memory_limit_bytes: int = 0
    cpuset_cpus: str = ""
    unified: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ContainerRequest:
    """CRI Create/Start/Update/StopContainer subset."""

    container_id: str = ""
    sandbox_id: str = ""
    name: str = ""
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    envs: Dict[str, str] = dataclasses.field(default_factory=dict)
    cpu_shares: int = 0
    cpu_quota: int = 0
    memory_limit_bytes: int = 0
    cpuset_cpus: str = ""
    unified: Dict[str, str] = dataclasses.field(default_factory=dict)


class RuntimeBackend(Protocol):
    """The real runtime (containerd/dockerd stand-in)."""

    def run_pod_sandbox(self, req: PodSandboxRequest) -> None: ...
    def stop_pod_sandbox(self, req: PodSandboxRequest) -> None: ...
    def create_container(self, req: ContainerRequest) -> None: ...
    def start_container(self, req: ContainerRequest) -> None: ...
    def update_container_resources(self, req: ContainerRequest) -> None: ...
    def stop_container(self, req: ContainerRequest) -> None: ...


def _resources_to_pb(req) -> pb.LinuxContainerResources:
    res = pb.LinuxContainerResources(
        cpu_shares=req.cpu_shares, cpu_quota=req.cpu_quota,
        memory_limit_in_bytes=req.memory_limit_bytes,
        cpuset_cpus=req.cpuset_cpus)
    for k, v in req.unified.items():
        res.unified[k] = v
    return res


def _merge_resources(req, res: pb.LinuxContainerResources) -> None:
    """Hook response fields override the forwarded request where set
    (works on both sandbox and container requests)."""
    if res.cpu_shares:
        req.cpu_shares = res.cpu_shares
    if res.cpu_quota:
        req.cpu_quota = res.cpu_quota
    if res.memory_limit_in_bytes:
        req.memory_limit_bytes = res.memory_limit_in_bytes
    if res.cpuset_cpus:
        req.cpuset_cpus = res.cpuset_cpus
    for k, v in res.unified.items():
        req.unified[k] = v


class RuntimeProxy:
    def __init__(self, backend: RuntimeBackend,
                 hook_client: Optional[RpcClient] = None,
                 failure_policy: FailurePolicy = FailurePolicy.IGNORE,
                 store: Optional[MetaStore] = None):
        self.backend = backend
        self.hooks = hook_client
        self.failure_policy = failure_policy
        self.store = store or MetaStore()

    # -- hook plumbing -------------------------------------------------------

    def _call_hook(self, method: str, request, response_cls):
        if self.hooks is None:
            return None
        try:
            return self.hooks.call(method, request, response_cls)
        except (RpcError, OSError) as e:
            if self.failure_policy is FailurePolicy.FAIL:
                raise
            log.warning("hook %s failed (policy Ignore): %s", method, e)
            return None

    def _pod_hook_request(self, req: PodSandboxRequest
                          ) -> pb.PodSandboxHookRequest:
        out = pb.PodSandboxHookRequest(
            pod_meta=pb.PodSandboxMetadata(name=req.name,
                                           namespace=req.namespace,
                                           uid=req.uid),
            cgroup_parent=req.cgroup_parent,
            runtime_handler=req.runtime_handler,
            resources=_resources_to_pb(req))
        for k, v in req.labels.items():
            out.labels[k] = v
        for k, v in req.annotations.items():
            out.annotations[k] = v
        return out

    def _container_hook_request(self, req: ContainerRequest
                                ) -> pb.ContainerResourceHookRequest:
        pod = (self.store.pods.get(req.sandbox_id)
               or self.store.pod_of_container(req.container_id)
               or PodSandboxInfo())
        out = pb.ContainerResourceHookRequest(
            pod_meta=pb.PodSandboxMetadata(name=pod.name,
                                           namespace=pod.namespace,
                                           uid=pod.uid),
            container_meta=pb.ContainerMetadata(name=req.name,
                                                id=req.container_id),
            container_resources=_resources_to_pb(req),
            pod_cgroup_parent=pod.cgroup_parent)
        for k, v in req.annotations.items():
            out.container_annotations[k] = v
        for k, v in pod.labels.items():
            out.pod_labels[k] = v
        for k, v in pod.annotations.items():
            out.pod_annotations[k] = v
        for k, v in req.envs.items():
            out.container_envs[k] = v
        return out

    # -- CRI surface ---------------------------------------------------------

    def _post_stop_hook(self, method: str, request, response_cls) -> None:
        """Post-stop hooks are cleanup notifications: the backend operation
        already succeeded and cannot be undone, so a hook failure must
        neither fail the CRI op nor skip store cleanup — always Ignore."""
        try:
            if self.hooks is not None:
                self.hooks.call(method, request, response_cls)
        except (RpcError, OSError) as e:
            log.warning("post-stop hook %s failed (ignored): %s", method, e)

    def run_pod_sandbox(self, req: PodSandboxRequest) -> None:
        resp = self._call_hook("PreRunPodSandboxHook",
                               self._pod_hook_request(req),
                               pb.PodSandboxHookResponse)
        if resp is not None:
            if resp.cgroup_parent:
                req.cgroup_parent = resp.cgroup_parent
            for k, v in resp.labels.items():
                req.labels[k] = v
            for k, v in resp.annotations.items():
                req.annotations[k] = v
            # sandbox-level cgroup adjustments (e.g. BE group identity)
            # ride the created sandbox, not a later update
            _merge_resources(req, resp.resources)
        self.backend.run_pod_sandbox(req)
        # register only after the sandbox truly exists (no phantom entries
        # in the checkpointed store on backend failure)
        self.store.put_pod(req.sandbox_id, PodSandboxInfo(
            name=req.name, namespace=req.namespace, uid=req.uid,
            labels=dict(req.labels), annotations=dict(req.annotations),
            cgroup_parent=req.cgroup_parent))

    def stop_pod_sandbox(self, req: PodSandboxRequest) -> None:
        # a real CRI StopPodSandbox carries only the sandbox id; restore
        # the pod metadata from the checkpoint so teardown hooks see the
        # same labels/annotations the creation hooks did
        pod = self.store.pods.get(req.sandbox_id)
        if pod is not None:
            req = dataclasses.replace(
                req, name=pod.name or req.name,
                namespace=pod.namespace or req.namespace,
                uid=pod.uid or req.uid,
                labels={**pod.labels, **req.labels},
                annotations={**pod.annotations, **req.annotations},
                cgroup_parent=req.cgroup_parent or pod.cgroup_parent)
        self.backend.stop_pod_sandbox(req)
        self._post_stop_hook("PostStopPodSandboxHook",
                             self._pod_hook_request(req),
                             pb.PodSandboxHookResponse)
        self.store.delete_pod(req.sandbox_id)

    def create_container(self, req: ContainerRequest) -> None:
        resp = self._call_hook("PreCreateContainerHook",
                               self._container_hook_request(req),
                               pb.ContainerResourceHookResponse)
        if resp is not None:
            _merge_resources(req, resp.container_resources)
            for k, v in resp.container_envs.items():
                req.envs[k] = v
            for k, v in resp.container_annotations.items():
                req.annotations[k] = v
        self.backend.create_container(req)
        # register only once the container truly exists: a FAIL-policy
        # rejection or backend error must not leave a phantom entry in
        # the (checkpointed) store
        self.store.put_container(req.container_id, ContainerInfo(
            name=req.name, pod_sandbox_id=req.sandbox_id))

    def start_container(self, req: ContainerRequest) -> None:
        resp = self._call_hook("PreStartContainerHook",
                               self._container_hook_request(req),
                               pb.ContainerResourceHookResponse)
        if resp is not None:
            _merge_resources(req, resp.container_resources)
        self.backend.start_container(req)
        self._call_hook("PostStartContainerHook",
                        self._container_hook_request(req),
                        pb.ContainerResourceHookResponse)

    def update_container_resources(self, req: ContainerRequest) -> None:
        resp = self._call_hook("PreUpdateContainerResourcesHook",
                               self._container_hook_request(req),
                               pb.ContainerResourceHookResponse)
        if resp is not None:
            _merge_resources(req, resp.container_resources)
        self.backend.update_container_resources(req)

    def stop_container(self, req: ContainerRequest) -> None:
        self.backend.stop_container(req)
        self._post_stop_hook("PostStopContainerHook",
                             self._container_hook_request(req),
                             pb.ContainerResourceHookResponse)
        self.store.delete_container(req.container_id)
