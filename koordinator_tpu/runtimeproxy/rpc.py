"""Length-prefixed protobuf RPC over unix sockets.

The reference speaks gRPC over a unix socket between the proxy and the
hook server (runtimeproxy/server, koordlet proxyserver/server.go:101-112).
grpcio is not in this image, so the same service contract rides a minimal
framed protocol instead — protoc-generated messages on the wire, one
request/response per connection round:

    frame     := u32_be length ++ payload
    request   := u8 method_len ++ method_name ++ message_bytes
    response  := u8 status (0 ok / 1 error) ++ payload
                 (message_bytes on ok, utf-8 error text on error)
"""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple, Type


class RpcError(RuntimeError):
    pass


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise RpcError("connection closed mid-frame")
        buf += chunk
    return buf


def _read_frame(sock: socket.socket) -> bytes:
    (length,) = struct.unpack(">I", _read_exact(sock, 4))
    if length > 64 * 1024 * 1024:
        raise RpcError(f"frame too large: {length}")
    return _read_exact(sock, length)


def _write_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


class RpcServer:
    """Serves `handlers`: method name -> (request class, fn(req) -> resp).

    Runs on a background thread; `close()` stops it. One RPC per
    connection keeps the framing trivial (hook calls are rare: container
    lifecycle events)."""

    def __init__(self, sock_path: str,
                 handlers: Dict[str, Tuple[Type, Callable]]):
        self.sock_path = sock_path
        self.handlers = dict(handlers)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    payload = _read_frame(self.request)
                except RpcError:
                    return
                try:
                    mlen = payload[0]
                    method = payload[1:1 + mlen].decode()
                    body = payload[1 + mlen:]
                    entry = outer.handlers.get(method)
                    if entry is None:
                        raise RpcError(f"unknown method {method!r}")
                    req_cls, fn = entry
                    resp = fn(req_cls.FromString(body))
                    out = b"\x00" + resp.SerializeToString()
                except Exception as e:  # surfaced to the caller as status 1
                    out = b"\x01" + str(e).encode()
                _write_frame(self.request, out)

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

        # a crashed/restarted server leaves the socket file behind and
        # AF_UNIX bind() fails on it (allow_reuse_address is a no-op for
        # unix sockets) — but only unlink a DEAD socket: if a live server
        # still answers connect(), stealing its path would leave it
        # serving an unreachable unlinked inode
        if os.path.exists(sock_path):
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            probe.settimeout(0.2)
            try:
                probe.connect(sock_path)
                alive = True
            except OSError:
                alive = False
            finally:
                probe.close()
            if alive:
                raise RpcError(
                    f"socket {sock_path!r} is in use by a live server")
            try:
                os.unlink(sock_path)
            except FileNotFoundError:
                pass
        self._server = Server(sock_path, Handler)
        try:
            st = os.stat(sock_path)
            self._bound_inode = (st.st_dev, st.st_ino)
        except OSError:
            self._bound_inode = None  # raced away: never unlink blindly
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        # two defenses against deleting a successor's fresh socket during
        # leader handoff: (1) unlink BETWEEN shutdown and server_close —
        # the listening fd still answers the successor's liveness probe in
        # the common case, so the path is still ours; (2) the inode guard
        # covers the probe's failure modes (a full accept backlog makes a
        # live socket probe as dead), where the successor may already have
        # replaced the path. server_close always runs — the listening fd
        # must never leak to an unlink error.
        try:
            try:
                st = os.stat(self.sock_path)
                if self._bound_inode is not None and \
                        (st.st_dev, st.st_ino) == self._bound_inode:
                    os.unlink(self.sock_path)
            except OSError:
                pass
        finally:
            self._server.server_close()


class RpcClient:
    def __init__(self, sock_path: str, timeout: float = 5.0,
                 connect_retry_seconds: float = 2.0):
        self.sock_path = sock_path
        self.timeout = timeout
        self.connect_retry_seconds = connect_retry_seconds

    def _connect(self) -> socket.socket:
        """connect() with a short bounded retry on ECONNREFUSED/ENOENT: a
        server mid-construction has bound the path but not yet listened,
        and a leadership handoff leaves a gap between the old socket
        draining and the successor binding. Connecting is idempotent —
        nothing was sent yet — so retrying is always safe. A FRESH socket
        per attempt: POSIX leaves a socket in unspecified state after a
        failed connect()."""
        deadline = time.monotonic() + self.connect_retry_seconds
        while True:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self.timeout)
            try:
                sock.connect(self.sock_path)
                return sock
            except (ConnectionRefusedError, FileNotFoundError):
                sock.close()
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.02)
            except BaseException:
                sock.close()
                raise

    def call(self, method: str, request, response_cls: Type):
        sock = self._connect()
        try:
            name = method.encode()
            _write_frame(sock, bytes([len(name)]) + name
                         + request.SerializeToString())
            resp = _read_frame(sock)
        finally:
            sock.close()
        if not resp:
            raise RpcError("empty response")
        if resp[0] != 0:
            raise RpcError(resp[1:].decode(errors="replace"))
        return response_cls.FromString(resp[1:])
