"""koord-runtime-proxy equivalent: CRI-interposing proxy + RuntimeHookService
wire protocol (SURVEY.md 2.5, pkg/runtimeproxy + apis/runtime/v1alpha1)."""

from koordinator_tpu.runtimeproxy.rpc import RpcClient, RpcError, RpcServer  # noqa: F401
from koordinator_tpu.runtimeproxy.server import (  # noqa: F401
    FailurePolicy,
    RuntimeProxy,
)
from koordinator_tpu.runtimeproxy.store import MetaStore  # noqa: F401
