"""statesinformer: the agent's view of node/pod/SLO state + the NodeMetric
report loop.

Capability parity with `pkg/koordlet/statesinformer/impl/` (SURVEY.md 2.2):
- a registry of typed states (node, pods, NodeSLO, NodeResourceTopology,
  devices) with callback fan-out to subscribers (callback_runner.go),
- `NodeMetricReporter`: aggregates metriccache into a NodeMetric status —
  node avg usage over the aggregate window, p50/p90/p95/p99 percentile
  usage over longer windows, per-pod usage, prod-reclaimable from the peak
  predictor — on the report interval (states_nodemetric.go:202-250).

The reference pulls pods from the kubelet /pods endpoint; here pod
arrival/update is pushed through `set_pods` by the edge layer (or tests),
the same boundary shape without an HTTP dependency.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional

from koordinator_tpu.api import types as api
from koordinator_tpu.utils.sync import guarded_by
from koordinator_tpu.api.extension import (
    ANNOTATION_RESOURCE_STATUS,
    PriorityClass,
    QoSClass,
    ResourceKind,
    parse_system_qos_resource,
)
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.system import (
    CgroupDriver,
    format_cpuset,
    parse_cpuset,
    pod_cgroup_dir,
)

# state kinds for callback registration (impl/registry.go)
STATE_NODE = "node"
STATE_PODS = "pods"
STATE_NODE_SLO = "node_slo"
STATE_TOPOLOGY = "node_topology"
STATE_DEVICE = "device"
STATE_PVCS = "pvcs"

_BYTES_PER_MIB = float(1 << 20)


def _qos_tier(qos: QoSClass) -> str:
    """kubelet QoS tier dir for the pod cgroup path."""
    if qos in (QoSClass.BE,):
        return "besteffort"
    if qos in (QoSClass.LSE, QoSClass.LSR):
        return "guaranteed"
    return "burstable"


def _pod_pinned_cpus(pod: api.Pod) -> List[int]:
    """cpus pinned via the scheduler's resource-status annotation."""
    import json as _json

    raw = pod.meta.annotations.get(ANNOTATION_RESOURCE_STATUS, "")
    if not raw:
        return []
    try:
        return parse_cpuset(str(_json.loads(raw).get("cpuset", "")))
    except (ValueError, AttributeError):
        return []


def host_app_cgroup_dir(app: api.HostApplication) -> str:
    """Relative cgroup dir of an out-of-band host application
    (util/host_application.go:33-46): explicit override wins, else
    derived from the QoS class."""
    if app.cgroup_dir:
        return app.cgroup_dir
    if app.qos in (QoSClass.LSE, QoSClass.LSR, QoSClass.LS):
        return f"host-latency-sensitive/{app.name}"
    if app.qos is QoSClass.BE:
        return f"host-best-effort/{app.name}"
    return app.name


@dataclasses.dataclass
class PodMeta:
    """A pod plus its node-local cgroup location (statesinformer.PodMeta)."""

    pod: api.Pod
    cgroup_dir: str = ""

    def __post_init__(self) -> None:
        if not self.cgroup_dir:
            self.cgroup_dir = pod_cgroup_dir(
                _qos_tier(self.pod.qos), self.pod.meta.uid,
                CgroupDriver.CGROUPFS)


@guarded_by(
    _node="_lock",
    _pods="_lock",
    _node_slo="_lock",
    _topology="_lock",
    _device="_lock",
    _pvc_volumes="_lock",
    _callbacks="_lock",
)
class StatesInformer:
    """Typed state registry with subscriber callbacks."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._node: Optional[api.Node] = None
        self._pods: Dict[str, PodMeta] = {}
        self._node_slo: Optional[api.NodeSLO] = None
        self._topology: Optional[api.NodeResourceTopology] = None
        self._device: Optional[api.Device] = None
        self._pvc_volumes: Dict[str, str] = {}
        self._callbacks: Dict[str, List[Callable[[object], None]]] = {}

    def subscribe(self, state: str, cb: Callable[[object], None]) -> None:
        with self._lock:
            self._callbacks.setdefault(state, []).append(cb)

    def _notify(self, state: str, value: object) -> None:
        # snapshot the subscriber list under the lock, call OUTSIDE it:
        # iterating the live list races subscribe()'s append, and
        # holding an RLock through arbitrary callbacks invites
        # re-entrant surprises the setters never signed up for
        with self._lock:
            cbs = list(self._callbacks.get(state, []))
        for cb in cbs:
            cb(value)

    # --- setters (informer plugin update paths) -------------------------
    def set_node(self, node: api.Node) -> None:
        with self._lock:
            self._node = node
        self._notify(STATE_NODE, node)

    def set_pods(self, pods: List[PodMeta]) -> None:
        with self._lock:
            self._pods = {p.pod.meta.uid: p for p in pods}
        self._notify(STATE_PODS, pods)

    def set_node_slo(self, slo: api.NodeSLO) -> None:
        with self._lock:
            self._node_slo = slo
        self._notify(STATE_NODE_SLO, slo)

    def set_topology(self, topo: api.NodeResourceTopology) -> None:
        with self._lock:
            self._topology = topo
        self._notify(STATE_TOPOLOGY, topo)

    def set_device(self, device: api.Device) -> None:
        with self._lock:
            self._device = device
        self._notify(STATE_DEVICE, device)

    def set_pvcs(self, pvcs: List[api.PersistentVolumeClaim]) -> None:
        """PVC informer update (states_pvc.go updateVolumeNameMap): keeps
        the namespace/name -> bound-volume map the blkio strategy resolves
        podvolume block configs through."""
        with self._lock:
            self._pvc_volumes = {
                f"{p.meta.namespace}/{p.meta.name}": p.volume_name
                for p in pvcs}
        self._notify(STATE_PVCS, pvcs)

    # --- getters --------------------------------------------------------
    def get_node(self) -> Optional[api.Node]:
        with self._lock:
            return self._node

    def get_all_pods(self) -> List[PodMeta]:
        with self._lock:
            return list(self._pods.values())

    def get_pod(self, uid: str) -> Optional[PodMeta]:
        with self._lock:
            return self._pods.get(uid)

    def get_node_slo(self) -> Optional[api.NodeSLO]:
        with self._lock:
            return self._node_slo

    def get_topology(self) -> Optional[api.NodeResourceTopology]:
        with self._lock:
            return self._topology

    def get_device(self) -> Optional[api.Device]:
        with self._lock:
            return self._device

    def get_volume_name(self, namespace: str, claim_name: str) -> str:
        """'' when the claim is unknown/unbound (states_pvc.go
        GetVolumeName)."""
        with self._lock:
            return self._pvc_volumes.get(f"{namespace}/{claim_name}", "")


@dataclasses.dataclass
class CollectPolicy:
    """NodeMetric spec collect policy (nodemetric_types.go:79)."""

    report_interval_seconds: float = 60.0
    aggregate_duration_seconds: float = 300.0
    # windows for the aggregated percentile usages
    aggregate_policy_durations: tuple = (300.0, 1800.0, 86400.0)


class NodeMetricReporter:
    """Builds NodeMetric statuses from the metric cache
    (nodeMetricInformer sync, states_nodemetric.go:202-250).

    `predictor`, when given, supplies prod-reclaimable resources
    (prediction.PeakPredictServer -> prodReclaimableMetric).
    """

    def __init__(self, informer: StatesInformer, cache: mc.MetricCache,
                 policy: Optional[CollectPolicy] = None,
                 predictor: Optional[object] = None):
        self.informer = informer
        self.cache = cache
        self.policy = policy or CollectPolicy()
        self.predictor = predictor

    def collect(self, now: Optional[float] = None) -> Optional[api.NodeMetric]:
        now = time.time() if now is None else now
        node = self.informer.get_node()
        if node is None:
            return None
        win = self.policy.aggregate_duration_seconds
        cpu = self.cache.query(mc.NODE_CPU_USAGE, now - win, now, agg="avg")
        memb = self.cache.query(mc.NODE_MEMORY_USAGE, now - win, now, agg="avg")
        if cpu is None and memb is None:
            return None  # "node metric is not ready, skip this round"

        def usage_rl(cpu_cores: Optional[float],
                     mem_bytes: Optional[float]) -> dict:
            return {
                ResourceKind.CPU: (cpu_cores or 0.0) * 1000.0,
                ResourceKind.MEMORY: (mem_bytes or 0.0) / _BYTES_PER_MIB,
            }

        nm = api.NodeMetric(
            node_name=node.meta.name,
            update_time=now,
            node_usage=usage_rl(cpu, memb),
        )
        sys_cpu = self.cache.query(mc.SYS_CPU_USAGE, now - win, now, agg="avg")
        if sys_cpu is not None:
            nm.system_usage = {ResourceKind.CPU: sys_cpu * 1000.0,
                               ResourceKind.MEMORY: 0.0}

        # aggregated percentiles per window (AggregatedUsage, p50/p90/p95/p99)
        for dur in self.policy.aggregate_policy_durations:
            usages: Dict[str, dict] = {}
            for agg in ("p50", "p90", "p95", "p99"):
                c = self.cache.query(mc.NODE_CPU_USAGE, now - dur, now, agg=agg)
                m = self.cache.query(mc.NODE_MEMORY_USAGE, now - dur, now,
                                     agg=agg)
                if c is not None or m is not None:
                    usages[agg] = usage_rl(c, m)
            if usages:
                nm.aggregated.append(api.AggregatedUsage(
                    duration_seconds=dur, usages=usages))

        # per-pod usage
        for meta in self.informer.get_all_pods():
            uid = meta.pod.meta.uid
            labels = {"pod_uid": uid}
            pc = self.cache.query(mc.POD_CPU_USAGE, now - win, now, labels,
                                  "avg")
            pm = self.cache.query(mc.POD_MEMORY_USAGE, now - win, now, labels,
                                  "avg")
            if pc is None and pm is None:
                continue
            nm.pods_metric.append(api.PodMetricInfo(
                namespace=meta.pod.meta.namespace,
                name=meta.pod.meta.name,
                priority_class=meta.pod.priority_class,
                usage=usage_rl(pc, pm)))

        # host application usage (states_nodemetric.go:357-389 /
        # collectHostAppMetric:717-757)
        slo = self.informer.get_node_slo()
        for app in (slo.host_applications if slo else []):
            labels = {"app": app.name}
            ac = self.cache.query(mc.HOST_APP_CPU_USAGE, now - win, now,
                                  labels, "avg")
            am = self.cache.query(mc.HOST_APP_MEMORY_USAGE, now - win, now,
                                  labels, "avg")
            if ac is None and am is None:
                continue
            nm.host_app_metric.append(api.HostApplicationMetricInfo(
                name=app.name, usage=usage_rl(ac, am),
                priority_class=app.priority_class, qos=app.qos))

        if self.predictor is not None:
            reclaimable = self.predictor.prod_reclaimable(now=now)
            if reclaimable:
                nm.prod_reclaimable = reclaimable
        return nm


def prod_pods(pods: List[PodMeta]) -> List[PodMeta]:
    """Pods in the Prod priority band (helpers for suppress/overcommit)."""
    return [p for p in pods if p.pod.priority_class == PriorityClass.PROD]


def be_pods(pods: List[PodMeta]) -> List[PodMeta]:
    return [p for p in pods if p.pod.qos == QoSClass.BE]


class TopologyReporter:
    """NodeResourceTopology reporting from the kernel CPU topology
    (statesinformer/impl noderesourcetopology: zones + per-zone capacity;
    SURVEY.md 2.2). Memory capacity is split evenly across NUMA zones —
    per-zone meminfo is a later refinement; cpu capacity is exact."""

    def __init__(self, host, informer: StatesInformer, node_name: str = ""):
        self.host = host
        self.informer = informer
        self.node_name = node_name

    def _system_qos_exclusive(self) -> set:
        """Exclusive SystemQOS cores (node system-qos-resource annotation)
        are carved OUT of the reported topology — the scheduler must not
        hand them to LS/LSR/BE pods (states_noderesourcetopology.go:359-360
        removeSystemQOSCPUs)."""
        node = self.informer.get_node()
        if node is None:
            return set()
        res = parse_system_qos_resource(node.meta.annotations)
        if res and res["exclusive"]:
            return set(res["cpus"])
        return set()

    def report(self) -> api.NodeResourceTopology:
        cpus = self.host.cpu_topology()
        excl = self._system_qos_exclusive()
        by_node: Dict[int, List] = {}
        for c in cpus:
            by_node.setdefault(c.node_id, []).append(c)
        mem_total_mib = self.host.meminfo().get("MemTotal", 0) / (1 << 20)
        n_zones = max(len(by_node), 1)
        zones = []
        for node_id in sorted(by_node):
            members = [c for c in by_node[node_id] if c.cpu_id not in excl]
            mask = 0
            for c in members:
                mask |= 1 << c.cpu_id
            zones.append(api.NUMAZone(
                cpus_milli=1000.0 * len(members),
                memory_mib=mem_total_mib / n_zones,
                cpuset=mask))
        # core_id is only unique within a package: group SMT siblings by
        # (socket, core) or multi-socket hosts double-count thread width
        by_core: Dict[tuple, int] = {}
        for c in cpus:
            key = (c.socket_id, c.core_id)
            by_core[key] = by_core.get(key, 0) + 1
        cpus_per_core = max(by_core.values(), default=1)
        # CPU share pools: everything not pinned by an LSE/LSR pod and not
        # exclusive-SystemQOS roams for LS; the BE pool is the same set
        # (suppress narrows it live). Pinned sets come from the pods'
        # resource-status annotations — the same source the reference's
        # NRT reporter reads its pod CPU allocs from.
        pinned: set = set(excl)
        for meta in self.informer.get_all_pods():
            if meta.pod.qos in (QoSClass.LSE, QoSClass.LSR):
                pinned.update(_pod_pinned_cpus(meta.pod))
        pool = sorted(c.cpu_id for c in cpus if c.cpu_id not in pinned)
        pool_spec = format_cpuset(pool) if pool else ""
        topo = api.NodeResourceTopology(
            node_name=self.node_name, zones=zones,
            cpus_per_core=cpus_per_core,
            ls_share_pool=pool_spec, be_share_pool=pool_spec)
        self.informer.set_topology(topo)
        return topo


class DeviceReporter:
    """Device CR reporting from an injected discovery callable (the NVML
    polling of states_device_linux.go; SURVEY.md 2.2). `discover()` returns
    the node's DeviceInfo list — hermetic tests inject a fake inventory."""

    def __init__(self, discover: Callable[[], List[api.DeviceInfo]],
                 informer: StatesInformer, node_name: str = ""):
        self.discover = discover
        self.informer = informer
        self.node_name = node_name

    def report(self) -> api.Device:
        device = api.Device(node_name=self.node_name,
                            devices=self.discover())
        self.informer.set_device(device)
        return device
