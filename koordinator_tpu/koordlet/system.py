"""Kernel interface surface: cgroup v1/v2, procfs, resctrl, PSI.

Capability parity with `pkg/koordlet/util/system/` (SURVEY.md 2.2):
- cgroup v1+v2 abstraction with a registry of known resource files
  (cgroup_resource.go, incl. `cpu.bvt_warp_ns`),
- cgroup driver layout (cgroupfs vs systemd pod dir naming),
- PSI pressure files (resourceexecutor/psi.go),
- resctrl schemata read/write (resctrl.go:38-69),
- CPU topology discovery (used by cpusuppress cpuset policy).

Design: a `Host` object owns the filesystem root. Production uses
`Host("/")`; tests use `Host(tmpdir)` — the hermetic fake-host fixture
(reference: util_test_tool.go NewFileTestUtil). No module-level path
globals, so parallel tests never collide.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple


class CgroupVersion(enum.Enum):
    V1 = 1
    V2 = 2


class CgroupDriver(enum.Enum):
    CGROUPFS = "cgroupfs"
    SYSTEMD = "systemd"


@dataclasses.dataclass(frozen=True)
class CgroupResource:
    """One known cgroup file (cgroup_resource.go registry entry)."""

    name: str            # logical name, e.g. "cpu.cfs_quota_us"
    v1_subsystem: str    # v1 controller dir ("cpu", "memory", "cpuset", ...)
    v1_file: str
    v2_file: str         # "" if absent in v2
    # inclusive valid int range, None = unchecked / non-numeric
    valid_range: Optional[Tuple[int, int]] = None

    def filename(self, version: CgroupVersion) -> str:
        return self.v1_file if version is CgroupVersion.V1 else self.v2_file

    def supported(self, version: CgroupVersion) -> bool:
        return bool(self.filename(version))


_I64 = (-(2**63), 2**63 - 1)

# The known-files registry (subset of cgroup_resource.go that the agent
# actually reads/writes; extend as strategies land).
RESOURCES: Dict[str, CgroupResource] = {r.name: r for r in [
    CgroupResource("cpu.shares", "cpu", "cpu.shares", "cpu.weight", (2, 262144)),
    CgroupResource("cpu.cfs_quota_us", "cpu", "cpu.cfs_quota_us", "cpu.max", (-1, _I64[1])),
    CgroupResource("cpu.cfs_period_us", "cpu", "cpu.cfs_period_us", "cpu.max", (1000, 1000000)),
    CgroupResource("cpu.cfs_burst_us", "cpu", "cpu.cfs_burst_us", "cpu.max.burst", (0, _I64[1])),
    CgroupResource("cpu.bvt_warp_ns", "cpu", "cpu.bvt_warp_ns", "cpu.bvt_warp_ns", (-1, 2)),
    CgroupResource("cpu.idle", "cpu", "cpu.idle", "cpu.idle", (0, 1)),
    CgroupResource("cpuset.cpus", "cpuset", "cpuset.cpus", "cpuset.cpus"),
    CgroupResource("cpuset.mems", "cpuset", "cpuset.mems", "cpuset.mems"),
    CgroupResource("cpuacct.usage", "cpuacct", "cpuacct.usage", ""),
    CgroupResource("cpu.stat", "cpu", "cpu.stat", "cpu.stat"),
    CgroupResource("memory.limit_in_bytes", "memory", "memory.limit_in_bytes", "memory.max", (-1, _I64[1])),
    CgroupResource("memory.min", "memory", "memory.min", "memory.min", (0, _I64[1])),
    CgroupResource("memory.low", "memory", "memory.low", "memory.low", (0, _I64[1])),
    CgroupResource("memory.high", "memory", "memory.high", "memory.high", (-1, _I64[1])),
    CgroupResource("memory.wmark_ratio", "memory", "memory.wmark_ratio", "memory.wmark_ratio", (0, 100)),
    CgroupResource("memory.usage_in_bytes", "memory", "memory.usage_in_bytes", "memory.current"),
    CgroupResource("memory.stat", "memory", "memory.stat", "memory.stat"),
    CgroupResource("memory.oom.group", "memory", "memory.oom.group", "memory.oom.group", (0, 1)),
    CgroupResource("memory.idle_page_stats", "memory", "memory.idle_page_stats", "memory.idle_page_stats"),
    CgroupResource("cgroup.procs", "cpu", "cgroup.procs", "cgroup.procs"),
    CgroupResource("cpu.pressure", "cpu", "cpu.pressure", "cpu.pressure"),
    CgroupResource("memory.pressure", "memory", "memory.pressure", "memory.pressure"),
    CgroupResource("io.pressure", "io", "io.pressure", "io.pressure"),
    CgroupResource("blkio.throttle.read_bps_device", "blkio", "blkio.throttle.read_bps_device", "io.max"),
    CgroupResource("blkio.throttle.write_bps_device", "blkio", "blkio.throttle.write_bps_device", "io.max"),
    CgroupResource("blkio.throttle.read_iops_device", "blkio", "blkio.throttle.read_iops_device", "io.max"),
    CgroupResource("blkio.throttle.write_iops_device", "blkio", "blkio.throttle.write_iops_device", "io.max"),
    # "<device> <weight>" lines — no scalar range check
    CgroupResource("blkio.cost.weight", "blkio", "blkio.cost.weight", "io.cost.weight"),
    CgroupResource("blkio.weight", "blkio", "blkio.weight", "io.weight", (1, 1000)),
]}

# kubelet cgroup tree roots per QoS class (v1 path under each subsystem;
# v2 path under the unified mount).
KUBEPODS_ROOT = "kubepods"
QOS_DIRS = {"guaranteed": "", "burstable": "burstable", "besteffort": "besteffort"}


def pod_cgroup_dir(qos: str, pod_uid: str,
                   driver: CgroupDriver = CgroupDriver.CGROUPFS) -> str:
    """Relative cgroup dir of a pod under the kubepods root.

    cgroupfs: kubepods/besteffort/pod<uid>
    systemd:  kubepods.slice/kubepods-besteffort.slice/
              kubepods-besteffort-pod<uid_with_underscores>.slice
    """
    qos_dir = QOS_DIRS.get(qos.lower())
    if qos_dir is None:
        raise ValueError(f"unknown qos tier {qos!r}")
    if driver is CgroupDriver.CGROUPFS:
        parts = [KUBEPODS_ROOT] + ([qos_dir] if qos_dir else []) + [f"pod{pod_uid}"]
        return "/".join(parts)
    uid = pod_uid.replace("-", "_")
    if qos_dir:
        return (f"{KUBEPODS_ROOT}.slice/{KUBEPODS_ROOT}-{qos_dir}.slice/"
                f"{KUBEPODS_ROOT}-{qos_dir}-pod{uid}.slice")
    return f"{KUBEPODS_ROOT}.slice/{KUBEPODS_ROOT}-pod{uid}.slice"


def parse_cpuset(s: str) -> List[int]:
    """'0-2,5,7-8' -> [0,1,2,5,7,8] (util/cpuset parse)."""
    cpus: List[int] = []
    s = s.strip()
    if not s:
        return cpus
    for part in s.split(","):
        part = part.strip()
        if "-" in part:
            lo, hi = part.split("-", 1)
            cpus.extend(range(int(lo), int(hi) + 1))
        else:
            cpus.append(int(part))
    return sorted(set(cpus))


def format_cpuset(cpus: Sequence[int]) -> str:
    """[0,1,2,5] -> '0-2,5'."""
    cpus = sorted(set(int(c) for c in cpus))
    if not cpus:
        return ""
    runs: List[Tuple[int, int]] = []
    start = prev = cpus[0]
    for c in cpus[1:]:
        if c == prev + 1:
            prev = c
            continue
        runs.append((start, prev))
        start = prev = c
    runs.append((start, prev))
    return ",".join(f"{a}-{b}" if b > a else f"{a}" for a, b in runs)


@dataclasses.dataclass(frozen=True)
class ProcessorInfo:
    """One logical CPU (util.ProcessorInfo): ids used by the cpuset
    suppress policy to avoid LSE/LSR cores and spread over physical cores."""

    cpu_id: int
    core_id: int
    socket_id: int
    node_id: int  # NUMA node


@dataclasses.dataclass
class PSIStats:
    """Pressure-stall info of one resource ('some'/'full' avg10/avg60/
    avg300 in percent, total in microseconds)."""

    some_avg10: float = 0.0
    some_avg60: float = 0.0
    some_avg300: float = 0.0
    some_total: int = 0
    full_avg10: float = 0.0
    full_avg60: float = 0.0
    full_avg300: float = 0.0
    full_total: int = 0


def parse_psi(text: str) -> PSIStats:
    out = PSIStats()
    for line in text.splitlines():
        m = re.match(r"(some|full) avg10=([\d.]+) avg60=([\d.]+) "
                     r"avg300=([\d.]+) total=(\d+)", line.strip())
        if not m:
            continue
        kind = m.group(1)
        setattr(out, f"{kind}_avg10", float(m.group(2)))
        setattr(out, f"{kind}_avg60", float(m.group(3)))
        setattr(out, f"{kind}_avg300", float(m.group(4)))
        setattr(out, f"{kind}_total", int(m.group(5)))
    return out


class Host:
    """A (redirectable-root) view of the kernel interface filesystem.

    Layout under `root`:
      proc/...                         procfs
      sys/fs/cgroup/<subsys>/...      cgroup v1 mount
      sys/fs/cgroup/...                cgroup v2 unified mount
      sys/fs/resctrl/...               resctrl
    """

    def __init__(self, root: str = "/",
                 cgroup_version: Optional[CgroupVersion] = None,
                 driver: CgroupDriver = CgroupDriver.CGROUPFS):
        self.root = root
        self.driver = driver
        self._version = cgroup_version or self._detect_version()

    # --- path helpers ---------------------------------------------------
    def path(self, *parts: str) -> str:
        return os.path.join(self.root, *[p.lstrip("/") for p in parts])

    @property
    def proc_root(self) -> str:
        return self.path("proc")

    @property
    def cgroup_root(self) -> str:
        return self.path("sys/fs/cgroup")

    @property
    def resctrl_root(self) -> str:
        return self.path("sys/fs/resctrl")

    def _detect_version(self) -> CgroupVersion:
        # unified mount has cgroup.controllers at its root
        if os.path.exists(os.path.join(self.cgroup_root, "cgroup.controllers")):
            return CgroupVersion.V2
        return CgroupVersion.V1

    @property
    def cgroup_version(self) -> CgroupVersion:
        return self._version

    def cgroup_file(self, cgroup_dir: str, resource: str) -> str:
        """Absolute path of `resource` (registry name) for a cgroup dir
        relative to the kubepods mount (e.g. 'kubepods/besteffort')."""
        res = RESOURCES[resource]
        if not res.supported(self._version):
            raise FileNotFoundError(
                f"{resource} unsupported on cgroup {self._version.name}")
        if self._version is CgroupVersion.V1:
            return os.path.join(self.cgroup_root, res.v1_subsystem,
                                cgroup_dir, res.v1_file)
        return os.path.join(self.cgroup_root, cgroup_dir, res.v2_file)

    # --- raw IO ---------------------------------------------------------
    def read(self, abs_path: str) -> str:
        with open(abs_path, "r", encoding="utf-8") as f:
            return f.read()

    def write(self, abs_path: str, value: str) -> None:
        # No makedirs: in a real cgroupfs, mkdir CREATES a cgroup — writes
        # to vanished dirs must fail loudly (the executor catches and
        # audits), not resurrect them as ghosts. The FakeHost builder
        # helpers create dirs explicitly.
        with open(abs_path, "w", encoding="utf-8") as f:
            f.write(value)

    # --- v1<->v2 value translation -------------------------------------
    # Logical values are always the v1 convention; v2 files with different
    # value syntax are translated on the way in/out.

    def _read_v2_cpu_max(self, cgroup_dir: str) -> Tuple[str, str]:
        raw = self.read(os.path.join(self.cgroup_root, cgroup_dir,
                                     "cpu.max")).split()
        quota = raw[0] if raw else "max"
        period = raw[1] if len(raw) > 1 else "100000"
        return quota, period

    def _translate_v2_read(self, cgroup_dir: str, resource: str,
                           raw: str) -> str:
        if resource == "cpu.cfs_quota_us":
            quota, _ = self._read_v2_cpu_max(cgroup_dir)
            return "-1" if quota == "max" else quota
        if resource == "cpu.cfs_period_us":
            _, period = self._read_v2_cpu_max(cgroup_dir)
            return period
        if resource == "cpu.shares":
            # kernel mapping: weight = 1 + ((shares-2)*9999)/262142
            weight = int(raw)
            return str(2 + (weight - 1) * 262142 // 9999)
        if resource in ("memory.limit_in_bytes", "memory.high") \
                and raw == "max":
            return "-1"
        return raw

    def _translate_v2_write(self, cgroup_dir: str, resource: str,
                            value: str) -> str:
        if resource == "cpu.cfs_quota_us":
            _, period = self._read_v2_cpu_max(cgroup_dir)
            return f"max {period}" if int(value) < 0 else f"{value} {period}"
        if resource == "cpu.cfs_period_us":
            quota, _ = self._read_v2_cpu_max(cgroup_dir)
            return f"{quota} {value}"
        if resource == "cpu.shares":
            shares = int(value)
            return str(1 + (shares - 2) * 9999 // 262142)
        if resource in ("memory.limit_in_bytes", "memory.high") \
                and int(value) < 0:
            return "max"
        return value

    def read_cgroup(self, cgroup_dir: str, resource: str) -> str:
        raw = self.read(self.cgroup_file(cgroup_dir, resource)).strip()
        if self._version is CgroupVersion.V2:
            return self._translate_v2_read(cgroup_dir, resource, raw)
        return raw

    def write_cgroup(self, cgroup_dir: str, resource: str, value: str) -> None:
        res = RESOURCES[resource]
        if res.valid_range is not None:
            try:
                v = int(value)
            except ValueError:
                raise ValueError(f"{resource}: non-numeric {value!r}")
            lo, hi = res.valid_range
            if not lo <= v <= hi:
                raise ValueError(f"{resource}: {v} outside [{lo}, {hi}]")
        if self._version is CgroupVersion.V2:
            value = self._translate_v2_write(cgroup_dir, resource, value)
        self.write(self.cgroup_file(cgroup_dir, resource), value)

    # --- typed readers --------------------------------------------------
    def cpu_acct_usage_ns(self, cgroup_dir: str) -> int:
        """Cumulative cgroup CPU time in nanoseconds (v1 cpuacct.usage;
        v2 cpu.stat usage_usec*1000)."""
        if self._version is CgroupVersion.V1:
            return int(self.read_cgroup(cgroup_dir, "cpuacct.usage"))
        for line in self.read_cgroup(cgroup_dir, "cpu.stat").splitlines():
            k, _, v = line.partition(" ")
            if k == "usage_usec":
                return int(v) * 1000
        raise ValueError("cpu.stat missing usage_usec")

    def memory_usage_bytes(self, cgroup_dir: str) -> int:
        """Working-set-ish usage: usage minus inactive file cache
        (reference collectors subtract total_inactive_file)."""
        usage = int(self.read_cgroup(cgroup_dir, "memory.usage_in_bytes"))
        inactive = 0
        try:
            for line in self.read_cgroup(cgroup_dir, "memory.stat").splitlines():
                k, _, v = line.partition(" ")
                if k in ("total_inactive_file", "inactive_file"):
                    inactive = int(v)
                    break
        except (FileNotFoundError, ValueError):
            pass
        return max(0, usage - inactive)

    def psi(self, cgroup_dir: str, resource: str) -> PSIStats:
        """resource in {cpu, memory, io}."""
        return parse_psi(self.read_cgroup(cgroup_dir, f"{resource}.pressure"))

    def memory_usage_with_page_cache_bytes(self, cgroup_dir: str) -> int:
        """Raw cgroup usage INCLUDING page cache (pagecache collector;
        page_cache_collector.go collectPodPageCache reads usage without
        the inactive-file subtraction)."""
        return int(self.read_cgroup(cgroup_dir, "memory.usage_in_bytes"))

    # -- kidled cold memory (util/system/kidled_util.go) ---------------------

    @property
    def kidled_root(self) -> str:
        return self.path("sys", "kernel", "mm", "kidled")

    def kidled_supported(self) -> bool:
        """IsKidledSupport: both kidled sysfs knobs exist."""
        return (os.path.isfile(os.path.join(self.kidled_root,
                                            "scan_period_in_seconds"))
                and os.path.isfile(os.path.join(self.kidled_root,
                                                "use_hierarchy")))

    def kidled_start(self, scan_period_s: int = 5,
                     use_hierarchy: int = 1) -> None:
        """SetKidledScanPeriodInSeconds/SetKidledUseHierarchy — arm the
        kernel idle-page scanner (NewDefaultKidledConfig)."""
        self.write(os.path.join(self.kidled_root, "scan_period_in_seconds"),
                   str(scan_period_s))
        self.write(os.path.join(self.kidled_root, "use_hierarchy"),
                   str(use_hierarchy))

    def cold_page_bytes(self, cgroup_dir: str) -> int:
        """Idle (cold) file-page bytes of a cgroup from kidled's
        memory.idle_page_stats: Σ cfei+dfei+cfui+dfui over all age
        buckets (ColdPageInfoByKidled.GetColdPageTotalBytes,
        kidled_util.go:140-143)."""
        text = self.read_cgroup(cgroup_dir, "memory.idle_page_stats")
        total = 0
        for line in text.splitlines():
            fields = line.split()
            if not fields or fields[0].lstrip("#") == "":
                continue
            if fields[0] in ("cfei", "dfei", "cfui", "dfui"):
                total += sum(int(x) for x in fields[1:])
        return total

    # -- local storage (nodestorageinfo collector) ---------------------------

    def diskstats(self) -> List[Dict[str, int]]:
        """/proc/diskstats rows as dicts (device, reads, read_sectors,
        writes, write_sectors, io_in_progress, io_ticks_ms); partition
        rows included — callers filter."""
        out: List[Dict[str, int]] = []
        try:
            text = self.read(os.path.join(self.proc_root, "diskstats"))
        except FileNotFoundError:
            return out
        for line in text.splitlines():
            f = line.split()
            if len(f) < 13:
                continue
            out.append({
                "major": int(f[0]), "minor": int(f[1]), "device": f[2],
                "reads": int(f[3]), "read_sectors": int(f[5]),
                "writes": int(f[7]), "write_sectors": int(f[9]),
                "io_in_progress": int(f[11]), "io_ticks_ms": int(f[12]),
            })
        return out

    def cpu_stat_throttled(self, cgroup_dir: str) -> Tuple[int, int]:
        """(nr_periods, nr_throttled) from cpu.stat (ParseCPUStatRaw,
        util/system/cgroup.go:85-100; feeds the podthrottled
        collector)."""
        periods = throttled = 0
        for line in self.read_cgroup(cgroup_dir, "cpu.stat").splitlines():
            k, _, v = line.partition(" ")
            if k == "nr_periods":
                periods = int(v)
            elif k == "nr_throttled":
                throttled = int(v)
        return periods, throttled

    def cpu_model(self) -> str:
        """CPU model name from /proc/cpuinfo (NodeCPUInfo, the nodeinfo
        collector's KV payload)."""
        try:
            text = self.read(os.path.join(self.proc_root, "cpuinfo"))
        except FileNotFoundError:
            return ""
        for line in text.splitlines():
            k, _, v = line.partition(":")
            if k.strip() == "model name":
                return v.strip()
        return ""

    def cgroup_procs_recursive(self, cgroup_dir: str) -> List[int]:
        """PIDs of the cgroup AND all descendants; used to attribute
        device/process usage to pods (the GPU collector's pid->pod match,
        collector_gpu_linux.go:200-250, via the inverse /proc/<pid>/cgroup
        join). A pod cgroup is an interior node — its own cgroup.procs is
        empty (v2 forbids interior processes; v1 keeps them in the
        container leaves), so attribution must walk the subtree."""
        res = RESOURCES["cgroup.procs"]
        if self._version is CgroupVersion.V1:
            base = os.path.join(self.cgroup_root, res.v1_subsystem, cgroup_dir)
        else:
            base = os.path.join(self.cgroup_root, cgroup_dir)
        pids: List[int] = []
        for dirpath, _dirnames, filenames in os.walk(base):
            if "cgroup.procs" not in filenames:
                continue
            try:
                text = self.read(os.path.join(dirpath, "cgroup.procs"))
            except OSError:
                continue
            pids.extend(int(x) for x in text.split() if x.strip().isdigit())
        return pids

    def proc_stat_cpu_ticks(self) -> Tuple[int, int]:
        """(total_ticks, idle_ticks incl. iowait) from /proc/stat."""
        text = self.read(os.path.join(self.proc_root, "stat"))
        for line in text.splitlines():
            if line.startswith("cpu "):
                f = [int(x) for x in line.split()[1:]]
                total = sum(f)
                idle = f[3] + (f[4] if len(f) > 4 else 0)
                return total, idle
        raise ValueError("/proc/stat missing cpu line")

    def meminfo(self) -> Dict[str, int]:
        """/proc/meminfo in bytes."""
        out: Dict[str, int] = {}
        for line in self.read(os.path.join(self.proc_root, "meminfo")).splitlines():
            m = re.match(r"(\w+):\s+(\d+)(?:\s+kB)?", line)
            if m:
                out[m.group(1)] = int(m.group(2)) * 1024
        return out

    def cpu_topology(self) -> List[ProcessorInfo]:
        """Logical CPUs from sys/devices topology files (fallback:
        /proc/cpuinfo fields physical id / core id). Topology is static —
        cached after the first scan (collectors call this every tick)."""
        cached = getattr(self, "_topology_cache", None)
        if cached is not None:
            return cached
        cpus = self._scan_cpu_topology()
        self._topology_cache = cpus
        return cpus

    def invalidate_topology_cache(self) -> None:
        self._topology_cache = None

    def _scan_cpu_topology(self) -> List[ProcessorInfo]:
        cpus: List[ProcessorInfo] = []
        sys_cpu = self.path("sys/devices/system/cpu")
        if os.path.isdir(sys_cpu):
            for name in sorted(os.listdir(sys_cpu)):
                m = re.fullmatch(r"cpu(\d+)", name)
                if not m:
                    continue
                cpu_id = int(m.group(1))
                topo = os.path.join(sys_cpu, name, "topology")
                try:
                    core = int(self.read(os.path.join(topo, "core_id")))
                    sock = int(self.read(os.path.join(topo,
                                                      "physical_package_id")))
                except (FileNotFoundError, ValueError):
                    core, sock = cpu_id, 0
                node = 0
                for entry in os.listdir(os.path.join(sys_cpu, name)) \
                        if os.path.isdir(os.path.join(sys_cpu, name)) else []:
                    nm = re.fullmatch(r"node(\d+)", entry)
                    if nm:
                        node = int(nm.group(1))
                        break
                cpus.append(ProcessorInfo(cpu_id, core, sock, node))
        if cpus:
            return cpus
        # /proc/cpuinfo fallback
        cur: Dict[str, int] = {}
        for line in self.read(os.path.join(self.proc_root, "cpuinfo")).splitlines() + [""]:
            if not line.strip():
                if "processor" in cur:
                    cpus.append(ProcessorInfo(
                        cur["processor"], cur.get("core id", cur["processor"]),
                        cur.get("physical id", 0), cur.get("physical id", 0)))
                cur = {}
                continue
            k, _, v = line.partition(":")
            k, v = k.strip(), v.strip()
            if k in ("processor", "core id", "physical id") and v.isdigit():
                cur[k] = int(v)
        return cpus

    # --- resctrl (resctrl.go:38-69) ------------------------------------
    def resctrl_schemata(self, group: str = "") -> Dict[str, str]:
        """Read schemata lines of a resctrl group, keyed by resource
        ('L3', 'MB')."""
        p = os.path.join(self.resctrl_root, group, "schemata")
        out: Dict[str, str] = {}
        for line in self.read(p).splitlines():
            k, _, v = line.partition(":")
            if v:
                out[k.strip()] = v.strip()
        return out

    def write_resctrl_schemata(self, group: str, lines: Dict[str, str]) -> None:
        # unlike cgroupfs, mkdir in resctrl legitimately CREATES the group
        # (resctrl.go creates LS/BE groups this way)
        p = os.path.join(self.resctrl_root, group, "schemata")
        os.makedirs(os.path.dirname(p), exist_ok=True)
        body = "".join(f"{k}:{v}\n" for k, v in lines.items())
        self.write(p, body)

    def write_resctrl_tasks(self, group: str, pids: Sequence[int]) -> None:
        p = os.path.join(self.resctrl_root, group, "tasks")
        os.makedirs(os.path.dirname(p), exist_ok=True)
        # kernel protocol: one pid per write; the fake FS accepts a batch
        with open(p, "a", encoding="utf-8") as f:
            for pid in pids:
                f.write(f"{pid}\n")
