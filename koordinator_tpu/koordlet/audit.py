"""Audit: append-only record of every agent action on the kernel.

Capability parity with `pkg/koordlet/audit/` (auditor.go): an in-memory ring
buffer plus size-rotated on-disk log files, with a query API — `query()`
for in-process callers and `AuditQueryServer` for the paginated HTTP
endpoint (gated by AuditEventsHTTPHandler).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import uuid
from typing import Dict, Iterator, List, Optional

from koordinator_tpu.utils.httpserver import (
    BackgroundHTTPServer,
    QuietJsonHandler,
)
from koordinator_tpu.utils.sync import guarded_by


@dataclasses.dataclass
class Event:
    ts: float
    level: str        # "info" | "warn" | "error"
    component: str    # e.g. "resourceexecutor", "cpusuppress"
    operation: str    # e.g. "write", "evict"
    target: str       # e.g. cgroup file path, pod uid
    detail: str = ""

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), separators=(",", ":"))

    @classmethod
    def from_json(cls, line: str) -> "Event":
        return cls(**json.loads(line))


@guarded_by(
    _ring="_lock",
    _fh="_lock",
    _fh_bytes="_lock",
    _ring_size="publish-once",
    _log_dir="publish-once",
    _max_file_bytes="publish-once",
    _max_files="publish-once",
)
class Auditor:
    """Ring buffer + rotating files. Thread-safe."""

    def __init__(self, log_dir: Optional[str] = None,
                 ring_size: int = 4096,
                 max_file_bytes: int = 4 * 1024 * 1024,
                 max_files: int = 8):
        self._ring: List[Event] = []
        self._ring_size = ring_size
        self._log_dir = log_dir
        self._max_file_bytes = max_file_bytes
        self._max_files = max_files
        self._lock = threading.Lock()
        self._fh = None
        self._fh_bytes = 0
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._open_file()

    def _open_file(self) -> None:
        path = os.path.join(self._log_dir, "audit.log")
        self._fh = open(path, "a", encoding="utf-8")
        self._fh_bytes = self._fh.tell()

    def _rotate(self) -> None:
        self._fh.close()
        base = os.path.join(self._log_dir, "audit.log")
        for i in range(self._max_files - 1, 0, -1):
            src = base if i == 1 else f"{base}.{i - 1}"
            dst = f"{base}.{i}"
            if os.path.exists(src):
                os.replace(src, dst)
        self._open_file()

    def record(self, level: str, component: str, operation: str,
               target: str, detail: str = "") -> None:
        ev = Event(time.time(), level, component, operation, target, detail)
        with self._lock:
            self._ring.append(ev)
            if len(self._ring) > self._ring_size:
                del self._ring[:len(self._ring) - self._ring_size]
            if self._fh is not None:
                line = ev.to_json() + "\n"
                self._fh.write(line)
                self._fh.flush()
                self._fh_bytes += len(line)
                if self._fh_bytes >= self._max_file_bytes:
                    self._rotate()

    def info(self, component: str, operation: str, target: str,
             detail: str = "") -> None:
        self.record("info", component, operation, target, detail)

    def query(self, component: Optional[str] = None,
              since: Optional[float] = None,
              limit: int = 256) -> List[Event]:
        """Newest-first query over the ring (auditor.go:130 HTTP handler)."""
        with self._lock:
            events: Iterator[Event] = reversed(self._ring)
            out: List[Event] = []
            for ev in events:
                if component is not None and ev.component != component:
                    continue
                if since is not None and ev.ts < since:
                    break
                out.append(ev)
                if len(out) >= limit:
                    break
        return out

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


NULL_AUDITOR = Auditor(log_dir=None, ring_size=1)


class _Reader:
    """One paginated query cursor (auditor.go readerContext): a reverse
    snapshot of the ring at first request, a position, and a refresh
    timestamp for TTL expiry."""

    __slots__ = ("token", "events", "pos", "refresh_at")

    def __init__(self, token: str, events: List[Event], now: float):
        self.token = token
        self.events = events
        self.pos = 0
        self.refresh_at = now


@guarded_by(
    _readers="_lock",
    auditor="publish-once",
    default_limit="publish-once",
    max_limit="publish-once",
    reader_ttl="publish-once",
    max_readers="publish-once",
    _server="publish-once",
    port="publish-once",
)
class AuditQueryServer:
    """HTTP query endpoint for audit events (auditor.go:130 HttpHandler,
    gated by AuditEventsHTTPHandler): GET /events?size=N&pageToken=T
    returns {"events": [...], "pageToken": T, "eof": bool}. The first
    request (no token) opens a cursor over a reverse snapshot of the
    ring; follow-ups page through it. Cursors expire after `reader_ttl`
    seconds idle and the oldest are dropped past `max_readers`
    (popExpiredReaderNoLock); an expired/unknown token answers 409, an
    oversized request 400 — the reference's status choices."""

    def __init__(self, auditor: Auditor, host: str = "127.0.0.1",
                 port: int = 0, default_limit: int = 256,
                 max_limit: int = 1024, reader_ttl: float = 120.0,
                 max_readers: int = 16):
        self.auditor = auditor
        self.default_limit = default_limit
        self.max_limit = max_limit
        self.reader_ttl = reader_ttl
        self.max_readers = max_readers
        self._readers: Dict[str, _Reader] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(QuietJsonHandler):
            def do_GET(self):
                from urllib.parse import parse_qs, urlparse
                u = urlparse(self.path)
                if u.path not in ("/events", "/apis/v1/audit"):
                    self.reply_json(404, {"error": "not found"})
                    return
                q = parse_qs(u.query)
                code, payload = outer.handle(
                    size=q.get("size", [""])[0],
                    page_token=q.get("pageToken", [""])[0])
                self.reply_json(code, payload)

        self._server = BackgroundHTTPServer(Handler, host, port)
        self.port = self._server.port

    # handler body, separately callable for tests / other transports
    def handle(self, size: str = "", page_token: str = "",
               now: Optional[float] = None):
        now = time.time() if now is None else now
        limit = self.default_limit
        if size:
            try:
                limit = int(size)
            except ValueError:
                return 400, {"error": f"bad size {size!r}"}
            if limit > self.max_limit:
                return 400, {"error": f"size({limit}) exceeds the limit"
                             f"({self.max_limit})"}
            if limit <= 0:
                # a negative size would slice past the cap; zero would
                # page forever without reaching eof
                return 400, {"error": f"size({limit}) must be positive"}
        with self._lock:
            self._gc(now)
            if page_token:
                reader = self._readers.get(page_token)
                if reader is None:
                    return 409, {"error": f"invalid pageToken {page_token}"}
            else:
                reader = _Reader(str(uuid.uuid4()),
                                 self.auditor.query(limit=self.max_limit
                                                    * 64), now)
                self._readers[reader.token] = reader
            reader.refresh_at = now
            page = reader.events[reader.pos:reader.pos + limit]
            reader.pos += len(page)
            eof = reader.pos >= len(reader.events)
            if eof:
                self._readers.pop(reader.token, None)
        return 200, {"events": [dataclasses.asdict(e) for e in page],
                     "pageToken": reader.token, "eof": eof}

    def _gc(self, now: float) -> None:
        # TTL expiry + cap on concurrent cursors, oldest evicted first
        expired = [t for t, r in self._readers.items()
                   if now > r.refresh_at + self.reader_ttl]
        for t in expired:
            del self._readers[t]
        overflow = len(self._readers) - self.max_readers
        if overflow > 0:
            for t in sorted(self._readers,
                            key=lambda t: self._readers[t].refresh_at
                            )[:overflow]:
                del self._readers[t]

    def close(self) -> None:
        self._server.close()
