"""Audit: append-only record of every agent action on the kernel.

Capability parity with `pkg/koordlet/audit/` (auditor.go): an in-memory ring
buffer plus size-rotated on-disk log files, with a query API (the reference
serves it over HTTP gated by AuditEventsHTTPHandler; here `query()` is the
handler body and edge/service.py exposes it).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Iterator, List, Optional


@dataclasses.dataclass
class Event:
    ts: float
    level: str        # "info" | "warn" | "error"
    component: str    # e.g. "resourceexecutor", "cpusuppress"
    operation: str    # e.g. "write", "evict"
    target: str       # e.g. cgroup file path, pod uid
    detail: str = ""

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), separators=(",", ":"))

    @classmethod
    def from_json(cls, line: str) -> "Event":
        return cls(**json.loads(line))


class Auditor:
    """Ring buffer + rotating files. Thread-safe."""

    def __init__(self, log_dir: Optional[str] = None,
                 ring_size: int = 4096,
                 max_file_bytes: int = 4 * 1024 * 1024,
                 max_files: int = 8):
        self._ring: List[Event] = []
        self._ring_size = ring_size
        self._log_dir = log_dir
        self._max_file_bytes = max_file_bytes
        self._max_files = max_files
        self._lock = threading.Lock()
        self._fh = None
        self._fh_bytes = 0
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._open_file()

    def _open_file(self) -> None:
        path = os.path.join(self._log_dir, "audit.log")
        self._fh = open(path, "a", encoding="utf-8")
        self._fh_bytes = self._fh.tell()

    def _rotate(self) -> None:
        self._fh.close()
        base = os.path.join(self._log_dir, "audit.log")
        for i in range(self._max_files - 1, 0, -1):
            src = base if i == 1 else f"{base}.{i - 1}"
            dst = f"{base}.{i}"
            if os.path.exists(src):
                os.replace(src, dst)
        self._open_file()

    def record(self, level: str, component: str, operation: str,
               target: str, detail: str = "") -> None:
        ev = Event(time.time(), level, component, operation, target, detail)
        with self._lock:
            self._ring.append(ev)
            if len(self._ring) > self._ring_size:
                del self._ring[:len(self._ring) - self._ring_size]
            if self._fh is not None:
                line = ev.to_json() + "\n"
                self._fh.write(line)
                self._fh.flush()
                self._fh_bytes += len(line)
                if self._fh_bytes >= self._max_file_bytes:
                    self._rotate()

    def info(self, component: str, operation: str, target: str,
             detail: str = "") -> None:
        self.record("info", component, operation, target, detail)

    def query(self, component: Optional[str] = None,
              since: Optional[float] = None,
              limit: int = 256) -> List[Event]:
        """Newest-first query over the ring (auditor.go:130 HTTP handler)."""
        with self._lock:
            events: Iterator[Event] = reversed(self._ring)
            out: List[Event] = []
            for ev in events:
                if component is not None and ev.component != component:
                    continue
                if since is not None and ev.ts < since:
                    break
                out.append(ev)
                if len(out) >= limit:
                    break
        return out

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


NULL_AUDITOR = Auditor(log_dir=None, ring_size=1)
