"""pleg: pod lifecycle event generator from cgroup directory watches.

Capability parity with `pkg/koordlet/pleg/` (SURVEY.md 2.2): sub-second
pod/container arrival signal for the runtimehooks reconciler, produced by
watching the kubepods cgroup tree for directory create/remove
(pleg.go:81-148, inotify in watcher_linux.go).

Native path: inotify through ctypes against libc (IN_CREATE|IN_DELETE on
the QoS-tier dirs) — the same kernel facility the reference binds via
fsnotify. Fallback (non-Linux / fake hosts without inotify coverage of
test tmpfs): an mtime/dirset polling scanner with identical event output,
so consumers are agnostic.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import dataclasses
import enum
import errno
import os
import re
import select
import struct
import threading
from typing import Callable, Dict, List, Optional, Set

from koordinator_tpu.utils.sync import guarded_by

IN_CREATE = 0x00000100
IN_DELETE = 0x00000200
IN_ISDIR = 0x40000000
_EVENT_FMT = "iIII"
_EVENT_SIZE = struct.calcsize(_EVENT_FMT)


class EventType(enum.Enum):
    POD_ADDED = "pod_added"
    POD_DELETED = "pod_deleted"
    CONTAINER_ADDED = "container_added"
    CONTAINER_DELETED = "container_deleted"


@dataclasses.dataclass(frozen=True)
class Event:
    type: EventType
    cgroup_dir: str       # relative dir under the cgroup root
    pod_uid: str = ""


_POD_DIR = re.compile(r"pod([0-9a-f-]+)$")


def classify(parent_rel: str, name: str,
             created: bool) -> Optional[Event]:
    """Map a directory create/delete under kubepods to a PLEG event."""
    rel = f"{parent_rel}/{name}" if parent_rel else name
    m = _POD_DIR.search(name)
    if m:
        t = EventType.POD_ADDED if created else EventType.POD_DELETED
        return Event(t, rel, m.group(1))
    pm = _POD_DIR.search(parent_rel)
    if pm:
        t = (EventType.CONTAINER_ADDED if created
             else EventType.CONTAINER_DELETED)
        return Event(t, rel, pm.group(1))
    return None


class PollingWatcher:
    """Dirset-diff scanner with the same event semantics."""

    def __init__(self, root: str, watch_dirs: List[str]):
        self.root = root
        self.watch_dirs = watch_dirs
        self._seen: Dict[str, Set[str]] = {}
        self.prime()

    def _list(self, rel: str) -> Set[str]:
        p = os.path.join(self.root, rel)
        try:
            return {d for d in os.listdir(p)
                    if os.path.isdir(os.path.join(p, d))}
        except FileNotFoundError:
            return set()

    def prime(self) -> None:
        self._seen = {rel: self._list(rel) for rel in self._watched()}

    def _watched(self) -> List[str]:
        # watch the tier dirs plus every known pod dir (for containers)
        out = list(self.watch_dirs)
        for rel in self.watch_dirs:
            for d in self._list(rel):
                if _POD_DIR.search(d):
                    out.append(f"{rel}/{d}")
        return out

    def poll(self) -> List[Event]:
        events: List[Event] = []
        for rel in self._watched():
            now = self._list(rel)
            before = self._seen.get(rel, set())
            for name in sorted(now - before):
                ev = classify(rel, name, created=True)
                if ev:
                    events.append(ev)
            for name in sorted(before - now):
                ev = classify(rel, name, created=False)
                if ev:
                    events.append(ev)
            self._seen[rel] = now
        return events


class InotifyWatcher:
    """ctypes libc inotify watcher (watcher_linux.go equivalent)."""

    def __init__(self, root: str, watch_dirs: List[str]):
        libc_name = ctypes.util.find_library("c")
        if not libc_name:
            raise OSError("libc not found")
        self._libc = ctypes.CDLL(libc_name, use_errno=True)
        self._fd = self._libc.inotify_init1(os.O_NONBLOCK)
        if self._fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1")
        self.root = root
        self._wd_to_rel: Dict[int, str] = {}
        for rel in watch_dirs:
            self.add_watch(rel)

    def add_watch(self, rel: str) -> None:
        path = os.path.join(self.root, rel).encode()
        wd = self._libc.inotify_add_watch(
            self._fd, path, IN_CREATE | IN_DELETE)
        if wd >= 0:
            self._wd_to_rel[wd] = rel

    def poll(self, timeout: float = 0.0) -> List[Event]:
        r, _, _ = select.select([self._fd], [], [], timeout)
        if not r:
            return []
        try:
            data = os.read(self._fd, 64 * 1024)
        except OSError as e:
            if e.errno == errno.EAGAIN:
                return []
            raise
        events: List[Event] = []
        off = 0
        while off + _EVENT_SIZE <= len(data):
            wd, mask, _cookie, length = struct.unpack_from(_EVENT_FMT, data,
                                                           off)
            name = data[off + _EVENT_SIZE: off + _EVENT_SIZE + length]
            name = name.split(b"\0", 1)[0].decode()
            off += _EVENT_SIZE + length
            rel = self._wd_to_rel.get(wd)
            if rel is None or not (mask & IN_ISDIR):
                continue
            created = bool(mask & IN_CREATE)
            ev = classify(rel, name, created)
            if ev:
                events.append(ev)
                # recursively watch new pod dirs for container arrival
                if created and ev.type is EventType.POD_ADDED:
                    self.add_watch(ev.cgroup_dir)
        return events

    def close(self) -> None:
        os.close(self._fd)


@guarded_by(_handlers="_lock", watcher="publish-once")
class Pleg:
    """Drives a watcher and fans events out to handlers (pleg.go)."""

    DEFAULT_WATCH = ["kubepods", "kubepods/burstable", "kubepods/besteffort"]

    @classmethod
    def for_host(cls, host, use_inotify: bool = True) -> "Pleg":
        """Watch the kubepods tree of a system.Host: the v1 'cpu' subsystem
        mount (the reference watches the cpu hierarchy) or the v2 unified
        mount."""
        from koordinator_tpu.koordlet.system import CgroupVersion
        root = host.cgroup_root
        if host.cgroup_version is CgroupVersion.V1:
            root = os.path.join(root, "cpu")
        return cls(root, use_inotify=use_inotify)

    def __init__(self, cgroup_root: str,
                 use_inotify: bool = True,
                 watch_dirs: Optional[List[str]] = None):
        dirs = watch_dirs or self.DEFAULT_WATCH
        self.watcher = None
        if use_inotify:
            try:
                self.watcher = InotifyWatcher(cgroup_root, dirs)
            except OSError:
                self.watcher = None
        if self.watcher is None:
            self.watcher = PollingWatcher(cgroup_root, dirs)
        self._handlers: List[Callable[[Event], None]] = []
        self._lock = threading.Lock()

    def subscribe(self, handler: Callable[[Event], None]) -> None:
        with self._lock:
            self._handlers.append(handler)

    def poll_once(self) -> List[Event]:
        events = self.watcher.poll()
        with self._lock:
            handlers = list(self._handlers)
        for ev in events:
            for h in handlers:
                h(ev)
        return events
