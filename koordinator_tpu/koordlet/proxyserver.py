"""koordlet proxy-mode hook server: serves RuntimeHookService over the
framed unix-socket RPC, translating wire requests into HookContext runs.

Capability parity with koordlet runtimehooks/proxyserver/server.go:101-112
(SURVEY.md 2.2 delivery mode 2): the runtime proxy calls these endpoints
around CRI operations; each maps to a hook Stage, the registered hook
plugins (groupidentity/cpuset/batchresource/gpu...) produce cgroup updates
and env injections, and those are folded into the protobuf response the
proxy merges into the forwarded CRI request. Known cgroup files map onto
the typed LinuxContainerResources fields; everything else rides the
cgroup-v2-style `unified` map.
"""

from __future__ import annotations

from typing import Dict, Optional

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    LABEL_POD_QOS,
    parse_extended_resource_spec,
)
from koordinator_tpu.koordlet.runtimehooks import HookContext, HookServer, Stage
from koordinator_tpu.koordlet.statesinformer import PodMeta
from koordinator_tpu.runtimeproxy import api_pb2 as pb
from koordinator_tpu.runtimeproxy.rpc import RpcServer

# cgroup file -> typed LinuxContainerResources field
_TYPED_FIELDS = {
    "cpu.shares": "cpu_shares",
    "cpu.cfs_quota_us": "cpu_quota",
    "cpu.cfs_period_us": "cpu_period",
    "memory.limit_in_bytes": "memory_limit_in_bytes",
}

_POD_STAGES = {
    "PreRunPodSandboxHook": Stage.PRE_RUN_POD_SANDBOX,
    "PostStopPodSandboxHook": Stage.POST_STOP_POD_SANDBOX,
}
_CONTAINER_STAGES = {
    "PreCreateContainerHook": Stage.PRE_CREATE_CONTAINER,
    "PreStartContainerHook": Stage.PRE_CREATE_CONTAINER,
    "PostStartContainerHook": Stage.POST_START_CONTAINER,
    # container teardown is its own stage: pod-level cleanup plugins must
    # NOT fire when one container of a live pod stops
    "PostStopContainerHook": Stage.POST_STOP_CONTAINER,
    "PreUpdateContainerResourcesHook": Stage.PRE_UPDATE_CONTAINER,
}


def _pod_meta(name: str, namespace: str, uid: str,
              labels: Dict[str, str], annotations: Dict[str, str],
              cgroup_parent: str) -> PodMeta:
    annotations = dict(annotations)
    # wire requests have no pod spec; batch/mid tiers arrive through the
    # webhook-written extended-resource-spec annotation
    # (container_context.go FromProxy -> GetExtendedResourceSpec)
    requests, limits = parse_extended_resource_spec(annotations)
    pod = api.Pod(meta=api.ObjectMeta(name=name, namespace=namespace,
                                      uid=uid, labels=dict(labels),
                                      annotations=annotations),
                  requests=requests, limits=limits,
                  qos_label=labels.get(LABEL_POD_QOS, ""))
    return PodMeta(pod=pod, cgroup_dir=cgroup_parent or "")


def _fold_updates(ctx: HookContext,
                  resources: pb.LinuxContainerResources) -> None:
    for upd in ctx.cgroup_updates:
        field = _TYPED_FIELDS.get(upd.resource)
        if field is not None:
            try:
                setattr(resources, field, int(float(upd.value)))
                continue
            except ValueError:
                pass
        if upd.resource == "cpuset.cpus":
            resources.cpuset_cpus = upd.value
        else:
            resources.unified[upd.resource] = upd.value


class ProxyHookService:
    """The RuntimeHookService implementation backed by a HookServer."""

    def __init__(self, hook_server: HookServer):
        self.hook_server = hook_server

    # -- pod sandbox ---------------------------------------------------------

    def _pod_hook(self, method: str, req: pb.PodSandboxHookRequest
                  ) -> pb.PodSandboxHookResponse:
        meta = _pod_meta(req.pod_meta.name, req.pod_meta.namespace,
                         req.pod_meta.uid, req.labels, req.annotations,
                         req.cgroup_parent)
        ctx = HookContext(pod=meta, stage=_POD_STAGES[method])
        self.hook_server.run_hooks(ctx.stage, ctx)
        resp = pb.PodSandboxHookResponse(cgroup_parent=req.cgroup_parent)
        resources = pb.LinuxContainerResources()
        _fold_updates(ctx, resources)
        resp.resources.CopyFrom(resources)
        return resp

    # -- containers ----------------------------------------------------------

    def _container_hook(self, method: str,
                        req: pb.ContainerResourceHookRequest
                        ) -> pb.ContainerResourceHookResponse:
        meta = _pod_meta(req.pod_meta.name, req.pod_meta.namespace,
                         req.pod_meta.uid, req.pod_labels,
                         req.pod_annotations, req.pod_cgroup_parent)
        ctx = HookContext(pod=meta, stage=_CONTAINER_STAGES[method],
                          container_name=req.container_meta.name)
        self.hook_server.run_hooks(ctx.stage, ctx)
        resp = pb.ContainerResourceHookResponse(
            pod_cgroup_parent=req.pod_cgroup_parent)
        resources = pb.LinuxContainerResources()
        resources.CopyFrom(req.container_resources)
        _fold_updates(ctx, resources)
        resp.container_resources.CopyFrom(resources)
        for k, v in ctx.env.items():
            resp.container_envs[k] = v
        return resp

    # -- serving -------------------------------------------------------------

    def serve(self, sock_path: str) -> RpcServer:
        handlers = {}
        for method in _POD_STAGES:
            handlers[method] = (
                pb.PodSandboxHookRequest,
                lambda req, m=method: self._pod_hook(m, req))
        for method in _CONTAINER_STAGES:
            handlers[method] = (
                pb.ContainerResourceHookRequest,
                lambda req, m=method: self._container_hook(m, req))
        return RpcServer(sock_path, handlers)
