"""koordlet metric series — parity with pkg/koordlet/metrics/ (one
reference file per series group: cpi.go, psi.go, cpu_suppress.go,
cpu_burst.go, core_sched.go, prediction.go, resource_summary.go,
common.go).

Label vocabularies follow the reference (NodeKey/PodUID/... in
common.go); the node label is bound once via `for_node` so call sites
pass only the varying labels.
"""

from __future__ import annotations

from koordinator_tpu.metrics import Registry, global_registry


class KoordletMetrics:
    def __init__(self, registry: Registry = None):
        r = registry if registry is not None else global_registry()
        self.start_time = r.gauge(
            "koordlet_start_time",
            "Unix time the agent started (common.go StartTime)",
            labels=("node",))
        # --- performance collector (cpi.go, psi.go) ---
        self.container_cpi = r.gauge(
            "koordlet_container_cpi",
            "Container cycles-per-instruction collected by the perf group "
            "reader", labels=("node", "pod_uid", "container_id", "field"))
        self.container_psi = r.gauge(
            "koordlet_container_psi",
            "Container pressure-stall information",
            labels=("node", "pod_uid", "container_id", "resource",
                    "precision", "degree"))
        self.pod_psi = r.gauge(
            "koordlet_pod_psi", "Pod pressure-stall information",
            labels=("node", "pod_uid", "resource", "precision", "degree"))
        # --- qos strategies (cpu_suppress.go, cpu_burst.go) ---
        self.be_suppress_cpu_cores = r.gauge(
            "koordlet_be_suppress_cpu_cores",
            "Cores granted to the BE tier by the suppress policy",
            labels=("node", "type"))  # type: cfsQuota | cpuset
        self.be_suppress_ls_used_cpu_cores = r.gauge(
            "koordlet_be_suppress_ls_used_cpu_cores",
            "Cores the LS tier currently uses as seen by the suppress "
            "policy", labels=("node",))
        self.container_scaled_cfs_quota_us = r.gauge(
            "koordlet_container_scaled_cfs_quota_us",
            "cfs quota written by the burst strategy",
            labels=("node", "pod_uid", "container_id"))
        self.container_scaled_cfs_burst_us = r.gauge(
            "koordlet_container_scaled_cfs_burst_us",
            "cfs burst written by the burst strategy",
            labels=("node", "pod_uid", "container_id"))
        self.pod_eviction = r.counter(
            "koordlet_pod_eviction",
            "Evictions requested by QoS strategies by reason",
            labels=("node", "reason"))
        # --- core scheduling (core_sched.go) ---
        self.container_core_sched_cookie = r.gauge(
            "koordlet_container_core_sched_cookie",
            "Core-scheduling cookie assigned to the container",
            labels=("node", "pod_uid", "container_id", "group"))
        self.core_sched_cookie_manage_status = r.counter(
            "koordlet_core_sched_cookie_manage_status",
            "Cookie assign/clear operations by status",
            labels=("node", "group", "status"))
        # --- prediction / node summary (prediction.go, resource_summary.go)
        self.node_predicted_resource_reclaimable = r.gauge(
            "koordlet_node_predicted_resource_reclaimable",
            "Reclaimable resource predicted by the peak predictor",
            labels=("node", "predictor", "resource", "unit"))
        self.node_resource_allocatable = r.gauge(
            "koordlet_node_resource_allocatable",
            "Node allocatable as reported",
            labels=("node", "resource", "unit"))
        self.node_used_cpu_cores = r.gauge(
            "koordlet_node_used_cpu_cores",
            "Node CPU usage in cores (resource_summary.go)",
            labels=("node",))
