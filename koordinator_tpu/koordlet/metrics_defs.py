"""koordlet metric series — parity with pkg/koordlet/metrics/ (one
reference file per series group: cpi.go, psi.go, cpu_suppress.go,
cpu_burst.go, core_sched.go, prediction.go, resource_summary.go,
common.go).

Label vocabularies follow the reference (NodeKey/PodUID/... in
common.go); the node label is bound once via `for_node` so call sites
pass only the varying labels.

Family names come from the shared name registry
(koordinator_tpu/metrics/registry.py) and are re-exported here; the
koordlint metric-registry pass rejects bare literals so the catalogs
cannot drift.
"""

from __future__ import annotations

from koordinator_tpu.metrics import Registry, global_registry
from koordinator_tpu.metrics.registry import (  # noqa: F401  (re-export)
    KOORDLET_BE_SUPPRESS_CPU_CORES,
    KOORDLET_BE_SUPPRESS_LS_USED_CPU_CORES,
    KOORDLET_CONTAINER_CORE_SCHED_COOKIE,
    KOORDLET_CONTAINER_CPI,
    KOORDLET_CONTAINER_PSI,
    KOORDLET_CONTAINER_SCALED_CFS_BURST_US,
    KOORDLET_CONTAINER_SCALED_CFS_QUOTA_US,
    KOORDLET_CORE_SCHED_COOKIE_MANAGE_STATUS,
    KOORDLET_NODE_PREDICTED_RESOURCE_RECLAIMABLE,
    KOORDLET_NODE_RESOURCE_ALLOCATABLE,
    KOORDLET_NODE_USED_CPU_CORES,
    KOORDLET_POD_EVICTION,
    KOORDLET_POD_PSI,
    KOORDLET_START_TIME,
)


class KoordletMetrics:
    def __init__(self, registry: Registry = None):
        r = registry if registry is not None else global_registry()
        self.start_time = r.gauge(
            KOORDLET_START_TIME,
            "Unix time the agent started (common.go StartTime)",
            labels=("node",))
        # --- performance collector (cpi.go, psi.go) ---
        self.container_cpi = r.gauge(
            KOORDLET_CONTAINER_CPI,
            "Container cycles-per-instruction collected by the perf group "
            "reader", labels=("node", "pod_uid", "container_id", "field"))
        self.container_psi = r.gauge(
            KOORDLET_CONTAINER_PSI,
            "Container pressure-stall information",
            labels=("node", "pod_uid", "container_id", "resource",
                    "precision", "degree"))
        self.pod_psi = r.gauge(
            KOORDLET_POD_PSI, "Pod pressure-stall information",
            labels=("node", "pod_uid", "resource", "precision", "degree"))
        # --- qos strategies (cpu_suppress.go, cpu_burst.go) ---
        self.be_suppress_cpu_cores = r.gauge(
            KOORDLET_BE_SUPPRESS_CPU_CORES,
            "Cores granted to the BE tier by the suppress policy",
            labels=("node", "type"))  # type: cfsQuota | cpuset
        self.be_suppress_ls_used_cpu_cores = r.gauge(
            KOORDLET_BE_SUPPRESS_LS_USED_CPU_CORES,
            "Cores the LS tier currently uses as seen by the suppress "
            "policy", labels=("node",))
        self.container_scaled_cfs_quota_us = r.gauge(
            KOORDLET_CONTAINER_SCALED_CFS_QUOTA_US,
            "cfs quota written by the burst strategy",
            labels=("node", "pod_uid", "container_id"))
        self.container_scaled_cfs_burst_us = r.gauge(
            KOORDLET_CONTAINER_SCALED_CFS_BURST_US,
            "cfs burst written by the burst strategy",
            labels=("node", "pod_uid", "container_id"))
        self.pod_eviction = r.counter(
            KOORDLET_POD_EVICTION,
            "Evictions requested by QoS strategies by reason",
            labels=("node", "reason"))
        # --- core scheduling (core_sched.go) ---
        self.container_core_sched_cookie = r.gauge(
            KOORDLET_CONTAINER_CORE_SCHED_COOKIE,
            "Core-scheduling cookie assigned to the container",
            labels=("node", "pod_uid", "container_id", "group"))
        self.core_sched_cookie_manage_status = r.counter(
            KOORDLET_CORE_SCHED_COOKIE_MANAGE_STATUS,
            "Cookie assign/clear operations by status",
            labels=("node", "group", "status"))
        # --- prediction / node summary (prediction.go, resource_summary.go)
        self.node_predicted_resource_reclaimable = r.gauge(
            KOORDLET_NODE_PREDICTED_RESOURCE_RECLAIMABLE,
            "Reclaimable resource predicted by the peak predictor",
            labels=("node", "predictor", "resource", "unit"))
        self.node_resource_allocatable = r.gauge(
            KOORDLET_NODE_RESOURCE_ALLOCATABLE,
            "Node allocatable as reported",
            labels=("node", "resource", "unit"))
        self.node_used_cpu_cores = r.gauge(
            KOORDLET_NODE_USED_CPU_CORES,
            "Node CPU usage in cores (resource_summary.go)",
            labels=("node",))
