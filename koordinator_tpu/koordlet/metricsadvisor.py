"""metricsadvisor: the collector framework that samples kernel state into
the metric cache.

Capability parity with `pkg/koordlet/metricsadvisor/` (SURVEY.md 2.2):
a registry of periodic collectors (framework/plugin.go) — noderesource
(/proc/stat + meminfo), podresource (per-pod cgroup cpuacct/memory),
beresource (BE-tier cgroup totals), sysresource (node minus pods),
PSI, and performance/CPI (grouped perf counters via the native shim,
performance_collector_linux.go:85-120).

Counter-based rates (CPU) are computed from deltas between ticks, so each
collector is stateful; `Advisor.collect_once(now)` drives them all — the
run loop calls it on the collect interval, tests call it directly.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Protocol, Tuple

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.system import Host

_NS = 1e9


class Collector(Protocol):
    name: str

    def collect(self, now: float) -> None: ...


class NodeResourceCollector:
    """Node CPU (cores, from /proc/stat tick deltas) + memory used
    (MemTotal - MemAvailable)."""

    name = "noderesource"

    def __init__(self, host: Host, cache: mc.MetricCache):
        self.host = host
        self.cache = cache
        self._prev: Optional[Tuple[float, int, int]] = None  # (now, total, idle)

    def collect(self, now: float) -> None:
        try:
            total, idle = self.host.proc_stat_cpu_ticks()
            meminfo = self.host.meminfo()
        except (FileNotFoundError, ValueError):
            return
        if self._prev is not None:
            _, ptotal, pidle = self._prev
            dt_total, dt_idle = total - ptotal, idle - pidle
            if dt_total > 0:
                n_cpus = len(self.host.cpu_topology()) or 1
                used_cores = n_cpus * (dt_total - dt_idle) / dt_total
                self.cache.append(mc.NODE_CPU_USAGE, now, used_cores)
        self._prev = (now, total, idle)
        if "MemTotal" in meminfo:
            avail = meminfo.get("MemAvailable",
                                meminfo.get("MemFree", 0))
            self.cache.append(mc.NODE_MEMORY_USAGE, now,
                              float(meminfo["MemTotal"] - avail))


class _CgroupCPUTracker:
    """cpuacct cumulative-ns -> cores, keyed by cgroup dir."""

    def __init__(self, host: Host):
        self.host = host
        self._prev: Dict[str, Tuple[float, int]] = {}

    def cores(self, cgroup_dir: str, now: float) -> Optional[float]:
        try:
            ns = self.host.cpu_acct_usage_ns(cgroup_dir)
        except (FileNotFoundError, ValueError):
            self._prev.pop(cgroup_dir, None)
            return None
        prev = self._prev.get(cgroup_dir)
        self._prev[cgroup_dir] = (now, ns)
        if prev is None or now <= prev[0]:
            return None
        return max(0.0, (ns - prev[1]) / _NS / (now - prev[0]))


class PodResourceCollector:
    """Per-pod cgroup CPU/memory usage (collectors/podresource)."""

    name = "podresource"

    def __init__(self, host: Host, cache: mc.MetricCache,
                 informer: StatesInformer):
        self.host = host
        self.cache = cache
        self.informer = informer
        self._cpu = _CgroupCPUTracker(host)

    def collect(self, now: float) -> None:
        for meta in self.informer.get_all_pods():
            uid = meta.pod.meta.uid
            labels = {"pod_uid": uid}
            cores = self._cpu.cores(meta.cgroup_dir, now)
            if cores is not None:
                self.cache.append(mc.POD_CPU_USAGE, now, cores, labels)
            try:
                b = self.host.memory_usage_bytes(meta.cgroup_dir)
            except (FileNotFoundError, ValueError):
                continue
            self.cache.append(mc.POD_MEMORY_USAGE, now, float(b), labels)


class BEResourceCollector:
    """BE tier total usage from the besteffort QoS cgroup
    (collectors/beresource; feeds cpusuppress/cpuevict)."""

    name = "beresource"
    be_dir = "kubepods/besteffort"

    def __init__(self, host: Host, cache: mc.MetricCache):
        self.host = host
        self.cache = cache
        self._cpu = _CgroupCPUTracker(host)

    def collect(self, now: float) -> None:
        cores = self._cpu.cores(self.be_dir, now)
        if cores is not None:
            self.cache.append(mc.BE_CPU_USAGE, now, cores)


class SysResourceCollector:
    """system.Used = node.Used - Σ pod.Used, floored at 0
    (collectors/sysresource)."""

    name = "sysresource"

    def __init__(self, cache: mc.MetricCache, window: float = 60.0):
        self.cache = cache
        self.window = window

    def collect(self, now: float) -> None:
        node = self.cache.query(mc.NODE_CPU_USAGE, now - self.window, now,
                                agg="latest")
        if node is None:
            return
        pods = self.cache.query_all(mc.POD_CPU_USAGE, now - self.window, now,
                                    agg="latest")
        self.cache.append(mc.SYS_CPU_USAGE, now,
                          max(0.0, node - sum(pods.values())))


class PSICollector:
    """Pressure-stall sampling for node + per-pod cgroups
    (metricsadvisor performance PSI path)."""

    name = "psi"

    def __init__(self, host: Host, cache: mc.MetricCache,
                 informer: StatesInformer):
        self.host = host
        self.cache = cache
        self.informer = informer

    def _sample(self, cgroup_dir: str, now: float) -> None:
        for res, metric in (("cpu", mc.PSI_CPU_SOME_AVG10),
                            ("memory", mc.PSI_MEM_FULL_AVG10),
                            ("io", mc.PSI_IO_FULL_AVG10)):
            try:
                psi = self.host.psi(cgroup_dir, res)
            except (FileNotFoundError, ValueError):
                continue
            value = psi.full_avg10 if res != "cpu" else psi.some_avg10
            self.cache.append(metric, now, value, {"cgroup": cgroup_dir})

    def collect(self, now: float) -> None:
        self._sample("kubepods", now)
        for meta in self.informer.get_all_pods():
            self._sample(meta.cgroup_dir, now)


class PerformanceCollector:
    """Container CPI via grouped hardware counters (cycles+instructions),
    read through the native perf shim (performance_collector_linux.go:
    85-120; native/perf_group.cpp). `perf_reader(cgroup_dir)` returns
    (cycles, instructions) deltas for the sample window or None."""

    name = "performance"

    def __init__(self, cache: mc.MetricCache, informer: StatesInformer,
                 perf_reader: Callable[[str], Optional[Tuple[int, int]]]):
        self.cache = cache
        self.informer = informer
        self.perf_reader = perf_reader

    def collect(self, now: float) -> None:
        for meta in self.informer.get_all_pods():
            res = self.perf_reader(meta.cgroup_dir)
            if res is None:
                continue
            cycles, instructions = res
            labels = {"pod_uid": meta.pod.meta.uid, "container": ""}
            self.cache.append(mc.CONTAINER_CPI_CYCLES, now, float(cycles),
                              labels)
            self.cache.append(mc.CONTAINER_CPI_INSTRUCTIONS, now,
                              float(instructions), labels)


class Advisor:
    """The collector registry + drive loop (framework/plugin.go registry;
    metrics_advisor.go:72-102 per-collector goroutines collapse into one
    tick since every collector is cheap and non-blocking here)."""

    def __init__(self, collectors: List[Collector],
                 collect_interval: float = 1.0):
        self.collectors = collectors
        self.collect_interval = collect_interval

    def collect_once(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for c in self.collectors:
            c.collect(now)

    def run(self, stop: Callable[[], bool]) -> None:
        while not stop():
            self.collect_once()
            time.sleep(self.collect_interval)


def default_advisor(host: Host, cache: mc.MetricCache,
                    informer: StatesInformer,
                    perf_reader: Optional[Callable] = None) -> Advisor:
    cs: List[Collector] = [
        NodeResourceCollector(host, cache),
        PodResourceCollector(host, cache, informer),
        BEResourceCollector(host, cache),
        SysResourceCollector(cache),
        PSICollector(host, cache, informer),
    ]
    if perf_reader is not None:
        cs.append(PerformanceCollector(cache, informer, perf_reader))
    return Advisor(cs)
