"""metricsadvisor: the collector framework that samples kernel state into
the metric cache.

Capability parity with `pkg/koordlet/metricsadvisor/` (SURVEY.md 2.2):
a registry of periodic collectors (framework/plugin.go) — noderesource
(/proc/stat + meminfo), podresource (per-pod cgroup cpuacct/memory),
beresource (BE-tier cgroup totals), sysresource (node minus pods), PSI,
performance/CPI (grouped perf counters via the native shim,
performance_collector_linux.go:85-120), pagecache, kidled cold memory,
hostapplication, nodestorageinfo (+ disk IO rates), accelerator devices
(pid->pod attribution), podthrottled, and nodeinfo.

Counter-based rates (CPU) are computed from deltas between ticks, so each
collector is stateful; `Advisor.collect_once(now)` drives them all — the
run loop calls it on the collect interval, tests call it directly — and
isolates per-collector failures (the reference's per-collector
goroutines).
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional, Protocol, Tuple

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.statesinformer import (
    StatesInformer,
    host_app_cgroup_dir,
)
from koordinator_tpu.koordlet.system import Host

_NS = 1e9


class Collector(Protocol):
    name: str

    def collect(self, now: float) -> None: ...


class NodeResourceCollector:
    """Node CPU (cores, from /proc/stat tick deltas) + memory used
    (MemTotal - MemAvailable)."""

    name = "noderesource"

    def __init__(self, host: Host, cache: mc.MetricCache):
        self.host = host
        self.cache = cache
        self._prev: Optional[Tuple[float, int, int]] = None  # (now, total, idle)

    def collect(self, now: float) -> None:
        try:
            total, idle = self.host.proc_stat_cpu_ticks()
            meminfo = self.host.meminfo()
        except (FileNotFoundError, ValueError):
            return
        if self._prev is not None:
            _, ptotal, pidle = self._prev
            dt_total, dt_idle = total - ptotal, idle - pidle
            if dt_total > 0:
                n_cpus = len(self.host.cpu_topology()) or 1
                used_cores = n_cpus * (dt_total - dt_idle) / dt_total
                self.cache.append(mc.NODE_CPU_USAGE, now, used_cores)
        self._prev = (now, total, idle)
        if "MemTotal" in meminfo:
            avail = meminfo.get("MemAvailable",
                                meminfo.get("MemFree", 0))
            self.cache.append(mc.NODE_MEMORY_USAGE, now,
                              float(meminfo["MemTotal"] - avail))


class _CgroupCPUTracker:
    """cpuacct cumulative-ns -> cores, keyed by cgroup dir."""

    def __init__(self, host: Host):
        self.host = host
        self._prev: Dict[str, Tuple[float, int]] = {}

    def cores(self, cgroup_dir: str, now: float) -> Optional[float]:
        try:
            ns = self.host.cpu_acct_usage_ns(cgroup_dir)
        except (FileNotFoundError, ValueError):
            self._prev.pop(cgroup_dir, None)
            return None
        prev = self._prev.get(cgroup_dir)
        self._prev[cgroup_dir] = (now, ns)
        if prev is None or now <= prev[0]:
            return None
        return max(0.0, (ns - prev[1]) / _NS / (now - prev[0]))


class PodResourceCollector:
    """Per-pod cgroup CPU/memory usage (collectors/podresource)."""

    name = "podresource"

    def __init__(self, host: Host, cache: mc.MetricCache,
                 informer: StatesInformer):
        self.host = host
        self.cache = cache
        self.informer = informer
        self._cpu = _CgroupCPUTracker(host)

    def collect(self, now: float) -> None:
        for meta in self.informer.get_all_pods():
            uid = meta.pod.meta.uid
            labels = {"pod_uid": uid}
            cores = self._cpu.cores(meta.cgroup_dir, now)
            if cores is not None:
                self.cache.append(mc.POD_CPU_USAGE, now, cores, labels)
            try:
                b = self.host.memory_usage_bytes(meta.cgroup_dir)
            except (FileNotFoundError, ValueError):
                continue
            self.cache.append(mc.POD_MEMORY_USAGE, now, float(b), labels)


class BEResourceCollector:
    """BE tier total usage from the besteffort QoS cgroup
    (collectors/beresource; feeds cpusuppress/cpuevict)."""

    name = "beresource"
    be_dir = "kubepods/besteffort"

    def __init__(self, host: Host, cache: mc.MetricCache):
        self.host = host
        self.cache = cache
        self._cpu = _CgroupCPUTracker(host)

    def collect(self, now: float) -> None:
        cores = self._cpu.cores(self.be_dir, now)
        if cores is not None:
            self.cache.append(mc.BE_CPU_USAGE, now, cores)


class SysResourceCollector:
    """system.Used = node.Used - Σ pod.Used, floored at 0
    (collectors/sysresource)."""

    name = "sysresource"

    def __init__(self, cache: mc.MetricCache, window: float = 60.0):
        self.cache = cache
        self.window = window

    def collect(self, now: float) -> None:
        node = self.cache.query(mc.NODE_CPU_USAGE, now - self.window, now,
                                agg="latest")
        if node is None:
            return
        pods = self.cache.query_all(mc.POD_CPU_USAGE, now - self.window, now,
                                    agg="latest")
        self.cache.append(mc.SYS_CPU_USAGE, now,
                          max(0.0, node - sum(pods.values())))


class PSICollector:
    """Pressure-stall sampling for node + per-pod cgroups
    (metricsadvisor performance PSI path)."""

    name = "psi"

    def __init__(self, host: Host, cache: mc.MetricCache,
                 informer: StatesInformer):
        self.host = host
        self.cache = cache
        self.informer = informer

    def _sample(self, cgroup_dir: str, now: float) -> None:
        for res, metric in (("cpu", mc.PSI_CPU_SOME_AVG10),
                            ("memory", mc.PSI_MEM_FULL_AVG10),
                            ("io", mc.PSI_IO_FULL_AVG10)):
            try:
                psi = self.host.psi(cgroup_dir, res)
            except (FileNotFoundError, ValueError):
                continue
            value = psi.full_avg10 if res != "cpu" else psi.some_avg10
            self.cache.append(metric, now, value, {"cgroup": cgroup_dir})

    def collect(self, now: float) -> None:
        self._sample("kubepods", now)
        for meta in self.informer.get_all_pods():
            self._sample(meta.cgroup_dir, now)


class PerformanceCollector:
    """Container CPI via grouped hardware counters (cycles+instructions),
    read through the native perf shim (performance_collector_linux.go:
    85-120; native/perf_group.cpp). `perf_reader(cgroup_dir)` returns
    (cycles, instructions) deltas for the sample window or None."""

    name = "performance"

    def __init__(self, cache: mc.MetricCache, informer: StatesInformer,
                 perf_reader: Callable[[str], Optional[Tuple[int, int]]]):
        self.cache = cache
        self.informer = informer
        self.perf_reader = perf_reader

    def collect(self, now: float) -> None:
        for meta in self.informer.get_all_pods():
            res = self.perf_reader(meta.cgroup_dir)
            if res is None:
                continue
            cycles, instructions = res
            labels = {"pod_uid": meta.pod.meta.uid, "container": ""}
            self.cache.append(mc.CONTAINER_CPI_CYCLES, now, float(cycles),
                              labels)
            self.cache.append(mc.CONTAINER_CPI_INSTRUCTIONS, now,
                              float(instructions), labels)


class PageCacheCollector:
    """Memory usage INCLUDING page cache (collectors/pagecache/
    page_cache_collector.go): node = MemTotal - MemFree (no MemAvailable
    credit, meminfo.go:107-110); pod = raw cgroup usage without the
    inactive-file subtraction."""

    name = "pagecache"

    def __init__(self, host: Host, cache: mc.MetricCache,
                 informer: StatesInformer):
        self.host = host
        self.cache = cache
        self.informer = informer

    def collect(self, now: float) -> None:
        try:
            meminfo = self.host.meminfo()
        except (FileNotFoundError, ValueError):
            return
        if "MemTotal" in meminfo:
            used = float(meminfo["MemTotal"] - meminfo.get("MemFree", 0))
            self.cache.append(mc.NODE_MEMORY_USAGE_WITH_PAGE_CACHE, now, used)
        for meta in self.informer.get_all_pods():
            try:
                b = self.host.memory_usage_with_page_cache_bytes(
                    meta.cgroup_dir)
            except (FileNotFoundError, ValueError):
                continue
            self.cache.append(mc.POD_MEMORY_USAGE_WITH_PAGE_CACHE, now,
                              float(b), {"pod_uid": meta.pod.meta.uid})


class ColdPageCollector:
    """kidled cold-page accounting (collectors/coldmemoryresource/
    cold_page_kidled.go): arms the kernel idle-page scanner once, then
    samples cold bytes for node / pods / host apps plus the node
    hot-page usage (= usage-with-page-cache - cold, cold_page.go:23-28).
    Inert when the kernel lacks kidled (cold_page_collector.go Enabled)."""

    name = "coldmemory"

    def __init__(self, host: Host, cache: mc.MetricCache,
                 informer: StatesInformer):
        self.host = host
        self.cache = cache
        self.informer = informer
        self._armed = False

    def collect(self, now: float) -> None:
        if not self.host.kidled_supported():
            return
        if not self._armed:
            try:
                self.host.kidled_start()
            except OSError:
                return
            self._armed = True
        try:
            node_cold = self.host.cold_page_bytes("")
        except (FileNotFoundError, ValueError):
            return
        self.cache.append(mc.COLD_PAGE_BYTES, now, float(node_cold))
        # the derived hot-page series alone depends on meminfo — a meminfo
        # hiccup must not drop the per-pod/per-app samples below
        try:
            meminfo = self.host.meminfo()
        except (FileNotFoundError, ValueError):
            meminfo = {}
        if "MemTotal" in meminfo:
            with_cache = meminfo["MemTotal"] - meminfo.get("MemFree", 0)
            self.cache.append(mc.NODE_MEMORY_WITH_HOT_PAGE_USAGE, now,
                              float(max(0, with_cache - node_cold)))
        for meta in self.informer.get_all_pods():
            try:
                cold = self.host.cold_page_bytes(meta.cgroup_dir)
            except (FileNotFoundError, ValueError):
                continue
            self.cache.append(mc.COLD_PAGE_BYTES, now, float(cold),
                              {"pod_uid": meta.pod.meta.uid})
        slo = self.informer.get_node_slo()
        for app in (slo.host_applications if slo else []):
            try:
                cold = self.host.cold_page_bytes(host_app_cgroup_dir(app))
            except (FileNotFoundError, ValueError):
                continue
            self.cache.append(mc.COLD_PAGE_BYTES, now, float(cold),
                              {"app": app.name})


class HostAppCollector:
    """CPU/memory usage of NodeSLO host applications (collectors/
    hostapplication/host_app_collector.go:87-140): cgroup CPU delta ->
    cores, working-set memory; first sample per app is skipped (needs a
    prior cpuacct reading)."""

    name = "hostapplication"

    def __init__(self, host: Host, cache: mc.MetricCache,
                 informer: StatesInformer):
        self.host = host
        self.cache = cache
        self.informer = informer
        self._cpu = _CgroupCPUTracker(host)

    def collect(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        if slo is None:
            return
        for app in slo.host_applications:
            cgroup_dir = host_app_cgroup_dir(app)
            labels = {"app": app.name}
            cores = self._cpu.cores(cgroup_dir, now)
            if cores is not None:
                self.cache.append(mc.HOST_APP_CPU_USAGE, now, cores, labels)
            try:
                b = self.host.memory_usage_bytes(cgroup_dir)
            except (FileNotFoundError, ValueError):
                continue
            self.cache.append(mc.HOST_APP_MEMORY_USAGE, now, float(b), labels)


class NodeStorageInfoCollector:
    """Local-storage inventory + IO rates (collectors/nodestorageinfo/
    node_info_collector.go:65-88): the disk/partition maps land in the
    metric-cache KV as `NODE_LOCAL_STORAGE_KEY` (the reference stores
    NodeLocalStorageInfo the same way); /proc/diskstats counter deltas
    additionally feed busy-percent and read/write byte-rate series. Disks are
    distinguished from partitions by /sys/block/<dev> existence."""

    name = "nodestorageinfo"
    _SECTOR = 512

    def __init__(self, host: Host, cache: mc.MetricCache):
        self.host = host
        self.cache = cache
        self._prev: Dict[str, Tuple[float, Dict[str, int]]] = {}

    def collect(self, now: float) -> None:
        rows = self.host.diskstats()
        if not rows:
            return
        sys_block = self.host.path("sys", "block")
        disks = set()
        try:
            disks = set(os.listdir(sys_block))
        except FileNotFoundError:
            pass
        partition_disk: Dict[str, str] = {}
        for r in rows:
            if r["device"] in disks:
                continue
            # longest disk name that prefixes the partition name
            owner = max((d for d in disks if r["device"].startswith(d)),
                        key=len, default="")
            if owner:
                partition_disk[r["device"]] = owner
        self.cache.set_kv(mc.NODE_LOCAL_STORAGE_KEY, {
            "disks": sorted(disks & {r["device"] for r in rows}),
            "partition_disk": partition_disk,
        })
        seen = set()
        for r in rows:
            dev = r["device"]
            if dev not in disks:
                continue
            prev = self._prev.get(dev)
            self._prev[dev] = (now, r)
            seen.add(dev)
            if prev is None or now <= prev[0]:
                continue
            dt = now - prev[0]
            p = prev[1]
            labels = {"device": dev}
            # clamp both ends: counter resets (device re-add, 32-bit wrap)
            # must not record negative utilization
            self.cache.append(
                mc.NODE_DISK_IO_UTIL, now,
                max(0.0, min(100.0, (r["io_ticks_ms"] - p["io_ticks_ms"])
                             / (10.0 * dt))), labels)
            self.cache.append(
                mc.NODE_DISK_READ_BPS, now,
                max(0.0, (r["read_sectors"] - p["read_sectors"])
                    * self._SECTOR / dt), labels)
            self.cache.append(
                mc.NODE_DISK_WRITE_BPS, now,
                max(0.0, (r["write_sectors"] - p["write_sectors"])
                    * self._SECTOR / dt), labels)
        # prune trackers for removed devices: a later same-named device
        # (dm-N churn) must start a fresh delta, and retired names must
        # not accumulate for the daemon's lifetime
        for dev in list(self._prev):
            if dev not in seen:
                del self._prev[dev]


class DeviceUsage:
    """One accelerator's instantaneous usage as returned by the injected
    device reader (the NVML poll of collector_gpu_linux.go:100-135;
    TPU builds read the same shape from the runtime's per-chip stats).
    `procs` maps pid -> (core_usage_percent, memory_bytes) for pod
    attribution."""

    __slots__ = ("minor", "core_usage", "memory_used", "memory_total",
                 "procs")

    def __init__(self, minor: int, core_usage: float, memory_used: int,
                 memory_total: int = 0,
                 procs: Optional[Dict[int, Tuple[float, int]]] = None):
        self.minor = minor
        self.core_usage = core_usage
        self.memory_used = memory_used
        self.memory_total = memory_total
        self.procs = procs or {}


class DeviceCollector:
    """Accelerator usage collector (metricsadvisor/devices/gpu/
    collector_gpu_linux.go): node series per minor, pod series by joining
    device process pids against pod cgroup.procs (the reference joins the
    other way round via /proc/<pid>/cgroup; same equivalence class)."""

    name = "device"

    def __init__(self, host: Host, cache: mc.MetricCache,
                 informer: StatesInformer,
                 device_reader: Callable[[], List[DeviceUsage]]):
        self.host = host
        self.cache = cache
        self.informer = informer
        self.device_reader = device_reader

    def _pid_to_pod(self) -> Dict[int, str]:
        # recursive: pod cgroups are interior nodes whose processes live in
        # container leaf cgroups (v2 forbids interior procs outright)
        out: Dict[int, str] = {}
        for meta in self.informer.get_all_pods():
            for pid in self.host.cgroup_procs_recursive(meta.cgroup_dir):
                out[pid] = meta.pod.meta.uid
        return out

    def collect(self, now: float) -> None:
        usages = self.device_reader()
        if not usages:
            return
        # the cgroup-tree walk is only worth it when something needs
        # attributing (TPU readers usually report device-level only)
        pid_pod = (self._pid_to_pod()
                   if any(u.procs for u in usages) else {})
        per_pod: Dict[Tuple[str, int], Tuple[float, int]] = {}
        for u in usages:
            labels = {"minor": str(u.minor)}
            self.cache.append(mc.GPU_CORE_USAGE, now, float(u.core_usage),
                              labels)
            self.cache.append(mc.GPU_MEMORY_USED, now, float(u.memory_used),
                              labels)
            if u.memory_total > 0:
                self.cache.append(mc.GPU_MEMORY_TOTAL, now,
                                  float(u.memory_total), labels)
            for pid, (core, membytes) in u.procs.items():
                uid = pid_pod.get(pid)
                if uid is None:
                    continue
                c, m = per_pod.get((uid, u.minor), (0.0, 0))
                per_pod[(uid, u.minor)] = (c + core, m + membytes)
        for (uid, minor), (core, membytes) in per_pod.items():
            labels = {"pod_uid": uid, "minor": str(minor)}
            self.cache.append(mc.POD_GPU_CORE_USAGE, now, core, labels)
            self.cache.append(mc.POD_GPU_MEMORY_USED, now, float(membytes),
                              labels)


class PodThrottledCollector:
    """Per-pod CFS throttling ratio (collectors/podthrottled/
    pod_throttled_collector.go): delta(nr_throttled)/delta(nr_periods)
    over the sample window; first sample per pod primes the baseline."""

    name = "podthrottled"

    def __init__(self, host: Host, cache: mc.MetricCache,
                 informer: StatesInformer):
        self.host = host
        self.cache = cache
        self.informer = informer
        self._prev: Dict[str, Tuple[int, int]] = {}

    def collect(self, now: float) -> None:
        live = set()
        for meta in self.informer.get_all_pods():
            uid = meta.pod.meta.uid
            live.add(uid)
            try:
                periods, throttled = self.host.cpu_stat_throttled(
                    meta.cgroup_dir)
            except (FileNotFoundError, ValueError):
                continue
            prev = self._prev.get(uid)
            self._prev[uid] = (periods, throttled)
            if prev is None:
                continue
            dp, dt = periods - prev[0], throttled - prev[1]
            if dp <= 0:
                continue  # no CFS periods elapsed (or counter reset)
            self.cache.append(mc.POD_CPU_THROTTLED_RATIO, now,
                              min(1.0, max(0.0, dt / dp)),
                              {"pod_uid": uid})
        for uid in list(self._prev):
            if uid not in live:
                del self._prev[uid]


class NodeInfoCollector:
    """Point-in-time node CPU inventory into the KV (collectors/nodeinfo/
    node_info_collector.go NodeCPUInfo): model, logical CPUs, physical
    cores, sockets, NUMA nodes — the scheduler-facing hardware shape."""

    name = "nodeinfo"

    def __init__(self, host: Host, cache: mc.MetricCache):
        self.host = host
        self.cache = cache
        self._done = False

    def collect(self, now: float) -> None:
        if self._done:
            return  # static for the node's lifetime; one read suffices
        cpus = self.host.cpu_topology()
        if not cpus:
            return
        self._done = True
        self.cache.set_kv(mc.NODE_CPU_INFO_KEY, {
            "model": self.host.cpu_model(),
            "cpus": len(cpus),
            "cores": len({(c.socket_id, c.core_id) for c in cpus}),
            "sockets": len({c.socket_id for c in cpus}),
            "numa_nodes": len({c.node_id for c in cpus}),
        })


class Advisor:
    """The collector registry + drive loop (framework/plugin.go registry;
    metrics_advisor.go:72-102 per-collector goroutines collapse into one
    tick since every collector is cheap and non-blocking here)."""

    def __init__(self, collectors: List[Collector],
                 collect_interval: float = 1.0):
        self.collectors = collectors
        self.collect_interval = collect_interval
        # collector name -> last raised exception; one failing collector
        # (e.g. a device reader hitting a driver reset) must not kill the
        # whole collection loop (the reference isolates collectors in their
        # own goroutines, metrics_advisor.go:72-102)
        self.last_errors: Dict[str, BaseException] = {}

    def collect_once(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for c in self.collectors:
            try:
                c.collect(now)
            except Exception as e:  # noqa: BLE001 - isolation boundary
                self.last_errors[c.name] = e
            else:
                self.last_errors.pop(c.name, None)

    def run(self, stop: Callable[[], bool]) -> None:
        while not stop():
            self.collect_once()
            time.sleep(self.collect_interval)


def default_advisor(host: Host, cache: mc.MetricCache,
                    informer: StatesInformer,
                    perf_reader: Optional[Callable] = None,
                    device_reader: Optional[
                        Callable[[], List[DeviceUsage]]] = None,
                    enable_page_cache: bool = False) -> Advisor:
    cs: List[Collector] = [
        NodeResourceCollector(host, cache),
        PodResourceCollector(host, cache, informer),
        BEResourceCollector(host, cache),
        SysResourceCollector(cache),
        PSICollector(host, cache, informer),
        HostAppCollector(host, cache, informer),
        NodeStorageInfoCollector(host, cache),
        PodThrottledCollector(host, cache, informer),
        NodeInfoCollector(host, cache),
        # self-gating: inert unless the kernel has kidled
        ColdPageCollector(host, cache, informer),
    ]
    if enable_page_cache:
        cs.append(PageCacheCollector(host, cache, informer))
    if perf_reader is not None:
        cs.append(PerformanceCollector(cache, informer, perf_reader))
    if device_reader is not None:
        cs.append(DeviceCollector(host, cache, informer, device_reader))
    return Advisor(cs)
