"""The koordlet daemon: component wiring + tick loop.

Capability parity with `pkg/koordlet/koordlet.go` (construct :70-125, start
order :127-188): executor → metriccache → statesinformer → metricsadvisor →
prediction → qosmanager → runtimehooks. One `Daemon.tick(now)` runs a full
agent cycle — collectors sample, prediction trains, QoS strategies enforce,
the hook reconciler levels the cgroup tree, and (on the report interval)
a NodeMetric status is produced for the control plane / snapshot ingest.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Optional

from koordinator_tpu.api import types as api
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.audit import Auditor, NULL_AUDITOR
from koordinator_tpu.koordlet.metricsadvisor import Advisor, default_advisor
from koordinator_tpu.koordlet.pleg import Pleg
from koordinator_tpu.koordlet.prediction import PeakPredictServer, PredictConfig
from koordinator_tpu.koordlet.qosmanager import (
    QoSManager,
    RecordingEvictor,
    default_qos_manager,
)
from koordinator_tpu.koordlet.resourceexecutor import Executor
from koordinator_tpu.koordlet.runtimehooks import (
    HookServer,
    Reconciler,
    default_hook_server,
)
from koordinator_tpu.koordlet.statesinformer import (
    CollectPolicy,
    NodeMetricReporter,
    StatesInformer,
)
from koordinator_tpu.koordlet.system import Host


@dataclasses.dataclass
class DaemonConfig:
    collect_interval_seconds: float = 1.0
    qos_interval_seconds: float = 10.0
    report_interval_seconds: float = 60.0
    predict_train_interval_seconds: float = 60.0
    checkpoint_path: str = ""
    # CPI collection via the native perf-group shim (the Libpfm4 feature
    # gate, koordlet_features.go:117); when enabled and no explicit
    # perf_reader is given, the Daemon probes the native shim and degrades
    # to no CPI if the host refuses perf access
    enable_perf_group: bool = False


class Daemon:
    """agent.Daemon (koordlet.go:56-58)."""

    def __init__(self, host: Host, cfg: Optional[DaemonConfig] = None,
                 auditor: Auditor = NULL_AUDITOR,
                 perf_reader: Optional[Callable] = None):
        self.host = host
        self.cfg = cfg or DaemonConfig()
        cfg = self.cfg
        self.auditor = auditor
        self.executor = Executor(host, auditor)
        self.metric_cache = mc.MetricCache()
        self.informer = StatesInformer()
        if perf_reader is None and cfg.enable_perf_group:
            from koordinator_tpu.native import cycles_instructions_reader
            perf_reader = cycles_instructions_reader()
        self.advisor: Advisor = default_advisor(
            host, self.metric_cache, self.informer, perf_reader)
        self.predictor = PeakPredictServer(
            self.informer, self.metric_cache,
            PredictConfig(checkpoint_path=cfg.checkpoint_path))
        self.predictor.restore()
        self.evictor = RecordingEvictor()
        self.qos: QoSManager = default_qos_manager(
            self.informer, self.metric_cache, self.executor, self.evictor,
            auditor)
        self.hook_server: HookServer = default_hook_server(self.informer)
        self.reconciler = Reconciler(self.informer, self.hook_server,
                                     self.executor)
        self.pleg = Pleg.for_host(host, use_inotify=False)
        self.pleg.subscribe(lambda ev: self.reconciler.reconcile_all())
        self.reporter = NodeMetricReporter(
            self.informer, self.metric_cache,
            CollectPolicy(report_interval_seconds=cfg.report_interval_seconds),
            predictor=self.predictor)
        self._last_qos = 0.0
        self._last_train = 0.0
        self._last_report = 0.0
        # bounded: the edge layer consumes reports; keep a short history
        # so a slow consumer never leaks memory in the long-running agent
        self.reports: "deque[api.NodeMetric]" = deque(maxlen=16)

    def tick(self, now: Optional[float] = None) -> Optional[api.NodeMetric]:
        """One agent cycle; returns a NodeMetric when the report interval
        elapsed."""
        now = time.time() if now is None else now
        self.advisor.collect_once(now)
        self.pleg.poll_once()
        report = None
        if now - self._last_train >= self.cfg.predict_train_interval_seconds:
            self.predictor.train_once(now)
            self.predictor.gc(
                [m.pod.meta.uid for m in self.informer.get_all_pods()])
            self._last_train = now
        if now - self._last_qos >= self.cfg.qos_interval_seconds:
            self.qos.reconcile_all(now)
            self.reconciler.reconcile_all()
            self._last_qos = now
        if now - self._last_report >= self.cfg.report_interval_seconds:
            report = self.reporter.collect(now)
            if report is not None:
                self.reports.append(report)
            self._last_report = now
            if self.cfg.checkpoint_path:
                self.predictor.checkpoint()
        return report

    def run(self, stop: Callable[[], bool],
            sleep: Callable[[float], None] = time.sleep) -> None:
        while not stop():
            self.tick()
            sleep(self.cfg.collect_interval_seconds)
