"""The koordlet daemon: component wiring + tick loop.

Capability parity with `pkg/koordlet/koordlet.go` (construct :70-125, start
order :127-188): executor → metriccache → statesinformer → metricsadvisor →
prediction → qosmanager → runtimehooks. One `Daemon.tick(now)` runs a full
agent cycle — collectors sample, prediction trains, QoS strategies enforce,
the hook reconciler levels the cgroup tree, and (on the report interval)
a NodeMetric status is produced for the control plane / snapshot ingest.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from collections import deque
from typing import Callable, Optional

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.audit import Auditor, NULL_AUDITOR
from koordinator_tpu.koordlet.metrics_defs import KoordletMetrics
from koordinator_tpu.koordlet.metricsadvisor import Advisor, default_advisor
from koordinator_tpu.koordlet.pleg import Pleg
from koordinator_tpu.koordlet.prediction import PeakPredictServer, PredictConfig
from koordinator_tpu.koordlet.qosmanager import (
    QoSManager,
    RecordingEvictor,
    default_qos_manager,
)
from koordinator_tpu.koordlet.resourceexecutor import Executor
from koordinator_tpu.koordlet.runtimehooks import (
    HookServer,
    Reconciler,
    default_hook_server,
)
from koordinator_tpu.koordlet.statesinformer import (
    CollectPolicy,
    NodeMetricReporter,
    StatesInformer,
)
from koordinator_tpu.koordlet.system import Host


@dataclasses.dataclass
class DaemonConfig:
    collect_interval_seconds: float = 1.0
    qos_interval_seconds: float = 10.0
    report_interval_seconds: float = 60.0
    predict_train_interval_seconds: float = 60.0
    checkpoint_path: str = ""
    # CPI collection via the native perf-group shim (the Libpfm4 feature
    # gate, koordlet_features.go:117); when enabled and no explicit
    # perf_reader is given, the Daemon probes the native shim and degrades
    # to no CPI if the host refuses perf access
    enable_perf_group: bool = False
    # PageCacheCollector gate (koordlet_features.go PageCacheCollector);
    # kidled cold memory self-gates on kernel support instead
    enable_page_cache: bool = False
    # CoreSched feature gate (koordlet_features.go CoreSched): when on AND
    # the kernel supports PR_SCHED_CORE, QoS cookie assignment goes through
    # the native prctl shim instead of the recording fake
    enable_core_sched: bool = False
    # AuditEventsHTTPHandler gate: >= 0 serves the paginated audit query
    # endpoint on this port (0 = ephemeral); -1 disabled
    audit_http_port: int = -1


class Daemon:
    """agent.Daemon (koordlet.go:56-58)."""

    def __init__(self, host: Host, cfg: Optional[DaemonConfig] = None,
                 auditor: Auditor = NULL_AUDITOR,
                 perf_reader: Optional[Callable] = None,
                 metrics: Optional[KoordletMetrics] = None,
                 device_reader: Optional[Callable] = None):
        self.host = host
        self.cfg = cfg or DaemonConfig()
        cfg = self.cfg
        self.auditor = auditor
        self.metrics = metrics if metrics is not None else KoordletMetrics()
        self.executor = Executor(host, auditor)
        self.metric_cache = mc.MetricCache()
        self.informer = StatesInformer()
        # optional kubelet /pods pull edge (cmd/koordlet --kubelet-addr);
        # None = pods arrive by push (set_pods)
        self.pods_puller = None
        # optional /metrics endpoint (cmd/koordlet --metrics-port)
        self.metrics_server = None
        if perf_reader is None and cfg.enable_perf_group:
            from koordinator_tpu.native import cycles_instructions_reader
            perf_reader = cycles_instructions_reader()
        self.advisor: Advisor = default_advisor(
            host, self.metric_cache, self.informer, perf_reader,
            device_reader=device_reader,
            enable_page_cache=cfg.enable_page_cache)
        self.predictor = PeakPredictServer(
            self.informer, self.metric_cache,
            PredictConfig(checkpoint_path=cfg.checkpoint_path))
        self.predictor.restore()
        self.evictor = RecordingEvictor(metrics=self.metrics)
        self.qos: QoSManager = default_qos_manager(
            self.informer, self.metric_cache, self.executor, self.evictor,
            auditor, metrics=self.metrics)
        self.audit_server = None
        if cfg.audit_http_port >= 0:
            from koordinator_tpu.koordlet.audit import AuditQueryServer
            self.audit_server = AuditQueryServer(auditor,
                                                 port=cfg.audit_http_port)
            # an ephemeral port (0) is useless unless announced
            logging.getLogger("koordlet").info(
                "audit query endpoint on 127.0.0.1:%d",
                self.audit_server.port)
        core_sched = None
        if cfg.enable_core_sched:
            from koordinator_tpu import native
            from koordinator_tpu.koordlet.runtimehooks import NativeCoreSched
            if native.core_sched_supported():
                core_sched = NativeCoreSched(host)
        self.hook_server: HookServer = default_hook_server(
            self.informer, core_sched)
        self.reconciler = Reconciler(self.informer, self.hook_server,
                                     self.executor)
        self.pleg = Pleg.for_host(host, use_inotify=False)
        self.pleg.subscribe(lambda ev: self.reconciler.reconcile_all())
        self.reporter = NodeMetricReporter(
            self.informer, self.metric_cache,
            CollectPolicy(report_interval_seconds=cfg.report_interval_seconds),
            predictor=self.predictor)
        self._last_qos = 0.0
        self._last_train = 0.0
        self._last_report = 0.0
        self._started_at: Optional[float] = None
        # bounded: the edge layer consumes reports; keep a short history
        # so a slow consumer never leaks memory in the long-running agent
        self.reports: "deque[api.NodeMetric]" = deque(maxlen=16)

    def tick(self, now: Optional[float] = None) -> Optional[api.NodeMetric]:
        """One agent cycle; returns a NodeMetric when the report interval
        elapsed."""
        now = time.time() if now is None else now
        if self.pods_puller is not None:
            # pull edge (kubelet /pods), interval-gated so a slow kubelet
            # never stalls the sampling loop; failures keep last state
            self.pods_puller.maybe_sync(now)
        self.advisor.collect_once(now)
        self.pleg.poll_once()
        self._publish_metrics(now)
        report = None
        if now - self._last_train >= self.cfg.predict_train_interval_seconds:
            self.predictor.train_once(now)
            self.predictor.gc(
                [m.pod.meta.uid for m in self.informer.get_all_pods()])
            self._last_train = now
        if now - self._last_qos >= self.cfg.qos_interval_seconds:
            self.qos.reconcile_all(now)
            self.reconciler.reconcile_all()
            self._last_qos = now
        if now - self._last_report >= self.cfg.report_interval_seconds:
            report = self.reporter.collect(now)
            if report is not None:
                self.reports.append(report)
            self._last_report = now
            if self.cfg.checkpoint_path:
                self.predictor.checkpoint()
            if report is not None:
                node = self.informer.get_node()
                node_name = node.meta.name if node else ""
                for kind, v in report.prod_reclaimable.items():
                    self.metrics.node_predicted_resource_reclaimable.labels(
                        node_name, "prodPeak", kind.name.lower(),
                        "").set(float(v))
        return report

    def _publish_metrics(self, now: float) -> None:
        """Export the latest cache samples as gauge series (the
        performance/resource-summary collectors' RecordX calls in the
        reference — here one pass over the TSDB-lite's freshest points,
        matching the columnar design)."""
        m = self.metrics
        node = self.informer.get_node()
        node_name = node.meta.name if node else ""
        if self._started_at is None:
            self._started_at = now
            m.start_time.labels(node_name).set(now)
        # the evictor is constructed before the informer knows the node
        self.evictor.node_name = node_name
        if node is not None:
            # canonical units are millicores/MiB; export CPU in cores so
            # the series divides cleanly by node_used_cpu_cores
            for kind, unit, scale in ((ResourceKind.CPU, "core", 1e-3),
                                      (ResourceKind.MEMORY, "MiB", 1.0)):
                v = node.allocatable.get(kind)
                if v is not None:
                    m.node_resource_allocatable.labels(
                        node_name, kind.name.lower(), unit).set(
                            float(v) * scale)
        cpu_cores = self.metric_cache.query(
            mc.NODE_CPU_USAGE, now - 60, now, agg="latest")
        if cpu_cores is not None:
            m.node_used_cpu_cores.labels(node_name).set(float(cpu_cores))
        # CPI = cycles / instructions per container series
        cycles = self.metric_cache.query_all(
            mc.CONTAINER_CPI_CYCLES, now - 60, now, agg="latest")
        instructions = self.metric_cache.query_all(
            mc.CONTAINER_CPI_INSTRUCTIONS, now - 60, now, agg="latest")
        for labels, cyc in cycles.items():
            ins = instructions.get(labels)
            lab = dict(labels)
            if ins:
                m.container_cpi.labels(
                    node_name, lab.get("pod_uid", ""),
                    lab.get("container", ""), "cpi").set(cyc / ins)
        # PSI per pod (some/avg10 precision, matching psi.go labels);
        # the cache keys PSI by cgroup dir — resolve to the owning pod's
        # UID so the series joins against the other pod-labelled series
        uid_of_cgroup = {meta.cgroup_dir: meta.pod.meta.uid
                         for meta in self.informer.get_all_pods()}
        for metric, resource in ((mc.PSI_CPU_SOME_AVG10, "cpu"),
                                 (mc.PSI_MEM_FULL_AVG10, "mem"),
                                 (mc.PSI_IO_FULL_AVG10, "io")):
            for labels, v in self.metric_cache.query_all(
                    metric, now - 60, now, agg="latest").items():
                uid = uid_of_cgroup.get(dict(labels).get("cgroup", ""))
                if uid is None:
                    continue
                degree = "full" if "full" in metric else "some"
                m.pod_psi.labels(node_name, uid, resource,
                                 "avg10", degree).set(float(v))

    def run(self, stop: Callable[[], bool],
            sleep: Callable[[float], None] = time.sleep) -> None:
        while not stop():
            self.tick()
            sleep(self.cfg.collect_interval_seconds)
