"""metriccache: the node-local TSDB the agent aggregates from.

Capability parity with `pkg/koordlet/metriccache/` (SURVEY.md 2.2): the
reference embeds a Prometheus TSDB + an in-memory KV; here each series is a
fixed-capacity numpy ring buffer (the agent only ever queries bounded
trailing windows — 5 min aggregate / 24h percentiles — so a ring sized by
retention/period is the idiomatic columnar equivalent, and percentile
queries become vectorized numpy reductions instead of TSDB iterators).

API parity: typed metric kinds + label sets (metric_resources.go), an
appender, range queries with the aggregation types the NodeMetric report
uses (avg/p50/p90/p95/p99/latest/count), and a KV store for point-in-time
objects (kv_storage.go).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.utils.sync import guarded_by

# --- metric kinds (metric_resources.go) ---------------------------------
NODE_CPU_USAGE = "node_cpu_usage"            # cores
NODE_MEMORY_USAGE = "node_memory_usage"      # bytes
POD_CPU_USAGE = "pod_cpu_usage"              # labels: pod_uid
POD_MEMORY_USAGE = "pod_memory_usage"
CONTAINER_CPU_USAGE = "container_cpu_usage"  # labels: pod_uid, container
CONTAINER_MEMORY_USAGE = "container_memory_usage"
BE_CPU_USAGE = "be_cpu_usage"                # BE tier total, cores
SYS_CPU_USAGE = "sys_cpu_usage"              # host system procs, cores
PSI_CPU_SOME_AVG10 = "psi_cpu_some_avg10"    # labels: cgroup
PSI_MEM_FULL_AVG10 = "psi_mem_full_avg10"
PSI_IO_FULL_AVG10 = "psi_io_full_avg10"
CONTAINER_CPI_CYCLES = "container_cpi_cycles"        # labels: pod_uid, container
CONTAINER_CPI_INSTRUCTIONS = "container_cpi_instructions"
HOST_APP_CPU_USAGE = "host_app_cpu_usage"    # labels: app
HOST_APP_MEMORY_USAGE = "host_app_memory_usage"  # labels: app
# kidled cold memory; labels: {} = node, pod_uid = pod, app = host app
COLD_PAGE_BYTES = "cold_page_bytes"
# usage WITHOUT the inactive-file subtraction (pagecache collector)
NODE_MEMORY_USAGE_WITH_PAGE_CACHE = "node_memory_usage_with_page_cache"
POD_MEMORY_USAGE_WITH_PAGE_CACHE = "pod_memory_usage_with_page_cache"
# usage counting only HOT page cache: with_page_cache - cold (kidled)
NODE_MEMORY_WITH_HOT_PAGE_USAGE = "node_memory_with_hot_page_usage"
# accelerator devices; labels: minor (+ pod_uid for the pod-level series)
GPU_CORE_USAGE = "gpu_core_usage"            # percent of device cores
GPU_MEMORY_USED = "gpu_memory_used"          # bytes
GPU_MEMORY_TOTAL = "gpu_memory_total"        # bytes (device capacity)
POD_GPU_CORE_USAGE = "pod_gpu_core_usage"    # labels: pod_uid, minor
POD_GPU_MEMORY_USED = "pod_gpu_memory_used"
# local storage; labels: device
NODE_DISK_IO_UTIL = "node_disk_io_util"      # percent busy
NODE_DISK_READ_BPS = "node_disk_read_bps"    # bytes/s
NODE_DISK_WRITE_BPS = "node_disk_write_bps"

# CFS throttling pressure: delta(nr_throttled)/delta(nr_periods) in [0,1]
POD_CPU_THROTTLED_RATIO = "pod_cpu_throttled_ratio"  # labels: pod_uid

# KV keys (kv_storage.go point-in-time objects)
NODE_LOCAL_STORAGE_KEY = "node_local_storage_info"
NODE_CPU_INFO_KEY = "node_cpu_info"

AGGREGATIONS = ("avg", "p50", "p90", "p95", "p99", "latest", "count", "max")

_SeriesKey = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(metric: str, labels: Optional[Dict[str, str]]) -> _SeriesKey:
    return metric, tuple(sorted((labels or {}).items()))


class _Ring:
    """Fixed-capacity (ts, value) ring with monotonically increasing ts."""

    __slots__ = ("ts", "val", "cap", "n", "head")

    def __init__(self, cap: int):
        self.cap = cap
        self.ts = np.zeros(cap, np.float64)
        self.val = np.zeros(cap, np.float64)
        self.n = 0
        self.head = 0  # next write slot

    def append(self, ts: float, value: float) -> None:
        self.ts[self.head] = ts
        self.val[self.head] = value
        self.head = (self.head + 1) % self.cap
        self.n = min(self.n + 1, self.cap)

    def window(self, start: float, end: float) -> np.ndarray:
        """Values with start <= ts <= end, oldest-first."""
        if self.n < self.cap:
            ts, val = self.ts[:self.n], self.val[:self.n]
        else:
            idx = np.r_[self.head:self.cap, 0:self.head]
            ts, val = self.ts[idx], self.val[idx]
        lo = bisect.bisect_left(ts, start)
        hi = bisect.bisect_right(ts, end)
        return val[lo:hi]

    def latest(self) -> Optional[Tuple[float, float]]:
        if self.n == 0:
            return None
        i = (self.head - 1) % self.cap
        return float(self.ts[i]), float(self.val[i])


@guarded_by(_series="_lock", _kv="_lock", _cap="publish-once")
class MetricCache:
    """Thread-safe append/query store (MetricCache interface,
    metric_cache.go:56-60)."""

    def __init__(self, capacity_per_series: int = 4096):
        self._cap = capacity_per_series
        self._series: Dict[_SeriesKey, _Ring] = {}
        self._kv: Dict[str, object] = {}
        self._lock = threading.Lock()

    # --- appender -------------------------------------------------------
    def append(self, metric: str, ts: float, value: float,
               labels: Optional[Dict[str, str]] = None) -> None:
        k = _key(metric, labels)
        with self._lock:
            ring = self._series.get(k)
            if ring is None:
                ring = self._series[k] = _Ring(self._cap)
            ring.append(ts, value)

    def append_many(self,
                    samples: Sequence[Tuple[str, float, float,
                                            Optional[Dict[str, str]]]]) -> None:
        for metric, ts, value, labels in samples:
            self.append(metric, ts, value, labels)

    # --- queries --------------------------------------------------------
    def query(self, metric: str, start: float, end: float,
              labels: Optional[Dict[str, str]] = None,
              agg: str = "avg") -> Optional[float]:
        """Aggregate one series over [start, end]; None when empty."""
        if agg not in AGGREGATIONS:
            raise ValueError(f"unknown aggregation {agg!r}")
        with self._lock:
            ring = self._series.get(_key(metric, labels))
            if ring is None:
                return None
            if agg == "latest":
                latest = ring.latest()
                if latest is None or not start <= latest[0] <= end:
                    return None
                return latest[1]
            vals = ring.window(start, end)
        if vals.size == 0:
            return None if agg != "count" else 0.0
        if agg == "avg":
            return float(vals.mean())
        if agg == "count":
            return float(vals.size)
        if agg == "max":
            return float(vals.max())
        pct = {"p50": 50, "p90": 90, "p95": 95, "p99": 99}[agg]
        return float(np.percentile(vals, pct))

    def query_all(self, metric: str, start: float, end: float,
                  agg: str = "avg") -> Dict[Tuple[Tuple[str, str], ...], float]:
        """Aggregate every label-set of `metric` (e.g. all pods)."""
        with self._lock:
            keys = [k for k in self._series if k[0] == metric]
        out = {}
        for k in keys:
            v = self.query(metric, start, end, dict(k[1]), agg)
            if v is not None:
                out[k[1]] = v
        return out

    def series_labels(self, metric: str) -> List[Dict[str, str]]:
        with self._lock:
            return [dict(k[1]) for k in self._series if k[0] == metric]

    # --- KV (kv_storage.go) ---------------------------------------------
    def set_kv(self, key: str, value: object) -> None:
        with self._lock:
            self._kv[key] = value

    def get_kv(self, key: str) -> Optional[object]:
        with self._lock:
            return self._kv.get(key)
