"""resourceexecutor: the single chokepoint for kernel writes.

Capability parity with `pkg/koordlet/resourceexecutor/` (SURVEY.md 2.2):
- `Executor.update_batch(cacheable, updaters)`: skips writes whose target
  file already holds the desired value (cache + readback),
- `Executor.leveled_update_batch(...)`: for hierarchical constraint files
  (cpuset.cpus, memory.min/low) writes a top-down MERGE pass (parent value
  becomes union/max of current and target so children never exceed an
  intermediate parent) followed by a bottom-up SET pass (executor.go:32-42),
- every write is audit-logged (audit.py).

All kernel IO goes through `system.Host`, so the whole module is hermetic
under the fake-host fixture.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

from koordinator_tpu.koordlet.audit import Auditor, NULL_AUDITOR
from koordinator_tpu.koordlet.system import Host, format_cpuset, parse_cpuset


def merge_cpuset(current: str, target: str) -> str:
    """Union merge for cpuset.cpus (never shrink in the merge pass)."""
    return format_cpuset(parse_cpuset(current) + parse_cpuset(target))


def merge_max_int(current: str, target: str) -> str:
    """Max merge for memory.min/low style protections."""
    try:
        return str(max(int(current), int(target)))
    except ValueError:
        return target


# resource name -> merge function for the leveled top-down pass
MERGE_FUNCS: Dict[str, Callable[[str, str], str]] = {
    "cpuset.cpus": merge_cpuset,
    "cpuset.mems": merge_cpuset,
    "memory.min": merge_max_int,
    "memory.low": merge_max_int,
}


@dataclasses.dataclass
class CgroupUpdate:
    """One desired (cgroup_dir, resource, value) write."""

    cgroup_dir: str
    resource: str
    value: str

    @property
    def key(self) -> str:
        return f"{self.cgroup_dir}:{self.resource}"


class Executor:
    """ResourceUpdateExecutor: cacheable, audited, leveled cgroup writes."""

    def __init__(self, host: Host, auditor: Auditor = NULL_AUDITOR):
        self.host = host
        self.auditor = auditor
        self._cache: Dict[str, str] = {}

    # --- reads (CgroupReader, reader.go) --------------------------------
    def read(self, cgroup_dir: str, resource: str) -> str:
        return self.host.read_cgroup(cgroup_dir, resource)

    def try_read(self, cgroup_dir: str, resource: str) -> Optional[str]:
        try:
            return self.read(cgroup_dir, resource)
        except (FileNotFoundError, ValueError):
            return None

    # --- writes ---------------------------------------------------------
    def _write(self, up: CgroupUpdate, value: str) -> bool:
        try:
            self.host.write_cgroup(up.cgroup_dir, up.resource, value)
        except (FileNotFoundError, ValueError, OSError) as e:
            self.auditor.record("error", "resourceexecutor", "write",
                                up.key, f"{value!r}: {e}")
            return False
        self._cache[up.key] = value
        self.auditor.info("resourceexecutor", "write", up.key, value)
        return True

    def update(self, up: CgroupUpdate, cacheable: bool = True) -> bool:
        """Write one file; with cacheable=True skip when the live value
        already matches (reference cacheable updaters)."""
        if cacheable:
            if self._cache.get(up.key) == up.value:
                return True
            live = self.try_read(up.cgroup_dir, up.resource)
            if live is not None and live == up.value:
                self._cache[up.key] = up.value
                return True
        return self._write(up, up.value)

    def update_batch(self, updates: Sequence[CgroupUpdate],
                     cacheable: bool = True) -> int:
        """Returns the number of successful (or cache-skipped) updates."""
        return sum(1 for up in updates if self.update(up, cacheable))

    def leveled_update_batch(self, updates: Sequence[CgroupUpdate]) -> int:
        """Top-down merge then bottom-up set (executor.go:32-42).

        Levels = cgroup path depth. The merge pass only touches resources
        with a registered merge function; others are written in the set
        pass only.
        """
        by_depth = sorted(updates, key=lambda u: u.cgroup_dir.count("/"))
        # pass 1: top-down, write merged value so a child's target never
        # exceeds its parent's intermediate value
        for up in by_depth:
            merge = MERGE_FUNCS.get(up.resource)
            if merge is None:
                continue
            current = self.try_read(up.cgroup_dir, up.resource)
            if current is None:
                continue
            merged = merge(current, up.value)
            if merged != current:
                self._write(up, merged)
        # pass 2: bottom-up, set final values
        ok = 0
        for up in reversed(by_depth):
            if self.update(up, cacheable=False):
                ok += 1
        return ok
