"""qosmanager: the node-side QoS enforcement strategies.

Capability parity with `pkg/koordlet/qosmanager/` (SURVEY.md 2.2, 3.3):
- **CPUSuppress** — shrink the BE tier so
  `BE <= node.Capacity * SLOPercent - (nonBE pod used) - system used`
  (cpu_suppress.go:137-160), applied either as a cpuset (cores picked
  NUMA-packed, avoiding LSE/LSR cores — calculateBESuppressCPUSetPolicy
  cpu_suppress.go:653) or as a cfs quota on the BE root cgroup.
- **CPUBurst** — grant cfs burst to LS pods and scale throttled containers'
  cfs quota by node share-pool state (cpu_burst.go: idle/cooling/overload,
  1.2x increase steps).
- **CPUEvict** — evict BE pods when BE cpu satisfaction
  (realLimit/request) stays under threshold (be satisfaction eviction).
- **MemoryEvict** — evict BE pods when node memory utilization exceeds
  threshold, until the release target is met.
- **ResctrlReconcile** — LLC/MBA schemata per QoS tier (resctrl groups).
- **CgroupReconcile** — memory protections (min/low/high) per QoS tier.

Every strategy is a pure-ish `reconcile(now)` over (statesinformer,
metriccache) that emits writes through the resourceexecutor — the test
fixture asserts resulting fake-FS file contents, reference-style.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    QoSClass,
    ResourceKind,
    parse_system_qos_resource,
)
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.audit import Auditor, NULL_AUDITOR
from koordinator_tpu.koordlet.metrics_defs import KoordletMetrics
from koordinator_tpu.koordlet.resourceexecutor import CgroupUpdate, Executor
from koordinator_tpu.koordlet.statesinformer import (
    PodMeta,
    StatesInformer,
    be_pods,
)
from koordinator_tpu.koordlet.system import (
    ProcessorInfo,
    format_cpuset,
    parse_cpuset,
)

BE_ROOT = "kubepods/besteffort"
CFS_PERIOD_US = 100000
MIN_SUPPRESS_CORES = 1  # beMinCPU floor: never suppress BE below one core


# --- eviction boundary ------------------------------------------------------

Evictor = Callable[[PodMeta, str], None]  # (pod, reason)


class RecordingEvictor:
    """Default evictor: records requests; the edge layer drains them to
    the control plane (helpers/evictor in the reference calls the API
    server eviction subresource). Deduped by pod uid so a persisting
    condition doesn't grow the queue every reconcile."""

    def __init__(self, metrics: Optional[KoordletMetrics] = None,
                 node_name: str = "") -> None:
        self.evicted: List[Tuple[PodMeta, str]] = []
        self.metrics = metrics
        self.node_name = node_name
        self._pending: set = set()

    def __call__(self, pod: PodMeta, reason: str) -> None:
        uid = pod.pod.meta.uid
        if uid in self._pending:
            return
        self._pending.add(uid)
        self.evicted.append((pod, reason))
        if self.metrics is not None:
            self.metrics.pod_eviction.labels(self.node_name, reason).inc()

    def drain(self) -> List[Tuple[PodMeta, str]]:
        out, self.evicted = self.evicted, []
        self._pending.clear()
        return out


def sort_be_pods_for_eviction(pods: Sequence[PodMeta],
                              usage: Dict[str, float]) -> List[PodMeta]:
    """Eviction order: lower priority first, then higher usage first
    (helpers/common evictor sort)."""
    return sorted(pods, key=lambda p: (
        p.pod.priority if p.pod.priority is not None else 0,
        -usage.get(p.pod.meta.uid, 0.0)))


# --- CPUSuppress ------------------------------------------------------------

def suppress_cpuset_policy(need_cpus: int,
                           processors: Sequence[ProcessorInfo],
                           exclude: Sequence[int] = ()) -> List[int]:
    """Pick `need_cpus` logical cpus for the BE cpuset: prefer filling
    whole physical cores, packed within (numa node, socket) buckets, and
    never the `exclude` (LSE/LSR-pinned) cpus
    (calculateBESuppressCPUSetPolicy, cpu_suppress.go:653)."""
    avail = [p for p in processors if p.cpu_id not in set(exclude)]
    if need_cpus <= 0 or not avail:
        return []
    # cap at what is grantable: when LSE/LSR pins leave fewer cpus than
    # requested, suppress BE onto ALL remaining cpus rather than skipping
    # the update (skipping would leave BE on the pinned cores)
    need_cpus = min(need_cpus, len(avail))
    buckets: Dict[Tuple[int, int], List[ProcessorInfo]] = {}
    for p in avail:
        buckets.setdefault((p.node_id, p.socket_id), []).append(p)
    ordered = sorted(buckets.values(),
                     key=lambda b: (-len(b), min(x.cpu_id for x in b)))
    for b in ordered:
        b.sort(key=lambda x: (x.core_id, x.cpu_id))
    out: List[int] = []
    for b in ordered:
        for p in b:
            out.append(p.cpu_id)
            if len(out) >= need_cpus:
                return sorted(out)
    return sorted(out)


@dataclasses.dataclass
class CPUSuppressConfig:
    policy: str = "cpuset"          # "cpuset" | "cfsQuota"
    window_seconds: float = 60.0


class CPUSuppress:
    """suppressBECPU (cpu_suppress.go:240-298)."""

    name = "cpusuppress"

    def __init__(self, informer: StatesInformer, cache: mc.MetricCache,
                 executor: Executor,
                 cfg: Optional[CPUSuppressConfig] = None,
                 auditor: Auditor = NULL_AUDITOR,
                 metrics: Optional[KoordletMetrics] = None):
        self.informer = informer
        self.cache = cache
        self.executor = executor
        self.cfg = cfg or CPUSuppressConfig()
        self.auditor = auditor
        self.metrics = metrics

    def _suppress_cores(self, now: float) -> Optional[Tuple[float, float]]:
        """(suppress cores, LS-tier used cores) or None when disabled/no
        data."""
        node = self.informer.get_node()
        slo = self.informer.get_node_slo()
        if node is None or slo is None or not slo.threshold.enable:
            return None
        threshold = slo.threshold.cpu_suppress_threshold_percent
        win = self.cfg.window_seconds
        node_used = self.cache.query(mc.NODE_CPU_USAGE, now - win, now)
        if node_used is None:
            return None
        be_used = self.cache.query(mc.BE_CPU_USAGE, now - win, now) or 0.0
        sys_used = self.cache.query(mc.SYS_CPU_USAGE, now - win, now) or 0.0
        capacity = node.allocatable.get(ResourceKind.CPU, 0.0) / 1000.0
        # suppress(BE) := capacity*SLO% - pod(nonBE).Used - system.Used
        non_be_pod_used = max(0.0, node_used - be_used - sys_used)
        suppress = capacity * threshold / 100.0 - non_be_pod_used - sys_used
        return (max(float(MIN_SUPPRESS_CORES), suppress),
                max(0.0, node_used - be_used))

    def _lse_lsr_cpus(self) -> List[int]:
        """CPUs pinned by LSE/LSR pods (read from their cpuset files),
        plus the node's exclusive SystemQOS cores — BE may never land on
        either (cpu_suppress.go:366-376 getSystemQOSExclusiveCPU)."""
        out: List[int] = []
        for meta in self.informer.get_all_pods():
            if meta.pod.qos in (QoSClass.LSE, QoSClass.LSR):
                cpus = self.executor.try_read(meta.cgroup_dir, "cpuset.cpus")
                if cpus:
                    out.extend(parse_cpuset(cpus))
        node = self.informer.get_node()
        if node is not None:
            res = parse_system_qos_resource(node.meta.annotations)
            if res and res["exclusive"]:
                out.extend(res["cpus"])
        return sorted(set(out))

    def reconcile(self, now: float) -> None:
        computed = self._suppress_cores(now)
        if computed is None:
            return
        suppress, ls_used = computed
        if self.metrics is not None:
            node = self.informer.get_node()
            node_name = node.meta.name if node else ""
            self.metrics.be_suppress_cpu_cores.labels(
                node_name, self.cfg.policy).set(float(suppress))
            self.metrics.be_suppress_ls_used_cpu_cores.labels(
                node_name).set(ls_used)
        host = self.executor.host
        if self.cfg.policy == "cfsQuota":
            quota = int(suppress * CFS_PERIOD_US)
            self.executor.update_batch([
                CgroupUpdate(BE_ROOT, "cpu.cfs_period_us", str(CFS_PERIOD_US)),
                CgroupUpdate(BE_ROOT, "cpu.cfs_quota_us", str(quota)),
            ])
        else:
            n = max(MIN_SUPPRESS_CORES, int(math.floor(suppress)))
            cpus = suppress_cpuset_policy(n, host.cpu_topology(),
                                          exclude=self._lse_lsr_cpus())
            if not cpus:
                return
            # leveled: BE root first (merge pass keeps parents superset),
            # then every BE pod cgroup
            ups = [CgroupUpdate(BE_ROOT, "cpuset.cpus", format_cpuset(cpus))]
            for meta in be_pods(self.informer.get_all_pods()):
                ups.append(CgroupUpdate(meta.cgroup_dir, "cpuset.cpus",
                                        format_cpuset(cpus)))
            self.executor.leveled_update_batch(ups)
        self.auditor.info(self.name, "suppress", BE_ROOT,
                          f"cores={suppress:.2f} policy={self.cfg.policy}")


# --- CPUBurst ---------------------------------------------------------------

CFS_INCREASE_STEP = 1.2  # cpu_burst.go:49
SHARE_POOL_COOLING_RATIO = 0.9

NODE_IDLE, NODE_COOLING, NODE_OVERLOAD = "idle", "cooling", "overload"


class CPUBurst:
    """cfs burst + throttled-quota scaling (cpu_burst.go)."""

    name = "cpuburst"

    def __init__(self, informer: StatesInformer, cache: mc.MetricCache,
                 executor: Executor, auditor: Auditor = NULL_AUDITOR,
                 metrics: Optional[KoordletMetrics] = None):
        self.informer = informer
        self.cache = cache
        self.executor = executor
        self.auditor = auditor
        self.metrics = metrics

    def _record(self, meta: PodMeta, file: str, value: float) -> None:
        if self.metrics is None:
            return
        node = self.informer.get_node()
        node_name = node.meta.name if node else ""
        gauge = (self.metrics.container_scaled_cfs_burst_us
                 if file == "cpu.cfs_burst_us"
                 else self.metrics.container_scaled_cfs_quota_us)
        gauge.labels(node_name, meta.pod.meta.uid,
                     os.path.basename(meta.cgroup_dir)).set(value)

    def node_state(self, now: float, threshold_percent: float) -> str:
        """Share-pool usage vs threshold (getNodeStateForBurst)."""
        node = self.informer.get_node()
        if node is None:
            return NODE_OVERLOAD
        total = node.allocatable.get(ResourceKind.CPU, 0.0) / 1000.0
        used = self.cache.query(mc.NODE_CPU_USAGE, now - 60, now)
        if used is None or total <= 0:
            return NODE_OVERLOAD
        ratio = used / total
        thresh = threshold_percent / 100.0
        if ratio >= thresh:
            return NODE_OVERLOAD
        if ratio >= thresh * SHARE_POOL_COOLING_RATIO:
            return NODE_COOLING
        return NODE_IDLE

    def reconcile(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        if slo is None or slo.cpu_burst.policy == "none":
            return
        policy = slo.cpu_burst.policy
        burst_pct = slo.cpu_burst.cpu_burst_percent
        state = self.node_state(now, slo.cpu_burst.share_pool_threshold_percent)
        for meta in self.informer.get_all_pods():
            pod = meta.pod
            if pod.qos not in (QoSClass.LS, QoSClass.NONE):
                continue
            limit_milli = pod.limits.get(ResourceKind.CPU, 0.0)
            if limit_milli <= 0:
                continue
            # cfs burst: limit * burstPercent (cpuBurstOnly | auto)
            if policy in ("cpuBurstOnly", "auto"):
                burst_us = int(limit_milli / 1000.0 * burst_pct / 100.0
                               * CFS_PERIOD_US)
                self.executor.update(
                    CgroupUpdate(meta.cgroup_dir, "cpu.cfs_burst_us",
                                 str(burst_us)))
                self._record(meta, "cpu.cfs_burst_us", float(burst_us))
            if policy not in ("cfsQuotaBurstOnly", "auto"):
                continue
            # throttled-quota scaling, bounded by cfsQuotaBurstPercent
            cur = self.executor.try_read(meta.cgroup_dir, "cpu.cfs_quota_us")
            if cur is None:
                continue
            base_quota = int(limit_milli / 1000.0 * CFS_PERIOD_US)
            max_quota = int(base_quota
                            * slo.cpu_burst.cfs_quota_burst_percent / 100.0)
            quota = int(cur)
            throttled = self._throttled(meta, now)
            new_quota = quota
            if state == NODE_IDLE and throttled:
                new_quota = min(max_quota,
                                int(max(quota, base_quota) * CFS_INCREASE_STEP))
            elif state == NODE_OVERLOAD and quota > base_quota:
                new_quota = base_quota
            if new_quota != quota:
                self.executor.update(
                    CgroupUpdate(meta.cgroup_dir, "cpu.cfs_quota_us",
                                 str(new_quota)), cacheable=False)
                self._record(meta, "cpu.cfs_quota_us", float(new_quota))
                self.auditor.info(self.name, "scale_quota", meta.cgroup_dir,
                                  f"{quota}->{new_quota} state={state}")

    def _throttled(self, meta: PodMeta, now: float) -> bool:
        v = self.cache.query(mc.PSI_CPU_SOME_AVG10, now - 60, now,
                             {"cgroup": meta.cgroup_dir}, "latest")
        return bool(v and v > 0.0)


# --- CPUEvict ---------------------------------------------------------------

@dataclasses.dataclass
class CPUEvictConfig:
    window_seconds: float = 300.0
    # evict when beUsage/beLimit over this AND satisfaction under threshold
    be_usage_threshold_percent: float = 90.0


class CPUEvict:
    """BE satisfaction eviction (cpuevict plugin): when the suppressed BE
    limit starves BE pods (satisfaction = limit/request < threshold) while
    BE usage presses the limit, evict lowest-priority BE pods until the
    release target (request*(threshold-satisfaction)) is met."""

    name = "cpuevict"

    def __init__(self, informer: StatesInformer, cache: mc.MetricCache,
                 executor: Executor, evictor: Evictor,
                 cfg: Optional[CPUEvictConfig] = None,
                 auditor: Auditor = NULL_AUDITOR):
        self.informer = informer
        self.cache = cache
        self.executor = executor
        self.evictor = evictor
        self.cfg = cfg or CPUEvictConfig()
        self.auditor = auditor

    def reconcile(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        if slo is None or not slo.threshold.enable:
            return
        thresh = slo.threshold.cpu_evict_satisfaction_lower_percent
        if thresh <= 0:
            return
        pods = be_pods(self.informer.get_all_pods())
        be_request_milli = sum(
            p.pod.requests.get(ResourceKind.BATCH_CPU,
                               p.pod.requests.get(ResourceKind.CPU, 0.0))
            for p in pods)
        if be_request_milli <= 0:
            return
        # real BE limit from the suppressed cgroup
        quota = self.executor.try_read(BE_ROOT, "cpu.cfs_quota_us")
        cpus = self.executor.try_read(BE_ROOT, "cpuset.cpus")
        if quota and int(quota) > 0:
            limit_milli = int(quota) / CFS_PERIOD_US * 1000.0
        elif cpus:
            limit_milli = len(parse_cpuset(cpus)) * 1000.0
        else:
            return
        win = self.cfg.window_seconds
        be_used = self.cache.query(mc.BE_CPU_USAGE, now - win, now)
        if be_used is None:
            return
        satisfaction = limit_milli / be_request_milli
        usage_ratio = be_used * 1000.0 / max(limit_milli, 1e-9)
        usage_thresh = slo.threshold.cpu_evict_be_usage_threshold_percent \
            or self.cfg.be_usage_threshold_percent
        if satisfaction >= thresh / 100.0 or \
                usage_ratio * 100.0 < usage_thresh:
            return
        release_target = be_request_milli * (thresh / 100.0 - satisfaction)
        usage = {k[0][1]: v * 1000.0 for k, v in
                 ((tuple(lbl), u) for lbl, u in self.cache.query_all(
                     mc.POD_CPU_USAGE, now - win, now).items())}
        released = 0.0
        for meta in sort_be_pods_for_eviction(pods, usage):
            if released >= release_target:
                break
            self.evictor(meta, "cpu satisfaction below threshold")
            released += meta.pod.requests.get(
                ResourceKind.BATCH_CPU,
                meta.pod.requests.get(ResourceKind.CPU, 0.0))
            self.auditor.info(self.name, "evict", meta.pod.meta.uid,
                              f"satisfaction={satisfaction:.2f}")


# --- MemoryEvict ------------------------------------------------------------

class MemoryEvict:
    """memoryevict plugin: node memory util over evictThresholdPercent →
    evict BE pods (priority asc, usage desc) until util falls to
    evictLowerPercent."""

    name = "memoryevict"

    def __init__(self, informer: StatesInformer, cache: mc.MetricCache,
                 evictor: Evictor, auditor: Auditor = NULL_AUDITOR):
        self.informer = informer
        self.cache = cache
        self.evictor = evictor
        self.auditor = auditor

    def reconcile(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        node = self.informer.get_node()
        if slo is None or node is None or not slo.threshold.enable:
            return
        thresh = slo.threshold.memory_evict_threshold_percent
        if thresh <= 0:
            return
        lower = slo.threshold.memory_evict_lower_percent or (thresh - 2.0)
        total_mib = node.allocatable.get(ResourceKind.MEMORY, 0.0)
        used_bytes = self.cache.query(mc.NODE_MEMORY_USAGE, now - 60, now,
                                      agg="latest")
        if used_bytes is None or total_mib <= 0:
            return
        used_mib = used_bytes / (1 << 20)
        if used_mib / total_mib * 100.0 < thresh:
            return
        target_release_mib = used_mib - total_mib * lower / 100.0
        usage = {dict(lbl)["pod_uid"]: u / (1 << 20) for lbl, u in
                 self.cache.query_all(mc.POD_MEMORY_USAGE, now - 60, now,
                                      agg="latest").items()}
        released = 0.0
        for meta in sort_be_pods_for_eviction(
                be_pods(self.informer.get_all_pods()), usage):
            if released >= target_release_mib:
                break
            self.evictor(meta, "node memory usage over threshold")
            released += usage.get(
                meta.pod.meta.uid,
                meta.pod.requests.get(ResourceKind.BATCH_MEMORY,
                                      meta.pod.requests.get(
                                          ResourceKind.MEMORY, 0.0)))
            self.auditor.info(self.name, "evict", meta.pod.meta.uid,
                              f"memory used={used_mib:.0f}MiB")


# --- ResctrlReconcile -------------------------------------------------------

QOS_RESCTRL_GROUPS = {"LSR": QoSClass.LSR, "LS": QoSClass.LS,
                      "BE": QoSClass.BE}


def cat_mask(percent: float, full_mask: str) -> str:
    """Rightmost ceil(bits*percent/100) contiguous ways of the L3 mask
    (resctrl "cache ways" semantics; percent-range from NodeSLO)."""
    bits = bin(int(full_mask, 16)).count("1")
    take = max(1, math.ceil(bits * percent / 100.0))
    return format((1 << take) - 1, "x")


class ResctrlReconcile:
    """LLC/MBA schemata per QoS tier (qosmanager resctrl plugin +
    util/system resctrl.go:38-69)."""

    name = "resctrl"

    def __init__(self, informer: StatesInformer, executor: Executor,
                 auditor: Auditor = NULL_AUDITOR):
        self.informer = informer
        self.executor = executor
        self.auditor = auditor

    def reconcile(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        if slo is None:
            return
        tiers = slo.resource_qos.tiers
        host = self.executor.host
        try:
            full_mask = host.read(
                f"{host.resctrl_root}/cbm_mask").strip()
        except FileNotFoundError:
            return
        for group in QOS_RESCTRL_GROUPS:
            cfg = tiers.get(group)
            if not cfg:
                continue
            lines: Dict[str, str] = {}
            if "catRangeEndPercent" in cfg:
                lines["L3"] = "0=" + cat_mask(cfg["catRangeEndPercent"],
                                              full_mask)
            if "mbaPercent" in cfg:
                lines["MB"] = f"0={int(cfg['mbaPercent'])}"
            if lines:
                host.write_resctrl_schemata(group, lines)
                self.auditor.info(self.name, "schemata", group, str(lines))


# --- CgroupReconcile (memory QoS) -------------------------------------------

class CgroupReconcile:
    """Per-tier memory protections: LS pods get memory.min/low from their
    requests scaled by the tier config (qosmanager cgreconcile)."""

    name = "cgreconcile"

    def __init__(self, informer: StatesInformer, executor: Executor):
        self.informer = informer
        self.executor = executor

    def reconcile(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        if slo is None:
            return
        tiers = slo.resource_qos.tiers
        ups: List[CgroupUpdate] = []
        for meta in self.informer.get_all_pods():
            cfg = tiers.get(meta.pod.qos.name)
            if not cfg:
                continue
            req_bytes = int(meta.pod.requests.get(ResourceKind.MEMORY, 0.0)
                            * (1 << 20))
            if "memoryMinPercent" in cfg:
                ups.append(CgroupUpdate(
                    meta.cgroup_dir, "memory.min",
                    str(int(req_bytes * cfg["memoryMinPercent"] / 100.0))))
            if "memoryLowPercent" in cfg:
                ups.append(CgroupUpdate(
                    meta.cgroup_dir, "memory.low",
                    str(int(req_bytes * cfg["memoryLowPercent"] / 100.0))))
        if ups:
            self.executor.leveled_update_batch(ups)


# --- manager ----------------------------------------------------------------

class SystemReconcile:
    """Host-level sysctl tuning from the NodeSLO system strategy
    (sysreconcile: min_free_kbytes factor + watermark_scale_factor,
    system_file.go vm knobs). Factors are permyriad of total memory,
    matching SystemStrategy defaults."""

    name = "sysreconcile"

    def __init__(self, informer: StatesInformer, executor: Executor,
                 auditor: Auditor = NULL_AUDITOR):
        self.informer = informer
        self.executor = executor
        self.auditor = auditor

    def _write_sysctl(self, rel: str, value: str) -> None:
        host = self.executor.host
        path = os.path.join(host.proc_root, "sys", "vm", rel)
        try:
            # cacheable-write discipline: rewriting min_free_kbytes
            # triggers kernel watermark recalculation even when unchanged
            try:
                if host.read(path).strip() == value:
                    return
            except OSError:
                pass
            host.write(path, value)
            self.auditor.info("sysreconcile", "write", rel, value)
        except OSError as e:
            self.auditor.record("error", "sysreconcile", "write", rel,
                                f"{value!r}: {e}")

    def reconcile(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        if slo is None:
            return
        sys_strategy = slo.system
        mem_total_kb = self.executor.host.meminfo().get("MemTotal", 0) // 1024
        if mem_total_kb > 0 and sys_strategy.min_free_kbytes_factor > 0:
            min_free = int(mem_total_kb
                           * sys_strategy.min_free_kbytes_factor / 10000.0)
            self._write_sysctl("min_free_kbytes", str(min_free))
        if sys_strategy.watermark_scale_factor > 0:
            self._write_sysctl("watermark_scale_factor",
                               str(int(sys_strategy.watermark_scale_factor)))


# per-QoS-tier blkio weight (blkio hook/strategy: BE gets low IO weight so
# batch IO cannot starve latency-sensitive pods); paths derive from the
# single cgroup-tree layout in koordlet/system.py
from koordinator_tpu.koordlet.system import KUBEPODS_ROOT, QOS_DIRS  # noqa: E402

BLKIO_TIER_WEIGHTS = {
    KUBEPODS_ROOT: 1000,
    f"{KUBEPODS_ROOT}/{QOS_DIRS['burstable']}": 500,
    f"{KUBEPODS_ROOT}/{QOS_DIRS['besteffort']}": 100,
}


class BlkIOReconcile:
    """blkio weight per QoS tier cgroup plus per-block IO throttles from
    the NodeSLO blkio blocks (qosmanager blkio strategy,
    blkio_reconcile.go): device blocks throttle by their own name,
    podvolume blocks resolve "namespace/claim" through the PVC informer
    map to the bound volume (blkio_reconcile.go:386-394 GetVolumeName)."""

    name = "blkio"
    THROTTLE_FILES = (("read_iops", "blkio.throttle.read_iops_device"),
                      ("write_iops", "blkio.throttle.write_iops_device"),
                      ("read_bps", "blkio.throttle.read_bps_device"),
                      ("write_bps", "blkio.throttle.write_bps_device"))

    def __init__(self, informer: StatesInformer, executor: Executor,
                 weights: Optional[Dict[str, int]] = None,
                 auditor: Auditor = NULL_AUDITOR):
        self.informer = informer
        self.executor = executor
        self.weights = dict(weights or BLKIO_TIER_WEIGHTS)
        self.auditor = auditor
        # (file, device) -> value applied last reconcile; entries that
        # drop out of the desired set are RESET (0 = unlimited for
        # throttles, 100 = default cost weight) — otherwise a removed
        # block config would leave its kernel limit in force forever
        self._applied: Dict[tuple, int] = {}

    def _resolve(self, block) -> str:
        """Block name -> the device the throttle applies to; '' = skip
        (unbound podvolume claims apply nowhere yet)."""
        if block.block_type == "podvolume":
            ns, _, claim = block.name.partition("/")
            return self.informer.get_volume_name(ns, claim)
        return block.name

    def _reset_stale(self, desired: Dict[tuple, int]) -> None:
        # sorted: reset writes (and their audit records) must land in
        # the same order every process, not hash-seed order
        for (file, dev) in sorted(set(self._applied) - set(desired)):
            reset = 100 if file == "blkio.cost.weight" else 0
            self.executor.update(CgroupUpdate(BE_ROOT, file,
                                              f"{dev} {reset}"))
        self._applied = desired

    def reconcile(self, now: float) -> None:
        # IO weights only apply once the control plane distributed an SLO
        # (the reference strategy reads the NodeSLO blkio config)
        slo = self.informer.get_node_slo()
        if slo is None:
            # an SLO withdrawal still resets limits WE applied — the
            # stale-limit hazard does not care why the config vanished
            self._reset_stale({})
            return
        for tier, weight in self.weights.items():
            self.executor.update(CgroupUpdate(tier, "blkio.weight",
                                              str(weight)))
        desired: Dict[tuple, int] = {}
        for block in slo.blkio_blocks:
            dev = self._resolve(block)
            if not dev:
                continue
            for attr, file in self.THROTTLE_FILES:
                value = int(getattr(block, attr))
                if value > 0:
                    desired[(file, dev)] = value
            if block.io_weight_percent != 100:
                desired[("blkio.cost.weight", dev)] = \
                    int(block.io_weight_percent)
        for (file, dev), value in desired.items():
            self.executor.update(CgroupUpdate(BE_ROOT, file,
                                              f"{dev} {value}"))
        self._reset_stale(desired)


class QoSManager:
    """Strategy registry + tick driver (qosmanager.go:72,
    plugins/register.go:32-41)."""

    def __init__(self, strategies: Sequence[object]):
        self.strategies = list(strategies)

    def reconcile_all(self, now: float) -> None:
        for s in self.strategies:
            s.reconcile(now)


def default_qos_manager(informer: StatesInformer, cache: mc.MetricCache,
                        executor: Executor, evictor: Evictor,
                        auditor: Auditor = NULL_AUDITOR,
                        feature_gate=None,
                        metrics: Optional[KoordletMetrics] = None) -> QoSManager:
    from koordinator_tpu.features import DEFAULT_FEATURE_GATE
    gate = feature_gate or DEFAULT_FEATURE_GATE
    strategies = [
        CPUSuppress(informer, cache, executor, auditor=auditor,
                    metrics=metrics),
        CPUBurst(informer, cache, executor, auditor=auditor,
                 metrics=metrics),
        CPUEvict(informer, cache, executor, evictor, auditor=auditor),
        MemoryEvict(informer, cache, evictor, auditor=auditor),
        ResctrlReconcile(informer, executor, auditor=auditor),
        CgroupReconcile(informer, executor),
    ]
    # host-global sysctl / IO-weight writes stay behind their gates
    # (default off, koordlet_features.go SystemConfig / BlkIOReconcile)
    if gate.enabled("SystemConfig"):
        strategies.append(SystemReconcile(informer, executor,
                                          auditor=auditor))
    if gate.enabled("BlkIOReconcile"):
        strategies.append(BlkIOReconcile(informer, executor,
                                         auditor=auditor))
    return QoSManager(strategies)
