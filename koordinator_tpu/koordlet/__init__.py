"""koordlet: the node agent.

Capability parity with the reference `pkg/koordlet/` (SURVEY.md 2.2): meters
real node/pod usage from kernel interfaces, aggregates it into NodeMetric
reports for the TPU scheduler's snapshot ingest, and enforces QoS by writing
cgroup / resctrl files.

Start order mirrors koordlet.go:127-188:
executor -> metriccache -> statesinformer -> metricsadvisor -> prediction ->
qosmanager -> runtimehooks.

Everything reads/writes the kernel through `system.Host`, whose filesystem
root is redirectable — the hermetic fake-host fixture the whole test suite
uses (reference: koordlet/util/system/util_test_tool.go NewFileTestUtil).
"""
