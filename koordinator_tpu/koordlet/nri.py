"""NRI delivery mode: the runtime pushes container-lifecycle events, the
plugin answers with OCI adjustments computed by the SAME hook plugins the
proxy server and the reconciler use.

Capability parity with pkg/koordlet/runtimehooks/nri/server.go:26,68-89
(the containerd ≥1.7 path that supersedes the standalone runtime proxy):
- Configure: negotiate the event mask (RunPodSandbox, CreateContainer,
  UpdateContainer — server.go `events`).
- Synchronize: existing pods/containers at plugin (re)start; answered
  with updates so drifted containers converge without waiting for the
  reconciler.
- RunPodSandbox: pod-level hooks run and their cgroup writes are applied
  DIRECTLY through the executor (podCtx.NriDone(executor) — the sandbox
  cgroup exists by the time the event fires, and NRI has no pod-level
  adjustment payload).
- CreateContainer: container hooks run; cgroup updates + env fold into a
  ContainerAdjustment the runtime applies to the OCI spec.
- UpdateContainer: hooks run; folded into a ContainerUpdate.

The wire is the repo's framed unix-socket RPC (the runtime side is an
RpcClient; tests drive a FakeNriRuntime) instead of containerd's ttRPC
stub — same events, same payload semantics, no containerd dependency.
Like the reference (koordlet.go tolerates NRI start failure), a missing
socket degrades to the reconciler-only mode.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    ANNOTATION_EXTENDED_RESOURCE_SPEC,
    LABEL_POD_QOS,
    encode_extended_resource_spec,
    parse_extended_resource_spec,
)
from koordinator_tpu.koordlet import nri_pb2 as pb
from koordinator_tpu.koordlet.resourceexecutor import Executor
from koordinator_tpu.koordlet.runtimehooks import (
    HookContext,
    HookServer,
    Stage,
)
from koordinator_tpu.koordlet.statesinformer import PodMeta
from koordinator_tpu.runtimeproxy.rpc import RpcServer

EVENTS = ("RunPodSandbox", "CreateContainer", "UpdateContainer")

# failure policies (runtimeproxy/config; nri server Options)
POLICY_IGNORE = "Ignore"
POLICY_FAIL = "Fail"

_TYPED_FIELDS = {
    "cpu.shares": "cpu_shares",
    "cpu.cfs_quota_us": "cpu_quota",
    "cpu.cfs_period_us": "cpu_period",
}


def _pod_meta(pod: pb.NriPodSandbox) -> PodMeta:
    annotations = dict(pod.annotations)
    # NRI carries no pod spec; the webhook-written extended-resource-spec
    # annotation is the only source of batch/mid requests
    # (container_context.go FromNri -> GetExtendedResourceSpec)
    requests, limits = parse_extended_resource_spec(annotations)
    p = api.Pod(meta=api.ObjectMeta(name=pod.name, namespace=pod.namespace,
                                    uid=pod.uid, labels=dict(pod.labels),
                                    annotations=annotations),
                requests=requests, limits=limits,
                qos_label=dict(pod.labels).get(LABEL_POD_QOS, ""))
    return PodMeta(pod=p, cgroup_dir=pod.cgroup_parent or "")


def _fold_resources(ctx: HookContext, res: pb.NriLinuxResources) -> None:
    """Hook cgroup updates -> NRI resource fields (ContainerAdjustment
    semantics: typed knobs where NRI has them, `unified` for the rest)."""
    for upd in ctx.cgroup_updates:
        field = _TYPED_FIELDS.get(upd.resource)
        if field is not None:
            try:
                setattr(res, field, int(float(upd.value)))
                continue
            except ValueError:
                pass
        if upd.resource == "cpuset.cpus":
            res.cpuset_cpus = upd.value
        elif upd.resource == "cpuset.mems":
            res.cpuset_mems = upd.value
        elif upd.resource == "memory.limit_in_bytes":
            res.memory_limit = int(float(upd.value))
        else:
            res.unified[upd.resource] = upd.value


class NriServer:
    """The plugin-side event handler (NriServer in server.go)."""

    def __init__(self, hook_server: HookServer, executor: Executor,
                 failure_policy: str = POLICY_IGNORE,
                 events: tuple = EVENTS):
        self.hook_server = hook_server
        self.executor = executor
        self.failure_policy = failure_policy
        self.events = list(events)

    # -- events --------------------------------------------------------------

    def configure(self, req: pb.NriConfigureRequest
                  ) -> pb.NriConfigureResponse:
        """Negotiate the event mask; an empty runtime config keeps the
        default subscription (server.go Configure)."""
        resp = pb.NriConfigureResponse()
        events = self.events
        if req.config:
            import json
            try:
                cfg = json.loads(req.config)
                events = list(cfg.get("events", events)) or events
            except ValueError:
                pass  # malformed runtime config keeps defaults
        resp.events.extend(events)
        return resp

    def synchronize(self, req: pb.NriSynchronizeRequest
                    ) -> pb.NriSynchronizeResponse:
        """Re-derive hook output for every existing container so state
        converges on plugin restart."""
        pods = {p.id: p for p in req.pods}
        resp = pb.NriSynchronizeResponse()
        for c in req.containers:
            pod = pods.get(c.pod_sandbox_id)
            if pod is None:
                continue
            ctx = self._run(Stage.PRE_UPDATE_CONTAINER, pod, c.name)
            if ctx is None or not ctx.cgroup_updates:
                continue
            upd = resp.updates.add()
            upd.container_id = c.id
            _fold_resources(ctx, upd.resources)
        return resp

    def run_pod_sandbox(self, req: pb.NriRunPodSandboxRequest) -> pb.NriEmpty:
        ctx = self._run(Stage.PRE_RUN_POD_SANDBOX, req.pod)
        if ctx is not None and ctx.cgroup_updates:
            # NriDone: pod-level writes go straight through the executor
            self.executor.leveled_update_batch(ctx.cgroup_updates)
        return pb.NriEmpty()

    def create_container(self, req: pb.NriCreateContainerRequest
                         ) -> pb.NriCreateContainerResponse:
        resp = pb.NriCreateContainerResponse()
        ctx = self._run(Stage.PRE_CREATE_CONTAINER, req.pod,
                        req.container.name)
        if ctx is not None:
            for k, v in ctx.env.items():
                resp.adjustment.env[k] = v
            _fold_resources(ctx, resp.adjustment.resources)
        return resp

    def update_container(self, req: pb.NriUpdateContainerRequest
                         ) -> pb.NriUpdateContainerResponse:
        resp = pb.NriUpdateContainerResponse()
        ctx = self._run(Stage.PRE_UPDATE_CONTAINER, req.pod,
                        req.container.name)
        if ctx is not None and ctx.cgroup_updates:
            upd = resp.updates.add()
            upd.container_id = req.container.id
            _fold_resources(ctx, upd.resources)
        return resp

    def _run(self, stage: Stage, pod: pb.NriPodSandbox,
             container_name: str = "") -> Optional[HookContext]:
        ctx = HookContext(pod=_pod_meta(pod), stage=stage,
                          container_name=container_name)
        try:
            self.hook_server.run_hooks(stage, ctx)
        except Exception:
            # PluginFailurePolicy (server.go): Fail surfaces the error to
            # the runtime (aborting the operation), Ignore drops the
            # adjustment and lets the container start untouched
            if self.failure_policy == POLICY_FAIL:
                raise
            return None
        return ctx

    # -- serving -------------------------------------------------------------

    def serve(self, sock_path: str) -> RpcServer:
        return RpcServer(sock_path, {
            "Configure": (pb.NriConfigureRequest, self.configure),
            "Synchronize": (pb.NriSynchronizeRequest, self.synchronize),
            "RunPodSandbox": (pb.NriRunPodSandboxRequest,
                              self.run_pod_sandbox),
            "CreateContainer": (pb.NriCreateContainerRequest,
                                self.create_container),
            "UpdateContainer": (pb.NriUpdateContainerRequest,
                                self.update_container),
        })


def pod_to_nri(meta: PodMeta, pod_id: str = "") -> pb.NriPodSandbox:
    """Typed PodMeta -> wire sandbox (the runtime side's view; used by the
    fake runtime and any in-process event source)."""
    pod = pb.NriPodSandbox(
        id=pod_id or meta.pod.meta.uid, name=meta.pod.meta.name,
        namespace=meta.pod.meta.namespace, uid=meta.pod.meta.uid,
        cgroup_parent=meta.cgroup_dir)
    for k, v in meta.pod.meta.labels.items():
        pod.labels[k] = v
    for k, v in meta.pod.meta.annotations.items():
        pod.annotations[k] = v
    if ANNOTATION_EXTENDED_RESOURCE_SPEC not in pod.annotations:
        # NRI carries no pod spec: the annotation is the only channel the
        # plugin-side _pod_meta can recover batch/mid requests from, so the
        # runtime view must carry the same spec the webhook guarantees
        # (container_context.go FromNri <- extended_resource_spec.go)
        spec = encode_extended_resource_spec(meta.pod.requests,
                                             meta.pod.limits)
        if spec:
            pod.annotations[ANNOTATION_EXTENDED_RESOURCE_SPEC] = spec
    if meta.pod.qos_label:
        pod.labels[LABEL_POD_QOS] = meta.pod.qos_label
    return pod
