"""prediction: decayed-histogram peak estimation of pod/priority usage.

Capability parity with `pkg/koordlet/prediction/` (SURVEY.md 2.2):
- VPA-style exponential-bucket histograms with half-life time decay
  (util/histogram; CPU 12h / memory 24h half-lives, config.go:28-42),
- per-pod and per-priority-class models updated from the metric cache,
- `PeakPredictServer.prediction(uid)` -> p60/p90/p95/p98/max,
- `prod_reclaimable()`: Σ over prod pods of
  max(0, request − peak·(1+safetyMargin)) with cold-start filtering
  (peak_predictor.go podReclaimablePredictor: CPU peak = p95, memory
  peak = p98), feeding NodeMetric.prodReclaimable → the Mid tier,
- disk checkpoint/restore (checkpoint.go).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Dict, List, Optional

from koordinator_tpu.api.extension import PriorityClass, ResourceKind
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.statesinformer import PodMeta, StatesInformer

_BYTES_PER_MIB = float(1 << 20)


class DecayedHistogram:
    """Exponential-bucket histogram with exponential time decay.

    Buckets: value v -> bucket floor(log(v/first)/log(ratio)); weights
    decay by 0.5 every `half_life_seconds` (decayed weight is applied
    lazily via a running reference time, the VPA trick: store weights
    scaled by 2^(t/half_life) and renormalize on overflow).
    """

    def __init__(self, first_bucket: float, ratio: float = 1.05,
                 num_buckets: int = 200,
                 half_life_seconds: float = 12 * 3600.0):
        self.first = first_bucket
        self.ratio = ratio
        self.n = num_buckets
        self.half_life = half_life_seconds
        self.weights = [0.0] * num_buckets
        self.total = 0.0
        # reference time for lazy decay; anchored to the FIRST sample's
        # timestamp (a fixed epoch would overflow 2**(t/half_life) for
        # wall-clock ts)
        self._ref_ts: Optional[float] = None

    def _bucket(self, value: float) -> int:
        if value <= self.first:
            return 0
        b = int(math.log(value / self.first) / math.log(self.ratio)) + 1
        return min(b, self.n - 1)

    def _bucket_value(self, b: int) -> float:
        # upper bound of the bucket (conservative for peaks)
        return self.first * (self.ratio ** b)

    def _scale(self, ts: float) -> float:
        # clamp the exponent: past ~40 half-lives old weights are zero
        # anyway, and an unbounded exponent overflows float64
        exp = min((ts - self._ref_ts) / self.half_life, 40.0)
        return 2.0 ** exp

    def add(self, value: float, ts: float, weight: float = 1.0) -> None:
        if self._ref_ts is None:
            self._ref_ts = ts
        w = weight * self._scale(ts)
        if w > 1e12:  # renormalize to keep floats sane
            inv = 1.0 / self._scale(ts)
            self.weights = [x * inv for x in self.weights]
            self.total *= inv
            self._ref_ts = ts
            w = weight
        b = self._bucket(value)
        self.weights[b] += w
        self.total += w

    def percentile(self, q: float) -> float:
        """q in [0,1]; 0 when empty."""
        if self.total <= 0:
            return 0.0
        target = q * self.total
        acc = 0.0
        for b, w in enumerate(self.weights):
            acc += w
            if acc >= target - 1e-12:
                return self._bucket_value(b)
        return self._bucket_value(self.n - 1)

    def to_dict(self) -> dict:
        return {"first": self.first, "ratio": self.ratio, "n": self.n,
                "half_life": self.half_life, "weights": self.weights,
                "total": self.total, "ref_ts": self._ref_ts}

    @classmethod
    def from_dict(cls, d: dict) -> "DecayedHistogram":
        h = cls(d["first"], d["ratio"], d["n"], d["half_life"])
        h.weights = list(d["weights"])
        h.total = d["total"]
        h._ref_ts = d["ref_ts"]
        return h


@dataclasses.dataclass
class PredictConfig:
    safety_margin_percent: float = 10.0
    cold_start_seconds: float = 3600.0
    cpu_half_life_seconds: float = 12 * 3600.0
    memory_half_life_seconds: float = 24 * 3600.0
    checkpoint_path: str = ""


class _Model:
    def __init__(self, cfg: PredictConfig):
        # first buckets: 10 millicores / 10 MiB
        self.cpu = DecayedHistogram(0.01, half_life_seconds=cfg.cpu_half_life_seconds)
        self.memory = DecayedHistogram(10 * _BYTES_PER_MIB,
                                       half_life_seconds=cfg.memory_half_life_seconds)


class PeakPredictServer:
    """Per-UID decayed histograms trained from the metric cache
    (predict_server.go:45-61)."""

    def __init__(self, informer: StatesInformer, cache: mc.MetricCache,
                 cfg: Optional[PredictConfig] = None):
        self.informer = informer
        self.cache = cache
        self.cfg = cfg or PredictConfig()
        self.models: Dict[str, _Model] = {}
        self.pod_start: Dict[str, float] = {}

    def _model(self, uid: str) -> _Model:
        m = self.models.get(uid)
        if m is None:
            m = self.models[uid] = _Model(self.cfg)
        return m

    def train_once(self, now: Optional[float] = None) -> None:
        """Sample current pod usages into per-pod AND per-priority models
        (the reference trains on the update interval)."""
        now = time.time() if now is None else now
        for meta in self.informer.get_all_pods():
            uid = meta.pod.meta.uid
            self.pod_start.setdefault(uid, now)
            labels = {"pod_uid": uid}
            cpu = self.cache.query(mc.POD_CPU_USAGE, now - 60, now, labels,
                                   "latest")
            mem = self.cache.query(mc.POD_MEMORY_USAGE, now - 60, now,
                                   labels, "latest")
            prio = f"priority/{meta.pod.priority_class.name}"
            if cpu is not None:
                self._model(uid).cpu.add(cpu, now)
                self._model(prio).cpu.add(cpu, now)
            if mem is not None:
                self._model(uid).memory.add(mem, now)
                self._model(prio).memory.add(mem, now)

    def prediction(self, uid: str) -> Optional[Dict[str, Dict[str, float]]]:
        """p60/p90/p95/p98/max -> {cpu: cores, memory: bytes}
        (GetPrediction, predict_server.go)."""
        m = self.models.get(uid)
        if m is None:
            return None
        out = {}
        for name, q in (("p60", 0.6), ("p90", 0.9), ("p95", 0.95),
                        ("p98", 0.98), ("max", 1.0)):
            out[name] = {"cpu": m.cpu.percentile(q),
                         "memory": m.memory.percentile(q)}
        return out

    def prod_reclaimable(self, now: Optional[float] = None) -> dict:
        """Σ max(0, request − peak·(1+margin)) over prod pods past cold
        start (peak_predictor.go AddPod/GetResult). Returns a ResourceList
        in canonical units (millicores / MiB)."""
        now = time.time() if now is None else now
        margin = (100.0 + self.cfg.safety_margin_percent) / 100.0
        cpu_milli = 0.0
        mem_mib = 0.0
        for meta in self.informer.get_all_pods():
            pod = meta.pod
            if pod.priority_class != PriorityClass.PROD:
                continue
            uid = pod.meta.uid
            start = self.pod_start.get(uid)
            if start is None or now - start <= self.cfg.cold_start_seconds:
                continue
            pred = self.prediction(uid)
            if pred is None:
                continue
            peak_cpu_milli = pred["p95"]["cpu"] * 1000.0 * margin
            peak_mem_mib = pred["p98"]["memory"] / _BYTES_PER_MIB * margin
            cpu_milli += max(0.0, pod.requests.get(ResourceKind.CPU, 0.0)
                             - peak_cpu_milli)
            mem_mib += max(0.0, pod.requests.get(ResourceKind.MEMORY, 0.0)
                           - peak_mem_mib)
        if cpu_milli <= 0 and mem_mib <= 0:
            return {}
        return {ResourceKind.CPU: cpu_milli, ResourceKind.MEMORY: mem_mib}

    # --- checkpoint (checkpoint.go) ------------------------------------
    def checkpoint(self, path: Optional[str] = None) -> None:
        path = path or self.cfg.checkpoint_path
        if not path:
            return
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        data = {
            "pod_start": self.pod_start,
            "models": {uid: {"cpu": m.cpu.to_dict(),
                             "memory": m.memory.to_dict()}
                       for uid, m in self.models.items()},
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
        os.replace(tmp, path)

    def restore(self, path: Optional[str] = None) -> bool:
        path = path or self.cfg.checkpoint_path
        if not path or not os.path.exists(path):
            return False
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        self.pod_start = dict(data.get("pod_start", {}))
        self.models = {}
        for uid, d in data.get("models", {}).items():
            m = _Model(self.cfg)
            m.cpu = DecayedHistogram.from_dict(d["cpu"])
            m.memory = DecayedHistogram.from_dict(d["memory"])
            self.models[uid] = m
        return True

    def gc(self, live_uids: List[str]) -> None:
        """Drop models of departed pods (predict_server GC loop)."""
        live = set(live_uids)
        for uid in list(self.models):
            if uid.startswith("priority/"):
                continue
            if uid not in live:
                del self.models[uid]
                self.pod_start.pop(uid, None)
