"""Kubelet pull edge: the agent reads pods from the kubelet's /pods
endpoint instead of watching the apiserver.

Capability parity with statesinformer/impl/kubelet_stub.go:38-80 — the
reference polls `GET <scheme>://<addr>:<port>/pods/` on the pods informer
resync and converts the returned PodList into the informer's pod state.
Here the same pull: an HTTP GET with a bearer token (the reference rides
the rest.Config transport), decoding a minimal PodList JSON (name/
namespace/uid/labels/annotations, per-container requests/limits, phase,
nodeName) into typed `api.Pod` rows pushed through
`StatesInformer.set_pods`, so every downstream consumer (qosmanager,
runtimehooks, reporters) is fed identically whether pods arrive by pull
or by push.
"""

from __future__ import annotations

import http.client
import json
import logging
import urllib.request
from typing import List, Optional

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    LABEL_POD_QOS,
    RESOURCE_NAMES,
    normalize_gpu_request,
)
from koordinator_tpu.koordlet.statesinformer import PodMeta, StatesInformer

log = logging.getLogger(__name__)


def _parse_quantity(v) -> float:
    """k8s quantity -> this framework's native units (milli-cpu for cpu,
    MiB for memory, raw float otherwise). Supports the suffixes kubelet
    emits for pod resources: m, Ki/Mi/Gi/Ti, k/M/G/T, plain numbers."""
    s = str(v).strip()
    try:
        return float(s)
    except ValueError:
        pass
    suffixes = {"m": 1e-3, "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12,
                "Ki": float(1 << 10), "Mi": float(1 << 20),
                "Gi": float(1 << 30), "Ti": float(1 << 40)}
    for suf in ("Ki", "Mi", "Gi", "Ti", "m", "k", "M", "G", "T"):
        if s.endswith(suf):
            try:
                return float(s[:-len(suf)]) * suffixes[suf]
            except ValueError:
                return 0.0
    return 0.0


_MEMORY_NAMES = ("memory", "kubernetes.io/batch-memory",
                 "kubernetes.io/mid-memory")


def _resource_list(d: Optional[dict]) -> dict:
    out = {}
    for name, v in (d or {}).items():
        kind = RESOURCE_NAMES.get(name)
        if kind is None:
            continue
        q = _parse_quantity(v)
        # native units: cpu -> milli, memory tiers -> MiB (extended cpu
        # tiers are declared in milli already)
        if name == "cpu":
            q = q * 1000.0
        elif name in _MEMORY_NAMES:
            q = q / float(1 << 20)
        out[kind] = out.get(kind, 0.0) + q
    return out


def _container_resources(c: dict) -> tuple:
    """One container spec -> (requests, limits, gpu_ratio) in native
    units. Extended GPU resources: requests default to limits when only
    the limits block is authored (k8s defaulting) — BOTH the core and
    the memory-ratio halves, never just one."""
    gpu_core_kind = RESOURCE_NAMES["koordinator.sh/gpu-core"]
    res = c.get("resources", {})
    raw_req, pct_req = normalize_gpu_request(
        res.get("requests") or {}, parse=_parse_quantity)
    raw_lim, pct_lim = normalize_gpu_request(
        res.get("limits") or {}, parse=_parse_quantity)
    pct_eff = pct_req if pct_req > 0 else pct_lim
    req = _resource_list(raw_req)
    lim = _resource_list(raw_lim)
    if pct_eff > 0:
        req[gpu_core_kind] = req.get(gpu_core_kind, 0.0) + pct_eff
    if pct_lim > 0:
        lim[gpu_core_kind] = lim.get(gpu_core_kind, 0.0) + pct_lim
    return req, lim, pct_eff


def pod_from_manifest(item: dict) -> api.Pod:
    """One PodList item -> typed Pod (container requests/limits summed to
    pod granularity, the shape the batched layers use). The pod-level
    footprint follows k8s effective-request rules: regular init
    containers run sequentially BEFORE the main set (each one's peak is
    its own request plus any sidecars already started); sidecars
    (initContainers with restartPolicy: Always) keep running alongside
    the main set and SUM with it; the pod charges
    max(sum(containers)+sum(sidecars), each init peak). spec.overhead
    adds to requests always, and to limits only where a limit already
    exists (kubelet never fabricates a limit for an unlimited pod)."""
    meta = item.get("metadata", {})
    spec = item.get("spec", {})
    status = item.get("status", {})
    requests: dict = {}
    limits: dict = {}
    gpu_ratio = 0.0
    for c in spec.get("containers", []):
        req, lim, pct = _container_resources(c)
        gpu_ratio += pct
        for k, v in req.items():
            requests[k] = requests.get(k, 0.0) + v
        for k, v in lim.items():
            limits[k] = limits.get(k, 0.0) + v
    side_req: dict = {}
    side_lim: dict = {}
    side_pct = 0.0
    init_req_peak: dict = {}
    init_lim_peak: dict = {}
    init_pct_peak = 0.0
    for c in spec.get("initContainers", []):
        req, lim, pct = _container_resources(c)
        if c.get("restartPolicy") == "Always":  # native sidecar
            for k, v in req.items():
                side_req[k] = side_req.get(k, 0.0) + v
            for k, v in lim.items():
                side_lim[k] = side_lim.get(k, 0.0) + v
            side_pct += pct
        else:
            for k, v in req.items():
                init_req_peak[k] = max(init_req_peak.get(k, 0.0),
                                       v + side_req.get(k, 0.0))
            for k, v in lim.items():
                init_lim_peak[k] = max(init_lim_peak.get(k, 0.0),
                                       v + side_lim.get(k, 0.0))
            init_pct_peak = max(init_pct_peak, pct + side_pct)
    for k, v in side_req.items():
        requests[k] = requests.get(k, 0.0) + v
    for k, v in side_lim.items():
        limits[k] = limits.get(k, 0.0) + v
    gpu_ratio += side_pct
    for k, v in init_req_peak.items():
        requests[k] = max(requests.get(k, 0.0), v)
    for k, v in init_lim_peak.items():
        limits[k] = max(limits.get(k, 0.0), v)
    gpu_ratio = max(gpu_ratio, init_pct_peak)
    for k, v in _resource_list(spec.get("overhead") or {}).items():
        requests[k] = requests.get(k, 0.0) + v
        if limits.get(k, 0.0) > 0:
            limits[k] += v
    labels = dict(meta.get("labels") or {})
    return api.Pod(
        meta=api.ObjectMeta(name=meta.get("name", ""),
                            namespace=meta.get("namespace", "default"),
                            uid=meta.get("uid", ""),
                            labels=labels,
                            annotations=dict(meta.get("annotations") or {})),
        requests=requests, limits=limits,
        qos_label=labels.get(LABEL_POD_QOS, ""),
        priority=int(spec.get("priority", 0) or 0),
        node_name=spec.get("nodeName", ""),
        gpu_memory_ratio=gpu_ratio,
        phase=status.get("phase", "Pending"))


class KubeletStub:
    """GET /pods/ on the kubelet (kubelet_stub.go GetAllPods).
    `insecure_tls` skips certificate verification — kubelet serving
    certs are typically self-signed, and the reference's rest.Config
    transport runs with InsecureSkipVerify in the same deployment."""

    def __init__(self, addr: str = "127.0.0.1", port: int = 10250,
                 scheme: str = "https", token: str = "",
                 timeout: float = 10.0, insecure_tls: bool = False):
        self.url = f"{scheme}://{addr}:{port}/pods/"
        self.token = token
        self.timeout = timeout
        self._ctx = None
        if scheme == "https" and insecure_tls:
            import ssl

            self._ctx = ssl.create_default_context()
            self._ctx.check_hostname = False
            self._ctx.verify_mode = ssl.CERT_NONE

    def get_all_pods(self) -> List[api.Pod]:
        req = urllib.request.Request(self.url)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        with urllib.request.urlopen(req, timeout=self.timeout,
                                    context=self._ctx) as resp:
            data = json.loads(resp.read().decode("utf-8"))
        return [pod_from_manifest(item) for item in data.get("items", [])]


class PodsPuller:
    """The pods-informer resync body: pull from the kubelet, push into the
    StatesInformer (states_pods.go syncPods). Pull failures keep the last
    good state (the reference logs and retries next resync)."""

    def __init__(self, stub: KubeletStub, informer: StatesInformer,
                 resync_interval_seconds: float = 60.0):
        self.stub = stub
        self.informer = informer
        self.resync_interval = resync_interval_seconds
        self.last_error: Optional[str] = None
        self._last_sync: Optional[float] = None

    def maybe_sync(self, now: float) -> bool:
        """Interval-gated sync for callers on a fast tick loop: the
        kubelet is polled on the resync interval (the reference's
        informer resync, ~minutes), never per agent tick — a slow
        kubelet must not stall metric sampling and QoS enforcement."""
        if (self._last_sync is not None
                and now - self._last_sync < self.resync_interval):
            return False
        self._last_sync = now
        return self.sync()

    def sync(self) -> bool:
        try:
            pods = self.stub.get_all_pods()
        except (OSError, ValueError, http.client.HTTPException) as e:
            self.last_error = str(e)
            log.warning("kubelet /pods pull failed: %s", e)
            return False
        self.last_error = None
        self.informer.set_pods([PodMeta(pod=p) for p in pods])
        return True
