"""runtimehooks: QoS injection at container lifecycle.

Capability parity with `pkg/koordlet/runtimehooks/` (SURVEY.md 2.2, 3.4):
hook plugins mutate a protocol context (cgroup writes + env/device
injection) at sandbox/container lifecycle stages. Three delivery modes
share these plugins, matching the reference:
1. **NRI events** (koordlet/nri.py — nri/server.go): the runtime pushes
   RunPodSandbox/CreateContainer/UpdateContainer and applies the
   returned OCI adjustments,
2. **proxy mode** (koordlet/proxyserver.py — proxyserver/server.go): the
   CRI-interposing runtime proxy calls the hook service around CRI ops,
3. **reconciler fallback** (below) that level-walks every known pod
   cgroup and re-applies the same rules directly
   (reconciler/reconciler.go:34-54).

Plugins (hooks/):
- **groupidentity**: per-QoS `cpu.bvt_warp_ns` (bvt.go),
- **cpuset**: the scheduler's fine-grained CPU assignment (pod annotation
  `scheduling.koordinator.sh/resource-status`) -> `cpuset.cpus`,
- **batchresource**: BE batch-cpu/batch-memory -> cpu.shares/cfs quota/
  memory limits (batchresource hook),
- **coresched**: core-scheduling cookies per QoS group through a
  `CoreSchedIface` (prctl PR_SCHED_CORE in production via the native
  shim; a fake in tests — core_sched_linux.go:44-78),
- **gpu**: device env injection (NVIDIA_VISIBLE_DEVICES) from the device
  allocation annotation.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import math
from typing import Dict, List, Optional, Protocol, Tuple

from koordinator_tpu.api.extension import (
    QoSClass,
    ResourceKind,
    parse_system_qos_resource,
)
from koordinator_tpu.koordlet.resourceexecutor import CgroupUpdate, Executor
from koordinator_tpu.koordlet.statesinformer import PodMeta, StatesInformer

CFS_PERIOD_US = 100000

ANNOTATION_RESOURCE_STATUS = "scheduling.koordinator.sh/resource-status"
ANNOTATION_DEVICE_ALLOCATED = "scheduling.koordinator.sh/device-allocated"


class Stage(enum.Enum):
    """Hook stages (runtimehooks/protocol; api.proto:148-171)."""

    PRE_RUN_POD_SANDBOX = "PreRunPodSandbox"
    PRE_CREATE_CONTAINER = "PreCreateContainer"
    PRE_UPDATE_CONTAINER = "PreUpdateContainerResources"
    POST_START_CONTAINER = "PostStartContainer"
    POST_STOP_CONTAINER = "PostStopContainer"
    POST_STOP_POD_SANDBOX = "PostStopPodSandbox"


@dataclasses.dataclass
class HookContext:
    """Mutable protocol object passed through hooks (protocol structs →
    OCI adjustments). Hooks append cgroup writes and env vars."""

    pod: PodMeta
    stage: Stage
    container_name: str = ""
    cgroup_updates: List[CgroupUpdate] = dataclasses.field(default_factory=list)
    env: Dict[str, str] = dataclasses.field(default_factory=dict)

    def add_update(self, resource: str, value: str,
                   cgroup_dir: Optional[str] = None) -> None:
        self.cgroup_updates.append(CgroupUpdate(
            cgroup_dir or self.pod.cgroup_dir, resource, value))


class CoreSchedIface(Protocol):
    def assign_cookie(self, cgroup_dir: str, group_id: str) -> None: ...


class FakeCoreSched:
    """Records cookie assignments (prctl is kernel-only)."""

    def __init__(self) -> None:
        self.assignments: Dict[str, str] = {}

    def assign_cookie(self, cgroup_dir: str, group_id: str) -> None:
        self.assignments[cgroup_dir] = group_id


class NativeCoreSched:
    """prctl-backed cookie manager: every pod in a QoS group shares one
    core-sched cookie, so SMT siblings never co-run threads of different
    groups (the coresched rule's cookie cache,
    runtimehooks/hooks/coresched/rule.go; prctl verbs per
    core_sched_linux.go:40-176 via the native shim).

    Group state is (reference pid, expected cookie) per group id — the
    live holder the kernel copies the cookie from. The reference is only
    reused while its CURRENT cookie equals the recorded one: a recycled
    pid (died + number reused by some other process, possibly in another
    group) would otherwise leak a foreign cookie into this group and let
    different QoS tiers co-run on SMT siblings. On any mismatch or death
    the group is re-keyed from the pod's own pids (cookies are compared
    by value, not identity, so a re-created cookie keeps isolating the
    group's remaining members; the reference accepts the same semantics
    on its cookie-cache eviction)."""

    def __init__(self, host, ops=None):
        if ops is None:
            from koordinator_tpu import native
            ops = native.CoreSched()
        self.host = host
        self.ops = ops
        self._group_ref: Dict[str, Tuple[int, int]] = {}

    def assign_cookie(self, cgroup_dir: str, group_id: str) -> None:
        pids = self.host.cgroup_procs_recursive(cgroup_dir)
        if not pids:
            return
        ref = self._group_ref.get(group_id)
        if ref is not None:
            ref_pid, expect = ref
            try:
                if expect != 0 and self.ops.get(ref_pid) == expect:
                    self.ops.assign(ref_pid,
                                    [p for p in pids if p != ref_pid])
                    return
            except OSError:
                pass  # reference pid gone — re-key the group below
        first, rest = pids[0], pids[1:]
        try:
            self.ops.create(first)
            cookie = self.ops.get(first)
            if rest:
                self.ops.assign(first, rest)
        except OSError:
            return  # pod exited mid-assign; next reconcile retries
        self._group_ref[group_id] = (first, cookie)


# --- hook plugins -----------------------------------------------------------

# default group identities per QoS (bvt.go defaults; overridable via
# NodeSLO resourceQOS tiers `groupIdentity`)
DEFAULT_BVT = {QoSClass.LSE: 2, QoSClass.LSR: 2, QoSClass.LS: 2,
               QoSClass.NONE: 0, QoSClass.SYSTEM: 0, QoSClass.BE: -1}


class GroupIdentityHook:
    name = "groupidentity"
    stages = (Stage.PRE_RUN_POD_SANDBOX, Stage.PRE_UPDATE_CONTAINER)

    def __init__(self, informer: StatesInformer):
        self.informer = informer

    def _bvt(self, pod: PodMeta) -> int:
        slo = self.informer.get_node_slo()
        if slo is not None:
            tier = slo.resource_qos.tiers.get(pod.pod.qos.name, {})
            if "groupIdentity" in tier:
                return int(tier["groupIdentity"])
        return DEFAULT_BVT.get(pod.pod.qos, 0)

    def apply(self, ctx: HookContext) -> None:
        ctx.add_update("cpu.bvt_warp_ns", str(self._bvt(ctx.pod)))


class CPUSetHook:
    """Scheduler's NUMA/cpuset decision -> cgroup (cpuset/rule.go). The
    annotation value is the JSON the NodeNUMAResource PreBind writes:
    {"cpuset": "0-3", "numaNodes": [0]}. SYSTEM QoS pods instead get the
    node's system-qos-resource cpuset when one is declared
    (rule.go:105-111; informer optional — without it the SYSTEM branch is
    inert)."""

    name = "cpuset"
    stages = (Stage.PRE_CREATE_CONTAINER, Stage.PRE_UPDATE_CONTAINER)

    def __init__(self, informer: Optional[StatesInformer] = None):
        self.informer = informer

    def _system_qos_cpuset(self) -> str:
        if self.informer is None:
            return ""
        node = self.informer.get_node()
        if node is None:
            return ""
        res = parse_system_qos_resource(node.meta.annotations)
        return res["cpuset"] if res else ""

    def _ls_share_pool(self) -> str:
        if self.informer is None:
            return ""
        topo = self.informer.get_topology()
        return topo.ls_share_pool if topo is not None else ""

    def apply(self, ctx: HookContext) -> None:
        if ctx.pod.pod.qos == QoSClass.SYSTEM:
            sys_set = self._system_qos_cpuset()
            if sys_set:
                ctx.add_update("cpuset.cpus", sys_set)
            return
        raw = ctx.pod.pod.meta.annotations.get(ANNOTATION_RESOURCE_STATUS)
        if not raw:
            # no fine-grained assignment: LS pods roam the share pool
            # (rule.go:113-124 — all share-pool cpus; BE stays empty, the
            # suppress policy owns its cpuset)
            if ctx.pod.pod.qos == QoSClass.LS:
                pool = self._ls_share_pool()
                if pool:
                    ctx.add_update("cpuset.cpus", pool)
            return
        try:
            status = json.loads(raw)
        except ValueError:
            return
        cpuset = status.get("cpuset", "")
        if cpuset:
            ctx.add_update("cpuset.cpus", cpuset)
        numa = status.get("numaNodes")
        if numa:
            ctx.add_update("cpuset.mems",
                           ",".join(str(int(z)) for z in numa))


class BatchResourceHook:
    """batch-cpu/batch-memory -> cgroup limits for BE pods
    (batchresource hook: shares = milli*1024/1000, quota = milli/1000 *
    period, memory.limit = batch-memory)."""

    name = "batchresource"
    # pod level at sandbox start, container level at create/update
    # (batch_resource.go:62-64 registers all three)
    stages = (Stage.PRE_RUN_POD_SANDBOX, Stage.PRE_CREATE_CONTAINER,
              Stage.PRE_UPDATE_CONTAINER)

    def apply(self, ctx: HookContext) -> None:
        pod = ctx.pod.pod
        if pod.qos != QoSClass.BE:
            return
        cpu_milli = pod.requests.get(ResourceKind.BATCH_CPU, 0.0)
        cpu_limit_milli = pod.limits.get(ResourceKind.BATCH_CPU, cpu_milli)
        mem_mib = pod.limits.get(
            ResourceKind.BATCH_MEMORY,
            pod.requests.get(ResourceKind.BATCH_MEMORY, 0.0))
        if cpu_milli > 0:
            ctx.add_update("cpu.shares",
                           str(max(2, int(cpu_milli * 1024 / 1000))))
        if cpu_limit_milli > 0:
            ctx.add_update("cpu.cfs_quota_us",
                           str(int(cpu_limit_milli / 1000.0 * CFS_PERIOD_US)))
        if mem_mib > 0:
            ctx.add_update("memory.limit_in_bytes",
                           str(int(mem_mib * (1 << 20))))


class CoreSchedHook:
    """Core-scheduling cookie per QoS group (coresched hook)."""

    name = "coresched"
    stages = (Stage.PRE_RUN_POD_SANDBOX, Stage.PRE_UPDATE_CONTAINER)

    def __init__(self, core_sched: CoreSchedIface):
        self.core_sched = core_sched

    def apply(self, ctx: HookContext) -> None:
        qos = ctx.pod.pod.qos
        if qos in (QoSClass.BE, QoSClass.LS, QoSClass.LSR):
            self.core_sched.assign_cookie(ctx.pod.cgroup_dir,
                                          f"qos/{qos.name}")


class CPUNormalizationHook:
    """Scale CFS quota by the node's CPU normalization ratio
    (runtimehooks/hooks/cpunormalization/cpu_normalization.go:121-146):
    a node R times faster than the basic model delivers a requested
    millicore with quota/R. Runs LAST so it post-processes every quota
    the earlier hooks emitted."""

    name = "cpunormalization"
    stages = (Stage.PRE_RUN_POD_SANDBOX, Stage.PRE_CREATE_CONTAINER,
              Stage.PRE_UPDATE_CONTAINER)

    def __init__(self, informer: StatesInformer):
        self.informer = informer

    def apply(self, ctx: HookContext) -> None:
        from koordinator_tpu.slo_controller.cpu_normalization import (
            node_ratio,
        )

        ratio = node_ratio(self.informer.get_node())
        if ratio <= 1.0:
            return
        for upd in ctx.cgroup_updates:
            if upd.resource != "cpu.cfs_quota_us":
                continue
            quota = int(upd.value)
            if quota > 0:
                upd.value = str(math.ceil(quota / ratio))


class GPUEnvHook:
    """Device allocation annotation -> container env (gpu hook)."""

    name = "gpu"
    stages = (Stage.PRE_CREATE_CONTAINER,)

    def apply(self, ctx: HookContext) -> None:
        raw = ctx.pod.pod.meta.annotations.get(ANNOTATION_DEVICE_ALLOCATED)
        if not raw:
            return
        try:
            alloc = json.loads(raw)
        except ValueError:
            return
        minors = [str(d.get("minor", 0)) for d in alloc.get("gpu", [])]
        if minors:
            ctx.env["NVIDIA_VISIBLE_DEVICES"] = ",".join(minors)


# --- dispatch + reconciler --------------------------------------------------

class HookServer:
    """Dispatch table stage -> plugins (hooks/hooks.go:97-99)."""

    def __init__(self, plugins: List[object]):
        self.plugins = plugins

    def run_hooks(self, stage: Stage, ctx: HookContext) -> HookContext:
        for p in self.plugins:
            if stage in p.stages:
                p.apply(ctx)
        return ctx


class Reconciler:
    """Fallback level-walk: re-derive and write every pod's hook output
    directly through the executor (reconciler/reconciler.go:34-54). In
    production this runs on PLEG events + a period; tests call
    `reconcile_all` directly."""

    def __init__(self, informer: StatesInformer, server: HookServer,
                 executor: Executor):
        self.informer = informer
        self.server = server
        self.executor = executor

    def reconcile_pod(self, meta: PodMeta) -> HookContext:
        ctx = HookContext(pod=meta, stage=Stage.PRE_UPDATE_CONTAINER)
        self.server.run_hooks(Stage.PRE_UPDATE_CONTAINER, ctx)
        if ctx.cgroup_updates:
            self.executor.leveled_update_batch(ctx.cgroup_updates)
        return ctx

    def reconcile_all(self) -> None:
        for meta in self.informer.get_all_pods():
            self.reconcile_pod(meta)


def default_hook_server(informer: StatesInformer,
                        core_sched: Optional[CoreSchedIface] = None
                        ) -> HookServer:
    return HookServer([
        GroupIdentityHook(informer),
        CPUSetHook(informer),
        BatchResourceHook(),
        CoreSchedHook(core_sched or FakeCoreSched()),
        GPUEnvHook(),
        # LAST: post-processes every cfs-quota update the hooks above emit
        CPUNormalizationHook(informer),
    ])
