"""Hermetic fake-host fixture: a writable kernel-interface tree.

Capability parity with the reference's fake kernel FS
(koordlet/util/system/util_test_tool.go NewFileTestUtil, SURVEY.md 4):
every koordlet test writes and asserts real file contents under a temp root
— no kernel, no cluster. Also used by the agent demo runner.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, Optional

from koordinator_tpu.koordlet.system import (
    RESOURCES,
    CgroupVersion,
    Host,
)


class FakeHost(Host):
    """A Host rooted in a temp dir with builder helpers."""

    def __init__(self, root: str,
                 cgroup_version: CgroupVersion = CgroupVersion.V1,
                 num_cpus: int = 8, mem_bytes: int = 16 << 30,
                 numa_nodes: int = 1):
        os.makedirs(root, exist_ok=True)
        if cgroup_version is CgroupVersion.V2:
            # marker file that _detect_version keys on
            p = os.path.join(root, "sys/fs/cgroup/cgroup.controllers")
            os.makedirs(os.path.dirname(p), exist_ok=True)
            with open(p, "w") as f:
                f.write("cpu cpuset memory io\n")
        super().__init__(root, cgroup_version)
        self.num_cpus = num_cpus
        self.mem_bytes = mem_bytes
        self._ticks_total = 0
        self._ticks_idle = 0
        self.set_proc_stat(0, 0)
        self.set_meminfo(available=mem_bytes)
        self.add_cpus(num_cpus, numa_nodes)
        for d in ("kubepods", "kubepods/burstable", "kubepods/besteffort"):
            self.make_cgroup(d)

    def _seed(self, abs_path: str, value: str) -> None:
        """Builder write: creates parent dirs (unlike Host.write, which
        must fail on vanished cgroup dirs in production)."""
        os.makedirs(os.path.dirname(abs_path), exist_ok=True)
        with open(abs_path, "w", encoding="utf-8") as f:
            f.write(value)

    # --- procfs ---------------------------------------------------------
    def set_proc_stat(self, total_ticks: int, idle_ticks: int) -> None:
        self._ticks_total, self._ticks_idle = total_ticks, idle_ticks
        busy = total_ticks - idle_ticks
        self._seed(os.path.join(self.proc_root, "stat"),
                   f"cpu {busy} 0 0 {idle_ticks} 0 0 0 0 0 0\n")

    def advance_cpu(self, busy_ticks: int, idle_ticks: int) -> None:
        """Advance the /proc/stat counters by the given deltas."""
        self.set_proc_stat(self._ticks_total + busy_ticks + idle_ticks,
                           self._ticks_idle + idle_ticks)

    def set_meminfo(self, available: int,
                    total: Optional[int] = None) -> None:
        total = self.mem_bytes if total is None else total
        self._seed(os.path.join(self.proc_root, "meminfo"),
                   f"MemTotal: {total // 1024} kB\n"
                   f"MemFree: {available // 1024} kB\n"
                   f"MemAvailable: {available // 1024} kB\n")

    def add_cpus(self, n: int, numa_nodes: int = 1) -> None:
        """Create sys/devices/system/cpu/cpuN/topology; 2 threads per
        physical core, cores split evenly over `numa_nodes` sockets."""
        per_node = max(1, n // max(1, numa_nodes))
        for cpu in range(n):
            node = min(cpu // per_node, numa_nodes - 1)
            topo = self.path(f"sys/devices/system/cpu/cpu{cpu}/topology")
            os.makedirs(topo, exist_ok=True)
            with open(os.path.join(topo, "core_id"), "w") as f:
                f.write(str(cpu // 2))
            with open(os.path.join(topo, "physical_package_id"), "w") as f:
                f.write(str(node))
            nd = self.path(f"sys/devices/system/cpu/cpu{cpu}/node{node}")
            os.makedirs(nd, exist_ok=True)
        self.invalidate_topology_cache()

    # --- cgroupfs -------------------------------------------------------
    def make_cgroup(self, cgroup_dir: str,
                    defaults: Optional[Dict[str, str]] = None) -> None:
        """Create a cgroup dir with kernel-default file contents.

        `defaults` overrides use LOGICAL (v1-convention) values; on a v2
        host they are seeded raw first (correct v2 syntax) then overridden
        through `write_cgroup`, which translates.
        """
        psi_line = ("some avg10=0.00 avg60=0.00 avg300=0.00 total=0\n"
                    "full avg10=0.00 avg60=0.00 avg300=0.00 total=0\n")
        cpus = f"0-{self.num_cpus - 1}" if self.num_cpus > 1 else "0"
        if self.cgroup_version is CgroupVersion.V1:
            raw = {
                "cpu.shares": "1024", "cpu.cfs_quota_us": "-1",
                "cpu.cfs_period_us": "100000", "cpu.cfs_burst_us": "0",
                "cpu.bvt_warp_ns": "0", "cpu.idle": "0",
                "cpuset.cpus": cpus, "cpuset.mems": "0",
                "cpuacct.usage": "0", "cpu.stat": "usage_usec 0\n",
                "memory.limit_in_bytes": str(self.mem_bytes),
                "memory.min": "0", "memory.low": "0", "memory.high": "-1",
                "memory.usage_in_bytes": "0",
                "memory.stat": "total_inactive_file 0\n",
                "cpu.pressure": psi_line, "memory.pressure": psi_line,
                "io.pressure": psi_line,
            }
        else:
            # raw v2 file contents, kernel syntax
            raw = {
                "cpu.shares": "100",          # cpu.weight default
                "cpu.cfs_quota_us": "max 100000",  # cpu.max
                "cpu.cfs_burst_us": "0",
                "cpu.bvt_warp_ns": "0", "cpu.idle": "0",
                "cpuset.cpus": cpus, "cpuset.mems": "0",
                "cpu.stat": "usage_usec 0\n",
                "memory.limit_in_bytes": "max",    # memory.max
                "memory.min": "0", "memory.low": "0",
                "memory.high": "max",
                "memory.usage_in_bytes": "0",      # memory.current
                "memory.stat": "inactive_file 0\n",
                "cpu.pressure": psi_line, "memory.pressure": psi_line,
                "io.pressure": psi_line,
            }
        for name, value in raw.items():
            res = RESOURCES.get(name)
            if res is None or not res.supported(self.cgroup_version):
                continue
            self._seed(self.cgroup_file(cgroup_dir, name), value)
        for name, value in (defaults or {}).items():
            self.write_cgroup(cgroup_dir, name, value)

    def set_cgroup_cpu_ns(self, cgroup_dir: str, total_ns: int) -> None:
        if self.cgroup_version is CgroupVersion.V1:
            self.write(self.cgroup_file(cgroup_dir, "cpuacct.usage"),
                       str(total_ns))
        else:
            self.write(self.cgroup_file(cgroup_dir, "cpu.stat"),
                       f"usage_usec {total_ns // 1000}\n")

    def set_cgroup_memory(self, cgroup_dir: str, usage_bytes: int,
                          inactive_file: int = 0) -> None:
        self.write(self.cgroup_file(cgroup_dir, "memory.usage_in_bytes"),
                   str(usage_bytes))
        self.write(self.cgroup_file(cgroup_dir, "memory.stat"),
                   f"total_inactive_file {inactive_file}\n"
                   f"inactive_file {inactive_file}\n")

    def set_psi(self, cgroup_dir: str, resource: str, some_avg10: float,
                full_avg10: float = 0.0) -> None:
        self.write(self.cgroup_file(cgroup_dir, f"{resource}.pressure"),
                   f"some avg10={some_avg10:.2f} avg60=0.00 avg300=0.00 total=0\n"
                   f"full avg10={full_avg10:.2f} avg60=0.00 avg300=0.00 total=0\n")

    def set_cgroup_throttled(self, cgroup_dir: str, nr_periods: int,
                             nr_throttled: int,
                             usage_usec: int = 0) -> None:
        self.write(self.cgroup_file(cgroup_dir, "cpu.stat"),
                   f"usage_usec {usage_usec}\n"
                   f"nr_periods {nr_periods}\n"
                   f"nr_throttled {nr_throttled}\n")

    def set_cpu_model(self, model: str) -> None:
        self._seed(os.path.join(self.proc_root, "cpuinfo"),
                   f"processor\t: 0\nmodel name\t: {model}\n")

    def set_cgroup_procs(self, cgroup_dir: str, pids: Iterable[int]) -> None:
        self.write(self.cgroup_file(cgroup_dir, "cgroup.procs"),
                   "".join(f"{p}\n" for p in pids))

    # --- kidled (idle-page scanner) -------------------------------------
    def enable_kidled(self) -> None:
        """Create the kidled sysfs knobs so kidled_supported() is true."""
        self._seed(os.path.join(self.kidled_root, "scan_period_in_seconds"),
                   "120")
        self._seed(os.path.join(self.kidled_root, "use_hierarchy"), "0")

    def set_cold_pages(self, cgroup_dir: str, cold_bytes: int) -> None:
        """Seed memory.idle_page_stats so cold_page_bytes() returns
        `cold_bytes` (one cfei bucket carries it all)."""
        self.write(self.cgroup_file(cgroup_dir, "memory.idle_page_stats"),
                   "# version: 1.0\n"
                   f"cfei {cold_bytes} 0 0 0 0 0 0 0\n"
                   "dfei 0 0 0 0 0 0 0 0\n"
                   "cfui 0 0 0 0 0 0 0 0\n"
                   "dfui 0 0 0 0 0 0 0 0\n")

    # --- block devices ---------------------------------------------------
    def set_diskstats(self, rows: Iterable[Dict[str, int]]) -> None:
        """Seed /proc/diskstats. Row keys: device (str), reads,
        read_sectors, writes, write_sectors, io_in_progress, io_ticks_ms;
        whole disks additionally get a /sys/block entry."""
        lines = []
        for i, r in enumerate(rows):
            lines.append(
                f"   8 {i * 16} {r['device']} {r.get('reads', 0)} 0 "
                f"{r.get('read_sectors', 0)} 0 {r.get('writes', 0)} 0 "
                f"{r.get('write_sectors', 0)} 0 "
                f"{r.get('io_in_progress', 0)} {r.get('io_ticks_ms', 0)} 0\n")
        self._seed(os.path.join(self.proc_root, "diskstats"), "".join(lines))

    def add_disk(self, name: str) -> None:
        os.makedirs(self.path("sys", "block", name), exist_ok=True)

    # --- resctrl --------------------------------------------------------
    def init_resctrl(self, l3_mask: str = "fff", mb_percent: int = 100,
                     num_l3: int = 1) -> None:
        lines = "".join([
            f"L3:{';'.join(f'{i}={l3_mask}' for i in range(num_l3))}\n",
            f"MB:{';'.join(f'{i}={mb_percent}' for i in range(num_l3))}\n"])
        self._seed(os.path.join(self.resctrl_root, "schemata"), lines)
        self._seed(os.path.join(self.resctrl_root, "cbm_mask"), l3_mask)
