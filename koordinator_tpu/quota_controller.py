"""ElasticQuotaProfile controller: quota-tree provisioning.

Behavior parity with pkg/quota-controller/profile/profile_controller.go
(SURVEY.md 2.3): each profile owns one ROOT ElasticQuota; on reconcile the
quota's min is set to the total allocatable of the nodes matching the
profile's nodeSelector (scaled by the resource ratio,
DecorateResourceByResourceRatio :259-272), max is unbounded, the tree id is
derived deterministically from the profile name (:96-100 hash), and the
quota is labeled a tree root / parent.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Sequence

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import selector_matches
from koordinator_tpu.webhook.elasticquota import QuotaTopology


def _tree_id(profile: api.ElasticQuotaProfile) -> str:
    key = f"{profile.meta.namespace}/{profile.meta.name}"
    return hashlib.sha256(key.encode()).hexdigest()[:16]


class QuotaProfileReconciler:
    """Reconciles profiles into root quotas; hand the result to the quota
    topology/webhook and the scheduler's quota snapshot build."""

    UNBOUNDED = float(2**62)

    def __init__(self, topology: QuotaTopology = None):
        self.topology = topology
        self.quotas: Dict[str, api.ElasticQuota] = {}

    def reconcile(self, profile: api.ElasticQuotaProfile,
                  nodes: Sequence[api.Node]) -> api.ElasticQuota:
        if not profile.tree_id:
            profile.tree_id = _tree_id(profile)
        total: Dict = {}
        for node in nodes:
            if selector_matches(profile.node_selector, node.meta.labels):
                for kind, v in node.allocatable.items():
                    total[kind] = total.get(kind, 0.0) + v
        existing = self.quotas.get(profile.quota_name)
        # a FRESH object every reconcile: the topology holds the previously
        # admitted one, so valid_update's old-vs-new comparison is against
        # real prior state, never against an in-place-mutated alias
        quota = api.ElasticQuota(
            meta=api.ObjectMeta(name=profile.quota_name,
                                namespace=profile.meta.namespace))
        quota.min = {k: total.get(k, 0.0) * profile.resource_ratio
                     for k in profile.resource_keys}
        quota.max = {k: self.UNBOUNDED for k in profile.resource_keys}
        quota.tree_id = profile.tree_id
        quota.is_parent = True
        # admission gates BEFORE the cache commit (the reference updates
        # through the apiserver, where the webhook runs first): a rejected
        # quota leaves both self.quotas and the topology unchanged
        if self.topology is not None:
            if existing is not None:
                self.topology.valid_update(quota)
            else:
                self.topology.valid_add(quota)
        self.quotas[profile.quota_name] = quota
        return quota
