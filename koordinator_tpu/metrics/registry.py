"""The shared metric-name registry: every Prometheus family name any
component catalog registers, as one constant each.

Single source of truth for the cross-file consistency pass
(`tools/lint` metric-registry analyzer): the per-component
`metrics_defs.py` catalogs import these constants instead of spelling
names inline, so two components can't silently claim the same family in
the process-global registry and a renamed series can't drift from its
dashboards. The analyzer enforces all three directions — duplicate
resolved names (MN001), bare literals in a catalog (MN002), and
constants no catalog registers (MN003).

Grouped per component, mirroring the reference's
pkg/<component>/metrics/ layout.
"""

from __future__ import annotations

# --- scheduler (pkg/scheduler/metrics/metrics.go + TPU kernel series) ---
SCHEDULER_SCHEDULING_TIMEOUT = "scheduler_scheduling_timeout"
SCHEDULER_SCHEDULE_CYCLE_SECONDS = "scheduler_schedule_cycle_seconds"
SCHEDULER_SCHEDULE_BATCH_KERNEL_SECONDS = \
    "scheduler_schedule_batch_kernel_seconds"
SCHEDULER_PODS_SCHEDULED = "scheduler_pods_scheduled"
SCHEDULER_SNAPSHOT_VERSION = "scheduler_snapshot_version"
# resilience layer (scheduler/guards.py + the frameworkext ladder)
SCHEDULER_FAILURES_CLASSIFIED = "scheduler_failures_classified"
SCHEDULER_GUARD_TRIPS = "scheduler_guard_trips"
SCHEDULER_QUARANTINED_INPUTS = "scheduler_quarantined_inputs"
SCHEDULER_DEGRADED_CYCLES = "scheduler_degraded_cycles"
SCHEDULER_DEGRADATION_LEVEL = "scheduler_degradation_level"
SCHEDULER_DELTA_REJECTED = "scheduler_delta_rejected"
# crash recovery (scheduler/journal.py + SnapshotStore checkpoints +
# the mesh-shrink ladder rung)
SCHEDULER_JOURNAL_APPENDS = "scheduler_journal_appends"
SCHEDULER_JOURNAL_BYTES = "scheduler_journal_bytes"
SCHEDULER_RECOVERY_REPLAYED_RECORDS = \
    "scheduler_recovery_replayed_records"
SCHEDULER_RECOVERY_SECONDS = "scheduler_recovery_seconds"
SCHEDULER_MESH_SHRINK_EVENTS = "scheduler_mesh_shrink_events"
SCHEDULER_MESH_SIZE = "scheduler_mesh_size"
# warm-start layer (koordinator_tpu/compilecache/): the AOT compile
# cache's hit/miss ledger, the warmer's per-program cost, and the
# replay-vs-compile split of recovery time
SCHEDULER_COMPILE_CACHE_HITS = "scheduler_compile_cache_hits"
SCHEDULER_COMPILE_CACHE_MISSES = "scheduler_compile_cache_misses"
SCHEDULER_PRECOMPILE_SECONDS = "scheduler_precompile_seconds"
SCHEDULER_RECOVERY_REPLAY_SECONDS = "scheduler_recovery_replay_seconds"
SCHEDULER_RECOVERY_COMPILE_SECONDS = \
    "scheduler_recovery_compile_seconds"
# koordtrace observability plane (koordinator_tpu/obs/): span-buffer
# overflow accounting and the per-phase cycle-time breakdown every
# closed host span feeds (phase label values come from obs/phases.py)
SCHEDULER_TRACE_SPANS_DROPPED = "scheduler_trace_spans_dropped"
SCHEDULER_CYCLE_PHASE_SECONDS = "scheduler_cycle_phase_seconds"
# koordcost resource/SLO plane (obs/slo.py + obs/memwatch.py +
# tools/costcheck.py): error-budget accounting per objective, device
# memory in use / peak as sampled at the dispatch span boundaries, the
# leak sentinel's fire count, and the drift gate's verdict ledger
SCHEDULER_SLO_BUDGET_REMAINING = "scheduler_slo_budget_remaining"
SCHEDULER_SLO_BURN_RATE = "scheduler_slo_burn_rate"
SCHEDULER_HBM_BYTES_IN_USE = "scheduler_hbm_bytes_in_use"
SCHEDULER_HBM_BYTES_PEAK = "scheduler_hbm_bytes_peak"
SCHEDULER_MEMWATCH_LEAK_EVENTS = "scheduler_memwatch_leak_events"
SCHEDULER_COST_DRIFT_CHECKS = "scheduler_cost_drift_checks"

# --- koordlet (pkg/koordlet/metrics/: cpi.go, psi.go, cpu_suppress.go,
#     cpu_burst.go, core_sched.go, prediction.go, resource_summary.go,
#     common.go) ---
KOORDLET_START_TIME = "koordlet_start_time"
KOORDLET_CONTAINER_CPI = "koordlet_container_cpi"
KOORDLET_CONTAINER_PSI = "koordlet_container_psi"
KOORDLET_POD_PSI = "koordlet_pod_psi"
KOORDLET_BE_SUPPRESS_CPU_CORES = "koordlet_be_suppress_cpu_cores"
KOORDLET_BE_SUPPRESS_LS_USED_CPU_CORES = \
    "koordlet_be_suppress_ls_used_cpu_cores"
KOORDLET_CONTAINER_SCALED_CFS_QUOTA_US = \
    "koordlet_container_scaled_cfs_quota_us"
KOORDLET_CONTAINER_SCALED_CFS_BURST_US = \
    "koordlet_container_scaled_cfs_burst_us"
KOORDLET_POD_EVICTION = "koordlet_pod_eviction"
KOORDLET_CONTAINER_CORE_SCHED_COOKIE = \
    "koordlet_container_core_sched_cookie"
KOORDLET_CORE_SCHED_COOKIE_MANAGE_STATUS = \
    "koordlet_core_sched_cookie_manage_status"
KOORDLET_NODE_PREDICTED_RESOURCE_RECLAIMABLE = \
    "koordlet_node_predicted_resource_reclaimable"
KOORDLET_NODE_RESOURCE_ALLOCATABLE = "koordlet_node_resource_allocatable"
KOORDLET_NODE_USED_CPU_CORES = "koordlet_node_used_cpu_cores"

# --- descheduler (pkg/descheduler/metrics/metrics.go) ---
DESCHEDULER_PODS_EVICTED = "descheduler_pods_evicted"
DESCHEDULER_MIGRATION_JOBS = "descheduler_migration_jobs"

# --- slo-controller (pkg/slo-controller/metrics/) ---
SLO_NODEMETRIC_RECONCILE_COUNT = "slo_controller_nodemetric_reconcile_count"
SLO_NODEMETRIC_SPEC_PARSE_COUNT = \
    "slo_controller_nodemetric_spec_parse_count"
SLO_NODESLO_RECONCILE_COUNT = "slo_controller_nodeslo_reconcile_count"
SLO_NODESLO_SPEC_PARSE_COUNT = "slo_controller_nodeslo_spec_parse_count"
SLO_NODE_RESOURCE_RECONCILE_COUNT = \
    "slo_controller_node_resource_reconcile_count"
SLO_NODE_RESOURCE_RUN_PLUGIN_STATUS = \
    "slo_controller_node_resource_run_plugin_status"
SLO_NODE_EXTENDED_RESOURCE_ALLOCATABLE = \
    "slo_controller_node_extended_resource_allocatable_internal"
