"""Metrics/observability layer: a small Prometheus-style registry shared by
every component, plus TPU kernel timing helpers.

Capability parity with the reference's per-component registries
(pkg/koordlet/metrics/ — CPI/PSI/suppress/burst/coresched/prediction
series; pkg/scheduler/metrics/metrics.go; pkg/slo-controller/metrics/;
pkg/descheduler/metrics/metrics.go): counters, gauges, histograms with
labels, and text exposition in the Prometheus scrape format. The reference
links client_golang; here a ~200-line registry is the idiomatic equivalent
— the series catalogs live next to each component
(scheduler/metrics_defs.py, koordlet/metrics_defs.py, ...) exactly like the
reference's one-file-per-series layout.

TPU addition (SURVEY.md §5 "jax profiler hooks + per-batch kernel
timing"): `kernel_timer` wraps a jitted call in a
jax.profiler.TraceAnnotation and records blocked wall time into a
histogram, so schedule-batch device time shows up as a series alongside
the control-plane counters.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from koordinator_tpu.utils.sync import guarded_by

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "global_registry", "kernel_timer",
]

# classic client_golang default buckets; fine for seconds-scale latencies
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0)


def _validate_labels(names: Sequence[str], values: Sequence[str]) -> Tuple[str, ...]:
    if len(names) != len(values):
        raise ValueError(f"expected labels {list(names)}, got {list(values)}")
    return tuple(str(v) for v in values)


@guarded_by(
    _children="_lock",
    name="publish-once",
    help="publish-once",
    label_names="publish-once",
)
class _Metric:
    """Base: a named family of label-keyed children."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str = "",
                 labels: Sequence[str] = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(labels)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], float] = {}

    def labels(self, *values: str) -> "_Bound":
        return _Bound(self, _validate_labels(self.label_names, values))

    def _set(self, key: Tuple[str, ...], value: float) -> None:
        with self._lock:
            self._children[key] = value

    def _add(self, key: Tuple[str, ...], delta: float) -> None:
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + delta

    def value(self, *values: str) -> float:
        key = _validate_labels(self.label_names, values)
        with self._lock:
            return self._children.get(key, 0.0)

    def samples(self) -> List[Tuple[str, Tuple[Tuple[str, str], ...], float]]:
        with self._lock:
            return [(self.name, tuple(zip(self.label_names, key)), v)
                    for key, v in sorted(self._children.items())]

    def clear(self) -> None:
        with self._lock:
            self._children.clear()


class _Bound:
    """A metric bound to one label vector."""

    def __init__(self, metric: "_Metric", key: Tuple[str, ...]):
        self._m = metric
        self._key = key

    def inc(self, delta: float = 1.0) -> None:
        if delta < 0:
            raise ValueError("counters only go up")
        self._m._add(self._key, delta)

    def add(self, delta: float) -> None:
        self._m._add(self._key, delta)

    def set(self, value: float) -> None:
        self._m._set(self._key, value)

    def observe(self, value: float) -> None:
        if not isinstance(self._m, Histogram):
            raise TypeError(
                f"{self._m.name} is a {self._m.kind}; observe() needs a "
                f"histogram")
        self._m.observe_key(self._key, value)

    def get(self) -> float:
        if isinstance(self._m, Histogram):
            raise TypeError(
                f"{self._m.name} is a histogram; read count()/sum(), "
                f"not get()")
        with self._m._lock:
            return self._m._children.get(self._key, 0.0)


class Counter(_Metric):
    kind = "counter"

    def inc(self, delta: float = 1.0) -> None:
        if self.label_names:
            raise ValueError(f"{self.name} needs labels()")
        if delta < 0:
            raise ValueError("counters only go up")
        self._add((), delta)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float) -> None:
        if self.label_names:
            raise ValueError(f"{self.name} needs labels()")
        self._set((), value)

    def add(self, delta: float) -> None:
        if self.label_names:
            raise ValueError(f"{self.name} needs labels()")
        self._add((), delta)


@guarded_by(
    # _lock is INHERITED from _Metric — one lock guards both the
    # scalar children and the bucket arrays
    _hist="_lock",
    buckets="publish-once",
)
class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_text: str = "",
                 labels: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_text, labels)
        self.buckets = tuple(sorted(buckets))
        # per child: [bucket counts..., +Inf count, sum]
        self._hist: Dict[Tuple[str, ...], List[float]] = {}

    def observe(self, value: float) -> None:
        if self.label_names:
            raise ValueError(f"{self.name} needs labels()")
        self.observe_key((), value)

    def observe_key(self, key: Tuple[str, ...], value: float) -> None:
        with self._lock:
            h = self._hist.setdefault(key, [0.0] * (len(self.buckets) + 2))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    h[i] += 1
            h[len(self.buckets)] += 1       # +Inf / count
            h[len(self.buckets) + 1] += value  # sum

    def count(self, *values: str) -> float:
        key = _validate_labels(self.label_names, values)
        with self._lock:
            h = self._hist.get(key)
            return 0.0 if h is None else h[len(self.buckets)]

    def sum(self, *values: str) -> float:
        key = _validate_labels(self.label_names, values)
        with self._lock:
            h = self._hist.get(key)
            return 0.0 if h is None else h[len(self.buckets) + 1]

    def count_le(self, value: float, *values: str) -> float:
        """Cumulative count of observations <= the first bucket bound
        at or above `value` (Prometheus `le` semantics: the answer is
        bucket-resolution, so thresholds should sit on bucket bounds).
        The SLO plane reads good-event counts off this."""
        key = _validate_labels(self.label_names, values)
        with self._lock:
            h = self._hist.get(key)
            if h is None:
                return 0.0
            for i, b in enumerate(self.buckets):
                if value <= b:
                    return h[i]
            return h[len(self.buckets)]  # above every finite bound

    def percentile(self, q: float, *values: str) -> Optional[float]:
        """Bucketed quantile estimate (Prometheus histogram_quantile
        semantics): find the first bucket whose CUMULATIVE count
        reaches q*total and interpolate linearly inside it, taking the
        lowest bucket's lower bound as 0 (latencies are non-negative)
        and clamping the +Inf bucket to the last finite bound.

        Returns None for an empty child. Accuracy is bounded by bucket
        width — tests/test_trace.py pins it against numpy.quantile
        within that bound.
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        key = _validate_labels(self.label_names, values)
        with self._lock:
            h = self._hist.get(key)
            if h is None:
                return None
            total = h[len(self.buckets)]
            if total <= 0:
                return None
            target = q * total
            prev_bound = 0.0
            prev_count = 0.0
            for i, b in enumerate(self.buckets):
                if h[i] >= target:
                    in_bucket = h[i] - prev_count
                    if in_bucket <= 0:
                        return float(b)
                    frac = (target - prev_count) / in_bucket
                    return prev_bound + (float(b) - prev_bound) * frac
                prev_bound = float(b)
                prev_count = h[i]
            # q falls in the +Inf bucket: no finite upper bound to
            # interpolate toward, so report the last finite bound
            # (Prometheus does the same)
            return float(self.buckets[-1]) if self.buckets else None

    def samples(self) -> List[Tuple[str, Tuple[Tuple[str, str], ...], float]]:
        out = []
        with self._lock:
            for key, h in sorted(self._hist.items()):
                base = tuple(zip(self.label_names, key))
                for i, b in enumerate(self.buckets):
                    out.append((f"{self.name}_bucket",
                                base + (("le", repr(float(b))),), h[i]))
                out.append((f"{self.name}_bucket", base + (("le", "+Inf"),),
                            h[len(self.buckets)]))
                out.append((f"{self.name}_count", base,
                            h[len(self.buckets)]))
                out.append((f"{self.name}_sum", base,
                            h[len(self.buckets) + 1]))
        return out

    def clear(self) -> None:
        with self._lock:
            self._hist.clear()


@guarded_by(_metrics="_lock", prefix="publish-once")
class Registry:
    """A named collection of metric families with text exposition."""

    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric) or \
                        existing.label_names != metric.label_names or \
                        getattr(existing, "buckets", None) != \
                        getattr(metric, "buckets", None):
                    raise ValueError(
                        f"metric {metric.name} re-registered with a "
                        f"different shape")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name: str, help_text: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._register(Counter(self._full(name), help_text, labels))  # type: ignore[return-value]

    def gauge(self, name: str, help_text: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(self._full(name), help_text, labels))  # type: ignore[return-value]

    def histogram(self, name: str, help_text: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._register(
            Histogram(self._full(name), help_text, labels, buckets))  # type: ignore[return-value]

    def _full(self, name: str) -> str:
        return f"{self.prefix}_{name}" if self.prefix else name

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(self._full(name))

    def families(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def expose(self) -> str:
        """Prometheus text format (the /metrics payload)."""
        lines: List[str] = []
        for m in self.families():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, label_pairs, value in m.samples():
                if label_pairs:
                    body = ",".join(f'{k}="{_escape(v)}"'
                                    for k, v in label_pairs)
                    lines.append(f"{name}{{{body}}} {_fmt(value)}")
                else:
                    lines.append(f"{name} {_fmt(value)}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every family (test isolation)."""
        for m in self.families():
            m.clear()


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


_GLOBAL = Registry()


def global_registry() -> Registry:
    """The process-wide registry every component catalog registers into
    (the reference's prometheus.DefaultRegisterer equivalent); components
    may still construct private Registries for tests."""
    return _GLOBAL


@contextmanager
def kernel_timer(histogram: Histogram, annotation: str,
                 labels: Tuple[str, ...] = ()):
    """Per-batch kernel timing: annotate the region for the jax profiler
    (visible in a captured trace) and record blocked wall time.

    The body must block on its device result (e.g. np.asarray of an
    output) for the recorded time to mean device time; the scheduler's
    single-readback pattern already does.
    """
    import jax.profiler

    key = _validate_labels(histogram.label_names, labels)
    with jax.profiler.TraceAnnotation(annotation):
        start = time.perf_counter()
        try:
            yield
        finally:
            histogram.observe_key(key, time.perf_counter() - start)
