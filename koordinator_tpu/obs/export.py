"""koordtrace export surface: render a span buffer plus the metrics
registry into one observability dump.

Three formats, one call:
  * chrome — Chrome trace-event JSON (load the file in Perfetto /
    chrome://tracing),
  * jsonl — one span record per line (the format profile_fullgate's
    bisection deltas and trace_fullgate's per-phase table also emit,
    so all three join on the phase names in obs/phases.py),
  * prom — the metrics `Registry.expose()` text payload.

`dump(...)` writes the chosen formats side by side into a directory;
the CLI converts a saved JSONL dump to Chrome JSON after the fact
(`python -m koordinator_tpu.obs.export --in trace.jsonl --format
chrome`), so a service dump taken in one process can be inspected in
Perfetto from another.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Iterable, List, Optional, Sequence

from koordinator_tpu.obs.trace import Tracer


def render_chrome(tracer: Tracer) -> str:
    return json.dumps(tracer.to_chrome(), sort_keys=True)


def render_jsonl(tracer: Tracer) -> str:
    return tracer.to_jsonl()


def render_prom(registry) -> str:
    return registry.expose()


def jsonl_to_chrome(lines: Iterable[str], pid: int = 0) -> dict:
    """Rebuild a Chrome trace-event object from koordtrace JSONL lines
    (the inverse of `Tracer.to_jsonl`, minus the wall-clock anchor)."""
    events = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        r = json.loads(line)
        ev = {
            "name": r["span"],
            "cat": "koordtrace",
            "ph": "X",
            "ts": r["t_start_ns"] / 1e3,
            "dur": (r["t_end_ns"] - r["t_start_ns"]) / 1e3,
            "pid": pid,
            "tid": r.get("thread", 0),
            "args": {"cycle": r.get("cycle", -1),
                     "parent": r.get("parent"),
                     **r.get("attrs", {})},
        }
        if r["t_end_ns"] == r["t_start_ns"]:
            ev["ph"] = "i"
            ev["s"] = "t"
            del ev["dur"]
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


FORMATS = ("chrome", "jsonl", "prom")


def dump(tracer: Optional[Tracer], registry=None, out_dir: str = ".",
         prefix: str = "koordtrace",
         formats: Sequence[str] = ("chrome", "jsonl")) -> List[str]:
    """Write the requested formats into `out_dir`; returns the written
    paths. `prom` requires `registry`; chrome/jsonl require `tracer`
    (each silently skipped when its source is absent, so one call
    serves every knob combination)."""
    os.makedirs(out_dir, exist_ok=True)
    paths: List[str] = []
    for fmt in formats:
        if fmt not in FORMATS:
            raise ValueError(f"unknown format {fmt!r}; want one of {FORMATS}")
        if fmt == "prom":
            if registry is None:
                continue
            path = os.path.join(out_dir, f"{prefix}.prom")
            payload = render_prom(registry)
        elif tracer is None:
            continue
        elif fmt == "chrome":
            path = os.path.join(out_dir, f"{prefix}.trace.json")
            payload = render_chrome(tracer)
        else:
            path = os.path.join(out_dir, f"{prefix}.jsonl")
            payload = render_jsonl(tracer)
        with open(path, "w") as f:
            f.write(payload)
        paths.append(path)
    return paths


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="convert a koordtrace JSONL dump to Chrome trace JSON")
    ap.add_argument("--in", dest="inp", required=True,
                    help="koordtrace JSONL file")
    ap.add_argument("--format", choices=("chrome", "jsonl"),
                    default="chrome")
    ap.add_argument("--out", default="-",
                    help="output path ('-' = stdout)")
    args = ap.parse_args(argv)
    with open(args.inp) as f:
        lines = f.readlines()
    if args.format == "chrome":
        payload = json.dumps(jsonl_to_chrome(lines), sort_keys=True)
    else:
        payload = "".join(lines)
    if args.out == "-":
        sys.stdout.write(payload + "\n")
    else:
        with open(args.out, "w") as f:
            f.write(payload)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
