"""Shared HLO `op_name` phase attribution — the one parser joining
compiled-program metadata to the koordtrace phase table.

Every kernel region is wrapped in a `jax.named_scope` phase label
(obs.phase(...)), and XLA threads those labels into each instruction's
`op_name="...koord/<phase>/..."` metadata. Two views consume that
metadata and MUST agree on the join:

  * the sampled-time view (tools/trace_fullgate.py): profiler trace
    events joined to phases through the instruction-name map;
  * the static-cost view (obs/costmodel.py): per-instruction output
    bytes and instruction counts attributed per phase.

Before koordcost the parser lived inside trace_fullgate; extracting it
here means the two views literally share one regex pair and one
innermost-scope-wins rule, so they can never drift apart.

The byte model is deliberately simple and SELF-CONSISTENT: each parsed
instruction contributes its output-buffer size (dtype width x element
count, tuples summed), and per-phase attribution always sums to the
total over the same instruction set — `costmodel` and its tests rely
on that closure property, not on matching XLA's internal buffer
assignment.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

from koordinator_tpu.obs import phases as obs_phases

__all__ = [
    "OP_NAME_RE", "PHASE_IN_OP_RE", "HloInstruction", "UNATTRIBUTED",
    "parse_instructions", "instruction_phases", "phase_of_event",
    "attribute_bytes", "coverage",
]

# one instruction line of HLO text: `%name = <type> opcode(...)`, with
# optional metadata={... op_name="..."} — the same two regexes the
# sampled and static views both join on
OP_NAME_RE = re.compile(r'%?([\w.-]+) = [^\n]*op_name="([^"]*)"')
PHASE_IN_OP_RE = re.compile(r"(koord/\w+)")

# the bucket for instructions whose op_name carries no koord/ scope
# (XLA-introduced copies, parameter plumbing, un-scoped library calls)
UNATTRIBUTED = "unattributed"

_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.-]+)\s+=\s+")
_ARRAY_TYPE_RE = re.compile(r"([a-z]+\d*)\[([\d,]*)\]")

# HLO primitive dtype -> bytes per element (pred is byte-backed)
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16,
}


@dataclass(frozen=True)
class HloInstruction:
    """One parsed HLO instruction: its name, the total output-buffer
    bytes of its result type (tuple elements summed), and the phase
    its op_name metadata resolves to (UNATTRIBUTED when none)."""

    name: str
    output_bytes: int
    phase: str


def _type_bytes(type_str: str) -> int:
    """Output-buffer bytes of one HLO result type string — an array
    type (`f32[64,32]{1,0}`), a scalar (`f32[]`), or a tuple
    (`(f32[4], s32[4])`); layout annotations are ignored and unknown
    dtypes contribute zero rather than guessing a width."""
    total = 0
    for dtype, dims in _ARRAY_TYPE_RE.findall(type_str):
        width = _DTYPE_BYTES.get(dtype)
        if width is None:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += width * n
    return total


def _result_type(line: str, start: int) -> str:
    """The result-type portion of an instruction line, starting at
    `start` (just past `= `): a parenthesized tuple runs to its
    matching close, an array type to the first space."""
    if start < len(line) and line[start] == "(":
        depth = 0
        for i in range(start, len(line)):
            if line[i] == "(":
                depth += 1
            elif line[i] == ")":
                depth -= 1
                if depth == 0:
                    return line[start:i + 1]
        return line[start:]
    end = line.find(" ", start)
    return line[start:] if end < 0 else line[start:end]


def parse_instructions(hlo_text: str,
                       phases: Optional[Iterable[str]] = None
                       ) -> List[HloInstruction]:
    """Every instruction line of `hlo_text` (entry and nested
    computations alike) as an HloInstruction, phase-resolved against
    `phases` (default: the kernel-phase table). Innermost scope wins
    when named scopes nest — op_name records the scope PATH, and the
    rightmost koord/ component is the narrowest enclosing phase."""
    table = frozenset(phases if phases is not None
                      else obs_phases.KERNEL_PHASES)
    out: List[HloInstruction] = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        name = m.group(1)
        type_str = _result_type(line, m.end())
        phase = UNATTRIBUTED
        om = re.search(r'op_name="([^"]*)"', line)
        op_name = om.group(1) if om else ""
        if op_name:
            hits = [p for p in PHASE_IN_OP_RE.findall(op_name)
                    if p in table]
            if hits:
                phase = hits[-1]  # innermost (rightmost in the path)
        out.append(HloInstruction(name=name,
                                  output_bytes=_type_bytes(type_str),
                                  phase=phase))
    return out


def instruction_phases(hlo_text: str,
                       phases: Optional[Iterable[str]] = None
                       ) -> Dict[str, str]:
    """{hlo instruction name: phase} for every instruction whose
    op_name metadata resolves to a phase — the map trace_fullgate joins
    profiler events through (CPU captures carry only bare instruction
    names). Unattributed instructions are deliberately absent: the
    sampled view reports them as coverage gaps, never as phantom
    phases."""
    return {i.name: i.phase
            for i in parse_instructions(hlo_text, phases)
            if i.phase != UNATTRIBUTED}


def phase_of_event(name: str, extra_haystacks: Iterable[str],
                   instr2phase: Dict[str, str],
                   phases: Optional[Iterable[str]] = None
                   ) -> Optional[str]:
    """Map one profiler event to a phase, or None. Exact
    instruction-name join first (the CPU stream carries nothing else);
    scope-substring match over name + string args second (TPU-style
    captures embed the full path) — innermost (longest) phase wins
    when scopes nest."""
    hit = instr2phase.get(name)
    if hit is not None:
        return hit
    table = phases if phases is not None else obs_phases.KERNEL_PHASES
    hay = [name]
    hay.extend(extra_haystacks)
    best = None
    for phase in table:
        if any(phase in h for h in hay):
            if best is None or len(phase) > len(best):
                best = phase
    return best


def attribute_bytes(hlo_text: str,
                    phases: Optional[Iterable[str]] = None
                    ) -> Dict[str, Dict[str, int]]:
    """{phase: {"instructions": n, "output_bytes": b}} over EVERY
    parsed instruction, UNATTRIBUTED bucket included — so the per-phase
    attribution sums to the totals over the same instruction set by
    construction (the closure property tests/test_costmodel.py pins)."""
    out: Dict[str, Dict[str, int]] = {}
    for instr in parse_instructions(hlo_text, phases):
        slot = out.setdefault(instr.phase,
                              {"instructions": 0, "output_bytes": 0})
        slot["instructions"] += 1
        slot["output_bytes"] += instr.output_bytes
    return out


def coverage(attribution: Dict[str, Dict[str, int]]
             ) -> Dict[str, float]:
    """Attribution coverage of one program/capture: what fraction of
    instructions (and of output bytes) resolved to a phase. A silent
    gap in the mapped set shows up here as a dropped fraction instead
    of vanishing — trace_fullgate's coverage floor reads this."""
    instr_total = sum(v["instructions"] for v in attribution.values())
    bytes_total = sum(v["output_bytes"] for v in attribution.values())
    un = attribution.get(UNATTRIBUTED,
                         {"instructions": 0, "output_bytes": 0})
    mapped_i = instr_total - un["instructions"]
    mapped_b = bytes_total - un["output_bytes"]
    return {
        "instructions_total": float(instr_total),
        "instructions_mapped": float(mapped_i),
        "instruction_coverage": (mapped_i / instr_total
                                 if instr_total else 0.0),
        "output_bytes_total": float(bytes_total),
        "output_bytes_mapped": float(mapped_b),
        "output_byte_coverage": (mapped_b / bytes_total
                                 if bytes_total else 0.0),
    }
