"""koordcost SLO plane: objectives, multi-window error-budget burn
rate, and the health verdict — computed off the metric series the
scheduler already records.

An objective is a budgeted bad-event fraction:

  * `cycle_latency_p99` — a committed cycle is BAD when its wall time
    exceeds the latency target; the 1% default budget makes the
    objective exactly "p99 cycle latency <= target". Events come from
    the existing `scheduler_cycle_phase_seconds{phase="cycle"}`
    histogram (falling back to `scheduler_schedule_cycle_seconds` on
    an untraced service) via `Histogram.count_le` — so the SLO, the
    trace, and the dashboards all read the same measurements, and the
    target should sit on a bucket bound.
  * `placement_success` — a pod-event is BAD when it lands
    unschedulable; events come from `scheduler_pods_scheduled`.

Burn rate follows the multi-window error-budget idiom (Koordinator's
slo-controller turns metrics into SLO decisions the same way; SRE
workbook otherwise): per window of N committed cycles, burn =
(bad fraction over the window) / budget — 1.0 means burning exactly
the budget, sustained >1 on the long window means the budget exhausts
early, and the short window catches fast regressions the long window
dilutes. The tracker keeps a ring of CUMULATIVE (total, bad) counter
snapshots, one per committed cycle, so windowed deltas are two
subtractions — no per-event storage.

Strictly opt-in at the service (`slo=True|SloTracker(...)`); disabled
adds zero work to the cycle.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

from koordinator_tpu.obs import phases as obs_phases
from koordinator_tpu.utils.sync import guarded_by

__all__ = ["SloObjective", "DEFAULT_OBJECTIVES", "DEFAULT_WINDOWS",
           "SloTracker"]


@dataclass(frozen=True)
class SloObjective:
    """One budgeted objective: `budget` is the allowed bad-event
    fraction; `threshold_s` is the latency target (latency kind only,
    and it should sit on a PHASE_BUCKETS bound — `count_le` is
    bucket-resolution)."""

    name: str
    kind: str  # "latency" | "placement"
    budget: float
    threshold_s: float = 0.0

    def __post_init__(self):
        if self.kind not in ("latency", "placement"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if not 0.0 < self.budget <= 1.0:
            raise ValueError("budget is a fraction in (0, 1]")


# generous defaults: a CPU CI service and the soak must sit deep inside
# them, so a non-green health() always means something real moved
DEFAULT_OBJECTIVES: Tuple[SloObjective, ...] = (
    SloObjective(name="cycle_latency_p99", kind="latency",
                 budget=0.01, threshold_s=30.0),
    SloObjective(name="placement_success", kind="placement",
                 budget=0.05),
)

# windows in COMMITTED CYCLES (not wall time — a paused service burns
# no budget): short catches fast regressions, long sets the verdict
DEFAULT_WINDOWS: Tuple[int, ...] = (8, 64)


@guarded_by(
    _rings="_lock",
    # wiring, fixed before concurrent traffic
    metrics="publish-once",
    objectives="publish-once",
    windows="publish-once",
)
class SloTracker:
    """Rings of cumulative (total, bad) event counts per objective,
    advanced once per committed cycle; burn rates and remaining budget
    fall out as windowed deltas."""

    def __init__(self, metrics,
                 objectives: Sequence[SloObjective] = DEFAULT_OBJECTIVES,
                 windows: Sequence[int] = DEFAULT_WINDOWS):
        if not objectives:
            raise ValueError("need at least one objective")
        if not windows or any(w < 1 for w in windows):
            raise ValueError("windows are positive cycle counts")
        self.metrics = metrics
        self.objectives = tuple(objectives)
        self.windows = tuple(sorted(set(int(w) for w in windows)))
        self._lock = threading.Lock()
        # ring of cumulative snapshots; +1 so the longest window has a
        # reference point one cycle before its start
        self._rings: Dict[str, deque] = {
            o.name: deque(maxlen=self.windows[-1] + 1)
            for o in self.objectives}
        # seed each ring with the counters AT ATTACH TIME: the first
        # cycle's window delta must cover that cycle's events, and a
        # tracker attached to a long-running service must not charge
        # itself history it never watched
        for o in self.objectives:
            self._rings[o.name].append(self._cumulative(o))

    def _cumulative(self, obj: SloObjective) -> Tuple[float, float]:
        """(total events, bad events) since process start, off the live
        metric families."""
        m = self.metrics
        if obj.kind == "latency":
            h = m.cycle_phase_seconds
            total = h.count(obs_phases.SPAN_CYCLE)
            if total > 0:
                good = h.count_le(obj.threshold_s, obs_phases.SPAN_CYCLE)
            else:  # untraced service: no cycle spans, same measurement
                h = m.cycle_seconds
                total = h.count()
                good = h.count_le(obj.threshold_s)
            return total, total - good
        placed = m.pods_scheduled.value("placed")
        bad = m.pods_scheduled.value("unschedulable")
        return placed + bad, bad

    def observe_cycle(self) -> None:
        """Append one cumulative snapshot per objective (call once per
        committed cycle) and publish the burn/budget gauges."""
        status = None
        with self._lock:
            for obj in self.objectives:
                self._rings[obj.name].append(self._cumulative(obj))
            status = self._status_locked()
        if self.metrics is not None:
            for name, s in status["objectives"].items():
                for w, rate in s["burn_rate"].items():
                    self.metrics.slo_burn_rate.labels(name, w).set(rate)
                self.metrics.slo_budget_remaining.labels(name).set(
                    s["budget_remaining"])

    def _window_delta(self, ring, w: int) -> Tuple[float, float]:
        """(total, bad) accrued over the last `w` cycles (or since
        start, early on): current minus the reference snapshot."""
        cur_t, cur_b = ring[-1]
        ref_t, ref_b = ring[-(w + 1)] if len(ring) > w else ring[0]
        return cur_t - ref_t, cur_b - ref_b

    def _status_locked(self) -> dict:
        objectives: Dict[str, dict] = {}
        for obj in self.objectives:
            ring = self._rings[obj.name]
            if not ring:
                objectives[obj.name] = {
                    "kind": obj.kind, "budget": obj.budget, "ok": True,
                    "burn_rate": {f"{w}c": 0.0 for w in self.windows},
                    "budget_remaining": 1.0,
                    "events_total": 0.0, "events_bad": 0.0,
                }
                continue
            burn: Dict[str, float] = {}
            for w in self.windows:
                dt, db = self._window_delta(ring, w)
                frac = db / dt if dt > 0 else 0.0
                burn[f"{w}c"] = frac / obj.budget
            # the verdict window is the longest: remaining budget is
            # what its bad fraction leaves of the allowance
            long_rate = burn[f"{self.windows[-1]}c"]
            total, bad = ring[-1]
            objectives[obj.name] = {
                "kind": obj.kind,
                "budget": obj.budget,
                "ok": all(r <= 1.0 for r in burn.values()),
                "burn_rate": burn,
                "budget_remaining": max(0.0, 1.0 - long_rate),
                "events_total": total,
                "events_bad": bad,
            }
        return {
            "ok": all(s["ok"] for s in objectives.values()),
            "budget_remaining": min(
                (s["budget_remaining"] for s in objectives.values()),
                default=1.0),
            "windows": [f"{w}c" for w in self.windows],
            "objectives": objectives,
        }

    def status(self) -> dict:
        """The health() view: per-objective burn rates over every
        window, remaining budget on the verdict window, and the
        aggregate ok bit."""
        with self._lock:
            return self._status_locked()
