"""koordcost device-memory telemetry: where the HBM actually is, and a
leak sentinel over committed cycles.

`sample_devices()` answers "how many device bytes are in use right now,
per device" from the best source the backend offers:

  * `device.memory_stats()` — TPU/GPU allocator stats: bytes in use,
    allocator peak, and the bytes limit (which gives real HBM
    headroom);
  * a live-buffer walk (`jax.live_arrays()` summed per device) when
    the backend reports no allocator stats (CPU) — no peak or limit,
    but the in-use series still feeds the leak sentinel.

`MemWatch` is the service-side consumer: the scheduler samples at the
dispatch/device_wait span boundaries (cheap: one stats call per
device), and after each COMMITTED cycle feeds the freshest sample into
a per-device window. The sentinel fires when in-use bytes grew
strictly monotonically across the whole window AND the total growth
clears a floor — a resident service re-dispatching the same programs
over a bounded store should plateau, so N cycles of uninterrupted
growth is the leak signature, while the floor keeps allocator jitter
and small caches quiet. Firing clears the window (one event per
sustained climb, not one per cycle).

Strictly opt-in at the service (`memwatch=True|MemWatch(...)`); the
disabled path adds zero work to the cycle.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from koordinator_tpu.utils.sync import guarded_by

__all__ = ["MemorySample", "sample_devices", "MemWatch"]


@dataclass(frozen=True)
class MemorySample:
    """One device's memory reading: in-use bytes, the allocator's peak
    and limit when the backend reports them (None on the live-buffer
    fallback), and which source answered."""

    device: str
    bytes_in_use: int
    peak_bytes: Optional[int]
    limit_bytes: Optional[int]
    source: str  # "memory_stats" | "live_buffers"


def _device_label(d) -> str:
    return f"{d.platform}:{d.id}"


def sample_devices(devices=None) -> Dict[str, MemorySample]:
    """Per-device memory readings, preferring allocator stats and
    falling back to one shared live-array walk for every device whose
    backend reports none."""
    import jax

    devs = list(jax.devices() if devices is None else devices)
    out: Dict[str, MemorySample] = {}
    fallback: List = []
    for d in devs:
        label = _device_label(d)
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_in_use" in stats:
            peak = stats.get("peak_bytes_in_use")
            limit = stats.get("bytes_limit")
            out[label] = MemorySample(
                device=label,
                bytes_in_use=int(stats["bytes_in_use"]),
                peak_bytes=None if peak is None else int(peak),
                limit_bytes=None if limit is None else int(limit),
                source="memory_stats")
        else:
            fallback.append((d, label))
    if fallback:
        per = {label: 0 for _, label in fallback}
        try:
            arrays = jax.live_arrays()
        except Exception:
            arrays = []
        for a in arrays:
            try:
                holders = list(a.devices())
            except Exception:
                continue
            if not holders:
                continue
            # a replicated array charges each holding device its share
            nbytes = int(getattr(a, "nbytes", 0)) // len(holders)
            for d in holders:
                label = _device_label(d)
                if label in per:
                    per[label] += nbytes
        for _, label in fallback:
            out[label] = MemorySample(
                device=label, bytes_in_use=per[label], peak_bytes=None,
                limit_bytes=None, source="live_buffers")
    return out


@guarded_by(
    _last="_lock",
    _peaks="_lock",
    _history="_lock",
    _leak_events="_lock",
    # wiring, fixed before concurrent traffic
    leak_window="publish-once",
    min_growth_bytes="publish-once",
    metrics="publish-once",
    _sampler="publish-once",
)
class MemWatch:
    """The per-service memory monitor: boundary samples, high-water
    peaks, and the monotonic-growth leak sentinel. Thread-safe — the
    scheduler samples under its commit lock while health() readers
    snapshot from any thread."""

    def __init__(self, leak_window: int = 8,
                 min_growth_bytes: int = 1 << 20,
                 metrics=None,
                 sampler: Callable[[], Dict[str, MemorySample]]
                 = sample_devices):
        if leak_window < 2:
            raise ValueError("leak_window must cover >= 2 cycles")
        self.leak_window = int(leak_window)
        self.min_growth_bytes = int(min_growth_bytes)
        # a SchedulerMetrics catalog (or None): leak events and the
        # in-use/peak gauges publish through it when attached
        self.metrics = metrics
        self._sampler = sampler
        self._lock = threading.Lock()
        self._last: Dict[str, MemorySample] = {}
        self._peaks: Dict[str, int] = {}
        self._history: Dict[str, deque] = {}
        self._leak_events = 0

    def sample(self) -> Dict[str, MemorySample]:
        """Take one boundary sample (dispatch open / device_wait close)
        and fold it into the high-water marks. Does NOT advance the
        leak window — that is per committed cycle, not per boundary."""
        samples = self._sampler()
        with self._lock:
            self._last = dict(samples)
            for label, s in samples.items():
                peak = s.bytes_in_use if s.peak_bytes is None \
                    else max(s.peak_bytes, s.bytes_in_use)
                if peak > self._peaks.get(label, 0):
                    self._peaks[label] = peak
        return samples

    def observe_cycle(self) -> List[str]:
        """Advance the leak window with the freshest boundary sample —
        once per COMMITTED cycle. Returns the devices whose sentinel
        fired, publishes gauges/counters when a catalog is attached."""
        fired: List[str] = []
        with self._lock:
            for label, s in self._last.items():
                hist = self._history.setdefault(
                    label, deque(maxlen=self.leak_window))
                hist.append(s.bytes_in_use)
                if len(hist) == self.leak_window and \
                        all(b > a for a, b in zip(hist, list(hist)[1:])) \
                        and hist[-1] - hist[0] >= self.min_growth_bytes:
                    self._leak_events += 1
                    fired.append(label)
                    hist.clear()  # one event per sustained climb
            latest = dict(self._last)
            peaks = dict(self._peaks)
        if self.metrics is not None:
            for label, s in latest.items():
                self.metrics.hbm_bytes_in_use.labels(label).set(
                    float(s.bytes_in_use))
                self.metrics.hbm_bytes_peak.labels(label).set(
                    float(peaks.get(label, s.bytes_in_use)))
            for label in fired:
                self.metrics.memwatch_leak_events.labels(label).inc()
        return fired

    def snapshot(self) -> dict:
        """The health() view: per-device readings + peaks, total leak
        events, and HBM headroom (min over devices reporting a limit;
        None when no backend reports one — the CPU fallback)."""
        with self._lock:
            latest = dict(self._last)
            peaks = dict(self._peaks)
            leaks = self._leak_events
        headrooms = [s.limit_bytes - s.bytes_in_use
                     for s in latest.values()
                     if s.limit_bytes is not None]
        return {
            "devices": {
                label: {
                    "bytes_in_use": s.bytes_in_use,
                    "peak_bytes": peaks.get(label, s.bytes_in_use),
                    "limit_bytes": s.limit_bytes,
                    "source": s.source,
                } for label, s in sorted(latest.items())},
            "leak_events": leaks,
            "leak_window": self.leak_window,
            "headroom_bytes": min(headrooms) if headrooms else None,
        }
