"""koordtrace span tracer: a bounded, thread-safe ring buffer of
structured span records on `time.monotonic_ns`.

Design constraints (tests/test_trace.py pins each):
  * bounded memory — a deque ring; overflow drops the OLDEST record
    and counts the drop (surfaced as `scheduler_trace_spans_dropped`),
  * thread-safe — one lock around buffer mutation; the span stack is
    thread-local so concurrent cycles nest independently,
  * zero overhead when disabled — callers hold `tracer = None` and
    route through a shared no-op span (`NOOP_SPAN`), so the dispatch
    hot path allocates NOTHING when tracing is off,
  * exportable — Chrome trace-event JSON (Perfetto-loadable) and
    JSONL, both carrying (cycle, span, parent, t_start, t_end, attrs).

Timestamps are `monotonic_ns` (immune to wall-clock steps); exports
convert to the microseconds Chrome's `ts`/`dur` expect. A wall-clock
anchor is recorded at construction so post-hoc analysis can map
monotonic time back to an absolute epoch.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from koordinator_tpu.utils.sync import guarded_by


@dataclass(frozen=True)
class SpanRecord:
    """One closed span (or instant event, when t_end == t_start)."""

    cycle: int
    name: str
    parent: Optional[str]
    t_start_ns: int
    t_end_ns: int
    thread_id: int
    attrs: Dict[str, object] = field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return (self.t_end_ns - self.t_start_ns) / 1e9


class _NoopSpan:
    """The disabled-path span: a single shared instance, no state.

    `__enter__` returns None (NOT an attrs dict) so disabled-path
    callers that try to attach attrs fail loudly in tests rather than
    silently building dicts nobody reads.
    """

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    """An open span; context manager. `__enter__` yields the attrs
    dict so the caller can attach attributes before close (recover()
    uses this for its replay-vs-compile split)."""

    __slots__ = ("_tracer", "name", "cycle", "attrs", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cycle: Optional[int],
                 attrs: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.cycle = cycle
        self.attrs = dict(attrs) if attrs else {}
        self._t0 = 0

    def __enter__(self) -> dict:
        self._t0 = time.monotonic_ns()
        self._tracer._push(self)
        return self.attrs

    def __exit__(self, exc_type, exc, tb):
        t1 = time.monotonic_ns()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self._tracer._pop(self, t1)
        return False


@guarded_by(
    _buf="_lock",
    _head="_lock",
    _dropped="_lock",
    # the span stack lives behind a threading.local handle: each
    # thread nests its own cycles without touching the lock
    _tls="confined",
    # wired by the owning service before the first span opens, never
    # rebound after; hook CALLS deliberately run outside the lock
    observer="publish-once",
    on_drop="publish-once",
    capacity="publish-once",
    anchor_monotonic_ns="publish-once",
    anchor_unix_ns="publish-once",
    pid="publish-once",
)
class Tracer:
    """Bounded structured span tracer.

    `capacity` bounds the ring; `observer(name, duration_s)` fires on
    every span close (the service wires it to
    `scheduler_cycle_phase_seconds{phase=...}`); `on_drop()` fires per
    overflow-dropped record (wired to `scheduler_trace_spans_dropped`).
    """

    def __init__(self, capacity: int = 65536,
                 observer: Optional[Callable[[str, float], None]] = None,
                 on_drop: Optional[Callable[[], None]] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._buf: List[SpanRecord] = []
        self._head = 0          # ring start index once full
        self._dropped = 0
        self._lock = threading.Lock()
        self._tls = threading.local()
        # public, mutable: SchedulerService wires its metric hooks into
        # a caller-supplied tracer through these when they are unset
        self.observer = observer
        self.on_drop = on_drop
        # wall-clock anchor: monotonic t and epoch t sampled together
        self.anchor_monotonic_ns = time.monotonic_ns()
        self.anchor_unix_ns = time.time_ns()
        self.pid = os.getpid()

    # --- span lifecycle ---

    def _stack(self) -> List[_Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def span(self, name: str, attrs: Optional[dict] = None,
             cycle: Optional[int] = None) -> _Span:
        """Open a span as a context manager; `with tracer.span(n) as a:`
        yields the attrs dict. Nested spans inherit `cycle` from the
        innermost enclosing span on this thread when not given."""
        return _Span(self, name, cycle, attrs)

    def event(self, name: str, attrs: Optional[dict] = None,
              cycle: Optional[int] = None) -> None:
        """Record an instant event (t_end == t_start)."""
        t = time.monotonic_ns()
        st = self._stack()
        parent = st[-1].name if st else None
        if cycle is None and st:
            cycle = st[-1].cycle
        self._append(SpanRecord(
            cycle=-1 if cycle is None else int(cycle), name=name,
            parent=parent, t_start_ns=t, t_end_ns=t,
            thread_id=threading.get_ident(),
            attrs=dict(attrs) if attrs else {}))

    def record_span(self, name: str, t_start_ns: int, t_end_ns: int,
                    attrs: Optional[dict] = None,
                    cycle: Optional[int] = None,
                    parent: Optional[str] = None) -> None:
        """Append a pre-timed span (tools that measure externally —
        profile_fullgate's gate-bisection deltas — still land in the
        same buffer/format)."""
        self._append(SpanRecord(
            cycle=-1 if cycle is None else int(cycle), name=name,
            parent=parent, t_start_ns=int(t_start_ns),
            t_end_ns=int(t_end_ns), thread_id=threading.get_ident(),
            attrs=dict(attrs) if attrs else {}))

    def _push(self, span: _Span) -> None:
        st = self._stack()
        if span.cycle is None and st:
            span.cycle = st[-1].cycle
        st.append(span)

    def _pop(self, span: _Span, t_end_ns: int) -> None:
        st = self._stack()
        # tolerate exception-unwound stacks: pop through to this span
        while st and st[-1] is not span:
            st.pop()
        if st:
            st.pop()
        parent = st[-1].name if st else None
        rec = SpanRecord(
            cycle=-1 if span.cycle is None else int(span.cycle),
            name=span.name, parent=parent, t_start_ns=span._t0,
            t_end_ns=t_end_ns, thread_id=threading.get_ident(),
            attrs=span.attrs)
        self._append(rec)
        if self.observer is not None:
            self.observer(span.name, rec.duration_s)

    def _append(self, rec: SpanRecord) -> None:
        dropped = False
        with self._lock:
            if len(self._buf) < self.capacity:
                self._buf.append(rec)
            else:
                # overwrite the oldest slot; the ring start advances
                self._buf[self._head] = rec
                self._head = (self._head + 1) % self.capacity
                self._dropped += 1
                dropped = True
        if dropped and self.on_drop is not None:
            self.on_drop()

    # --- query / export ---

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def records(self) -> List[SpanRecord]:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return self._buf[self._head:] + self._buf[:self._head]

    def durations_s(self, name: str) -> List[float]:
        """All closed durations of spans named `name`, in record order
        (bench.py derives p50/p99 cycle latency from these)."""
        return [r.duration_s for r in self.records() if r.name == name]

    def to_chrome(self) -> dict:
        """Chrome trace-event JSON (the object form Perfetto loads)."""
        events = []
        for r in self.records():
            ev = {
                "name": r.name,
                "cat": "koordtrace",
                "ph": "X",
                "ts": r.t_start_ns / 1e3,
                "dur": (r.t_end_ns - r.t_start_ns) / 1e3,
                "pid": self.pid,
                "tid": r.thread_id,
                "args": {"cycle": r.cycle, "parent": r.parent, **r.attrs},
            }
            if r.t_end_ns == r.t_start_ns:
                ev["ph"] = "i"
                ev["s"] = "t"
                del ev["dur"]
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "tracer": "koordtrace",
                "anchor_monotonic_ns": self.anchor_monotonic_ns,
                "anchor_unix_ns": self.anchor_unix_ns,
                "dropped": self.dropped,
            },
        }

    def to_jsonl(self) -> str:
        """One JSON object per record, oldest first."""
        out = io.StringIO()
        for r in self.records():
            out.write(json.dumps({
                "cycle": r.cycle, "span": r.name, "parent": r.parent,
                "t_start_ns": r.t_start_ns, "t_end_ns": r.t_end_ns,
                "thread": r.thread_id, "attrs": r.attrs,
            }, sort_keys=True))
            out.write("\n")
        return out.getvalue()


def jsonl_record(name: str, duration_s: float,
                 attrs: Optional[dict] = None,
                 cycle: int = -1,
                 parent: Optional[str] = None) -> str:
    """A single koordtrace-JSONL line for a synthetic (externally
    timed) span anchored at t=0 — the shared emit path for tools that
    produce per-phase deltas without a live Tracer
    (tools/profile_fullgate.py, tools/trace_fullgate.py)."""
    dur_ns = max(0, int(duration_s * 1e9))
    return json.dumps({
        "cycle": cycle, "span": name, "parent": parent,
        "t_start_ns": 0, "t_end_ns": dur_ns, "thread": 0,
        "attrs": dict(attrs) if attrs else {},
    }, sort_keys=True)
