"""koordcost: the registry-walking static cost accountant.

Where koordtrace answers "where did this cycle's wall-clock go", this
module answers "where do its FLOPs, bytes, and HBM go" — without a
device, before any hardware run. Every program the scheduler can
dispatch is already named: the koordshape contract registry
(snapshot/schema.SHAPE_CONTRACTS) names every contracted kernel, and
the compilecache enumerator (compilecache/precompile.py) names the
flagship cycle per cascade form plus the donated tail. This module
lowers each one at a fixed proxy working set and reads XLA's own
accounting off the compiled executable:

  * `compiled.cost_analysis()` — flops and bytes accessed;
  * `compiled.memory_analysis()` — argument/output/temp bytes and the
    donation-aliased bytes (a lost `donate_argnums` shows up here as
    alias_size collapsing to zero);
  * per-phase attribution of instructions and output bytes by parsing
    `op_name="...koord/<phase>/..."` metadata through the SHARED
    parser (obs/hloattrib.py) — the same join the sampled-time view
    (tools/trace_fullgate.py) uses, so the two can never drift.

The bf16 columnar packing layer (snapshot/packing.py) has no kernel of
its own, but its packed representation IS a byte contract: the model
prices the packed snapshot/pod footprint through `jax.eval_shape` over
the real pack functions, so an accidental bf16->f32 upcast doubles a
baseline number instead of silently doubling checkpoint and transfer
volume (tools/costcheck.py's planted-mutation smoke proves exactly
that path).

Everything here is static and deterministic for a fixed
(jax version, backend, contract fingerprint) — which is what makes the
checked-in perf/COST_BASELINE.json a meaningful drift gate.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from koordinator_tpu.obs import hloattrib

__all__ = [
    "COST_SIZES", "CostProgram", "enumerate_cost_programs",
    "program_report", "packing_report", "collect", "flagship_stamp",
]

# the proxy working set the checked-in baseline is stamped at: small
# enough that the full walk lowers in well under a CI minute, large
# enough that every axis is distinct and the cascade/tail forms are
# non-degenerate (TC < P so tail windows really gather)
COST_SIZES = {"P": 64, "N": 32, "TC": 16}

# baseline fields taken from XLA's analyses, in report order
MEMORY_FIELDS = ("argument_bytes", "output_bytes", "temp_bytes",
                 "alias_bytes", "peak_bytes")


@dataclass(frozen=True)
class CostProgram:
    """One program the cost model prices: a stable label (the baseline
    key) and a thunk returning the compiled executable."""

    label: str
    build: Callable[[], Any]
    kind: str  # "contract" | "cycle" | "tail"


def _first_computation(analysis) -> Dict[str, float]:
    """cost_analysis() returns one properties dict per computation on
    newer jax (a bare dict on older); the entry computation leads."""
    if isinstance(analysis, (list, tuple)):
        return dict(analysis[0]) if analysis else {}
    return dict(analysis or {})


def enumerate_cost_programs(sizes: Optional[Dict[str, int]] = None,
                            statics: Optional[Dict[str, Any]] = None
                            ) -> List[CostProgram]:
    """Every contracted kernel (the full SHAPE_CONTRACTS registry,
    abstract inputs built by the precompile enumerator's registry walk)
    plus the flagship cycle per cascade form and the donated tail (the
    compilecache enumerator verbatim, so donation aliasing is priced
    exactly as the warm path compiles it)."""
    import importlib

    import jax

    from koordinator_tpu.compilecache import precompile
    from tools.shapecheck import CONTRACT_MODULES  # registry imports

    for mod in CONTRACT_MODULES:
        importlib.import_module(mod)
    from koordinator_tpu.snapshot.schema import SHAPE_CONTRACTS

    sizes = dict(COST_SIZES if sizes is None else sizes)
    full = precompile.full_sizes(sizes)
    programs: List[CostProgram] = []
    for key in sorted(SHAPE_CONTRACTS):
        contract = SHAPE_CONTRACTS[key]
        kwargs = {}
        for name, raw in contract.args.items():
            v = precompile.abstract_value(raw, full)
            if v is precompile._SKIP:
                continue
            kwargs[name] = v
        static_kwargs: Dict[str, Any] = {}
        for name, value in contract.static.items():
            if isinstance(value, str) and value in full:
                value = full[value]
            static_kwargs[name] = value
        for name, dotted in contract.callables.items():
            static_kwargs[name] = SHAPE_CONTRACTS[dotted].fn
        fn = functools.partial(contract.fn, **static_kwargs) \
            if static_kwargs else contract.fn

        def build(fn=fn, kwargs=kwargs):
            return jax.jit(fn).lower(**kwargs).compile()

        short = key[len("koordinator_tpu."):] \
            if key.startswith("koordinator_tpu.") else key
        programs.append(CostProgram(label=f"contract/{short}",
                                    build=build, kind="contract"))
    # the flagship forms, through the SAME enumerator the AOT warmer
    # walks — donate_argnums survives only on this path (jax.jit of an
    # already-jitted fn re-wraps without donation)
    ws = precompile.WorkSet(sizes=sizes,
                            statics=dict(precompile.DEFAULT_STATICS,
                                         **(statics or {})),
                            devices=1)
    for spec in precompile.enumerate_programs(ws):
        programs.append(CostProgram(
            label=f"flagship/{spec.label}", build=spec.build,
            kind=spec.meta.get("form", "cycle")))
    return programs


def program_report(compiled) -> Dict[str, Any]:
    """The per-program cost record: XLA's flops/bytes/memory accounting
    plus the shared-parser per-phase attribution. `phases` sums to the
    hlo_* totals by construction (hloattrib closure property)."""
    cost = _first_computation(compiled.cost_analysis())
    mem = compiled.memory_analysis()
    arg = int(mem.argument_size_in_bytes)
    out = int(mem.output_size_in_bytes)
    tmp = int(mem.temp_size_in_bytes)
    alias = int(mem.alias_size_in_bytes)
    attribution = hloattrib.attribute_bytes(compiled.as_text())
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": tmp,
        "alias_bytes": alias,
        # the static peak proxy: everything resident at once, minus
        # what donation aliases into the outputs
        "peak_bytes": arg + out + tmp - alias,
        "hlo_instructions": sum(v["instructions"]
                                for v in attribution.values()),
        "hlo_output_bytes": sum(v["output_bytes"]
                                for v in attribution.values()),
        "phases": {phase: dict(v)
                   for phase, v in sorted(attribution.items())},
    }


def packing_report(sizes: Optional[Dict[str, int]] = None
                   ) -> Dict[str, Dict[str, int]]:
    """The packed-representation byte contract, priced through the REAL
    pack functions under jax.eval_shape (abstract: no device values).
    Routing through snapshot/packing.py is the point — a planted or
    accidental f32 upcast in its packable path moves `packed_bytes`
    here, which is what tools/costcheck.py's mutation smoke pins."""
    import jax

    from koordinator_tpu.compilecache import precompile
    from koordinator_tpu.snapshot import packing

    full = precompile.full_sizes(
        dict(COST_SIZES if sizes is None else sizes))

    def tree_bytes(tree) -> int:
        return int(sum(leaf.size * leaf.dtype.itemsize
                       for leaf in jax.tree_util.tree_leaves(tree)))

    out: Dict[str, Dict[str, int]] = {}
    for label, struct, pack in (
            ("packing/snapshot", "ClusterSnapshot", packing.pack_snapshot),
            ("packing/pods", "PodBatch", packing.pack_pods)):
        plain = precompile.abstract_struct(struct, full)
        packed = jax.eval_shape(pack, plain)
        pb, ub = tree_bytes(packed), tree_bytes(plain)
        out[label] = {"packed_bytes": pb, "unpacked_bytes": ub,
                      "saved_bytes": ub - pb}
    return out


def collect(sizes: Optional[Dict[str, int]] = None,
            statics: Optional[Dict[str, Any]] = None,
            log_fn: Optional[Callable[[str], None]] = None
            ) -> Dict[str, Dict[str, Any]]:
    """The full cost model at one working set: {label: report} over
    every contracted kernel, the flagship forms, and the packing byte
    contract. This is what `tools/costcheck.py --stamp` freezes into
    perf/COST_BASELINE.json and what the gate recomputes."""
    entries: Dict[str, Dict[str, Any]] = {}
    for prog in enumerate_cost_programs(sizes, statics):
        report = program_report(prog.build())
        report["kind"] = prog.kind
        entries[prog.label] = report
        if log_fn is not None:
            log_fn(f"costmodel: {prog.label} "
                   f"flops={report['flops']:.0f} "
                   f"bytes={report['bytes_accessed']:.0f} "
                   f"peak={report['peak_bytes']}")
    for label, report in packing_report(sizes).items():
        entries[label] = dict(report, kind="packing")
        if log_fn is not None:
            log_fn(f"costmodel: {label} "
                   f"packed={report['packed_bytes']} "
                   f"saved={report['saved_bytes']}")
    return entries


def flagship_stamp(compiled, num_pods: int) -> Dict[str, float]:
    """The bench-line cost stamp (bench.py BENCH_COST=1): static cost
    of the flagship program the bench actually compiled, normalized
    per pod so lines at different P join the same trajectory."""
    report = program_report(compiled)
    return {
        "flops": report["flops"],
        "bytes_accessed": report["bytes_accessed"],
        "hbm_peak_bytes": float(report["peak_bytes"]),
        "flops_per_pod": (report["flops"] / num_pods
                          if num_pods else 0.0),
    }
