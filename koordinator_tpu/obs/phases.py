"""koordtrace phase-name table — the single source of truth for every
span/annotation name in the system.

Three consumers join on these strings and MUST agree:
  * device-side `jax.named_scope`/`TraceAnnotation` labels (via
    `obs.phase(...)` — koordlint OB001 rejects bare literals),
  * host-side `SchedulerService` cycle spans (obs/trace.py records),
  * the trace parsers (`tools/trace_fullgate.py`,
    `tools/profile_fullgate.py`, `tools/trace_smoke.py`).

Names, not enums, because they end up verbatim in Chrome trace-event
JSON and in the `scheduler_cycle_phase_seconds{phase=...}` label set.
Kernel phases carry the `koord/` prefix (they appear inside XLA
profiler streams next to XLA-internal names and need a grep-able
namespace); host cycle spans are bare (they only ever appear in
koordtrace's own buffer).
"""

# --- device/kernel phases (named_scope / TraceAnnotation labels) ---

# the whole fused schedule_batch dispatch (kernel_timer annotation —
# predates koordtrace, kept verbatim so old traces still join)
PHASE_SCHEDULE_BATCH = "koord/schedule_batch"

# cascade stage 1 (cheap whole-batch prefilters)
PHASE_STAGE1_STATIC = "koord/stage1_static_gates"
PHASE_STAGE1_MASK = "koord/stage1_mask"

# stage-2 gate families (per-family score/prefilter kernels)
PHASE_STAGE2_DEVICESHARE = "koord/stage2_deviceshare"
PHASE_STAGE2_NUMA = "koord/stage2_numa"
PHASE_STAGE2_POLICY = "koord/stage2_policy"

# per-round selection + the cross-shard merge
PHASE_TOPK = "koord/topk_select"
PHASE_ICI_MERGE = "koord/ici_merge"

# adaptive tail
PHASE_TAIL_SELECT = "koord/tail_select"
PHASE_TAIL_PASS = "koord/tail_pass"
PHASE_TAIL_LOOP = "koord/tail_loop"

# --- host-side cycle spans (SchedulerService / bench) ---

SPAN_CYCLE = "cycle"
SPAN_ADMIT = "admit"
SPAN_GUARD_SCAN = "guard_scan"
SPAN_ENSURE_CACHED = "ensure_cached"
SPAN_DISPATCH = "dispatch"
SPAN_DEVICE_WAIT = "device_wait"
SPAN_JOURNAL_APPEND = "journal_append"
SPAN_PUBLISH = "publish"
SPAN_CHECKPOINT = "checkpoint"
SPAN_BACKOFF = "backoff"
SPAN_RECOVER = "recover"
SPAN_RECOVER_REPLAY = "recover_replay"
SPAN_RECOVER_COMPILE = "recover_compile"

# instant events (zero-duration marks)
EVENT_QUARANTINE = "quarantine"
EVENT_LADDER_TRANSITION = "ladder_transition"
EVENT_RETRY = "retry"

# bench spans (bench.py BENCH_TRACE mode)
SPAN_BENCH_WARMUP = "bench_warmup"
SPAN_BENCH_CYCLE = "bench_cycle"

KERNEL_PHASES = frozenset({
    PHASE_SCHEDULE_BATCH,
    PHASE_STAGE1_STATIC,
    PHASE_STAGE1_MASK,
    PHASE_STAGE2_DEVICESHARE,
    PHASE_STAGE2_NUMA,
    PHASE_STAGE2_POLICY,
    PHASE_TOPK,
    PHASE_ICI_MERGE,
    PHASE_TAIL_SELECT,
    PHASE_TAIL_PASS,
    PHASE_TAIL_LOOP,
})

HOST_SPANS = frozenset({
    SPAN_CYCLE,
    SPAN_ADMIT,
    SPAN_GUARD_SCAN,
    SPAN_ENSURE_CACHED,
    SPAN_DISPATCH,
    SPAN_DEVICE_WAIT,
    SPAN_JOURNAL_APPEND,
    SPAN_PUBLISH,
    SPAN_CHECKPOINT,
    SPAN_BACKOFF,
    SPAN_RECOVER,
    SPAN_RECOVER_REPLAY,
    SPAN_RECOVER_COMPILE,
    EVENT_QUARANTINE,
    EVENT_LADDER_TRANSITION,
    EVENT_RETRY,
    SPAN_BENCH_WARMUP,
    SPAN_BENCH_CYCLE,
})

ALL_PHASES = KERNEL_PHASES | HOST_SPANS

# the span skeleton every committed service cycle must carry, in order
# (tools/trace_smoke.py asserts it cycle-by-cycle)
CYCLE_SKELETON = (
    SPAN_ADMIT,
    SPAN_DISPATCH,
    SPAN_DEVICE_WAIT,
    SPAN_GUARD_SCAN,
    SPAN_JOURNAL_APPEND,
    SPAN_PUBLISH,
)


def check_phase(name: str) -> str:
    """Validate `name` against the table (raises ValueError on drift).

    The runtime complement of koordlint OB001: OB001 catches bare
    literals statically; this catches a constant that was renamed
    without updating the table.
    """
    if name not in ALL_PHASES:
        raise ValueError(
            f"unknown koordtrace phase {name!r}; add it to "
            "koordinator_tpu/obs/phases.py or use an existing constant")
    return name
